"""Observability overhead benchmark.

Measures the canonical hot-path workload (tasks_async_batch40, same as
bench_core.py) with tracing+core-metrics ON vs OFF, each in a fresh
subprocess so the RT_TRACE_EVENTS / RT_OBSERVABILITY_ENABLED kill
switches apply to every process in the cluster (driver, daemons, and
spawned workers all read them at import).

Also microbenchmarks the DISABLED guard itself (the single module-flag
check every instrumented site pays when observability is off) and
asserts the estimated per-task cost of those guards is <1% of the
measured per-task latency — the contract that instrumentation can never
silently regress the hot path when switched off.

Run: python bench_obs.py  → one JSON object per line, plus BENCH_OBS.json.
"""

import json
import os
import subprocess
import sys
import time

# Worst-case count of flag checks one task pays on the owner+executor
# when observability is OFF: submit stamp, dispatch stamp, exec stamp,
# lease-cache counter, per-RPC client stamps (send+recv, ~2 RPCs/task
# without batching), sched/lease-side guards. Deliberately generous.
GUARD_CHECKS_PER_TASK = 16


def _measure_batch40() -> float:
    """tasks_async_batch40 (bench_core.py parity): returns tasks/s."""
    import ray_tpu

    ray_tpu.init(num_cpus=32)

    @ray_tpu.remote
    def nop():
        return b"ok"

    def batch_async():
        ray_tpu.get([nop.remote() for _ in range(40)])

    for _ in range(8):
        batch_async()
    # best-of-5 windows: a 1-core CI box schedules daemons mid-window,
    # and a single sample can read 40% low on pure noise
    best = 0.0
    for _ in range(5):
        n = 8
        t0 = time.perf_counter()
        for _ in range(n):
            batch_async()
        dt = time.perf_counter() - t0
        best = max(best, 40 * n / dt)
    ray_tpu.shutdown()
    return best


def _run_mode(mode: str) -> float:
    env = dict(os.environ)
    flag = "1" if mode == "on" else "0"
    env["RT_TRACE_EVENTS"] = flag
    env["RT_OBSERVABILITY_ENABLED"] = flag
    env.setdefault("JAX_PLATFORMS", "cpu")
    out = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--mode", mode],
        env=env, capture_output=True, text=True, timeout=300, check=True,
    )
    for line in out.stdout.splitlines():
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if rec.get("metric") == "tasks_async_batch40":
            return float(rec["value"])
    raise RuntimeError(f"no metric line in {mode} run:\n{out.stdout}\n{out.stderr}")


def _guard_cost_ns() -> float:
    """Per-check cost of the disabled-path guard (one module attribute
    read + branch), measured against an empty loop baseline."""
    from ray_tpu.observability import core_metrics, tracing

    tracing.set_enabled(False)
    core_metrics.set_enabled(False)
    try:
        n = 2_000_000
        hits = 0
        t0 = time.perf_counter()
        for _ in range(n):
            if tracing.ENABLED:
                hits += 1
            if core_metrics.ENABLED:
                hits += 1
        guarded = time.perf_counter() - t0
        t0 = time.perf_counter()
        for _ in range(n):
            pass
        baseline = time.perf_counter() - t0
        assert hits == 0
        return max(guarded - baseline, 0.0) / (2 * n) * 1e9
    finally:
        tracing.set_enabled(True)
        core_metrics.set_enabled(True)


def main() -> int:
    if "--mode" in sys.argv:
        per_s = _measure_batch40()
        print(json.dumps({
            "metric": "tasks_async_batch40",
            "value": round(per_s, 1),
            "unit": "tasks/s",
        }), flush=True)
        return 0

    results = {}

    def record(name, value, unit):
        results[name] = {"value": value, "unit": unit}
        print(json.dumps({"metric": name, "value": value, "unit": unit}),
              flush=True)

    off = _run_mode("off")
    on = _run_mode("on")
    record("tasks_async_batch40_trace_off", round(off, 1), "tasks/s")
    record("tasks_async_batch40_trace_on", round(on, 1), "tasks/s")
    record(
        "tracing_on_overhead_pct",
        round((off / on - 1.0) * 100.0, 2) if on else 0.0,
        "%",
    )

    guard_ns = _guard_cost_ns()
    record("disabled_guard_cost_ns", round(guard_ns, 2), "ns/check")
    per_task_s = 1.0 / off
    off_overhead_pct = (
        GUARD_CHECKS_PER_TASK * guard_ns * 1e-9 / per_task_s * 100.0
    )
    record("tracing_off_overhead_pct", round(off_overhead_pct, 4), "%")

    with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "BENCH_OBS.json"), "w") as f:
        json.dump(results, f, indent=2)

    # The hard contract: with the kill switch off, the instrumented path
    # must cost (estimated, worst-case guard count) under 1% of a task.
    assert off_overhead_pct < 1.0, (
        f"tracing-off guard overhead {off_overhead_pct:.3f}% >= 1% "
        f"({guard_ns:.1f}ns/check x {GUARD_CHECKS_PER_TASK} checks at "
        f"{per_task_s * 1e6:.1f}us/task)"
    )
    print(json.dumps({"ok": True, "off_overhead_pct": round(off_overhead_pct, 4)}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
