"""Observability overhead benchmark.

A/Bs every instrumented hot path with tracing+core-metrics ON vs OFF,
each workload in a fresh subprocess so the RT_TRACE_EVENTS /
RT_OBSERVABILITY_ENABLED kill switches apply to every process in the
cluster (driver, daemons, and spawned workers all read them at import):

  tasks_async_batch40   the canonical task hot path (bench_core parity)
  serve_stream_tokens   LLM engine streaming decode (TTFT/ITL/token
                        counters + request-span stamp sites)
  pipeline_step_1f1b    compiled 1F1B train steps (per-op idle/fwd/bwd
                        slices + bubble/busy observations)
  collective_allreduce  2-rank cpu allreduce rounds (op spans + counters)
  serve_stream_sampled  streaming decode inside a live cluster with the
                        FULL observability plane on (head sampler +
                        alert engine ticking) vs everything off — pins
                        the history/alerting plane off the serving hot
                        path, and reports the sampler's steady-state
                        duty cycle (scrape time / interval, must be <1%)
  serve_stream_profiled streaming decode with the CONTINUOUS sampling
                        profiler on at its documented default rate
                        (RT_PROFILER_HZ=19) vs observability off — the
                        off mode proves the kill switch beats the hz
                        flag (no rt-prof thread), the on mode pins the
                        sampler's measured duty cycle (stack-walk time /
                        wall time) under 1%

Also microbenchmarks the DISABLED guard itself (the single module-flag
check every instrumented site pays when observability is off) and
asserts the estimated per-unit cost of those guards is <1% of each
workload's measured off-path unit latency — the contract that
instrumentation can never silently regress a hot path when switched off.

Run: python bench_obs.py  → one JSON object per line, plus BENCH_OBS.json.
"""

import json
import os
import subprocess
import sys
import time

# Worst-case count of flag checks one unit of each workload pays when
# observability is OFF. Deliberately generous.
#
# task: submit stamp, dispatch stamp, exec stamp, lease-cache counter,
#       per-RPC client stamps (send+recv, ~2 RPCs/task without
#       batching), sched/lease-side guards.
# token: engine-loop per-token stamps (ITL/TTFT observe, token counter,
#        record_step slice, per-token queue push guard).
# pipeline step: per microbatch x per stage: F op + B op, each with an
#        `obs` pre-check plus idle/slice emits and the step summary
#        (4 mb x 2 stages x 2 ops x ~4 guards + step stamps).
# collective op: per-rank op span emit + counters on both ranks.
GUARD_CHECKS_PER_UNIT = {
    "tasks_async_batch40": 16,
    "serve_stream_tokens": 8,
    "pipeline_step_1f1b": 96,
    "collective_allreduce": 8,
    "serve_stream_sampled": 8,
    "serve_stream_profiled": 8,
}

# Continuous-profiler rate the profiled leg pins its <1% duty-cycle
# contract at (the README's suggested always-on rate).
PROFILED_LEG_HZ = 19


def _measure_batch40() -> float:
    """tasks_async_batch40 (bench_core.py parity): returns tasks/s."""
    import ray_tpu

    ray_tpu.init(num_cpus=32)

    @ray_tpu.remote
    def nop():
        return b"ok"

    def batch_async():
        ray_tpu.get([nop.remote() for _ in range(40)])

    for _ in range(8):
        batch_async()
    # best-of-5 windows: a 1-core CI box schedules daemons mid-window,
    # and a single sample can read 40% low on pure noise
    best = 0.0
    for _ in range(5):
        n = 8
        t0 = time.perf_counter()
        for _ in range(n):
            batch_async()
        dt = time.perf_counter() - t0
        best = max(best, 40 * n / dt)
    ray_tpu.shutdown()
    return best


def _measure_engine_stream() -> float:
    """Streaming decode through a standalone LLMServer (no cluster):
    covers the engine's per-token TTFT/ITL/counter/slice stamp sites.
    Returns tokens/s."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    from ray_tpu.serve.llm import LLMConfig, LLMServer

    srv = LLMServer(LLMConfig(model_id="gpt2-tiny", max_batch_size=4))

    def stream_one(n_new: int) -> int:
        toks = 0
        for _ in srv({
            "prompt_tokens": [1, 2, 3], "max_new_tokens": n_new,
            "stream": True,
        }):
            toks += 1
        return toks

    stream_one(8)  # warm: jit compile prefill/decode
    best = 0.0
    for _ in range(3):
        t0 = time.perf_counter()
        toks = sum(stream_one(48) for _ in range(2))
        dt = time.perf_counter() - t0
        best = max(best, toks / dt)
    srv._stop.set()
    return best


def _measure_pipeline_step() -> float:
    """Compiled 1F1B train steps on a tiny 2-stage pipeline: covers the
    per-op idle/fwd/bwd slice and bubble/busy stamp sites. Returns
    steps/s."""
    import numpy as np

    import ray_tpu
    from ray_tpu.parallel.pipeline import Pipeline

    ray_tpu.init(num_cpus=8)
    rng = np.random.default_rng(7)
    W1 = rng.normal(size=(8, 16)).astype(np.float32) * 0.3
    W2 = rng.normal(size=(16, 4)).astype(np.float32) * 0.3
    X = rng.normal(size=(32, 8)).astype(np.float32)
    Y = rng.normal(size=(32, 4)).astype(np.float32)

    def stage1(params, x):
        import jax.numpy as jnp

        return jnp.tanh(x @ params["w"])

    def stage2(params, h):
        return h @ params["w"]

    def loss_fn(pred, target):
        import jax.numpy as jnp

        return jnp.mean((pred - target) ** 2)

    n_mb = 4
    xs = list(np.split(X, n_mb))
    ys = list(np.split(Y, n_mb))
    pipe = Pipeline([stage1, stage2], [{"w": W1}, {"w": W2}], loss_fn)
    cp = pipe.compile(schedule="1f1b", step_timeout_s=60.0)
    try:
        for _ in range(2):  # warm: jit compile fwd/bwd on both stages
            cp.train_step(xs, ys, lr=0.1)
        best = 0.0
        for _ in range(3):
            n = 4
            t0 = time.perf_counter()
            for _ in range(n):
                cp.train_step(xs, ys, lr=0.1)
            dt = time.perf_counter() - t0
            best = max(best, n / dt)
    finally:
        cp.teardown(timeout_s=30.0)
        pipe.shutdown()
        ray_tpu.shutdown()
    return best


def _measure_collective_allreduce() -> float:
    """2-rank cpu-backend allreduce rounds: covers the collective op
    span + counter stamp sites. Returns ops/s (one op = one allreduce
    across the group)."""
    import ray_tpu

    ray_tpu.init(num_cpus=4)

    @ray_tpu.remote
    class Member:
        def __init__(self, rank, world):
            self.rank, self.world = rank, world

        def setup(self, group):
            from ray_tpu import collective

            collective.init_collective_group(
                self.world, self.rank, "cpu", group
            )
            return True

        def do_allreduce(self, group):
            import numpy as np

            from ray_tpu import collective

            return collective.allreduce(
                np.full((64,), self.rank + 1.0), group_name=group
            )

    members = [Member.remote(i, 2) for i in range(2)]
    ray_tpu.get([m.setup.remote("bench") for m in members], timeout=60)

    def round_once():
        ray_tpu.get(
            [m.do_allreduce.remote("bench") for m in members], timeout=60
        )

    for _ in range(3):
        round_once()
    best = 0.0
    for _ in range(3):
        n = 10
        t0 = time.perf_counter()
        for _ in range(n):
            round_once()
        dt = time.perf_counter() - t0
        best = max(best, n / dt)
    ray_tpu.shutdown()
    return best


def _measure_serve_sampled() -> float:
    """Streaming decode inside a live cluster so the head's history
    sampler + alert engine tick concurrently with the serving loop.
    The off mode (RT_OBSERVABILITY_ENABLED=0 + sample interval 0) must
    start NO sampler thread; the on mode also reports the sampler duty
    cycle (median scrape seconds / interval). Returns tokens/s."""
    import threading

    import jax

    jax.config.update("jax_platforms", "cpu")
    import ray_tpu
    from ray_tpu import state
    from ray_tpu.observability.history import HistorySampler
    from ray_tpu.serve.llm import LLMConfig, LLMServer

    ray_tpu.init(num_cpus=4)
    try:
        plane_on = os.environ.get("RT_OBSERVABILITY_ENABLED", "1") != "0"
        names = [t.name for t in threading.enumerate()]
        hist = state.metrics_history()
        if plane_on:
            assert HistorySampler.THREAD_NAME in names, "sampler missing"
            assert hist["enabled"], "history store should be enabled"
        else:
            assert HistorySampler.THREAD_NAME not in names, (
                "sampler thread must not exist with the plane disabled"
            )
            assert hist == {"enabled": False}
            assert state.alerts() == {"enabled": False, "alerts": []}
        srv = LLMServer(LLMConfig(model_id="gpt2-tiny", max_batch_size=4))

        def stream_one(n_new: int) -> int:
            toks = 0
            for _ in srv({
                "prompt_tokens": [1, 2, 3], "max_new_tokens": n_new,
                "stream": True,
            }):
                toks += 1
            return toks

        stream_one(8)  # warm: jit compile prefill/decode
        # run long enough for several 1 s sampler ticks so the duty
        # cycle below is a steady-state median, not a cold-start sample
        best = 0.0
        deadline = time.time() + 4.5
        while time.time() < deadline:
            t0 = time.perf_counter()
            toks = sum(stream_one(48) for _ in range(2))
            dt = time.perf_counter() - t0
            best = max(best, toks / dt)
        srv._stop.set()
        if plane_on:
            st = state.metrics_history()
            ticks = st.get("ticks", 0)
            duty = (
                st["scrape_s_p50"] / st["base_step_s"] * 100.0
                if ticks else 0.0
            )
            print(json.dumps({
                "metric": "sampler_duty_pct", "value": round(duty, 4),
                "unit": "%",
            }), flush=True)
            print(json.dumps({
                "metric": "sampler_ticks", "value": ticks, "unit": "ticks",
            }), flush=True)
    finally:
        ray_tpu.shutdown()
    return best


def _measure_serve_profiled() -> float:
    """Streaming decode with the continuous sampling profiler running in
    the driver at the default always-on rate. The off mode
    (RT_OBSERVABILITY_ENABLED=0, RT_PROFILER_HZ still set) must start
    NO rt-prof thread — the kill switch wins; the on mode reports the
    sampler's measured duty cycle (stack-walk busy time / wall time),
    which the parent asserts is <1%. Returns tokens/s."""
    import threading

    import jax

    jax.config.update("jax_platforms", "cpu")
    import ray_tpu
    from ray_tpu.observability import profiler
    from ray_tpu.serve.llm import LLMConfig, LLMServer

    ray_tpu.init(num_cpus=4)
    try:
        plane_on = os.environ.get("RT_OBSERVABILITY_ENABLED", "1") != "0"
        names = [t.name for t in threading.enumerate()]
        if plane_on:
            assert profiler.SAMPLER_THREAD_NAME in names, (
                "continuous sampler thread missing with RT_PROFILER_HZ set"
            )
        else:
            assert profiler.SAMPLER_THREAD_NAME not in names, (
                "rt-prof thread must not exist with the plane disabled, "
                "even with RT_PROFILER_HZ set"
            )
            assert profiler.continuous_status() == {
                "running": False, "hz": 0.0,
            }
        srv = LLMServer(LLMConfig(model_id="gpt2-tiny", max_batch_size=4))

        def stream_one(n_new: int) -> int:
            toks = 0
            for _ in srv({
                "prompt_tokens": [1, 2, 3], "max_new_tokens": n_new,
                "stream": True,
            }):
                toks += 1
            return toks

        stream_one(8)  # warm: jit compile prefill/decode
        # run long enough for many sampler ticks so busy/wall is a
        # steady-state duty cycle, not a cold-start sample
        best = 0.0
        deadline = time.time() + 4.5
        while time.time() < deadline:
            t0 = time.perf_counter()
            toks = sum(stream_one(48) for _ in range(2))
            dt = time.perf_counter() - t0
            best = max(best, toks / dt)
        srv._stop.set()
        if plane_on:
            st = profiler.continuous_status()
            assert st.get("running"), "sampler died mid-benchmark"
            print(json.dumps({
                "metric": "profiler_duty_pct",
                "value": round(st.get("duty_pct", 0.0), 4), "unit": "%",
            }), flush=True)
            print(json.dumps({
                "metric": "profiler_samples",
                "value": int(st.get("samples", 0)), "unit": "samples",
            }), flush=True)
    finally:
        ray_tpu.shutdown()
    return best


BENCHES = {
    "tasks_async_batch40": (_measure_batch40, "tasks/s"),
    "serve_stream_tokens": (_measure_engine_stream, "tokens/s"),
    "pipeline_step_1f1b": (_measure_pipeline_step, "steps/s"),
    "collective_allreduce": (_measure_collective_allreduce, "ops/s"),
    "serve_stream_sampled": (_measure_serve_sampled, "tokens/s"),
    "serve_stream_profiled": (_measure_serve_profiled, "tokens/s"),
}


def _run_mode(mode: str, bench: str):
    """Run one bench in a fresh subprocess; returns (value, extras)
    where extras holds any additional metric lines the bench printed
    (e.g. the sampler duty cycle)."""
    env = dict(os.environ)
    flag = "1" if mode == "on" else "0"
    env["RT_TRACE_EVENTS"] = flag
    env["RT_OBSERVABILITY_ENABLED"] = flag
    # belt and braces for the sampled leg: the off mode disables the
    # history plane through BOTH kill switches
    if mode == "off":
        env["RT_METRICS_SAMPLE_INTERVAL_S"] = "0"
    else:
        env.pop("RT_METRICS_SAMPLE_INTERVAL_S", None)
    if bench == "serve_stream_profiled":
        # the hz flag is set in BOTH modes: off proves the kill switch
        # beats it (no rt-prof thread), on pins its duty cycle
        env["RT_PROFILER_HZ"] = str(PROFILED_LEG_HZ)
    else:
        env.pop("RT_PROFILER_HZ", None)
    env.setdefault("JAX_PLATFORMS", "cpu")
    out = subprocess.run(
        [sys.executable, os.path.abspath(__file__),
         "--mode", mode, "--bench", bench],
        env=env, capture_output=True, text=True, timeout=420, check=True,
    )
    value = None
    extras = {}
    for line in out.stdout.splitlines():
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if rec.get("metric") == bench:
            value = float(rec["value"])
        elif "metric" in rec:
            extras[rec["metric"]] = rec
    if value is None:
        raise RuntimeError(
            f"no metric line in {bench} {mode} run:\n"
            f"{out.stdout}\n{out.stderr}"
        )
    return value, extras


def _guard_cost_ns() -> float:
    """Per-check cost of the disabled-path guard (one module attribute
    read + branch), measured against an empty loop baseline."""
    from ray_tpu.observability import core_metrics, tracing

    tracing.set_enabled(False)
    core_metrics.set_enabled(False)
    try:
        n = 2_000_000
        hits = 0
        t0 = time.perf_counter()
        for _ in range(n):
            if tracing.ENABLED:
                hits += 1
            if core_metrics.ENABLED:
                hits += 1
        guarded = time.perf_counter() - t0
        t0 = time.perf_counter()
        for _ in range(n):
            pass
        baseline = time.perf_counter() - t0
        assert hits == 0
        return max(guarded - baseline, 0.0) / (2 * n) * 1e9
    finally:
        tracing.set_enabled(True)
        core_metrics.set_enabled(True)


def main() -> int:
    if "--mode" in sys.argv:
        bench = "tasks_async_batch40"
        if "--bench" in sys.argv:
            bench = sys.argv[sys.argv.index("--bench") + 1]
        fn, unit = BENCHES[bench]
        print(json.dumps({
            "metric": bench,
            "value": round(fn(), 1),
            "unit": unit,
        }), flush=True)
        return 0

    results = {}

    def record(name, value, unit):
        results[name] = {"value": value, "unit": unit}
        print(json.dumps({"metric": name, "value": value, "unit": unit}),
              flush=True)

    offs = {}
    sampler_duty_pct = None
    profiler_duty_pct = None
    for bench, (_fn, unit) in BENCHES.items():
        off, _ = _run_mode("off", bench)
        on, extras = _run_mode("on", bench)
        offs[bench] = off
        record(f"{bench}_trace_off", round(off, 1), unit)
        record(f"{bench}_trace_on", round(on, 1), unit)
        record(
            f"{bench}_on_overhead_pct",
            round((off / on - 1.0) * 100.0, 2) if on else 0.0,
            "%",
        )
        if "sampler_duty_pct" in extras:
            sampler_duty_pct = float(extras["sampler_duty_pct"]["value"])
            record("sampler_duty_pct", sampler_duty_pct, "%")
            record(
                "sampler_ticks",
                extras.get("sampler_ticks", {}).get("value", 0), "ticks",
            )
        if "profiler_duty_pct" in extras:
            profiler_duty_pct = float(extras["profiler_duty_pct"]["value"])
            record("profiler_duty_pct", profiler_duty_pct, "%")
            record(
                "profiler_samples",
                extras.get("profiler_samples", {}).get("value", 0),
                "samples",
            )

    guard_ns = _guard_cost_ns()
    record("disabled_guard_cost_ns", round(guard_ns, 2), "ns/check")

    # The hard contract: with the kill switch off, every instrumented
    # path must cost (estimated, worst-case guard count) under 1% of
    # one unit of that workload.
    failures = []
    for bench, checks in GUARD_CHECKS_PER_UNIT.items():
        per_unit_s = 1.0 / offs[bench]
        off_pct = checks * guard_ns * 1e-9 / per_unit_s * 100.0
        record(f"{bench}_off_overhead_pct", round(off_pct, 4), "%")
        if off_pct >= 1.0:
            failures.append(
                f"{bench}: tracing-off guard overhead {off_pct:.3f}% >= 1% "
                f"({guard_ns:.1f}ns/check x {checks} checks at "
                f"{per_unit_s * 1e6:.1f}us/unit)"
            )
    # second contract: when the plane is ON, the head sampler's duty
    # cycle (median scrape time over the sample interval) stays under 1%
    if sampler_duty_pct is None:
        failures.append("serve_stream_sampled never reported sampler duty")
    elif sampler_duty_pct >= 1.0:
        failures.append(
            f"sampler duty cycle {sampler_duty_pct:.3f}% >= 1% of the "
            f"sample interval"
        )
    # third contract: the continuous sampling profiler at its default
    # always-on rate stays under 1% of one core (busy / wall time)
    if profiler_duty_pct is None:
        failures.append(
            "serve_stream_profiled never reported profiler duty"
        )
    elif profiler_duty_pct >= 1.0:
        failures.append(
            f"continuous profiler duty cycle {profiler_duty_pct:.3f}% "
            f">= 1% at {PROFILED_LEG_HZ} Hz"
        )
    # legacy aliases kept for dashboards pinned to the original keys
    results["tracing_on_overhead_pct"] = results[
        "tasks_async_batch40_on_overhead_pct"
    ]
    results["tracing_off_overhead_pct"] = results[
        "tasks_async_batch40_off_overhead_pct"
    ]

    with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "BENCH_OBS.json"), "w") as f:
        json.dump(results, f, indent=2)

    assert not failures, "\n".join(failures)
    print(json.dumps({
        "ok": True,
        "off_overhead_pct": {
            b: results[f"{b}_off_overhead_pct"]["value"]
            for b in GUARD_CHECKS_PER_UNIT
        },
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
