"""Autoscaler — demand-driven node provisioning.

Parity: the reference autoscaler v2 (python/ray/autoscaler/v2/
autoscaler.py:50 — read cluster state, bin-pack pending demand,
reconcile instances through a NodeProvider). Demand here is the
pending-lease count each agent reports on its heartbeat (the role the
reference's resource_load syncer data plays); the provider abstraction
keeps cloud/k8s TPU-pod providers pluggable, with LocalNodeProvider
(subprocess node agents, the Cluster harness's mechanism) as the
in-repo implementation used by tests and single-host elasticity.
"""

from __future__ import annotations

import json
import logging
import os
import subprocess
import sys
import threading
import time
from typing import Any, Dict, List, Optional

from ray_tpu.utils.config import config
from ray_tpu.utils.rpc import RpcClient, RpcError

logger = logging.getLogger(__name__)


class NodeProvider:
    """Pluggable node lifecycle (reference: autoscaler node providers)."""

    def create_node(self) -> str:
        raise NotImplementedError

    def terminate_node(self, node_id: str) -> None:
        raise NotImplementedError

    def node_resources(self) -> Optional[Dict[str, float]]:
        """Resource shape of the node type this provider launches (the
        bin-packing target; reference autoscaler/v2/scheduler.py matches
        demand shapes to node types). None = unknown shape: providers
        that don't declare one keep the pre-shape-aware behavior (all
        demand counts as feasible) rather than having >1-CPU demand
        silently classified infeasible."""
        return None

    def shutdown(self) -> None:
        pass


class LocalNodeProvider(NodeProvider):
    """Spawns node agents as local processes (single-host elasticity and
    the test tier; a cloud provider would call instance APIs instead)."""

    def __init__(self, control_address: str, session_id: str,
                 resources: Optional[Dict[str, float]] = None):
        self.control_address = control_address
        self.session_id = session_id
        self.resources = dict(resources or {"CPU": 1.0})
        self._procs: Dict[str, subprocess.Popen] = {}

    def create_node(self) -> str:
        from ray_tpu.core.cluster_utils import spawn_node_agent

        proc, info = spawn_node_agent(
            self.control_address, self.session_id, self.resources
        )
        self._procs[info["node_id"]] = proc
        logger.info("autoscaler launched node %s", info["node_id"][:8])
        return info["node_id"]

    def terminate_node(self, node_id: str) -> None:
        proc = self._procs.pop(node_id, None)
        if proc is None:
            return
        try:
            os.killpg(os.getpgid(proc.pid), 15)
        except (ProcessLookupError, PermissionError):
            proc.terminate()
        logger.info("autoscaler terminated node %s", node_id[:8])

    def node_resources(self) -> Dict[str, float]:
        return dict(self.resources)

    def shutdown(self) -> None:
        for nid in list(self._procs):
            self.terminate_node(nid)


class TpuPodProvider(NodeProvider):
    """TPU-pod slice provider (mocked GKE backend): provisions WHOLE
    slices as atoms, the way a cloud provider adds a multi-host TPU node
    pool (reference autoscaler/_private/gcp/node_provider.py + the
    KubeRay TPU webhook's slice semantics). One v5e-16 slice = 4 hosts x
    4 chips; host 0 of each slice advertises the ``TPU-{pod}-head``
    resource that SlicePlacementGroup's bundle 0 claims. The mock
    backend spawns local node agents shaped like slice hosts; a real GKE
    backend would create the node pool instead — everything above the
    create/terminate calls is identical."""

    def __init__(self, control_address: str, session_id: str,
                 pod_type: str = "v5e-16", chips_per_host: int = 4):
        from ray_tpu.accelerators.tpu import TPUAcceleratorManager

        self.control_address = control_address
        self.session_id = session_id
        self.pod_type = pod_type
        self.chips_per_host = chips_per_host
        self.hosts_per_slice = TPUAcceleratorManager.num_workers_in_slice(
            pod_type
        )
        self._slices: Dict[str, List[tuple]] = {}  # slice_id -> [(nid, proc)]
        self._next_slice = 0

    def node_resources(self) -> Dict[str, float]:
        return {"TPU": float(self.chips_per_host)}

    def create_slice(self) -> List[str]:
        """Provision one whole slice; returns its node ids (exactly
        hosts_per_slice of them)."""
        from ray_tpu.core.cluster_utils import spawn_node_agent

        slice_id = f"{self.pod_type}-{self._next_slice}"
        self._next_slice += 1
        members: List[tuple] = []
        node_ids: List[str] = []
        for host in range(self.hosts_per_slice):
            res: Dict[str, float] = {
                "TPU": float(self.chips_per_host), "CPU": 1.0,
            }
            if host == 0:
                res[f"TPU-{self.pod_type}-head"] = 1.0
            proc, info = spawn_node_agent(
                self.control_address, self.session_id, res,
                labels={"tpu-pod-type": self.pod_type,
                        "tpu-slice": slice_id},
            )
            members.append((info["node_id"], proc))
            node_ids.append(info["node_id"])
        self._slices[slice_id] = members
        logger.info(
            "provisioned TPU slice %s (%d hosts)", slice_id, len(members)
        )
        return node_ids

    def create_node(self) -> str:
        # single-node requests still provision a whole slice (slices are
        # the provider's atom); callers wanting host granularity use the
        # slice API
        return self.create_slice()[0]

    def slice_of(self, node_id: str) -> Optional[str]:
        for sid, members in self._slices.items():
            if any(nid == node_id for nid, _ in members):
                return sid
        return None

    def slice_members(self, slice_id: str) -> List[str]:
        """Node ids of one slice — the provider-interface contract the
        autoscaler's busy-sibling and boot-settling checks rely on."""
        return [nid for nid, _ in self._slices.get(slice_id, [])]

    def all_slice_members(self) -> List[str]:
        return [
            nid for members in self._slices.values() for nid, _ in members
        ]

    def terminate_slice(self, slice_id: str) -> None:
        members = self._slices.pop(slice_id, None)
        if not members:
            return
        for _, proc in members:
            try:
                os.killpg(os.getpgid(proc.pid), 15)
            except (ProcessLookupError, PermissionError):
                proc.terminate()
        logger.info("terminated TPU slice %s", slice_id)

    def terminate_node(self, node_id: str) -> None:
        sid = self.slice_of(node_id)
        if sid is not None:
            self.terminate_slice(sid)

    def shutdown(self) -> None:
        for sid in list(self._slices):
            self.terminate_slice(sid)


def pending_slice_demand(pgs: List[Dict[str, Any]],
                         host_shape: Dict[str, float],
                         head_resource: Optional[str] = None) -> int:
    """Bin-pack pending placement-group bundles into hosts of
    ``host_shape``: how many hosts would satisfy every TPU bundle of
    every PENDING PG (reference autoscaler/v2/scheduler.py's shape
    matching, specialized to the one node type this provider launches).
    A bundle naming a ``TPU-<pod>-head`` resource fits ONLY when it
    matches this provider's ``head_resource`` — a v5e-64 PG must never
    drive a v5e-16 provider into provisioning slices that can't satisfy
    it."""
    hosts = 0
    for pg in pgs:
        if pg.get("state") not in ("PENDING", "RESCHEDULING"):
            continue
        for bundle in pg.get("bundles", []):
            needs_tpu = any(
                k == "TPU" or k.startswith("TPU-") for k in bundle
            )
            if not needs_tpu:
                continue
            heads = [k for k in bundle if k.startswith("TPU-")]
            if any(h != head_resource for h in heads):
                continue  # a different pod type's slice PG
            fits = all(
                v <= (
                    1.0 if k == head_resource else host_shape.get(k, 0.0)
                )
                for k, v in bundle.items() if v > 0
            )
            if fits:
                hosts += 1  # STRICT_SPREAD: one bundle per host
    return hosts


class Autoscaler:
    """Scale up while any node reports pending leases; scale an idle
    autoscaler-launched node down after idle_timeout_s."""

    def __init__(
        self,
        control_address: str,
        provider: NodeProvider,
        min_nodes: int = 1,
        max_nodes: int = 4,
        idle_timeout_s: float = 30.0,
        poll_period_s: float = 1.0,
        upscale_cooldown_s: float = 3.0,
    ):
        self.control_address = control_address
        self.provider = provider
        self.min_nodes = min_nodes
        self.max_nodes = max_nodes
        self.idle_timeout_s = idle_timeout_s
        self.poll_period_s = poll_period_s
        self.upscale_cooldown_s = upscale_cooldown_s
        self._launched: List[str] = []  # node_ids we created (LIFO down-scale)
        self._idle_since: Dict[str, float] = {}
        self._last_upscale = 0.0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._loop, name="autoscaler", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=10)
        self.provider.shutdown()

    def _loop(self) -> None:
        client = RpcClient(self.control_address, name="autoscaler")
        try:
            while not self._stop.wait(self.poll_period_s):
                try:
                    self._step(client)
                except Exception:  # noqa: BLE001 — keep reconciling
                    logger.exception("autoscaler step failed")
        finally:
            client.close()

    def _step_slices(self, client: RpcClient, nodes, n_alive: int) -> None:
        """Slice-atom scale-up: pending SlicePlacementGroup demand maps
        to WHOLE slices — a v5e-16 PG asks the provider for exactly its 4
        hosts, never CPU fillers (reference: the GKE provider adds a
        multi-host node pool per slice)."""
        provider = self.provider
        alive_ids = {n["node_id"] for n in nodes}
        booting = [
            nid for nid in provider.all_slice_members()
            if nid not in alive_ids
        ]
        if booting:
            # a previously-provisioned slice is still registering: wait
            # for it before judging demand again, or one pending PG
            # double-provisions every cooldown
            return
        try:
            pgs = client.call("list_placement_groups", timeout_s=10.0)
        except RpcError:
            return
        hosts_needed = pending_slice_demand(
            pgs, provider.node_resources(),
            head_resource=f"TPU-{provider.pod_type}-head",
        )
        if hosts_needed <= 0:
            return
        now = time.monotonic()
        if now - self._last_upscale < self.upscale_cooldown_s:
            return
        per_slice = provider.hosts_per_slice
        slices = -(-hosts_needed // per_slice)  # ceil
        budget = max(0, self.max_nodes - n_alive) // per_slice
        slices = min(slices, budget)
        if slices <= 0:
            return
        self._last_upscale = now
        for _ in range(slices):
            for nid in provider.create_slice():
                self._launched.append(nid)

    def _publish_infeasible(
        self, client: RpcClient, infeasible: List[Dict[str, float]],
        tmpl: Dict[str, float],
    ) -> None:
        """Surface truly-unschedulable demand in the control store KV so
        `rt status` can report it instead of the cluster silently scaling
        (or never scaling)."""
        if not infeasible:
            return  # last report ages out (status filters by timestamp)
        try:
            client.call(
                "kv_put", ns="autoscaler", key="infeasible",
                value=json.dumps(
                    {"shapes": infeasible, "node_type": tmpl,
                     "ts": time.time()}
                ).encode(),
            )
        except RpcError:
            pass

    def _step(self, client: RpcClient) -> None:
        try:
            nodes = client.call("get_nodes", alive_only=True, timeout_s=10.0)
        except RpcError:
            return
        n_alive = len(nodes)
        if hasattr(self.provider, "create_slice"):
            self._step_slices(client, nodes, n_alive)
        demand = sum(int(n.get("pending_leases", 0)) for n in nodes)
        # Shape-aware demand (reference autoscaler/v2/scheduler.py
        # bin-packs pending shapes into node types): upscale only when a
        # pending shape would actually FIT the provider's node type —
        # "any pending lease → +1 node" scaled to max_nodes forever on a
        # task no node size could ever serve.
        shapes: List[Dict[str, float]] = []
        for n in nodes:
            shapes.extend(n.get("pending_shapes") or [])
        tmpl = self.provider.node_resources()
        if tmpl is None:  # provider with an undeclared node shape
            feasible, infeasible = list(shapes), []
        else:
            feasible = [
                s for s in shapes
                if all(tmpl.get(k, 0.0) >= v for k, v in s.items() if v > 0)
            ]
            infeasible = [
                s for s in shapes
                if not all(tmpl.get(k, 0.0) >= v for k, v in s.items() if v > 0)
            ]
        self._publish_infeasible(client, infeasible, tmpl)
        # demand without shape info (older agents / flickering counters)
        # counts as feasible — the pre-shape behavior
        has_feasible_demand = bool(feasible) or (demand > 0 and not shapes)
        now = time.monotonic()
        if (
            has_feasible_demand
            and n_alive < self.max_nodes
            and now - self._last_upscale >= self.upscale_cooldown_s
        ):
            self._last_upscale = now
            node_id = self.provider.create_node()
            self._launched.append(node_id)
            return
        demand = demand if has_feasible_demand else 0
        # scale down: only nodes WE launched, newest first, when the whole
        # cluster has no demand and the node itself is idle
        alive_ids = {n["node_id"] for n in nodes}
        busy_ids = {
            n["node_id"] for n in nodes
            if n.get("active_leases", 0) or n.get("pending_leases", 0)
        }
        for nid in list(self._launched):
            if nid not in alive_ids:
                self._launched.remove(nid)
                self._idle_since.pop(nid, None)
                continue
            if demand > 0 or nid in busy_ids or n_alive <= self.min_nodes:
                self._idle_since.pop(nid, None)
                continue
            if hasattr(self.provider, "slice_of"):
                # slice atoms: terminate_node tears down the WHOLE slice,
                # so an idle host whose slice SIBLING is busy must wait —
                # never destroy a running actor on host 0 because host 3
                # went quiet
                sid = self.provider.slice_of(nid)
                members = (
                    set(self.provider.slice_members(sid)) if sid else set()
                )
                if members & busy_ids:
                    self._idle_since.pop(nid, None)
                    continue
            first = self._idle_since.setdefault(nid, now)
            if now - first >= self.idle_timeout_s:
                # heartbeat lease counts can be up to a period stale: ask
                # the agent DIRECTLY before killing, so a just-granted
                # lease is never torn down (two-phase drain)
                addr = next(
                    (n["address"] for n in nodes if n["node_id"] == nid), None
                )
                if addr:
                    probe = RpcClient(addr, name="autoscaler-probe")
                    try:
                        st = probe.call("get_state", timeout_s=5.0)
                        if st.get("leases"):
                            self._idle_since[nid] = now  # busy after all
                            continue
                    except RpcError:
                        pass  # unreachable: fall through and reap it
                    finally:
                        probe.close()
                try:
                    client.call("drain_node", node_id=nid, timeout_s=10.0)
                except RpcError:
                    pass
                self.provider.terminate_node(nid)
                self._launched.remove(nid)
                self._idle_since.pop(nid, None)
                n_alive -= 1
