"""Autoscaler — demand-driven node provisioning.

Parity: the reference autoscaler v2 (python/ray/autoscaler/v2/
autoscaler.py:50 — read cluster state, bin-pack pending demand,
reconcile instances through a NodeProvider). Demand here is the
pending-lease count each agent reports on its heartbeat (the role the
reference's resource_load syncer data plays); the provider abstraction
keeps cloud/k8s TPU-pod providers pluggable, with LocalNodeProvider
(subprocess node agents, the Cluster harness's mechanism) as the
in-repo implementation used by tests and single-host elasticity.
"""

from __future__ import annotations

import json
import logging
import os
import subprocess
import sys
import threading
import time
from typing import Any, Dict, List, Optional

from ray_tpu.utils.config import config
from ray_tpu.utils.rpc import RpcClient, RpcError

logger = logging.getLogger(__name__)


class NodeProvider:
    """Pluggable node lifecycle (reference: autoscaler node providers)."""

    def create_node(self) -> str:
        raise NotImplementedError

    def terminate_node(self, node_id: str) -> None:
        raise NotImplementedError

    def node_resources(self) -> Optional[Dict[str, float]]:
        """Resource shape of the node type this provider launches (the
        bin-packing target; reference autoscaler/v2/scheduler.py matches
        demand shapes to node types). None = unknown shape: providers
        that don't declare one keep the pre-shape-aware behavior (all
        demand counts as feasible) rather than having >1-CPU demand
        silently classified infeasible."""
        return None

    def shutdown(self) -> None:
        pass


class LocalNodeProvider(NodeProvider):
    """Spawns node agents as local processes (single-host elasticity and
    the test tier; a cloud provider would call instance APIs instead)."""

    def __init__(self, control_address: str, session_id: str,
                 resources: Optional[Dict[str, float]] = None):
        self.control_address = control_address
        self.session_id = session_id
        self.resources = dict(resources or {"CPU": 1.0})
        self._procs: Dict[str, subprocess.Popen] = {}

    def create_node(self) -> str:
        from ray_tpu.core.cluster_utils import spawn_node_agent

        proc, info = spawn_node_agent(
            self.control_address, self.session_id, self.resources
        )
        self._procs[info["node_id"]] = proc
        logger.info("autoscaler launched node %s", info["node_id"][:8])
        return info["node_id"]

    def terminate_node(self, node_id: str) -> None:
        proc = self._procs.pop(node_id, None)
        if proc is None:
            return
        try:
            os.killpg(os.getpgid(proc.pid), 15)
        except (ProcessLookupError, PermissionError):
            proc.terminate()
        logger.info("autoscaler terminated node %s", node_id[:8])

    def node_resources(self) -> Dict[str, float]:
        return dict(self.resources)

    def shutdown(self) -> None:
        for nid in list(self._procs):
            self.terminate_node(nid)


class Autoscaler:
    """Scale up while any node reports pending leases; scale an idle
    autoscaler-launched node down after idle_timeout_s."""

    def __init__(
        self,
        control_address: str,
        provider: NodeProvider,
        min_nodes: int = 1,
        max_nodes: int = 4,
        idle_timeout_s: float = 30.0,
        poll_period_s: float = 1.0,
        upscale_cooldown_s: float = 3.0,
    ):
        self.control_address = control_address
        self.provider = provider
        self.min_nodes = min_nodes
        self.max_nodes = max_nodes
        self.idle_timeout_s = idle_timeout_s
        self.poll_period_s = poll_period_s
        self.upscale_cooldown_s = upscale_cooldown_s
        self._launched: List[str] = []  # node_ids we created (LIFO down-scale)
        self._idle_since: Dict[str, float] = {}
        self._last_upscale = 0.0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._loop, name="autoscaler", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=10)
        self.provider.shutdown()

    def _loop(self) -> None:
        client = RpcClient(self.control_address, name="autoscaler")
        try:
            while not self._stop.wait(self.poll_period_s):
                try:
                    self._step(client)
                except Exception:  # noqa: BLE001 — keep reconciling
                    logger.exception("autoscaler step failed")
        finally:
            client.close()

    def _publish_infeasible(
        self, client: RpcClient, infeasible: List[Dict[str, float]],
        tmpl: Dict[str, float],
    ) -> None:
        """Surface truly-unschedulable demand in the control store KV so
        `rt status` can report it instead of the cluster silently scaling
        (or never scaling)."""
        if not infeasible:
            return  # last report ages out (status filters by timestamp)
        try:
            client.call(
                "kv_put", ns="autoscaler", key="infeasible",
                value=json.dumps(
                    {"shapes": infeasible, "node_type": tmpl,
                     "ts": time.time()}
                ).encode(),
            )
        except RpcError:
            pass

    def _step(self, client: RpcClient) -> None:
        try:
            nodes = client.call("get_nodes", alive_only=True, timeout_s=10.0)
        except RpcError:
            return
        n_alive = len(nodes)
        demand = sum(int(n.get("pending_leases", 0)) for n in nodes)
        # Shape-aware demand (reference autoscaler/v2/scheduler.py
        # bin-packs pending shapes into node types): upscale only when a
        # pending shape would actually FIT the provider's node type —
        # "any pending lease → +1 node" scaled to max_nodes forever on a
        # task no node size could ever serve.
        shapes: List[Dict[str, float]] = []
        for n in nodes:
            shapes.extend(n.get("pending_shapes") or [])
        tmpl = self.provider.node_resources()
        if tmpl is None:  # provider with an undeclared node shape
            feasible, infeasible = list(shapes), []
        else:
            feasible = [
                s for s in shapes
                if all(tmpl.get(k, 0.0) >= v for k, v in s.items() if v > 0)
            ]
            infeasible = [
                s for s in shapes
                if not all(tmpl.get(k, 0.0) >= v for k, v in s.items() if v > 0)
            ]
        self._publish_infeasible(client, infeasible, tmpl)
        # demand without shape info (older agents / flickering counters)
        # counts as feasible — the pre-shape behavior
        has_feasible_demand = bool(feasible) or (demand > 0 and not shapes)
        now = time.monotonic()
        if (
            has_feasible_demand
            and n_alive < self.max_nodes
            and now - self._last_upscale >= self.upscale_cooldown_s
        ):
            self._last_upscale = now
            node_id = self.provider.create_node()
            self._launched.append(node_id)
            return
        demand = demand if has_feasible_demand else 0
        # scale down: only nodes WE launched, newest first, when the whole
        # cluster has no demand and the node itself is idle
        alive_ids = {n["node_id"] for n in nodes}
        busy_ids = {
            n["node_id"] for n in nodes
            if n.get("active_leases", 0) or n.get("pending_leases", 0)
        }
        for nid in list(self._launched):
            if nid not in alive_ids:
                self._launched.remove(nid)
                self._idle_since.pop(nid, None)
                continue
            if demand > 0 or nid in busy_ids or n_alive <= self.min_nodes:
                self._idle_since.pop(nid, None)
                continue
            first = self._idle_since.setdefault(nid, now)
            if now - first >= self.idle_timeout_s:
                # heartbeat lease counts can be up to a period stale: ask
                # the agent DIRECTLY before killing, so a just-granted
                # lease is never torn down (two-phase drain)
                addr = next(
                    (n["address"] for n in nodes if n["node_id"] == nid), None
                )
                if addr:
                    probe = RpcClient(addr, name="autoscaler-probe")
                    try:
                        st = probe.call("get_state", timeout_s=5.0)
                        if st.get("leases"):
                            self._idle_since[nid] = now  # busy after all
                            continue
                    except RpcError:
                        pass  # unreachable: fall through and reap it
                    finally:
                        probe.close()
                try:
                    client.call("drain_node", node_id=nid, timeout_s=10.0)
                except RpcError:
                    pass
                self.provider.terminate_node(nid)
                self._launched.remove(nid)
                self._idle_since.pop(nid, None)
                n_alive -= 1
