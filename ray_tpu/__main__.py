"""`python -m ray_tpu <cmd>` — forwards to the rt CLI (ray_tpu/cli.py)."""

import sys

from ray_tpu.cli import main

sys.exit(main())
