"""ray_tpu.data — streaming Dataset library.

Parity target: Ray Data (reference python/ray/data — lazy logical plan,
streaming executor over the object plane, per-Train-worker iterators).
"""

from ray_tpu.data.block import Block, BlockAccessor, BlockMeta
from ray_tpu.data.dataset import (
    AggregateFn,
    Dataset,
    GroupedData,
    from_items,
    from_numpy,
    range,  # noqa: A004
    read_csv,
    read_json,
    read_numpy,
    read_parquet,
    read_text,
)
from ray_tpu.data.iterator import DataIterator

__all__ = [
    "AggregateFn",
    "Block",
    "BlockAccessor",
    "BlockMeta",
    "DataIterator",
    "Dataset",
    "GroupedData",
    "from_items",
    "from_numpy",
    "range",
    "read_csv",
    "read_json",
    "read_numpy",
    "read_parquet",
    "read_text",
]
