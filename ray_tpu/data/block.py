"""Blocks — the unit of data flowing through a Dataset pipeline.

Parity: reference Ray Data blocks (python/ray/data/block.py,
arrow_block.py) are Arrow tables living in plasma. TPU-first translation:
a block is a **column batch** — ``{column: np.ndarray}`` — because numpy
arrays round-trip through the shm object store zero-copy (pickle-5
out-of-band buffers mmap'd straight from the segment), and a column batch
is exactly the host-side layout `jax.device_put` wants when feeding a TPU
input pipeline. Row-oriented data (from_items over arbitrary Python
objects) uses a list block; both are handled through BlockAccessor, the
same dispatch pattern as the reference's BlockAccessor.
"""

from __future__ import annotations

from typing import Any, Dict, Iterator, List, Optional, Sequence, Union

import numpy as np

# A block is either a column batch or a list of rows.
Block = Union[Dict[str, np.ndarray], List[Any]]


class BlockAccessor:
    """Uniform ops over the two block representations."""

    def __init__(self, block: Block):
        self._block = block
        self._is_columnar = isinstance(block, dict)

    @staticmethod
    def for_block(block: Block) -> "BlockAccessor":
        return BlockAccessor(block)

    @property
    def is_columnar(self) -> bool:
        return self._is_columnar

    def num_rows(self) -> int:
        if self._is_columnar:
            if not self._block:
                return 0
            return len(next(iter(self._block.values())))
        return len(self._block)

    def size_bytes(self) -> int:
        if self._is_columnar:
            return int(sum(v.nbytes for v in self._block.values()))
        # rough: rows are small python objects
        return 64 * len(self._block)

    def slice(self, start: int, end: int) -> Block:
        if self._is_columnar:
            return {k: v[start:end] for k, v in self._block.items()}
        return self._block[start:end]

    def iter_rows(self) -> Iterator[Any]:
        if self._is_columnar:
            cols = list(self._block.keys())
            for i in range(self.num_rows()):
                yield {c: self._block[c][i] for c in cols}
        else:
            yield from self._block

    def to_batch(self) -> Dict[str, np.ndarray]:
        """Columnar view of the block (rows must be dicts of scalars)."""
        if self._is_columnar:
            return self._block
        if not self._block:
            return {}
        first = self._block[0]
        if isinstance(first, dict):
            return {
                k: np.asarray([row[k] for row in self._block])
                for k in first
            }
        return {"item": np.asarray(self._block)}

    @staticmethod
    def concat(blocks: Sequence[Block]) -> Block:
        blocks = [b for b in blocks if BlockAccessor(b).num_rows() > 0]
        if not blocks:
            return []
        if isinstance(blocks[0], dict):
            keys = blocks[0].keys()
            return {
                k: np.concatenate([b[k] for b in blocks], axis=0) for k in keys
            }
        out: List[Any] = []
        for b in blocks:
            out.extend(b)
        return out


def normalize_batch_output(out: Any) -> Block:
    """Coerce a map_batches UDF return into a block."""
    if isinstance(out, dict):
        return {k: np.asarray(v) for k, v in out.items()}
    if isinstance(out, list):
        return out
    if isinstance(out, np.ndarray):
        return {"item": out}
    raise TypeError(
        f"map_batches UDF must return dict[str, array] | list | ndarray, "
        f"got {type(out)}"
    )


class BlockMeta:
    """Lightweight sidecar describing a block ObjectRef (the executor
    schedules on metadata without fetching block payloads — the
    reference's BlockMetadata plays the same role)."""

    __slots__ = ("num_rows", "size_bytes")

    def __init__(self, num_rows: int, size_bytes: int):
        self.num_rows = num_rows
        self.size_bytes = size_bytes

    @staticmethod
    def of(block: Block) -> "BlockMeta":
        acc = BlockAccessor(block)
        return BlockMeta(acc.num_rows(), acc.size_bytes())

    def __repr__(self):
        return f"BlockMeta(rows={self.num_rows}, bytes={self.size_bytes})"


def build_batches(
    blocks: Iterator[Block],
    batch_size: Optional[int],
    drop_last: bool = False,
) -> Iterator[Dict[str, np.ndarray]]:
    """Re-chunk a stream of blocks into exact-size column batches.

    Zero-copy when block boundaries already align with batch_size (the
    common case when the pipeline was built with matching block sizes).
    """
    if batch_size is None:
        for b in blocks:
            yield BlockAccessor(b).to_batch()
        return
    pending: List[Block] = []
    pending_rows = 0
    for b in blocks:
        acc = BlockAccessor(b)
        n = acc.num_rows()
        if n == 0:
            continue
        # fast path: no carry-over and the block is an exact multiple
        if not pending and n == batch_size:
            yield acc.to_batch()
            continue
        pending.append(b)
        pending_rows += n
        while pending_rows >= batch_size:
            merged = BlockAccessor.concat(pending)
            macc = BlockAccessor(merged)
            total = macc.num_rows()
            offset = 0
            while total - offset >= batch_size:
                yield BlockAccessor(
                    macc.slice(offset, offset + batch_size)
                ).to_batch()
                offset += batch_size
            rest = macc.slice(offset, total)
            pending = [rest] if BlockAccessor(rest).num_rows() else []
            pending_rows = total - offset
    if pending and not drop_last:
        merged = BlockAccessor.concat(pending)
        if BlockAccessor(merged).num_rows():
            yield BlockAccessor(merged).to_batch()
