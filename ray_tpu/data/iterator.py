"""DataIterator — batch iteration with block prefetch.

Parity: the reference DataIterator (python/ray/data/iterator.py) feeding
Train workers. The prefetch thread keeps `prefetch_batches` of block
payloads fetched ahead of the consumer, so a training step overlaps with
the next batch's host-side fetch — on TPU this is the host half of
device double-buffering (pair with `jax.device_put` on the consumer
side)."""

from __future__ import annotations

import queue
import threading
from typing import TYPE_CHECKING, Dict, Iterator, Optional

import numpy as np

from ray_tpu.data.block import Block, build_batches

if TYPE_CHECKING:
    from ray_tpu.data.dataset import Dataset


class DataIterator:
    def __init__(self, dataset: "Dataset"):
        self._dataset = dataset

    def _prefetched_blocks(self, prefetch: int) -> Iterator[Block]:
        """Fetch block payloads ahead of the consumer in a thread. An
        abandoned iterator (train loop breaking early) stops the fill
        thread and shuts the streaming executor down instead of leaking
        both for the rest of the dataset."""
        from ray_tpu.core.api import get

        q: "queue.Queue" = queue.Queue(maxsize=max(1, prefetch))
        done = object()
        error: list = []
        stop = threading.Event()
        bundles = self._dataset._stream_bundles()

        def _put(item) -> bool:
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.25)
                    return True
                except queue.Full:
                    continue
            return False

        def fill():
            try:
                for ref, _ in bundles:
                    if stop.is_set() or not _put(get(ref)):
                        return
            except BaseException as e:  # noqa: BLE001
                error.append(e)
            finally:
                # closing the generator shuts the executor down
                bundles.close()
                _put(done)

        t = threading.Thread(target=fill, name="data-prefetch", daemon=True)
        t.start()
        try:
            while True:
                item = q.get()
                if item is done:
                    if error:
                        raise error[0]
                    return
                yield item
        finally:
            stop.set()

    def iter_batches(
        self,
        *,
        batch_size: Optional[int] = 256,
        prefetch_batches: int = 2,
        drop_last: bool = False,
    ) -> Iterator[Dict[str, np.ndarray]]:
        blocks = (
            self._prefetched_blocks(prefetch_batches)
            if prefetch_batches > 0
            else self._dataset.iter_blocks()
        )
        return build_batches(blocks, batch_size, drop_last=drop_last)

    def iter_epochs(
        self,
        epochs: int,
        **kwargs,
    ) -> Iterator[Iterator[Dict[str, np.ndarray]]]:
        for _ in range(epochs):
            yield self.iter_batches(**kwargs)
