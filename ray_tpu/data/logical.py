"""Logical plan for Datasets.

Parity: the reference's lazy logical plan + optimizer
(python/ray/data/_internal/logical/, optimizer rules optimizers.py:55-92).
A Dataset holds an immutable chain of logical operators; execution plans
it into streaming segments (executor.py). The one optimizer rule that
matters for performance — fusing adjacent one-to-one ops into a single
task per block, the reference's OperatorFusionRule — is implemented here
as `fuse_stages`.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

from ray_tpu.data.block import Block, BlockAccessor, normalize_batch_output


class LogicalOp:
    """Base logical operator. one_to_one ops transform one input block to
    one output block and can be fused; boundary ops (repartition, shuffle,
    sort) need all upstream blocks."""

    name = "op"
    one_to_one = True


class Read(LogicalOp):
    """Source: a list of read tasks, each a zero-arg callable returning a
    Block (runs remotely)."""

    name = "Read"

    def __init__(self, read_fns: List[Callable[[], Block]], source_name: str):
        self.read_fns = read_fns
        self.name = f"Read[{source_name}]"


class FromBlocks(LogicalOp):
    """Source: literal blocks already in driver memory (or refs)."""

    name = "FromBlocks"

    def __init__(self, blocks: List[Block]):
        self.blocks = blocks


class FromBundles(LogicalOp):
    """Source: already-materialized (block_ref, BlockMeta) bundles — the
    backing of a MaterializedDataset / split shard."""

    name = "FromBundles"

    def __init__(self, bundles: List[Any]):
        self.bundles = bundles


class MapBatches(LogicalOp):
    name = "MapBatches"

    def __init__(
        self,
        fn: Any,  # callable or callable class
        batch_size: Optional[int] = None,
        fn_constructor_args: Tuple = (),
        concurrency: Optional[int] = None,
        zero_copy_batch: bool = True,
    ):
        self.fn = fn
        self.batch_size = batch_size
        self.fn_constructor_args = fn_constructor_args
        self.concurrency = concurrency
        self.is_actor_fn = isinstance(fn, type)
        self.name = f"MapBatches({getattr(fn, '__name__', type(fn).__name__)})"

    def make_block_fn(self) -> Callable[[Block], Block]:
        """Plain-function path: the per-block transform (batch_size=None
        maps the whole block as one batch — the executor re-chunks blocks
        when an explicit batch_size is given)."""
        fn = self.fn

        def apply(block: Block) -> Block:
            batch = BlockAccessor.for_block(block).to_batch()
            return normalize_batch_output(fn(batch))

        return apply


class MapRows(LogicalOp):
    name = "Map"

    def __init__(self, fn: Callable[[Any], Any]):
        self.fn = fn
        self.name = f"Map({getattr(fn, '__name__', 'fn')})"

    def make_block_fn(self) -> Callable[[Block], Block]:
        fn = self.fn

        def apply(block: Block) -> Block:
            rows = [fn(r) for r in BlockAccessor.for_block(block).iter_rows()]
            if rows and isinstance(rows[0], dict):
                import numpy as np

                return {k: np.asarray([r[k] for r in rows]) for k in rows[0]}
            return rows

        return apply


class FlatMap(LogicalOp):
    name = "FlatMap"

    def __init__(self, fn: Callable[[Any], List[Any]]):
        self.fn = fn

    def make_block_fn(self) -> Callable[[Block], Block]:
        fn = self.fn

        def apply(block: Block) -> Block:
            rows: List[Any] = []
            for r in BlockAccessor.for_block(block).iter_rows():
                rows.extend(fn(r))
            return rows

        return apply


class Filter(LogicalOp):
    name = "Filter"

    def __init__(self, fn: Callable[[Any], bool]):
        self.fn = fn
        self.name = f"Filter({getattr(fn, '__name__', 'fn')})"

    def make_block_fn(self) -> Callable[[Block], Block]:
        fn = self.fn

        def apply(block: Block) -> Block:
            acc = BlockAccessor.for_block(block)
            if acc.is_columnar:
                import numpy as np

                keep = [
                    i for i, r in enumerate(acc.iter_rows()) if fn(r)
                ]
                idx = np.asarray(keep, dtype=np.int64)
                return {k: v[idx] for k, v in block.items()}
            return [r for r in block if fn(r)]

        return apply


class Limit(LogicalOp):
    """Streaming limit: executor stops scheduling upstream work once n
    rows have been emitted."""

    name = "Limit"
    one_to_one = True  # truncation handled specially by the executor

    def __init__(self, n: int):
        self.n = n
        self.name = f"Limit[{n}]"


class Repartition(LogicalOp):
    name = "Repartition"
    one_to_one = False

    def __init__(self, num_blocks: int):
        self.num_blocks = num_blocks
        self.name = f"Repartition[{num_blocks}]"


class RandomShuffle(LogicalOp):
    name = "RandomShuffle"
    one_to_one = False

    def __init__(self, seed: Optional[int] = None):
        # Pin the seed at plan-build time: the plan is serialized to every
        # train worker, and shard()'s disjoint-coverage guarantee requires
        # all ranks to observe the SAME shuffled block order.
        if seed is None:
            import random

            seed = random.randrange(2**31)
        self.seed = seed


class Union(LogicalOp):
    name = "Union"
    one_to_one = False

    def __init__(self, others: List["LogicalPlan"]):
        self.others = others


class Sort(LogicalOp):
    """Global sort: sample → range-partition → per-partition sort
    (parity: reference sort via all-to-all operator,
    python/ray/data/_internal/logical/operations/all_to_all_operator.py)."""

    name = "Sort"
    one_to_one = False

    def __init__(self, key: Any, descending: bool = False):
        self.key = key
        self.descending = descending


class GroupByAggregate(LogicalOp):
    """Hash-partition by key → per-partition grouped aggregation
    (parity: reference hash_shuffle.py groupby/aggregate)."""

    name = "GroupByAggregate"
    one_to_one = False

    def __init__(self, key: Any, aggs: List[Any]):
        self.key = key
        self.aggs = aggs


class MapGroups(LogicalOp):
    """Hash-partition by key → per-partition apply fn(group_rows)."""

    name = "MapGroups"
    one_to_one = False

    def __init__(self, key: Any, fn: Any):
        self.key = key
        self.fn = fn


class Join(LogicalOp):
    """Hash join with another plan (parity: reference
    python/ray/data/_internal/logical/operations/join.py)."""

    name = "Join"
    one_to_one = False

    def __init__(self, other: "LogicalPlan", on: Any, how: str = "inner"):
        if how not in ("inner", "left", "right", "outer"):
            raise ValueError(f"unsupported join how={how!r}")
        self.other = other
        self.on = on
        self.how = how


class LogicalPlan:
    """Immutable op chain; `with_op` returns an extended copy."""

    def __init__(self, ops: List[LogicalOp]):
        self.ops = ops

    def with_op(self, op: LogicalOp) -> "LogicalPlan":
        return LogicalPlan(self.ops + [op])

    def describe(self) -> str:
        return " -> ".join(op.name for op in self.ops)


def split_segments(plan: LogicalPlan) -> List[List[LogicalOp]]:
    """Split the chain at all-to-all boundaries. Each segment streams;
    boundaries materialize (the reference's streaming executor does the
    same around AllToAll operators)."""
    segments: List[List[LogicalOp]] = [[]]
    for op in plan.ops:
        if op.one_to_one:
            segments[-1].append(op)
        else:
            segments.append([op])
            segments.append([])
    return [s for s in segments if s]


def fuse_stages(
    ops: List[LogicalOp],
) -> List[Tuple[str, Callable[[Block], Block], Dict[str, Any]]]:
    """Fuse adjacent plain-function one-to-one ops into single per-block
    transforms. Actor-based MapBatches and Limit break the fusion chain
    (they need their own physical operator). Returns a list of
    (name, block_fn|None, info) physical stage descriptors."""
    stages: List[Tuple[str, Any, Dict[str, Any]]] = []
    pending: List[LogicalOp] = []

    def flush():
        if not pending:
            return
        fns = [op.make_block_fn() for op in pending]
        name = "+".join(op.name for op in pending)

        def fused(block: Block, _fns=tuple(fns)) -> Block:
            for f in _fns:
                block = f(block)
            return block

        stages.append((name, fused, {}))
        pending.clear()

    for op in ops:
        if isinstance(op, (Read, FromBlocks, FromBundles)):
            flush()
            stages.append((op.name, None, {"source": op}))
        elif isinstance(op, Limit):
            flush()
            stages.append((op.name, None, {"limit": op.n}))
        elif isinstance(op, MapBatches) and (op.is_actor_fn or op.batch_size):
            flush()
            stages.append((op.name, None, {"map_batches": op}))
        else:
            pending.append(op)
    flush()
    return stages
