"""Dataset — the public ray_tpu.data API.

Parity: the reference Dataset (python/ray/data/dataset.py:185): lazy
logical plan, streaming execution on iteration/consumption, blocks in the
shared-memory object store. TPU-first: columnar numpy blocks feed
`iter_batches` exactly-sized host batches ready for `jax.device_put`
double-buffering (iterator.py), and `shard()` gives each Train worker a
deterministic 1/n of the stream.
"""

from __future__ import annotations

import builtins
import itertools
from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from ray_tpu.data import datasource, executor, logical
from ray_tpu.data.block import Block, BlockAccessor, BlockMeta, build_batches
from ray_tpu.data.iterator import DataIterator


class Dataset:
    def __init__(
        self,
        plan: logical.LogicalPlan,
        parallelism_hint: int = 4,
        shard_spec: Optional[Tuple[int, int]] = None,
    ):
        self._plan = plan
        self._parallelism = parallelism_hint
        self._shard_spec = shard_spec  # (num_shards, index) block filter

    # -- transforms (lazy) ------------------------------------------------

    def _with(self, op: logical.LogicalOp) -> "Dataset":
        return Dataset(self._plan.with_op(op), self._parallelism, self._shard_spec)

    def map_batches(
        self,
        fn: Any,
        *,
        batch_size: Optional[int] = None,
        fn_constructor_args: Tuple = (),
        concurrency: Optional[int] = None,
    ) -> "Dataset":
        return self._with(
            logical.MapBatches(
                fn,
                batch_size=batch_size,
                fn_constructor_args=fn_constructor_args,
                concurrency=concurrency,
            )
        )

    def map(self, fn: Callable[[Any], Any]) -> "Dataset":
        return self._with(logical.MapRows(fn))

    def flat_map(self, fn: Callable[[Any], List[Any]]) -> "Dataset":
        return self._with(logical.FlatMap(fn))

    def filter(self, fn: Callable[[Any], bool]) -> "Dataset":
        return self._with(logical.Filter(fn))

    def limit(self, n: int) -> "Dataset":
        return self._with(logical.Limit(n))

    def repartition(self, num_blocks: int) -> "Dataset":
        return self._with(logical.Repartition(num_blocks))

    def random_shuffle(self, *, seed: Optional[int] = None) -> "Dataset":
        return self._with(logical.RandomShuffle(seed))

    def union(self, *others: "Dataset") -> "Dataset":
        return self._with(logical.Union([o._plan for o in others]))

    def sort(self, key: Any = None, *, descending: bool = False) -> "Dataset":
        """Global sort via sample → range-partition → per-partition sort
        (a true all-to-all; parity: reference Dataset.sort)."""
        return self._with(logical.Sort(key, descending))

    def groupby(self, key: Any) -> "GroupedData":
        """Hash-partitioned grouping (parity: reference Dataset.groupby)."""
        return GroupedData(self, key)

    def join(self, other: "Dataset", on: Any, how: str = "inner") -> "Dataset":
        """Distributed hash join (parity: reference joins,
        python/ray/data/_internal/logical/operations/join.py)."""
        return self._with(logical.Join(other._plan, on, how))

    def shard(self, num_shards: int, index: int) -> "Dataset":
        """Deterministic 1/num_shards of the block stream (round-robin by
        block position) — the per-Train-worker split."""
        if not 0 <= index < num_shards:
            raise ValueError(f"shard index {index} not in [0, {num_shards})")
        return Dataset(self._plan, self._parallelism, (num_shards, index))

    # -- execution --------------------------------------------------------

    def _stream_bundles(self) -> Iterator[executor.RefBundle]:
        it = executor.execute_plan_streaming(self._plan, self._parallelism)
        if self._shard_spec is None:
            yield from it
            return
        n, idx = self._shard_spec
        for pos, bundle in enumerate(it):
            if pos % n == idx:
                yield bundle

    def iter_blocks(self) -> Iterator[Block]:
        from ray_tpu.core.api import get

        for ref, _ in self._stream_bundles():
            yield get(ref)

    def iterator(self) -> DataIterator:
        return DataIterator(self)

    def iter_batches(
        self,
        *,
        batch_size: Optional[int] = 256,
        prefetch_batches: int = 2,
        drop_last: bool = False,
    ) -> Iterator[Dict[str, np.ndarray]]:
        return self.iterator().iter_batches(
            batch_size=batch_size,
            prefetch_batches=prefetch_batches,
            drop_last=drop_last,
        )

    def iter_rows(self) -> Iterator[Any]:
        for block in self.iter_blocks():
            yield from BlockAccessor.for_block(block).iter_rows()

    def take(self, n: int = 20) -> List[Any]:
        return list(itertools.islice(self.limit(n).iter_rows(), n))

    def take_all(self) -> List[Any]:
        return list(self.iter_rows())

    def count(self) -> int:
        return sum(m.num_rows for _, m in self._stream_bundles())

    def materialize(self) -> "Dataset":
        """Execute now; the result is backed by block refs in the object
        store (reference: Dataset.materialize -> MaterializedDataset)."""
        bundles = list(self._stream_bundles())
        plan = logical.LogicalPlan([logical.FromBundles(bundles)])
        return Dataset(plan, self._parallelism)

    def split(self, n: int) -> List["Dataset"]:
        """Materialize and split into n datasets with equal block counts
        (reference: Dataset.split for per-worker consumption)."""
        bundles = list(self._stream_bundles())
        shards: List[List[executor.RefBundle]] = [[] for _ in builtins.range(n)]
        for pos, bundle in enumerate(bundles):
            shards[pos % n].append(bundle)
        return [
            Dataset(
                logical.LogicalPlan([logical.FromBundles(s)]), self._parallelism
            )
            for s in shards
        ]

    def streaming_split(self, n: int) -> List[DataIterator]:
        """n iterators fed CONCURRENTLY from ONE streaming execution,
        each receiving a disjoint round-robin subset of blocks
        (reference: Dataset.streaming_split over the output splitter,
        data/_internal/execution/operators/output_splitter.py — the
        per-Train-worker consumption pattern). Every split must be
        consumed; an abandoned split eventually backpressures the pump
        (bounded queues)."""
        import queue as queue_mod
        import threading

        queues = [queue_mod.Queue(maxsize=4) for _ in builtins.range(n)]
        DONE = object()

        def pump():
            try:
                for pos, bundle in enumerate(self._stream_bundles()):
                    queues[pos % n].put(bundle)
            except BaseException as e:  # noqa: BLE001 — fan the error out
                for q in queues:
                    q.put(e)
            finally:
                for q in queues:
                    q.put(DONE)

        threading.Thread(
            target=pump, name="streaming-split-pump", daemon=True
        ).start()

        class _Split:
            def __init__(self, q):
                self._q = q

            def _stream_bundles(self):
                while True:
                    item = self._q.get()
                    if item is DONE:
                        return
                    if isinstance(item, BaseException):
                        raise item
                    yield item

        return [DataIterator(_Split(q)) for q in queues]

    # -- write path (reference: Dataset.write_* over datasinks,
    # data/_internal/datasource/*_datasink.py — one output file per
    # block, written by distributed tasks; `path` must be visible to
    # every node, e.g. shared storage, exactly like the reference) -----

    def write_json(self, path: str) -> List[str]:
        """One ndjson file per block (reference write_json)."""
        return self._write(path, "json")

    def write_csv(self, path: str) -> List[str]:
        return self._write(path, "csv")

    def write_numpy(self, path: str) -> List[str]:
        """One .npz per block holding the columnar batch."""
        return self._write(path, "npy")

    def write_parquet(self, path: str) -> List[str]:
        return self._write(path, "parquet")

    def _write(self, path: str, fmt: str) -> List[str]:
        import os

        from ray_tpu.core.api import get, remote

        if fmt == "parquet":
            try:
                import pyarrow  # noqa: F401
            except ImportError:
                raise ImportError(
                    "write_parquet requires pyarrow, which is not "
                    "available in this image; use write_json/write_csv/"
                    "write_numpy"
                ) from None
        os.makedirs(path, exist_ok=True)
        writer = remote(_write_block)
        refs = []
        for pos, (ref, _meta) in enumerate(self._stream_bundles()):
            out = os.path.join(path, f"part-{pos:05d}.{_EXT[fmt]}")
            refs.append(writer.remote(ref, out, fmt))
        return get(refs)

    def num_blocks(self) -> int:
        return sum(1 for _ in self._stream_bundles())

    def schema(self) -> Optional[Dict[str, str]]:
        for block in self.iter_blocks():
            acc = BlockAccessor.for_block(block)
            if acc.is_columnar:
                return {k: str(v.dtype) for k, v in block.items()}
            for row in acc.iter_rows():
                if isinstance(row, dict):
                    return {k: type(v).__name__ for k, v in row.items()}
                return {"item": type(row).__name__}
        return None

    def __repr__(self):
        return f"Dataset({self._plan.describe()})"


# ---------------------------------------------------------------------------
# constructors (parity: python/ray/data/read_api.py)
# ---------------------------------------------------------------------------


_EXT = {"json": "jsonl", "csv": "csv", "npy": "npz", "parquet": "parquet"}


def _write_block(block, out_path: str, fmt: str) -> str:
    """Executor-side: persist one block as one file (the distributed
    write task the reference's datasinks run per block)."""
    acc = BlockAccessor.for_block(block)
    if fmt == "json":
        import json as json_mod

        with open(out_path, "w") as f:
            for row in acc.iter_rows():
                f.write(json_mod.dumps(row, default=_jsonable) + "\n")
    elif fmt == "csv":
        import csv as csv_mod

        rows = list(acc.iter_rows())
        with open(out_path, "w", newline="") as f:
            if rows and isinstance(rows[0], dict):
                w = csv_mod.DictWriter(f, fieldnames=list(rows[0]))
                w.writeheader()
                w.writerows(rows)
            else:
                w = csv_mod.writer(f)
                w.writerows([r] if not isinstance(r, (list, tuple)) else r
                            for r in rows)
    elif fmt == "npy":
        batch = acc.to_batch()
        np.savez(out_path, **{str(k): v for k, v in batch.items()})
    elif fmt == "parquet":
        import pyarrow as pa
        import pyarrow.parquet as pq

        batch = acc.to_batch()
        pq.write_table(pa.table(dict(batch)), out_path)
    else:
        raise ValueError(f"unknown write format {fmt!r}")
    return out_path


def _jsonable(v):
    if isinstance(v, np.generic):
        return v.item()
    if isinstance(v, np.ndarray):
        return v.tolist()
    raise TypeError(f"not JSON serializable: {type(v)}")


def range(n: int, *, parallelism: int = 4) -> Dataset:  # noqa: A001
    return Dataset(
        logical.LogicalPlan(
            [logical.Read(datasource.range_tasks(n, parallelism), f"range({n})")]
        ),
        parallelism,
    )


def from_items(items: Sequence[Any], *, parallelism: int = 4) -> Dataset:
    return Dataset(
        logical.LogicalPlan(
            [logical.FromBlocks(datasource.from_items_blocks(items, parallelism))]
        ),
        parallelism,
    )


def from_numpy(arrays, *, column: str = "data") -> Dataset:
    return Dataset(
        logical.LogicalPlan(
            [logical.FromBlocks(datasource.from_numpy_blocks(arrays, column))]
        )
    )


def read_text(paths, *, parallelism: int = 4) -> Dataset:
    return Dataset(
        logical.LogicalPlan(
            [logical.Read(datasource.read_text_tasks(paths), "text")]
        ),
        parallelism,
    )


def read_json(paths, *, parallelism: int = 4) -> Dataset:
    return Dataset(
        logical.LogicalPlan(
            [logical.Read(datasource.read_json_tasks(paths), "json")]
        ),
        parallelism,
    )


def read_csv(paths, *, parallelism: int = 4) -> Dataset:
    return Dataset(
        logical.LogicalPlan(
            [logical.Read(datasource.read_csv_tasks(paths), "csv")]
        ),
        parallelism,
    )


def read_numpy(paths, *, parallelism: int = 4) -> Dataset:
    return Dataset(
        logical.LogicalPlan(
            [logical.Read(datasource.read_numpy_tasks(paths), "numpy")]
        ),
        parallelism,
    )


def read_parquet(paths, *, columns=None, parallelism: int = 4) -> Dataset:
    return Dataset(
        logical.LogicalPlan(
            [logical.Read(datasource.read_parquet_tasks(paths, columns), "parquet")]
        ),
        parallelism,
    )


class AggregateFn:
    """One aggregation over a group's rows (parity: reference
    ray.data.aggregate.AggregateFn)."""

    def __init__(self, name: str, compute: Callable[[List[Any]], Any]):
        self.name = name
        self.compute = compute

    @staticmethod
    def count(name: str = "count") -> "AggregateFn":
        return AggregateFn(name, lambda rows: len(rows))

    @staticmethod
    def of_column(kind: str, col: Any, name: Optional[str] = None) -> "AggregateFn":
        get = col if callable(col) else (lambda r, c=col: r[c])
        reducers = {
            "sum": lambda vals: sum(vals),
            "min": lambda vals: min(vals),
            "max": lambda vals: max(vals),
            "mean": lambda vals: sum(vals) / len(vals),
        }
        red = reducers[kind]
        label = name or (f"{kind}({col})" if isinstance(col, str) else kind)
        return AggregateFn(label, lambda rows: red([get(r) for r in rows]))


class GroupedData:
    """`ds.groupby(key)` result: aggregations run as a distributed hash
    shuffle (map: hash-partition by key; reduce: per-partition grouped
    aggregation). Parity: reference GroupedData
    (python/ray/data/grouped_data.py over hash_shuffle.py)."""

    def __init__(self, ds: Dataset, key: Any):
        self._ds = ds
        self._key = key

    def aggregate(self, *aggs) -> Dataset:
        """Accepts AggregateFn objects or ``(column, kind)`` tuple
        shorthand (kind ∈ sum/min/max/mean, named ``{column}_{kind}``)."""
        normalized: List[AggregateFn] = []
        for a in aggs:
            if isinstance(a, AggregateFn):
                normalized.append(a)
            elif (
                isinstance(a, tuple) and len(a) == 2
                and a[1] in ("sum", "min", "max", "mean")
            ):
                normalized.append(
                    AggregateFn.of_column(a[1], a[0], name=f"{a[0]}_{a[1]}")
                )
            else:
                raise TypeError(
                    f"aggregate spec {a!r} is not an AggregateFn or a "
                    "(column, 'sum'|'min'|'max'|'mean') tuple"
                )
        return self._ds._with(
            logical.GroupByAggregate(self._key, normalized)
        )

    def count(self) -> Dataset:
        return self.aggregate(AggregateFn.count())

    def sum(self, col: Any) -> Dataset:
        return self.aggregate(AggregateFn.of_column("sum", col))

    def min(self, col: Any) -> Dataset:
        return self.aggregate(AggregateFn.of_column("min", col))

    def max(self, col: Any) -> Dataset:
        return self.aggregate(AggregateFn.of_column("max", col))

    def mean(self, col: Any) -> Dataset:
        return self.aggregate(AggregateFn.of_column("mean", col))

    def map_groups(self, fn: Callable[[List[Any]], Any]) -> Dataset:
        return self._ds._with(logical.MapGroups(self._key, fn))
