"""Datasources — read task generators.

Parity: reference datasources (python/ray/data/datasource/,
read_api.py). Each `read_*` returns a list of zero-arg callables; each
runs remotely and returns one Block (the reference's ReadTask plays the
same role). Parquet is gated on pyarrow availability (not part of this
image's baked-in set) the way the reference gates optional datasources.
"""

from __future__ import annotations

import glob as _glob
import json
import os
from typing import Any, Callable, List, Optional, Sequence

import numpy as np

from ray_tpu.data.block import Block


def _split_range(n: int, k: int) -> List[tuple]:
    k = max(1, min(k, n)) if n else 1
    bounds = [(i * n) // k for i in range(k + 1)]
    return [(bounds[i], bounds[i + 1]) for i in range(k)]


def range_tasks(n: int, parallelism: int) -> List[Callable[[], Block]]:
    def make(lo: int, hi: int):
        def read() -> Block:
            return {"id": np.arange(lo, hi, dtype=np.int64)}

        return read

    return [make(lo, hi) for lo, hi in _split_range(n, parallelism)]


def from_items_blocks(items: Sequence[Any], parallelism: int) -> List[Block]:
    items = list(items)
    return [
        items[lo:hi] for lo, hi in _split_range(len(items), parallelism)
    ]


def from_numpy_blocks(
    arrays, column: str = "data"
) -> List[Block]:
    if isinstance(arrays, np.ndarray):
        arrays = [arrays]
    return [{column: np.asarray(a)} for a in arrays]


def _expand_paths(paths) -> List[str]:
    if isinstance(paths, str):
        paths = [paths]
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            out.extend(
                sorted(
                    os.path.join(p, f)
                    for f in os.listdir(p)
                    if os.path.isfile(os.path.join(p, f))
                )
            )
        elif any(c in p for c in "*?["):
            out.extend(sorted(_glob.glob(p)))
        else:
            out.append(p)
    if not out:
        raise FileNotFoundError(f"no files matched {paths!r}")
    return out


def read_text_tasks(paths) -> List[Callable[[], Block]]:
    def make(path: str):
        def read() -> Block:
            with open(path, "r", encoding="utf-8", errors="replace") as f:
                lines = [ln.rstrip("\n") for ln in f]
            return [{"text": ln} for ln in lines]

        return read

    return [make(p) for p in _expand_paths(paths)]


def read_json_tasks(paths) -> List[Callable[[], Block]]:
    """JSONL files: one object per line."""

    def make(path: str):
        def read() -> Block:
            rows = []
            with open(path, "r", encoding="utf-8") as f:
                for ln in f:
                    ln = ln.strip()
                    if ln:
                        rows.append(json.loads(ln))
            return rows

        return read

    return [make(p) for p in _expand_paths(paths)]


def read_csv_tasks(paths) -> List[Callable[[], Block]]:
    def make(path: str):
        def read() -> Block:
            import csv

            with open(path, "r", encoding="utf-8", newline="") as f:
                reader = csv.DictReader(f)
                rows = list(reader)
            if not rows:
                return []
            cols: dict = {}
            for k in rows[0]:
                vals = [r[k] for r in rows]
                try:
                    cols[k] = np.asarray([float(v) for v in vals])
                except (TypeError, ValueError):
                    cols[k] = np.asarray(vals)
            return cols

        return read

    return [make(p) for p in _expand_paths(paths)]


def read_numpy_tasks(paths) -> List[Callable[[], Block]]:
    def make(path: str):
        def read() -> Block:
            return {"data": np.load(path, allow_pickle=False)}

        return read

    return [make(p) for p in _expand_paths(paths)]


def read_parquet_tasks(
    paths, columns: Optional[List[str]] = None
) -> List[Callable[[], Block]]:
    try:
        import pyarrow.parquet  # noqa: F401
    except ImportError as e:
        raise ImportError(
            "read_parquet requires pyarrow, which is not available in this "
            "environment"
        ) from e

    def make(path: str):
        def read() -> Block:
            import pyarrow.parquet as pq

            table = pq.read_table(path, columns=columns)
            return {
                name: col.to_numpy(zero_copy_only=False)
                for name, col in zip(table.column_names, table.columns)
            }

        return read

    return [make(p) for p in _expand_paths(paths)]
