"""Streaming executor for Dataset pipelines.

Parity: the reference StreamingExecutor
(python/ray/data/_internal/execution/streaming_executor.py:70 — thread
loop :336, scheduling step :448) and its operator-selection policy
(streaming_executor_state.py:639 select_operator_to_run). Blocks flow
between physical operators as ObjectRefs (payloads stay in the shm store);
the driver-side loop schedules on BlockMeta only. Backpressure: each
operator has a bounded submit window, and the consumer-facing output
queue is bounded — a slow consumer stalls the whole pipeline instead of
buffering it in memory (the reference's resource_manager/backpressure
policies, reduced to the two knobs that matter at this scale).

All-to-all boundaries (repartition / random_shuffle) materialize the
segment and run as driver-coordinated task fan-outs, mirroring the
reference's AllToAll operators.
"""

from __future__ import annotations

import logging
import queue
import threading
from collections import deque
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import numpy as np

from ray_tpu.core.api import get, put, remote, wait
from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.data import logical
from ray_tpu.data.block import Block, BlockAccessor, BlockMeta, normalize_batch_output
from ray_tpu.utils import serialization

logger = logging.getLogger(__name__)

# (block_ref, meta) — the currency of the pipeline.
RefBundle = Tuple[ObjectRef, BlockMeta]


# ---------------------------------------------------------------------------
# remote transforms (registered once; UDFs travel as ObjectRef args)
# ---------------------------------------------------------------------------


@remote
def _exec_read(read_fn):
    block = read_fn()
    return block, BlockMeta.of(block)


@remote
def _apply_block_fn(fn, block):
    out = fn(block)
    return out, BlockMeta.of(out)


@remote
def _slice_block(block, start, end):
    out = BlockAccessor.for_block(block).slice(start, end)
    return out, BlockMeta.of(out)


@remote
def _concat_slices(slices, *blocks):
    """slices: [(block_pos, start, end)] into *blocks."""
    parts = [
        BlockAccessor.for_block(blocks[pos]).slice(start, end)
        for pos, start, end in slices
    ]
    out = BlockAccessor.concat(parts)
    return out, BlockMeta.of(out)


@remote
def _shuffle_rows(block, seed):
    acc = BlockAccessor.for_block(block)
    n = acc.num_rows()
    rng = np.random.default_rng(seed)
    perm = rng.permutation(n)
    if acc.is_columnar:
        out: Block = {k: v[perm] for k, v in block.items()}
    else:
        out = [block[i] for i in perm]
    return out, BlockMeta.of(out)


@remote
class _MapWorker:
    """Actor-pool worker for stateful (callable-class) map_batches UDFs
    (parity: the reference's ActorPoolMapOperator)."""

    def __init__(self, fn_cls, ctor_args, batch_size):
        self._fn = fn_cls(*ctor_args)
        self._batch_size = batch_size

    def apply(self, block):
        fn = _batched_apply(self._fn, self._batch_size)
        out = fn(block)
        return out, BlockMeta.of(out)


def _batched_apply(fn: Callable, batch_size: Optional[int]) -> Callable[[Block], Block]:
    """Apply a batch UDF to a block, re-chunking to batch_size inside the
    task when requested (keeps the pipeline 1 block in -> 1 block out)."""

    def apply(block: Block) -> Block:
        acc = BlockAccessor.for_block(block)
        batch = acc.to_batch()
        n = acc.num_rows()
        if not batch_size or n <= batch_size:
            return normalize_batch_output(fn(batch))
        outs = []
        for start in range(0, n, batch_size):
            sub = {k: v[start : start + batch_size] for k, v in batch.items()}
            outs.append(normalize_batch_output(fn(sub)))
        return BlockAccessor.concat(outs)

    return apply


# ---------------------------------------------------------------------------
# physical operators
# ---------------------------------------------------------------------------


class PhysicalOp:
    def __init__(self, name: str, max_inflight: int):
        self.name = name
        self.max_inflight = max_inflight
        self.inputs: deque = deque()  # RefBundle
        self.outputs: deque = deque()  # RefBundle
        # FIFO of (meta_ref, block_ref): outputs are emitted in SUBMISSION
        # order, not completion order, so the block stream is deterministic
        # — shard()'s disjoint-coverage guarantee depends on every rank
        # observing the same order (reference: preserve_order semantics).
        self.inflight: deque = deque()
        self.upstream_done = False
        self.stopped = False  # limit reached / executor shutdown

    def start(self) -> None:
        pass

    def close(self) -> None:
        pass

    def can_submit(self) -> bool:
        return (
            not self.stopped
            and bool(self.inputs)
            and len(self.inflight) < self.max_inflight
        )

    def submit_one(self) -> None:
        raise NotImplementedError

    def poll(self) -> None:
        """Move finished tasks (in submission order) to outputs."""
        while self.inflight:
            meta_ref, block_ref = self.inflight[0]
            ready, _ = wait(
                [meta_ref], num_returns=1, timeout=0, fetch_local=False
            )
            if not ready:
                return  # head still running: later completions wait (FIFO)
            self.inflight.popleft()
            meta = get(meta_ref)  # raises if the task failed
            self.outputs.append((block_ref, meta))

    def done(self) -> bool:
        return (
            (self.upstream_done or self.stopped)
            and not self.inputs
            and not self.inflight
        )

    def backlog(self) -> int:
        return len(self.inputs) + len(self.inflight) + len(self.outputs)


class SourceOp(PhysicalOp):
    """Read tasks or literal/pre-materialized blocks."""

    def __init__(self, source: logical.LogicalOp, max_inflight: int):
        super().__init__(getattr(source, "name", "Source"), max_inflight)
        self._read_fns: List[Callable] = []
        if isinstance(source, logical.Read):
            self._read_fns = list(source.read_fns)
        elif isinstance(source, logical.FromBlocks):
            for b in source.blocks:
                self.outputs.append((put(b), BlockMeta.of(b)))
        else:
            raise TypeError(f"unsupported source {source}")
        self.upstream_done = True

    def can_submit(self) -> bool:
        return (
            not self.stopped
            and bool(self._read_fns)
            and len(self.inflight) < self.max_inflight
        )

    def submit_one(self) -> None:
        fn = self._read_fns.pop(0)
        block_ref, meta_ref = _exec_read.options(num_returns=2).remote(fn)
        self.inflight.append((meta_ref, block_ref))

    def done(self) -> bool:
        return (
            (not self._read_fns or self.stopped)
            and not self.inflight
        )


class FromRefsOp(PhysicalOp):
    """Source fed by already-materialized RefBundles (segment boundary)."""

    def __init__(self, bundles: List[RefBundle]):
        super().__init__("FromRefs", 1)
        self.outputs.extend(bundles)
        self.upstream_done = True

    def can_submit(self) -> bool:
        return False

    def done(self) -> bool:
        return True


class TaskMapOp(PhysicalOp):
    """One task per block applying a fused block transform."""

    def __init__(self, name: str, block_fn: Callable[[Block], Block],
                 max_inflight: int):
        super().__init__(name, max_inflight)
        self._fn_ref: Optional[ObjectRef] = None
        self._block_fn = block_fn

    def start(self) -> None:
        # Ship the (possibly large) fused closure once, not per task.
        self._fn_ref = put(self._block_fn)

    def submit_one(self) -> None:
        block_ref, _ = self.inputs.popleft()
        out_ref, meta_ref = _apply_block_fn.options(num_returns=2).remote(
            self._fn_ref, block_ref
        )
        self.inflight.append((meta_ref, out_ref))


class ActorMapOp(PhysicalOp):
    """Fixed-size actor pool for stateful UDFs."""

    def __init__(self, op: logical.MapBatches, max_inflight: int):
        pool_size = op.concurrency or 2
        super().__init__(op.name, max_inflight=pool_size * 2)
        self._op = op
        self._pool_size = pool_size
        self._actors: List[Any] = []
        self._actor_load: Dict[int, int] = {}

    def start(self) -> None:
        for _ in range(self._pool_size):
            self._actors.append(
                _MapWorker.remote(
                    self._op.fn, self._op.fn_constructor_args, self._op.batch_size
                )
            )
        self._actor_load = {i: 0 for i in range(self._pool_size)}

    def close(self) -> None:
        from ray_tpu.core.api import kill

        for a in self._actors:
            try:
                kill(a)
            except Exception:  # noqa: BLE001
                pass

    def can_submit(self) -> bool:
        return (
            not self.stopped
            and bool(self.inputs)
            and min(self._actor_load.values(), default=0) < 2
        )

    def submit_one(self) -> None:
        block_ref, _ = self.inputs.popleft()
        idx = min(self._actor_load, key=self._actor_load.get)
        out_ref, meta_ref = self._actors[idx].apply.options(num_returns=2).remote(
            block_ref
        )
        self._actor_load[idx] += 1
        self.inflight.append((meta_ref, out_ref, idx))

    def poll(self) -> None:
        while self.inflight:
            meta_ref, block_ref, idx = self.inflight[0]
            ready, _ = wait(
                [meta_ref], num_returns=1, timeout=0, fetch_local=False
            )
            if not ready:
                return
            self.inflight.popleft()
            self._actor_load[idx] -= 1
            meta = get(meta_ref)
            self.outputs.append((block_ref, meta))


class LimitOp(PhysicalOp):
    """Streaming row limit; truncates the boundary block remotely and
    stops the pipeline upstream once satisfied."""

    def __init__(self, n: int):
        super().__init__(f"Limit[{n}]", max_inflight=1)
        self.n = n
        self.emitted = 0
        self.satisfied = False

    def can_submit(self) -> bool:
        return not self.stopped and bool(self.inputs) and not self.inflight

    def submit_one(self) -> None:
        block_ref, meta = self.inputs.popleft()
        if self.satisfied:
            return
        remaining = self.n - self.emitted
        if meta.num_rows <= remaining:
            self.emitted += meta.num_rows
            if self.emitted >= self.n:
                self.satisfied = True
            self.outputs.append((block_ref, meta))
            return
        out_ref, meta_ref = _slice_block.options(num_returns=2).remote(
            block_ref, 0, remaining
        )
        self.inflight.append((meta_ref, out_ref))
        self.emitted = self.n
        self.satisfied = True


# ---------------------------------------------------------------------------
# executor
# ---------------------------------------------------------------------------


class StreamingExecutor:
    """Runs one streaming segment (a chain of 1:1 physical operators)."""

    def __init__(
        self,
        ops: List[PhysicalOp],
        out_buffer_blocks: int = 8,
    ):
        self._ops = ops
        self._out: "queue.Queue" = queue.Queue(maxsize=out_buffer_blocks)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None

    def start(self) -> None:
        for op in self._ops:
            op.start()
        self._thread = threading.Thread(
            target=self._loop, name="data-executor", daemon=True
        )
        self._thread.start()

    def shutdown(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
        for op in self._ops:
            op.close()

    def _loop(self) -> None:
        ops = self._ops
        try:
            while not self._stop.is_set():
                progressed = False
                for op in ops:
                    before = len(op.outputs)
                    op.poll()
                    progressed |= len(op.outputs) != before
                # propagate limit-satisfied stop upstream
                for i, op in enumerate(ops):
                    if isinstance(op, LimitOp) and op.satisfied:
                        for up in ops[:i]:
                            up.stopped = True
                # move outputs downstream (respecting downstream windows)
                for i in range(len(ops) - 1):
                    nxt = ops[i + 1]
                    while ops[i].outputs and nxt.backlog() < 2 * nxt.max_inflight:
                        nxt.inputs.append(ops[i].outputs.popleft())
                        progressed = True
                    nxt.upstream_done = ops[i].done() and not ops[i].outputs
                # drain final op into the consumer queue
                while ops[-1].outputs:
                    try:
                        self._out.put(ops[-1].outputs[0], timeout=0.05)
                        ops[-1].outputs.popleft()
                        progressed = True
                    except queue.Full:
                        break
                # submit work, downstream-most first (drains the pipeline,
                # bounding memory — the reference's selection policy).
                # Fill EVERY op's window each pass: one-submission-per-pass
                # capped the whole pipeline at ~200 tasks/s (round-3 debt).
                for op in reversed(ops):
                    while op.can_submit():
                        op.submit_one()
                        progressed = True
                if all(op.done() for op in ops) and not any(
                    op.outputs for op in ops
                ):
                    break
                if not progressed:
                    self._stop.wait(0.005)
        except BaseException as e:  # noqa: BLE001 — surface to consumer
            self._error = e
        finally:
            # The sentinel MUST land or the consumer blocks forever on an
            # exhausted queue; keep trying until delivered or the consumer
            # abandons us (shutdown sets _stop).
            while True:
                try:
                    self._out.put(None, timeout=0.5)
                    break
                except queue.Full:
                    if self._stop.is_set():
                        break

    def iter_output(self) -> Iterator[RefBundle]:
        self.start()
        try:
            while True:
                item = self._out.get()
                if item is None:
                    if self._error is not None:
                        raise self._error
                    return
                yield item
        finally:
            self.shutdown()


# ---------------------------------------------------------------------------
# plan driver (segments + all-to-all boundaries)
# ---------------------------------------------------------------------------


def _build_segment_ops(
    seg: List[logical.LogicalOp],
    input_bundles: Optional[List[RefBundle]],
    parallelism_hint: int,
) -> List[PhysicalOp]:
    stages = logical.fuse_stages(seg)
    ops: List[PhysicalOp] = []
    window = max(2, min(8, parallelism_hint))
    for name, block_fn, info in stages:
        if "source" in info:
            src = info["source"]
            if isinstance(src, logical.FromBundles):
                ops.append(FromRefsOp(list(src.bundles)))
            else:
                ops.append(SourceOp(src, max_inflight=window))
        elif "limit" in info:
            ops.append(LimitOp(info["limit"]))
        elif "map_batches" in info:
            op = info["map_batches"]
            if op.is_actor_fn:
                ops.append(ActorMapOp(op, max_inflight=window))
            else:
                ops.append(
                    TaskMapOp(
                        op.name,
                        _batched_apply(op.fn, op.batch_size),
                        max_inflight=window,
                    )
                )
        else:
            ops.append(TaskMapOp(name, block_fn, max_inflight=window))
    if not ops or not isinstance(ops[0], (SourceOp,)):
        ops.insert(0, FromRefsOp(input_bundles or []))
    return ops


def _apply_boundary(
    op: logical.LogicalOp, bundles: List[RefBundle]
) -> List[RefBundle]:
    if isinstance(op, logical.Repartition):
        return _repartition(bundles, op.num_blocks)
    if isinstance(op, logical.RandomShuffle):
        return _random_shuffle(bundles, op.seed)
    if isinstance(op, logical.Sort):
        return _sort_boundary(bundles, op.key, op.descending)
    if isinstance(op, logical.GroupByAggregate):
        return _groupby_boundary(bundles, op.key, op.aggs)
    if isinstance(op, logical.MapGroups):
        return _map_groups_boundary(bundles, op.key, op.fn)
    if isinstance(op, logical.Join):
        right = execute_plan_materialized(op.other)
        return _join_boundary(bundles, right, op.on, op.how)
    if isinstance(op, logical.Union):
        out = list(bundles)
        for other in op.others:
            out.extend(execute_plan_materialized(other))
        return out
    raise TypeError(f"unsupported boundary op {op}")


def _repartition(bundles: List[RefBundle], n: int) -> List[RefBundle]:
    """Exact-row repartition into n blocks via remote concat tasks."""
    total = sum(m.num_rows for _, m in bundles)
    targets = [
        (j * total) // n for j in range(n + 1)
    ]  # row offsets of output boundaries
    # row offsets of input blocks
    offsets = [0]
    for _, m in bundles:
        offsets.append(offsets[-1] + m.num_rows)
    pending: List[Tuple[ObjectRef, ObjectRef]] = []
    for j in range(n):
        lo, hi = targets[j], targets[j + 1]
        slices: List[Tuple[int, int, int]] = []
        needed_refs: List[ObjectRef] = []
        for i, (ref, m) in enumerate(bundles):
            s = max(lo, offsets[i])
            e = min(hi, offsets[i + 1])
            if s < e:
                slices.append((len(needed_refs), s - offsets[i], e - offsets[i]))
                needed_refs.append(ref)
        pending.append(
            _concat_slices.options(num_returns=2).remote(slices, *needed_refs)
        )
    # submit all first, gather metas second: the fan-out runs concurrently
    return [(ref, get(meta_ref)) for ref, meta_ref in pending]


def _random_shuffle(
    bundles: List[RefBundle], seed: Optional[int]
) -> List[RefBundle]:
    """EXACT distributed shuffle: every row is hash-assigned a random
    output partition (map tasks), each partition concatenates its pieces
    from every input block and permutes locally (reduce tasks) — a true
    all-to-all through the object store (parity: reference
    hash_shuffle.py), replacing round 3's block-order permutation."""
    if not bundles:
        return []
    P = max(1, len(bundles))
    base = seed if seed is not None else 0
    map_blob = serialization.dumps_function(
        lambda rows, shard_seed: np.random.default_rng(
            (base, shard_seed)
        ).integers(0, P, size=len(rows))
    )
    reduce_blob = serialization.dumps_function(
        lambda rows, p: [
            rows[i]
            for i in np.random.default_rng((base, 1 << 20, p)).permutation(
                len(rows)
            )
        ]
    )
    return _all_to_all(bundles, P, map_blob, reduce_blob)


@remote
def _partition_block(map_blob, P: int, shard_id: int, block):
    """Map side of the all-to-all: rows → P partition piece-blocks plus a
    trailing None filler so num_returns is static (P + 1)."""
    fn = serialization.loads(map_blob)
    rows = list(BlockAccessor.for_block(block).iter_rows())
    assign = fn(rows, shard_id)
    pieces: List[List[Any]] = [[] for _ in range(P)]
    for row, p in zip(rows, assign):
        pieces[int(p)].append(row)
    return (*pieces, None)


@remote
def _reduce_partition(reduce_blob, p: int, *pieces):
    """Reduce side: concatenate this partition's pieces from every map
    task and apply the reduce fn."""
    fn = serialization.loads(reduce_blob)
    rows: List[Any] = []
    for piece in pieces:
        if piece:
            rows.extend(piece)
    out = fn(rows, p)
    return out, BlockMeta.of(out)


def _all_to_all(
    bundles: List[RefBundle], P: int, map_blob: bytes, reduce_blob: bytes
) -> List[RefBundle]:
    """Generic hash/range shuffle: map each block into P pieces, reduce
    each partition over all blocks' pieces. Pieces travel as ObjectRefs
    through the store — the transpose never lands on the driver."""
    piece_refs: List[List[ObjectRef]] = []
    for shard_id, (ref, _) in enumerate(bundles):
        refs = _partition_block.options(num_returns=P + 1).remote(
            map_blob, P, shard_id, ref
        )
        piece_refs.append(refs[:P])
    pending = [
        _reduce_partition.options(num_returns=2).remote(
            reduce_blob, p, *[piece_refs[i][p] for i in range(len(bundles))]
        )
        for p in range(P)
    ]
    return [(ref, get(meta_ref)) for ref, meta_ref in pending]


def _key_fn_blob(key) -> bytes:
    if callable(key):
        return serialization.dumps_function(key)
    if key is None:
        return serialization.dumps_function(lambda row: row)
    return serialization.dumps_function(lambda row, k=key: row[k])


def _sort_boundary(
    bundles: List[RefBundle], key, descending: bool
) -> List[RefBundle]:
    """Sample → range partition → per-partition sort; partition order =
    global order."""
    if not bundles:
        return []
    P = max(1, len(bundles))
    key_blob = _key_fn_blob(key)
    sample_refs = [
        _sample_keys.remote(key_blob, ref, 64) for ref, _ in bundles
    ]
    samples = sorted(x for part in get(sample_refs) for x in part)
    if not samples:
        return bundles
    # P-1 quantile boundaries over the sampled keys
    bounds = [
        samples[(j * len(samples)) // P] for j in range(1, P)
    ]

    def map_fn(rows, shard_id, key_blob=key_blob, bounds=bounds,
               descending=descending):
        import bisect

        kf = serialization.loads(key_blob)
        out = []
        for row in rows:
            p = bisect.bisect_right(bounds, kf(row))
            if descending:
                p = len(bounds) - p
            out.append(p)
        return out

    def reduce_fn(rows, p, key_blob=key_blob, descending=descending):
        kf = serialization.loads(key_blob)
        return sorted(rows, key=kf, reverse=descending)

    return _all_to_all(
        bundles, P,
        serialization.dumps_function(map_fn),
        serialization.dumps_function(reduce_fn),
    )


@remote
def _sample_keys(key_blob, block, k: int):
    kf = serialization.loads(key_blob)
    rows = list(BlockAccessor.for_block(block).iter_rows())
    if not rows:
        return []
    idx = np.random.default_rng(0).choice(
        len(rows), size=min(k, len(rows)), replace=False
    )
    return [kf(rows[i]) for i in idx]


def _hash_partition_map_blob(key_blob: bytes, P: int) -> bytes:
    def map_fn(rows, shard_id, key_blob=key_blob, P=P):
        kf = serialization.loads(key_blob)
        # stable across processes (python hash() is salted): md5 the repr
        import hashlib

        out = []
        for row in rows:
            h = hashlib.md5(repr(kf(row)).encode()).digest()
            out.append(int.from_bytes(h[:4], "little") % P)
        return out

    return serialization.dumps_function(map_fn)


def _groupby_boundary(
    bundles: List[RefBundle], key, aggs: List[Any]
) -> List[RefBundle]:
    if not bundles:
        return []
    P = max(1, len(bundles))
    key_blob = _key_fn_blob(key)
    aggs_blob = serialization.dumps_function(lambda: aggs)

    def reduce_fn(rows, p, key_blob=key_blob, aggs_blob=aggs_blob, key=key):
        kf = serialization.loads(key_blob)
        agg_list = serialization.loads(aggs_blob)()
        groups: Dict[Any, List[Any]] = {}
        for row in rows:
            groups.setdefault(kf(row), []).append(row)
        out = []
        key_col = key if isinstance(key, str) else "key"
        for gkey in sorted(groups, key=repr):
            grows = groups[gkey]
            rec = {key_col: gkey}
            for agg in agg_list:
                rec[agg.name] = agg.compute(grows)
            out.append(rec)
        return out

    return _all_to_all(
        bundles, P, _hash_partition_map_blob(key_blob, P),
        serialization.dumps_function(reduce_fn),
    )


def _map_groups_boundary(
    bundles: List[RefBundle], key, fn
) -> List[RefBundle]:
    if not bundles:
        return []
    P = max(1, len(bundles))
    key_blob = _key_fn_blob(key)
    fn_blob = serialization.dumps_function(fn)

    def reduce_fn(rows, p, key_blob=key_blob, fn_blob=fn_blob):
        kf = serialization.loads(key_blob)
        gfn = serialization.loads(fn_blob)
        groups: Dict[Any, List[Any]] = {}
        for row in rows:
            groups.setdefault(kf(row), []).append(row)
        out: List[Any] = []
        for gkey in sorted(groups, key=repr):
            res = gfn(groups[gkey])
            out.extend(res if isinstance(res, list) else [res])
        return out

    return _all_to_all(
        bundles, P, _hash_partition_map_blob(key_blob, P),
        serialization.dumps_function(reduce_fn),
    )


@remote
def _join_partition(on_blob, how: str, n_left: int, *pieces):
    """Hash-join one partition: pieces[:n_left] are left pieces,
    the rest right pieces."""
    kf = serialization.loads(on_blob)
    left_rows: List[Any] = []
    right_rows: List[Any] = []
    for piece in pieces[:n_left]:
        if piece:
            left_rows.extend(piece)
    for piece in pieces[n_left:]:
        if piece:
            right_rows.extend(piece)
    right_by_key: Dict[Any, List[Any]] = {}
    for row in right_rows:
        right_by_key.setdefault(kf(row), []).append(row)
    out = []
    matched_right: set = set()
    for lrow in left_rows:
        k = kf(lrow)
        matches = right_by_key.get(k)
        if matches:
            matched_right.add(repr(k))
            for rrow in matches:
                merged = dict(lrow)
                merged.update(rrow)
                out.append(merged)
        elif how in ("left", "outer"):
            out.append(dict(lrow))
    if how in ("right", "outer"):
        for k, rows in right_by_key.items():
            if repr(k) not in matched_right:
                out.extend(dict(r) for r in rows)
    return out, BlockMeta.of(out)


def _join_boundary(
    left: List[RefBundle], right: List[RefBundle], on, how: str
) -> List[RefBundle]:
    if not left and not right:
        return []
    P = max(1, max(len(left), len(right)))
    on_blob = _key_fn_blob(on)
    map_blob = _hash_partition_map_blob(on_blob, P)
    left_pieces: List[List[ObjectRef]] = []
    right_pieces: List[List[ObjectRef]] = []
    for shard_id, (ref, _) in enumerate(left):
        refs = _partition_block.options(num_returns=P + 1).remote(
            map_blob, P, shard_id, ref
        )
        left_pieces.append(refs[:P])
    for shard_id, (ref, _) in enumerate(right):
        refs = _partition_block.options(num_returns=P + 1).remote(
            map_blob, P, shard_id, ref
        )
        right_pieces.append(refs[:P])
    pending = [
        _join_partition.options(num_returns=2).remote(
            on_blob, how, len(left),
            *[left_pieces[i][p] for i in range(len(left))],
            *[right_pieces[i][p] for i in range(len(right))],
        )
        for p in range(P)
    ]
    return [(ref, get(meta_ref)) for ref, meta_ref in pending]


def execute_plan_streaming(
    plan: logical.LogicalPlan, parallelism_hint: int = 4
) -> Iterator[RefBundle]:
    """Stream the plan's output bundles; only all-to-all boundaries (and
    the segments before them) materialize."""
    segments = logical.split_segments(plan)
    bundles: Optional[List[RefBundle]] = None
    for seg in segments[:-1]:
        if len(seg) == 1 and not seg[0].one_to_one:
            bundles = _apply_boundary(seg[0], bundles or [])
        else:
            ops = _build_segment_ops(seg, bundles, parallelism_hint)
            bundles = list(StreamingExecutor(ops).iter_output())
    last = segments[-1]
    if len(last) == 1 and not last[0].one_to_one:
        yield from _apply_boundary(last[0], bundles or [])
        return
    ops = _build_segment_ops(last, bundles, parallelism_hint)
    yield from StreamingExecutor(ops).iter_output()


def execute_plan_materialized(
    plan: logical.LogicalPlan, parallelism_hint: int = 4
) -> List[RefBundle]:
    return list(execute_plan_streaming(plan, parallelism_hint))
