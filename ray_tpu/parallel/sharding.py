"""Partition rules: map model pytree paths to NamedShardings.

GSPMD-style sharding (the "How to Scale Your Model" recipe): annotate
params and activations with PartitionSpecs over the mesh; XLA inserts the
collectives. Rules are (regex, PartitionSpec) pairs matched against
"path/like/this" param names — first match wins, like t5x/flax logical
axis rules but without a framework dependency.
"""

from __future__ import annotations

import re
from typing import Any, List, Optional, Sequence, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


class PartitionRules:
    def __init__(self, rules: Sequence[Tuple[str, P]]):
        self._rules = [(re.compile(pat), spec) for pat, spec in rules]

    def spec_for(self, path: str) -> P:
        for pat, spec in self._rules:
            if pat.search(path):
                return spec
        return P()  # replicated by default

    def tree_specs(self, tree: Any) -> Any:
        """PartitionSpec pytree matching `tree`'s structure."""
        paths_and_leaves, treedef = jax.tree_util.tree_flatten_with_path(tree)
        specs = []
        for path, leaf in paths_and_leaves:
            name = path_str(path)
            spec = self.spec_for(name)
            # drop axes the leaf doesn't have
            if leaf is not None and hasattr(leaf, "ndim") and len(spec) > leaf.ndim:
                spec = P(*spec[: leaf.ndim])
            specs.append(spec)
        return jax.tree_util.tree_unflatten(treedef, specs)


def path_str(path) -> str:
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        else:
            parts.append(str(p))
    return "/".join(parts)


def named_sharding(mesh: Mesh, *spec) -> NamedSharding:
    return NamedSharding(mesh, P(*spec))


def tree_shardings(mesh: Mesh, rules: PartitionRules, tree: Any) -> Any:
    return jax.tree.map(
        lambda s: NamedSharding(mesh, s), rules.tree_specs(tree),
        is_leaf=lambda x: isinstance(x, P),
    )


def shard_pytree(tree: Any, mesh: Mesh, rules: PartitionRules) -> Any:
    """Place a pytree onto the mesh per the rules (device_put, zero-copy
    where layouts already match)."""
    shardings = tree_shardings(mesh, rules, tree)
    return jax.device_put(tree, shardings)


def with_sharding_constraint(x: Any, mesh: Optional[Mesh], *spec) -> Any:
    """Annotate an intermediate value inside jit (no-op without a mesh or
    on a trivial all-ones mesh)."""
    if mesh is None or all(s == 1 for s in mesh.shape.values()):
        return x
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, P(*spec)))


# ---------------------------------------------------------------------------
# Standard rule sets
# ---------------------------------------------------------------------------


def gpt_rules(fsdp: bool = True) -> PartitionRules:
    """Sharding for ray_tpu.models.gpt2's stacked-layer pytree.

    TP shards attention heads + MLP hidden; FSDP shards the complementary
    (large) dimension of each matrix — Megatron-style TP composed with
    ZeRO-3, expressed purely as GSPMD specs. Leading axis of block params
    is the lax.scan layer dim (never sharded).

    Shapes: wte (V,D) · wpe (T,D) · qkv/kernel (L,D,3,H,Dh) ·
    qkv/bias (L,3,H,Dh) · proj/kernel (L,H,Dh,D) · fc_in (L,D,F) ·
    fc_out (L,F,D).
    """
    f = "fsdp" if fsdp else None
    return PartitionRules([
        (r"wte", P("tp", f)),
        (r"wpe", P(None, f)),
        (r"attn/qkv/kernel", P(None, f, None, "tp", None)),
        (r"attn/qkv/bias", P(None, None, "tp", None)),
        (r"attn/proj/kernel", P(None, "tp", None, f)),
        (r"mlp/fc_in/kernel", P(None, f, "tp")),
        (r"mlp/fc_in/bias", P(None, "tp")),
        (r"mlp/fc_out/kernel", P(None, "tp", f)),
        # everything else (layernorms, remaining biases) replicated
        (r"bias|scale", P()),
    ])


def batch_spec() -> P:
    """Batch dims shard over all data axes (dcn outer, then dp, fsdp)."""
    return P(("dcn", "dp", "fsdp"))
