"""Parallelism strategies — native mesh/sharding layer.

The reference orchestrates parallelism but delegates the math/comm to
engines it launches (SURVEY.md §2.4). Here DP/FSDP/TP/CP/EP are provided
natively: a device mesh with standard axis names, NamedSharding partition
rules for model pytrees, and XLA collectives over ICI/DCN inserted by the
compiler from those shardings.
"""

from ray_tpu.parallel.mesh import MeshConfig, build_mesh, local_mesh
from ray_tpu.parallel.sharding import (
    PartitionRules,
    named_sharding,
    shard_pytree,
    with_sharding_constraint,
)

__all__ = [
    "MeshConfig",
    "PartitionRules",
    "build_mesh",
    "local_mesh",
    "named_sharding",
    "shard_pytree",
    "with_sharding_constraint",
]
