"""Pipeline parallelism — stage-per-actor microbatching, RPC or compiled.

Two execution tiers over the same :class:`PipelineStage` actors:

**RPC tier** (:class:`Pipeline`, the original): the driver submits the
microbatch forward chain and the reverse backward chain as ordered actor
calls; per-actor FIFO queues yield the GPipe overlap, activations flow
as ObjectRefs through the shm object plane. Every microbatch hop pays
the full submit→lease→push→reply RPC path (~1 ms class).

**Compiled tier** (:class:`CompiledPipeline`, via ``Pipeline.compile``):
the stage graph is compiled ONCE — a persistent exec loop parks on each
stage actor (``__rt_pipe_exec_loop__``, like dag.py's compiled-graph
loops) and all microbatch traffic rides native seqlock ring channels
(ray_tpu.core.channels.ShmChannel): one memcpy + atomic flip per
message, no scheduler, no lease, no RPC framing. Cross-host stage
boundaries ride :class:`~ray_tpu.core.channels.RpcChannel` instead —
one worker↔worker RPC per activation, ≥32 KiB payloads as raw
out-of-band multiseg segments. This is the workload compiled graphs
exist for (parity: python/ray/dag/compiled_dag_node.py:805 driving PP
microbatch loops; "Exploring the limits of Concurrency in ML Training
on Google TPUs", arxiv 2011.03641 — remove per-step host scheduling,
overlap transfer with compute).

Schedules (compiled tier):

- ``"gpipe"``: every stage runs all n forwards, then all n backwards.
  Peak saved activations per stage: O(n_microbatches).
- ``"1f1b"``: stage i runs ``min(n, S-1-i)`` warmup forwards, then
  alternates one-forward-one-backward to the steady state, then drains
  the remaining backwards. Peak saved activations per stage:
  O(min(n, S - i)) — the classic PipeDream-flush/1F1B memory win.
  Backwards run in the same microbatch order as GPipe at every stage,
  so accumulated gradients are BIT-IDENTICAL between the two schedules
  (pinned by tests).

Training semantics: forward saves each microbatch's VJP; backward pops
it, accumulates parameter grads; apply() runs the optimizer on the
accumulated (averaged) grads and clears them. Gradients are
mathematically identical to the unpipelined model, which the tests
assert.
"""

from __future__ import annotations

import logging
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import ray_tpu
from ray_tpu.observability import core_metrics, tracing
from ray_tpu.utils import serialization
from ray_tpu.utils.config import config

logger = logging.getLogger(__name__)


@ray_tpu.remote
class PipelineStage:
    """One pipeline stage: params + fn(params, x) -> y."""

    def __init__(self, stage_fn_blob: bytes, params: Any,
                 loss_fn_blob: Optional[bytes] = None,
                 optimizer_blob: Optional[bytes] = None):
        import jax

        self._jax = jax
        self._fn = serialization.loads(stage_fn_blob)
        self._loss_fn = (
            serialization.loads(loss_fn_blob) if loss_fn_blob else None
        )
        self.params = params
        self._opt = (
            serialization.loads(optimizer_blob) if optimizer_blob else None
        )
        self._opt_state = self._opt.init(params) if self._opt else None
        self._vjps: Dict[int, Any] = {}
        self._grad_acc = None
        self._n_acc = 0

    def forward(self, mb_id: int, x):
        y, vjp = self._jax.vjp(self._fn, self.params, x)
        self._vjps[mb_id] = vjp
        return y

    def forward_loss(self, mb_id: int, x, target):
        """Last stage: fn then loss; saves the combined VJP."""

        def stage_and_loss(params, x):
            return self._loss_fn(self._fn(params, x), target)

        loss, vjp = self._jax.vjp(stage_and_loss, self.params, x)
        self._vjps[mb_id] = vjp
        return float(loss)

    def backward(self, mb_id: int, gy):
        gp, gx = self._vjps.pop(mb_id)(gy)
        self._accumulate(gp)
        return gx

    def backward_from_loss(self, mb_id: int, scale: float = 1.0):
        import jax.numpy as jnp

        gp, gx = self._vjps.pop(mb_id)(jnp.float32(scale))
        self._accumulate(gp)
        return gx

    def _accumulate(self, gp):
        jax = self._jax
        if self._grad_acc is None:
            self._grad_acc = gp
        else:
            self._grad_acc = jax.tree.map(
                lambda a, b: a + b, self._grad_acc, gp
            )
        self._n_acc += 1

    def apply(self, lr: float = 1e-2):
        """Optimizer step on the accumulated (averaged) microbatch grads."""
        jax = self._jax
        if self._grad_acc is None:
            return False
        grads = jax.tree.map(lambda g: g / self._n_acc, self._grad_acc)
        if self._opt is not None:
            updates, self._opt_state = self._opt.update(
                grads, self._opt_state, self.params
            )
            self.params = jax.tree.map(
                lambda p, u: p + u, self.params, updates
            )
        else:
            self.params = jax.tree.map(
                lambda p, g: p - lr * g, self.params, grads
            )
        self._grad_acc = None
        self._n_acc = 0
        return True

    def predict(self, x):
        """Forward without saving a VJP (inference path)."""
        return self._fn(self.params, x)

    def get_params(self):
        return self.params

    def reset_step(self):
        """Drop saved VJPs and partial grad accumulation (a failed
        compiled step leaves the stage mid-flight; the next step must
        start clean)."""
        self._vjps.clear()
        self._grad_acc = None
        self._n_acc = 0
        return True

    def transport_info(self):
        """Where this stage's process lives — the compiled tier places
        ShmChannel on same-node stage edges and RpcChannel on
        cross-node ones."""
        from ray_tpu.core import worker as worker_mod

        w = worker_mod.global_worker()
        return {"node_id": w.node_id_hex, "address": w.address}

    def pid(self):
        """This stage's worker process id (chaos tests SIGKILL it)."""
        import os

        return os.getpid()


class Pipeline:
    """Driver-side GPipe coordinator over PipelineStage actors."""

    def __init__(
        self,
        stage_fns: Sequence[Callable],
        stage_params: Sequence[Any],
        loss_fn: Callable,
        optimizer=None,
        resources: Optional[Sequence[Dict[str, float]]] = None,
    ):
        if len(stage_fns) != len(stage_params):
            raise ValueError("one params pytree per stage fn")
        n = len(stage_fns)
        opt_blob = serialization.dumps_function(optimizer) if optimizer else None
        self.stages: List[Any] = []
        for i, (fn, params) in enumerate(zip(stage_fns, stage_params)):
            opts = dict(resources[i]) if resources else {}
            self.stages.append(
                PipelineStage.options(**opts).remote(
                    serialization.dumps_function(fn),
                    params,
                    serialization.dumps_function(loss_fn)
                    if i == n - 1 else None,
                    opt_blob,
                )
            )

    def train_step(
        self, microbatches: Sequence[Any], targets: Sequence[Any],
        lr: float = 1e-2,
    ) -> float:
        """One GPipe step: all microbatch forwards chained through the
        stages, then the reverse backward chains, then apply. Returns the
        mean microbatch loss."""
        if len(microbatches) != len(targets):
            raise ValueError("need one target per microbatch")
        last = self.stages[-1]
        loss_refs = []
        for i, (mb, tgt) in enumerate(zip(microbatches, targets)):
            h = mb
            for s in self.stages[:-1]:
                h = s.forward.remote(i, h)
            loss_refs.append(last.forward_loss.remote(i, h, tgt))
        grad_tails = []
        for i in range(len(microbatches)):
            g = last.backward_from_loss.remote(i)
            for s in reversed(self.stages[:-1]):
                g = s.backward.remote(i, g)
            grad_tails.append(g)
        losses = ray_tpu.get(loss_refs)
        ray_tpu.get(grad_tails)  # ensure all grads accumulated
        ray_tpu.get([s.apply.remote(lr) for s in self.stages])
        return sum(losses) / len(losses)

    def forward(self, x) -> Any:
        """Inference through the pipeline (single batch, no VJPs saved)."""
        h = x
        for s in self.stages:
            h = s.predict.remote(h)
        return ray_tpu.get(h)

    def get_params(self) -> List[Any]:
        return ray_tpu.get([s.get_params.remote() for s in self.stages])

    def compile(self, **kwargs) -> "CompiledPipeline":
        """Compile the stage graph once: park exec loops, stream every
        microbatch over seqlock channels. See :class:`CompiledPipeline`."""
        return CompiledPipeline(self, **kwargs)

    def shutdown(self) -> None:
        for s in self.stages:
            try:
                ray_tpu.kill(s)
            except Exception:  # noqa: BLE001
                pass


# ---------------------------------------------------------------------------
# Compiled tier: stage loops + seqlock channels (GPipe and 1F1B)
# ---------------------------------------------------------------------------

SCHEDULES = ("gpipe", "1f1b")


def _schedule_ops(schedule: str, n_stages: int, stage: int,
                  n_mb: int) -> List[Tuple[str, int]]:
    """The static per-stage op list for one training step.

    GPipe: all forwards, then all backwards. 1F1B: ``min(n_mb,
    n_stages-1-stage)`` warmup forwards, then one-forward-one-backward
    to steady state, then the backward drain. Both run backwards in
    microbatch order 0..n-1 at every stage, so gradient accumulation
    order — and therefore the accumulated gradient bits — are identical
    across schedules."""
    if schedule == "gpipe":
        return (
            [("F", k) for k in range(n_mb)]
            + [("B", k) for k in range(n_mb)]
        )
    if schedule != "1f1b":
        raise ValueError(
            f"unknown pipeline schedule {schedule!r}; expected one of "
            f"{SCHEDULES}"
        )
    warmup = min(n_mb, n_stages - 1 - stage)
    ops = [("F", k) for k in range(warmup)]
    nf, nb = warmup, 0
    while nb < n_mb:
        if nf < n_mb:
            ops.append(("F", nf))
            nf += 1
        ops.append(("B", nb))
        nb += 1
    return ops


def _max_live_activations(schedule: str, n_stages: int, stage: int,
                          n_mb: int) -> int:
    """Peak number of saved VJPs a stage holds under a schedule (the
    1F1B memory claim; README documents, tests pin)."""
    live = peak = 0
    for op, _ in _schedule_ops(schedule, n_stages, stage, n_mb):
        live += 1 if op == "F" else -1
        peak = max(peak, live)
    return peak


def _stage_exec_loop(instance, plan_blob: bytes) -> int:
    """The per-stage compiled loop (runs as a system actor task via
    ``__rt_pipe_exec_loop__`` and occupies one executor slot until
    teardown). Parks on the command channel; each ``step`` command runs
    the schedule's op list, streaming activations/gradients through the
    stage-boundary channels, then applies the optimizer and acks.

    Every channel op inside a step carries the op deadline, so a dead
    neighbor surfaces as a TimeoutError shipped to the driver on the ack
    channel (or, if the ack write itself cannot complete, as the
    driver's own step deadline) — never a wedged loop that teardown
    cannot drain."""
    from ray_tpu.core.channels import open_channel
    from ray_tpu.dag import _is_stop

    plan = serialization.unpack(plan_blob)
    idx, n_stages = plan["stage"], plan["n_stages"]
    op_t = plan["op_timeout_s"]
    last = idx == n_stages - 1

    def opt(name, role):
        h = plan.get(name)
        return open_channel(h, role) if h is not None else None

    cmd = open_channel(plan["cmd"], "read")
    ack = open_channel(plan["ack"], "write")
    fwd_in = open_channel(plan["fwd_in"], "read")
    fwd_out = opt("fwd_out", "write")
    bwd_in = opt("bwd_in", "read")
    bwd_out = opt("bwd_out", "write")
    tgt_in = opt("tgt", "read")
    loss_out = opt("loss", "write")

    steps = 0
    stopping = False
    while not stopping:
        frame = cmd.read(timeout_s=None)
        if _is_stop(frame):
            break
        command = serialization.unpack(frame)
        if command[0] == "get_params":
            # same ship-don't-die contract as a failed step: params too
            # big for the ack ring (or a dead driver) must not kill the
            # parked loop silently
            try:
                ack.write_value(instance.get_params(), timeout_s=op_t)
            except Exception as e:  # noqa: BLE001 — ship to the driver
                try:
                    ack.write_value(e, timeout_s=5.0)
                except Exception:  # noqa: BLE001 — driver gone too
                    pass
            continue
        _, schedule, n_mb, lr = command
        # per-step observability: input-channel wait counts as idle,
        # compute+output-write as busy; bubble fraction = idle/(idle+busy)
        obs = tracing.ENABLED or core_metrics.ENABLED
        idle_us = busy_us = 0
        step_t0 = tracing.now_us() if obs else 0
        try:
            for op, k in _schedule_ops(schedule, n_stages, idx, n_mb):
                t0 = tracing.now_us() if obs else 0
                if op == "F":
                    x = fwd_in.read(timeout_s=op_t)
                    if _is_stop(x):
                        stopping = True
                        break
                    x = serialization.unpack(x)
                    t1 = tracing.now_us() if obs else 0
                    if last:
                        target = tgt_in.read_value(timeout_s=op_t)
                        loss_out.write_value(
                            instance.forward_loss(k, x, target),
                            timeout_s=op_t,
                        )
                    else:
                        fwd_out.write_value(
                            instance.forward(k, x), timeout_s=op_t
                        )
                else:
                    if last:
                        t1 = t0
                        g = instance.backward_from_loss(k)
                    else:
                        g_in = bwd_in.read_value(timeout_s=op_t)
                        t1 = tracing.now_us() if obs else 0
                        g = instance.backward(k, g_in)
                    if bwd_out is not None:
                        bwd_out.write_value(g, timeout_s=op_t)
                if obs:
                    t2 = tracing.now_us()
                    idle_us += t1 - t0
                    busy_us += t2 - t1
                    if tracing.ENABLED:
                        if t1 > t0:
                            tracing.emit(tracing.pipeline_slice(
                                idx, "idle", t0, t1 - t0, steps,
                                microbatch=k,
                            ))
                        tracing.emit(tracing.pipeline_slice(
                            idx, "fwd" if op == "F" else "bwd", t1,
                            t2 - t1, steps, microbatch=k,
                            schedule=schedule,
                        ))
            if stopping:
                break
            instance.apply(lr)
            ack.write_value(("ok", n_mb), timeout_s=op_t)
            if obs:
                wall_us = max(tracing.now_us() - step_t0, 1)
                bubble = idle_us / max(idle_us + busy_us, 1)
                if tracing.ENABLED:
                    tracing.emit(tracing.pipeline_slice(
                        idx, "step", step_t0, wall_us, steps,
                        bubble_frac=bubble, schedule=schedule,
                        n_microbatches=n_mb,
                    ))
                if core_metrics.ENABLED:
                    core_metrics.pipeline_stage_busy_s.observe(
                        busy_us / 1e6, tags={"stage": str(idx)}
                    )
                    core_metrics.pipeline_bubble_fraction.observe(
                        bubble, tags={"stage": str(idx),
                                      "schedule": schedule}
                    )
            steps += 1
        except Exception as e:  # noqa: BLE001 — ship to the driver
            instance.reset_step()
            try:
                ack.write_value(e, timeout_s=5.0)
            except Exception:  # noqa: BLE001 — driver gone too
                pass
    for ch in (cmd, ack, fwd_in, fwd_out, bwd_in, bwd_out, tgt_in,
               loss_out):
        if ch is not None:
            try:
                ch.close()
            except Exception:  # noqa: BLE001 — best-effort reclaim
                pass
    return steps


class CompiledPipeline:
    """The compiled form of a :class:`Pipeline`: channels allocated,
    stage loops parked, every microbatch streamed over seqlock rings.

    Channels per stage boundary (driver counts as both ends):

    - forward activation channel stage i-1 → i (ring of
      ``channel_slots`` slots × ``channel_capacity`` bytes);
    - backward gradient channel stage i+1 → i (same geometry);
    - driver → last-stage target channel, last-stage → driver loss
      channel (loss ring holds ``max_microbatches`` slots so the last
      stage NEVER blocks publishing a loss — that bound is what makes
      the streaming schedule deadlock-free for any microbatch count up
      to the cap);
    - per-stage command/ack channels (tiny commands down, step acks /
      shipped exceptions / fetched params up).

    Same-node edges ride ShmChannel; cross-node edges ride RpcChannel
    (``RT_PIPELINE_FORCE_RPC_CHANNELS=1`` forces the RPC tier
    everywhere — the cross-host test/A-B lever).

    Failure contract: every channel op inside ``train_step`` carries
    the step deadline — a SIGKILLed stage or a wedged neighbor raises
    within ``step_timeout_s`` (a stage-shipped exception is re-raised
    verbatim), never hangs; the pipeline is then broken and must be
    torn down. ``teardown()`` drains and unlinks every channel it
    created, wedged loops or not."""

    def __init__(
        self,
        pipeline: Pipeline,
        schedule: str = "1f1b",
        channel_capacity: int = 4 * 1024 * 1024,
        channel_slots: int = 2,
        max_microbatches: int = 256,
        step_timeout_s: float = 60.0,
    ):
        from ray_tpu.core import worker as worker_mod
        from ray_tpu.core.channels import (
            RpcChannel, ShmChannel, rpc_channel_handle,
        )

        if schedule not in SCHEDULES:
            raise ValueError(
                f"unknown pipeline schedule {schedule!r}; expected one "
                f"of {SCHEDULES}"
            )
        if channel_slots < 1:
            raise ValueError("channel_slots must be >= 1")
        self._pipe = pipeline
        self.schedule = schedule
        self._n = len(pipeline.stages)
        self._capacity = channel_capacity
        self._slots = channel_slots
        self._max_mb = max_microbatches
        self._timeout = step_timeout_s
        self._broken = False
        self._torn_down = False

        self._w = worker_mod.global_worker()
        infos = ray_tpu.get(
            [s.transport_info.remote() for s in pipeline.stages],
            timeout=step_timeout_s,
        )
        driver = {"node_id": self._w.node_id_hex, "address": self._w.address}
        force_rpc = bool(config.pipeline_force_rpc_channels)

        self._shm_channels: List[ShmChannel] = []

        def make(writer, reader, capacity, slots):
            """One stage-boundary channel: shm when both ends live on
            THE DRIVER'S node (the driver creates the segment, so a
            same-node pair on a remote host could not attach it — those
            edges ride the RPC tier too), RPC mailbox on the reader's
            worker otherwise."""
            if (not force_rpc
                    and writer["node_id"] == reader["node_id"]
                    == driver["node_id"]):
                ch = ShmChannel.create(capacity, slots=slots)
                self._shm_channels.append(ch)
                return ch.handle()
            return rpc_channel_handle(reader["address"], capacity, slots)

        self._rpc_readers: List[RpcChannel] = []

        def driver_end(handle, role):
            if handle.get("kind") == "rpc":
                ch = RpcChannel(handle, role)
                if role == "read":
                    self._rpc_readers.append(ch)
                return ch
            for ch in self._shm_channels:
                if ch.path == handle["path"]:
                    return ch
            raise RuntimeError("driver end of an unknown channel")

        S = self._n
        parked_cmds: List[Any] = []
        try:
            x_h = [
                make(driver if i == 0 else infos[i - 1], infos[i],
                     channel_capacity, channel_slots)
                for i in range(S)
            ]
            g_h = [
                make(infos[i + 1], infos[i], channel_capacity,
                     channel_slots)
                for i in range(S - 1)
            ]
            tgt_h = make(driver, infos[S - 1], channel_capacity,
                         channel_slots)
            # losses are tiny; a slot per microbatch makes the last
            # stage's loss publish non-blocking (see class docstring)
            loss_h = make(infos[S - 1], driver, 16 * 1024,
                          max_microbatches)
            cmd_h = [make(driver, infos[i], 64 * 1024, 2)
                     for i in range(S)]
            # acks also carry fetched params / shipped exceptions
            ack_h = [make(infos[i], driver, channel_capacity, 2)
                     for i in range(S)]

            self._x0 = driver_end(x_h[0], "write")
            self._tgt = driver_end(tgt_h, "write")
            self._loss = driver_end(loss_h, "read")
            self._cmd = [driver_end(h, "write") for h in cmd_h]
            self._ack = [driver_end(h, "read") for h in ack_h]

            # park the stage loops (their returns arrive at teardown)
            self._loop_refs = []
            for i, stage in enumerate(pipeline.stages):
                plan = {
                    "stage": i,
                    "n_stages": S,
                    "op_timeout_s": step_timeout_s,
                    "cmd": cmd_h[i],
                    "ack": ack_h[i],
                    "fwd_in": x_h[i],
                    "fwd_out": x_h[i + 1] if i < S - 1 else None,
                    "bwd_in": g_h[i] if i < S - 1 else None,
                    "bwd_out": g_h[i - 1] if i > 0 else None,
                    "tgt": tgt_h if i == S - 1 else None,
                    "loss": loss_h if i == S - 1 else None,
                }
                refs = self._w.submit_actor_task(
                    stage._actor_id, "__rt_pipe_exec_loop__",
                    (serialization.pack(plan),), {}, num_returns=1,
                )
                self._loop_refs.extend(refs)
                parked_cmds.append(cmd_h[i])
        except BaseException:
            from ray_tpu.core.channels import open_channel
            from ray_tpu.dag import _STOP

            # a stage died mid-compile (or a channel failed to open):
            # the half-built object is unreachable, so unwedge every
            # ALREADY-PARKED loop (else it holds its actor's executor
            # slot forever) and reclaim every channel NOW — no
            # /dev/shm/rtchan_* debris from a failed compile
            for h in parked_cmds:
                try:
                    wch = open_channel(h, "write")
                    try:
                        wch.write(_STOP, timeout_s=1.0)
                    finally:
                        wch.close()
                except Exception:  # noqa: BLE001 — best-effort
                    pass
            for ch in self._shm_channels:
                try:
                    ch.close(unlink=True)
                except Exception:  # noqa: BLE001 — best-effort
                    pass
            for ch in self._rpc_readers:
                try:
                    ch.close()
                except Exception:  # noqa: BLE001 — best-effort
                    pass
            raise

    # -- driver-side hot path ------------------------------------------

    def _check_usable(self):
        if self._torn_down:
            raise RuntimeError("compiled pipeline was torn down")
        if self._broken:
            raise RuntimeError(
                "compiled pipeline is broken (an earlier step failed "
                "mid-stream); teardown and recompile"
            )

    def _sniff_stage_error(self) -> Optional[BaseException]:
        """Non-blocking scan of the ack channels for a shipped stage
        exception (a failed mid-pipeline stage cannot reach the loss
        channel, so the driver's loss read times out — the real cause
        is waiting on that stage's ack channel)."""
        for ch in self._ack:
            try:
                got = ch.read_value(timeout_s=0.0)
            except Exception:  # noqa: BLE001 — empty/closed: keep looking
                continue
            if isinstance(got, BaseException):
                return got
        return None

    def train_step(
        self,
        microbatches: Sequence[Any],
        targets: Sequence[Any],
        lr: float = 1e-2,
        schedule: Optional[str] = None,
    ) -> float:
        """One pipelined training step over the compiled channels.
        Streams each microbatch (and its target) as soon as the input
        ring has a free slot, collects the per-microbatch losses, then
        waits for every stage's apply ack. Returns the mean loss."""
        self._check_usable()
        if len(microbatches) != len(targets):
            raise ValueError("need one target per microbatch")
        n_mb = len(microbatches)
        if n_mb > self._max_mb:
            raise ValueError(
                f"{n_mb} microbatches > max_microbatches={self._max_mb} "
                f"(the loss ring is sized at compile time)"
            )
        sched = schedule or self.schedule
        if sched not in SCHEDULES:
            raise ValueError(f"unknown pipeline schedule {sched!r}")
        deadline = time.monotonic() + self._timeout

        def remaining():
            rem = deadline - time.monotonic()
            if rem <= 0:
                raise TimeoutError("pipeline step deadline exceeded")
            return rem

        losses: List[float] = []
        try:
            command = ("step", sched, n_mb, lr)
            for ch in self._cmd:
                ch.write_value(command, timeout_s=remaining())
            for mb, tv in zip(microbatches, targets):
                self._x0.write_value(mb, timeout_s=remaining())
                self._tgt.write_value(tv, timeout_s=remaining())
                # opportunistic drain: losses stream back while later
                # microbatches are still being fed
                while len(losses) < n_mb:
                    try:
                        losses.append(self._loss.read_value(timeout_s=0.0))
                    except TimeoutError:
                        break
            while len(losses) < n_mb:
                losses.append(
                    self._loss.read_value(timeout_s=remaining())
                )
            for ch in self._ack:
                got = ch.read_value(timeout_s=remaining())
                if isinstance(got, BaseException):
                    raise got
        except BaseException as e:
            self._broken = True
            if isinstance(e, TimeoutError):
                shipped = self._sniff_stage_error()
                if shipped is not None:
                    raise shipped from None
                raise RuntimeError(
                    f"compiled pipeline step did not complete within "
                    f"{self._timeout}s — a stage actor likely died "
                    f"mid-step; teardown() and recompile"
                ) from e
            raise
        return sum(losses) / n_mb

    def get_params(self) -> List[Any]:
        """Fetch every stage's params through the command/ack channels
        (the parked loops occupy the actors' executor slots, so plain
        RPC would queue until teardown). A failure mid-fetch breaks the
        pipeline: a late params reply left in an ack ring would
        otherwise be misread as the next step's ack."""
        self._check_usable()
        deadline = time.monotonic() + self._timeout
        out = []
        try:
            for ch in self._cmd:
                ch.write_value(("get_params",),
                               timeout_s=deadline - time.monotonic())
            for ch in self._ack:
                got = ch.read_value(
                    timeout_s=max(0.1, deadline - time.monotonic())
                )
                if isinstance(got, BaseException):
                    raise got
                out.append(got)
        except BaseException:
            self._broken = True
            raise
        return out

    def teardown(self, timeout_s: float = 60.0) -> None:
        """Stop the stage loops and reclaim every channel. Mirrors
        CompiledDAG.teardown: keep draining driver-facing channels while
        the stop sentinel propagates (a loop may be blocked writing a
        loss/ack the driver never consumed), then unlink all shm
        segments and close all RPC mailboxes — debris-free even when a
        loop outlives the drain deadline (which is surfaced, loudly)."""
        from ray_tpu.core import api
        from ray_tpu.dag import _STOP

        if self._torn_down:
            return
        self._torn_down = True
        pending = list(self._loop_refs)
        stop_sent = [False] * len(self._cmd)
        deadline = time.monotonic() + timeout_s
        while pending and time.monotonic() < deadline:
            for i, ch in enumerate(self._cmd):
                if not stop_sent[i]:
                    try:
                        ch.write(_STOP, timeout_s=0.2)
                        stop_sent[i] = True
                    except Exception:  # noqa: BLE001 — full/dead: retry
                        pass
            for ch in (self._loss, *self._ack):
                try:
                    ch.read(timeout_s=0.05)
                except Exception:  # noqa: BLE001 — empty/closed: fine
                    pass
            try:
                _, pending = api.wait(
                    pending, num_returns=len(pending), timeout=0.3
                )
            except Exception:  # noqa: BLE001 — actor may already be dead
                pending = []
                break
        if pending:
            logger.warning(
                "compiled pipeline teardown: %d stage loop(s) still "
                "running after the %.0fs drain deadline; unlinking all "
                "channels anyway",
                len(pending), timeout_s,
            )
        for ch in self._shm_channels:
            ch.close(unlink=True)
        for ch in self._rpc_readers:
            ch.close()  # driver-side mailboxes (shm ends closed above)
