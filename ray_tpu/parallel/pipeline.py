"""Pipeline parallelism — stage-per-actor GPipe microbatching.

Parity: the role Compiled Graphs play for PP in the reference
(python/ray/dag/compiled_dag_node.py:805 — static actor DAGs with
pre-allocated channels driving microbatch loops). Here each pipeline
stage is an actor holding its stage's parameters; the driver submits the
microbatch forward chain and the reverse backward chain as ordered actor
calls, so the per-actor FIFO queues yield the GPipe overlap (stage 1
computes microbatch k+1's forward while stage 2 works on k) without any
per-step scheduling — activations flow stage-to-stage as ObjectRefs
through the shm object plane (same-host consumers read them zero-copy;
ray_tpu.core.channels.ShmChannel is the mutable-channel primitive for
the µs-latency tier).

Training semantics: classic GPipe. forward saves each microbatch's VJP;
backward pops it, accumulates parameter grads; apply() runs the
optimizer on the accumulated grads and clears them. Gradients are
mathematically identical to the unpipelined model (microbatch gradient
averaging), which the tests assert.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence

import ray_tpu
from ray_tpu.utils import serialization


@ray_tpu.remote
class PipelineStage:
    """One pipeline stage: params + fn(params, x) -> y."""

    def __init__(self, stage_fn_blob: bytes, params: Any,
                 loss_fn_blob: Optional[bytes] = None,
                 optimizer_blob: Optional[bytes] = None):
        import jax

        self._jax = jax
        self._fn = serialization.loads(stage_fn_blob)
        self._loss_fn = (
            serialization.loads(loss_fn_blob) if loss_fn_blob else None
        )
        self.params = params
        self._opt = (
            serialization.loads(optimizer_blob) if optimizer_blob else None
        )
        self._opt_state = self._opt.init(params) if self._opt else None
        self._vjps: Dict[int, Any] = {}
        self._grad_acc = None
        self._n_acc = 0

    def forward(self, mb_id: int, x):
        y, vjp = self._jax.vjp(self._fn, self.params, x)
        self._vjps[mb_id] = vjp
        return y

    def forward_loss(self, mb_id: int, x, target):
        """Last stage: fn then loss; saves the combined VJP."""

        def stage_and_loss(params, x):
            return self._loss_fn(self._fn(params, x), target)

        loss, vjp = self._jax.vjp(stage_and_loss, self.params, x)
        self._vjps[mb_id] = vjp
        return float(loss)

    def backward(self, mb_id: int, gy):
        gp, gx = self._vjps.pop(mb_id)(gy)
        self._accumulate(gp)
        return gx

    def backward_from_loss(self, mb_id: int, scale: float = 1.0):
        import jax.numpy as jnp

        gp, gx = self._vjps.pop(mb_id)(jnp.float32(scale))
        self._accumulate(gp)
        return gx

    def _accumulate(self, gp):
        jax = self._jax
        if self._grad_acc is None:
            self._grad_acc = gp
        else:
            self._grad_acc = jax.tree.map(
                lambda a, b: a + b, self._grad_acc, gp
            )
        self._n_acc += 1

    def apply(self, lr: float = 1e-2):
        """Optimizer step on the accumulated (averaged) microbatch grads."""
        jax = self._jax
        if self._grad_acc is None:
            return False
        grads = jax.tree.map(lambda g: g / self._n_acc, self._grad_acc)
        if self._opt is not None:
            updates, self._opt_state = self._opt.update(
                grads, self._opt_state, self.params
            )
            self.params = jax.tree.map(
                lambda p, u: p + u, self.params, updates
            )
        else:
            self.params = jax.tree.map(
                lambda p, g: p - lr * g, self.params, grads
            )
        self._grad_acc = None
        self._n_acc = 0
        return True

    def predict(self, x):
        """Forward without saving a VJP (inference path)."""
        return self._fn(self.params, x)

    def get_params(self):
        return self.params


class Pipeline:
    """Driver-side GPipe coordinator over PipelineStage actors."""

    def __init__(
        self,
        stage_fns: Sequence[Callable],
        stage_params: Sequence[Any],
        loss_fn: Callable,
        optimizer=None,
        resources: Optional[Sequence[Dict[str, float]]] = None,
    ):
        if len(stage_fns) != len(stage_params):
            raise ValueError("one params pytree per stage fn")
        n = len(stage_fns)
        opt_blob = serialization.dumps_function(optimizer) if optimizer else None
        self.stages: List[Any] = []
        for i, (fn, params) in enumerate(zip(stage_fns, stage_params)):
            opts = dict(resources[i]) if resources else {}
            self.stages.append(
                PipelineStage.options(**opts).remote(
                    serialization.dumps_function(fn),
                    params,
                    serialization.dumps_function(loss_fn)
                    if i == n - 1 else None,
                    opt_blob,
                )
            )

    def train_step(
        self, microbatches: Sequence[Any], targets: Sequence[Any],
        lr: float = 1e-2,
    ) -> float:
        """One GPipe step: all microbatch forwards chained through the
        stages, then the reverse backward chains, then apply. Returns the
        mean microbatch loss."""
        if len(microbatches) != len(targets):
            raise ValueError("need one target per microbatch")
        last = self.stages[-1]
        loss_refs = []
        for i, (mb, tgt) in enumerate(zip(microbatches, targets)):
            h = mb
            for s in self.stages[:-1]:
                h = s.forward.remote(i, h)
            loss_refs.append(last.forward_loss.remote(i, h, tgt))
        grad_tails = []
        for i in range(len(microbatches)):
            g = last.backward_from_loss.remote(i)
            for s in reversed(self.stages[:-1]):
                g = s.backward.remote(i, g)
            grad_tails.append(g)
        losses = ray_tpu.get(loss_refs)
        ray_tpu.get(grad_tails)  # ensure all grads accumulated
        ray_tpu.get([s.apply.remote(lr) for s in self.stages])
        return sum(losses) / len(losses)

    def forward(self, x) -> Any:
        """Inference through the pipeline (single batch, no VJPs saved)."""
        h = x
        for s in self.stages:
            h = s.predict.remote(h)
        return ray_tpu.get(h)

    def get_params(self) -> List[Any]:
        return ray_tpu.get([s.get_params.remote() for s in self.stages])

    def shutdown(self) -> None:
        for s in self.stages:
            try:
                ray_tpu.kill(s)
            except Exception:  # noqa: BLE001
                pass
