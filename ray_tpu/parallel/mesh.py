"""Device mesh construction with standard parallelism axes.

TPU-first core of the framework (no reference equivalent — the reference
relies on NCCL process groups; SURVEY.md §2.4 maps each strategy to the
mesh axis built here):

  dp    data parallelism (batch split; gradient psum)
  fsdp  parameter sharding (ZeRO-3 style, GSPMD handles gather/scatter)
  tp    tensor parallelism (sharded matmuls over ICI)
  cp    context parallelism (sequence split; ring attention)
  ep    expert parallelism (MoE all-to-all)

Multislice: an extra leading "dcn" axis maps data parallelism across
slices (DCN), with all other axes inside a slice (ICI) — the hierarchical
mesh the MEGASCALE env (accelerators/tpu.py get_tpu_coordinator_env_vars)
configures.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, List, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

AXIS_ORDER = ("dcn", "dp", "fsdp", "ep", "cp", "tp")


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Sizes per axis; -1 on exactly one axis means "absorb the rest"."""

    dp: int = -1
    fsdp: int = 1
    tp: int = 1
    cp: int = 1
    ep: int = 1
    dcn: int = 1

    def resolve(self, num_devices: int) -> Dict[str, int]:
        sizes = {
            "dcn": self.dcn, "dp": self.dp, "fsdp": self.fsdp,
            "ep": self.ep, "cp": self.cp, "tp": self.tp,
        }
        wild = [k for k, v in sizes.items() if v == -1]
        if len(wild) > 1:
            raise ValueError(f"only one axis may be -1, got {wild}")
        fixed = math.prod(v for v in sizes.values() if v != -1)
        if wild:
            if num_devices % fixed != 0:
                raise ValueError(
                    f"{num_devices} devices not divisible by fixed axes {fixed}"
                )
            sizes[wild[0]] = num_devices // fixed
        if math.prod(sizes.values()) != num_devices:
            raise ValueError(
                f"mesh {sizes} does not cover {num_devices} devices"
            )
        return sizes


def build_mesh(
    config: Optional[MeshConfig] = None,
    devices: Optional[Sequence] = None,
    axis_names: Sequence[str] = AXIS_ORDER,
) -> Mesh:
    """Build a Mesh over all (or given) devices with the standard axes.

    Axis order puts dcn outermost (slowest-varying = cross-slice DCN) and
    tp innermost (fastest-varying = nearest-neighbor ICI), matching the
    physical topology so TP collectives ride the shortest links.
    """
    devices = list(devices if devices is not None else jax.devices())
    config = config or MeshConfig()
    sizes = config.resolve(len(devices))
    shape = tuple(sizes[a] for a in axis_names)
    try:
        from jax.experimental import mesh_utils

        if sizes.get("dcn", 1) > 1:
            per_slice = [s if a != "dcn" else 1 for a, s in zip(axis_names, shape)]
            dcn_shape = [sizes["dcn"] if a == "dcn" else 1 for a in axis_names]
            dev_array = mesh_utils.create_hybrid_device_mesh(
                per_slice, dcn_shape, devices=devices
            )
        else:
            dev_array = mesh_utils.create_device_mesh(shape, devices=devices)
    except (ValueError, AssertionError):
        # CPU meshes / odd shapes: plain reshape keeps semantics
        dev_array = np.array(devices).reshape(shape)
    return Mesh(dev_array, axis_names)


def local_mesh(**axis_sizes) -> Mesh:
    """Convenience: mesh over jax.devices() with given sizes, e.g.
    local_mesh(dp=2, tp=4)."""
    return build_mesh(MeshConfig(**axis_sizes))


def data_axes() -> List[str]:
    """Mesh axes a batch dimension is sharded over."""
    return ["dcn", "dp", "fsdp"]


def num_data_shards(mesh: Mesh) -> int:
    return int(np.prod([mesh.shape[a] for a in data_axes() if a in mesh.shape]))
