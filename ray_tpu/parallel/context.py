"""Ambient mesh context: lets model code (e.g. ring attention) find the
mesh it runs under without threading it through every call signature."""

from __future__ import annotations

import contextlib
import threading
from typing import Optional

from jax.sharding import Mesh

_state = threading.local()


def current_mesh() -> Optional[Mesh]:
    return getattr(_state, "mesh", None)


@contextlib.contextmanager
def use_mesh(mesh: Mesh):
    prev = current_mesh()
    _state.mesh = mesh
    try:
        yield mesh
    finally:
        _state.mesh = prev
