"""Job submission — run driver scripts on the cluster.

Parity: the reference job-submission stack (python/ray/dashboard/modules/
job/job_manager.py:62 + per-job JobSupervisor actor job_supervisor.py:57
+ the `ray job` CLI/SDK): submit_job starts a DETACHED supervisor actor
that runs the entrypoint command in a subprocess with RT_ADDRESS set (so
the script's ray_tpu.init(address=...) joins this cluster), captures its
output, and serves status/logs. Detached lifetime means the job outlives
the submitting client.
"""

from __future__ import annotations

import uuid
from typing import Any, Dict, List, Optional

import ray_tpu


class JobStatus:
    PENDING = "PENDING"
    RUNNING = "RUNNING"
    SUCCEEDED = "SUCCEEDED"
    FAILED = "FAILED"
    STOPPED = "STOPPED"


@ray_tpu.remote
class _JobSupervisor:
    """Owns one submitted job's subprocess (reference job_supervisor.py)."""

    def __init__(self, submission_id: str, entrypoint: str,
                 env_vars: Dict[str, str], control_address: str):
        self.submission_id = submission_id
        self.entrypoint = entrypoint
        self.env_vars = env_vars
        self.control_address = control_address
        self.status = JobStatus.PENDING
        self.returncode: Optional[int] = None
        self._proc = None
        self._log_chunks: List[str] = []
        import threading

        self._lock = threading.Lock()

    def start(self) -> bool:
        import os
        import subprocess
        import threading

        env = dict(os.environ)
        env.update(self.env_vars)
        env["RT_ADDRESS"] = self.control_address
        with self._lock:
            self.status = JobStatus.RUNNING
        self._proc = subprocess.Popen(
            self.entrypoint, shell=True, env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            start_new_session=True,
        )

        def pump():
            for line in self._proc.stdout:
                with self._lock:
                    self._log_chunks.append(line)
                    if len(self._log_chunks) > 100_000:
                        del self._log_chunks[:50_000]
            rc = self._proc.wait()
            with self._lock:
                self.returncode = rc
                if self.status != JobStatus.STOPPED:
                    self.status = (
                        JobStatus.SUCCEEDED if rc == 0 else JobStatus.FAILED
                    )

        threading.Thread(target=pump, name="job-pump", daemon=True).start()
        return True

    def get_status(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "submission_id": self.submission_id,
                "entrypoint": self.entrypoint,
                "status": self.status,
                "returncode": self.returncode,
            }

    def get_logs(self) -> str:
        with self._lock:
            return "".join(self._log_chunks)

    def stop(self) -> bool:
        import os
        import signal

        if self._proc is not None and self._proc.poll() is None:
            with self._lock:
                self.status = JobStatus.STOPPED
            try:
                os.killpg(os.getpgid(self._proc.pid), signal.SIGTERM)
            except (ProcessLookupError, PermissionError):
                pass
            return True
        return False


class JobSubmissionClient:
    """Parity: ray.job_submission.JobSubmissionClient."""

    def __init__(self, address: Optional[str] = None):
        if not ray_tpu.is_initialized():
            ray_tpu.init(address=address)
        from ray_tpu.core import worker as worker_mod

        self._control_address = worker_mod.global_worker().control_address

    def submit_job(
        self,
        *,
        entrypoint: str,
        submission_id: Optional[str] = None,
        runtime_env: Optional[Dict[str, Any]] = None,
    ) -> str:
        submission_id = submission_id or f"rtjob_{uuid.uuid4().hex[:10]}"
        env_vars = dict((runtime_env or {}).get("env_vars") or {})
        sup = _JobSupervisor.options(
            name=f"JOB_SUP::{submission_id}",
            lifetime="detached",
            num_cpus=1,
            max_concurrency=4,  # status/logs answer while the job runs
        ).remote(
            submission_id, entrypoint, env_vars, self._control_address
        )
        ray_tpu.get(sup.start.remote(), timeout=120)
        from ray_tpu.core import worker as worker_mod

        worker_mod.global_worker().control.call(
            "kv_put", ns="job_submissions", key=submission_id,
            value=submission_id.encode(), retryable=True,
        )
        return submission_id

    def _sup(self, submission_id: str):
        return ray_tpu.get_actor(f"JOB_SUP::{submission_id}")

    def get_job_status(self, submission_id: str) -> str:
        return ray_tpu.get(
            self._sup(submission_id).get_status.remote(), timeout=30
        )["status"]

    def get_job_info(self, submission_id: str) -> Dict[str, Any]:
        return ray_tpu.get(
            self._sup(submission_id).get_status.remote(), timeout=30
        )

    def get_job_logs(self, submission_id: str) -> str:
        return ray_tpu.get(
            self._sup(submission_id).get_logs.remote(), timeout=30
        )

    def stop_job(self, submission_id: str) -> bool:
        return ray_tpu.get(
            self._sup(submission_id).stop.remote(), timeout=30
        )

    def delete_job(self, submission_id: str) -> bool:
        """Remove a finished submission: kill its (detached) supervisor —
        freeing the CPU it holds for status/logs serving — and drop the
        registry entry (reference JobSubmissionClient.delete_job)."""
        status = self.get_job_status(submission_id)
        if status == JobStatus.RUNNING:
            raise RuntimeError(
                f"job {submission_id} is RUNNING; stop it first"
            )
        try:
            ray_tpu.kill(self._sup(submission_id))
        except Exception:  # noqa: BLE001 — already gone
            pass
        from ray_tpu.core import worker as worker_mod

        worker_mod.global_worker().control.call(
            "kv_del", ns="job_submissions", key=submission_id,
        )
        return True

    def list_jobs(self) -> List[Dict[str, Any]]:
        from ray_tpu.core import worker as worker_mod

        control = worker_mod.global_worker().control
        ids = control.call("kv_keys", ns="job_submissions", prefix="")
        out = []
        for sid in ids:
            try:
                out.append(self.get_job_info(sid))
            except Exception:  # noqa: BLE001 — supervisor gone
                out.append({"submission_id": sid, "status": "UNKNOWN"})
        return out

    def wait_until_finished(self, submission_id: str,
                            timeout_s: float = 600.0) -> str:
        import time

        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            status = self.get_job_status(submission_id)
            if status in (JobStatus.SUCCEEDED, JobStatus.FAILED,
                          JobStatus.STOPPED):
                return status
            time.sleep(0.5)
        raise TimeoutError(f"job {submission_id} still running")
