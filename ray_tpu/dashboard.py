"""Dashboard — HTTP view of the cluster.

Parity: the reference dashboard head (python/ray/dashboard/head.py) at
its observability core: JSON APIs over the state aggregator plus a
self-refreshing HTML summary. Heavy UI, per-node agents, and Grafana
provisioning are out of scope — the state API (state.py) carries the
same data to programmatic consumers.

Endpoints: /           HTML summary (auto-refresh)
           /api/status /api/nodes /api/actors /api/jobs /api/workers
           /api/placement_groups /api/timeline /api/alerts
           /api/profile?duration_s=&hz= (fleet sampling profile, merged)
           /api/stacks?node= (all-thread dumps)  /api/crash_reports
           /api/metrics/history?name=&window_s=&step_s=&tags={...}
           /metrics (Prometheus text)
"""

from __future__ import annotations

import html
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qsl, urlparse

from ray_tpu import state
from ray_tpu.utils import metrics as metrics_mod

_PAGE = """<!doctype html>
<html><head><title>ray_tpu dashboard</title>
<meta http-equiv="refresh" content="5">
<style>
 body {{ font-family: monospace; margin: 2em; }}
 table {{ border-collapse: collapse; margin: 1em 0; }}
 td, th {{ border: 1px solid #999; padding: 4px 10px; text-align: left; }}
 h2 {{ margin-top: 1.5em; }}
</style></head><body>
<h1>ray_tpu cluster</h1>
<pre>{status}</pre>
<h2>nodes</h2>{nodes}
<h2>actors</h2>{actors}
<h2>jobs</h2>{jobs}
<p>APIs: /api/status /api/nodes /api/actors /api/jobs /api/workers
/api/placement_groups /api/timeline /api/task_summary
/api/request_summary /api/alerts
/api/profile?duration_s=&amp;hz= /api/stacks?node=
/api/crash_reports?pid=&amp;node=
/api/metrics/history?name=&amp;window_s=&amp;step_s=&amp;tags= /metrics</p>
</body></html>"""


def _table(rows, columns) -> str:
    head = "".join(f"<th>{html.escape(c)}</th>" for c in columns)
    body = "".join(
        "<tr>" + "".join(
            f"<td>{html.escape(str(r.get(c, '')))}</td>" for c in columns
        ) + "</tr>"
        for r in rows
    )
    return f"<table><tr>{head}</tr>{body}</table>"


class Dashboard:
    def __init__(self, control_address: str, host: str = "127.0.0.1",
                 port: int = 0):
        # loopback by default: the JSON APIs are unauthenticated (the
        # reference dashboard binds localhost for the same reason);
        # exposing beyond the host is an explicit host= opt-in
        self.control_address = control_address
        dash = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):
                pass

            def _respond(self, status, ctype, body):
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                try:
                    status, ctype, body = dash._route(self.path)
                except Exception as e:  # noqa: BLE001
                    status, ctype = 500, "application/json"
                    body = json.dumps({"error": str(e)}).encode()
                self._respond(status, ctype, body)

            def do_POST(self):
                try:
                    length = int(self.headers.get("Content-Length") or 0)
                    payload = self.rfile.read(length) if length else b""
                    status, ctype, body = dash._route_post(
                        self.path, payload
                    )
                except Exception as e:  # noqa: BLE001
                    status, ctype = 500, "application/json"
                    body = json.dumps({"error": str(e)}).encode()
                self._respond(status, ctype, body)

            def do_DELETE(self):
                # DELETE /api/jobs/<sid> deletes a terminal job's record
                # (reference job API; stopping a running job is POST
                # .../stop)
                try:
                    status, ctype, body = dash._route_post(
                        self.path.rstrip("/") + "/delete", b""
                    )
                except Exception as e:  # noqa: BLE001
                    status, ctype = 500, "application/json"
                    body = json.dumps({"error": str(e)}).encode()
                self._respond(status, ctype, body)

        self._server = ThreadingHTTPServer((host, port), Handler)
        self._thread: Optional[threading.Thread] = None

    @property
    def address(self) -> str:
        host, port = self._server.server_address[:2]
        if host in ("0.0.0.0", "::"):  # wildcard bind isn't connectable
            host = "127.0.0.1"
        return f"{host}:{port}"

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="dashboard", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()

    # ------------------------------------------------------------------

    def _route(self, path: str):
        addr = self.control_address
        # split the query string: /api/metrics/history takes parameters,
        # and exact-path matching must not break on "?…" suffixes
        parsed = urlparse(path)
        path = parsed.path
        qs = dict(parse_qsl(parsed.query))
        apis = {
            "/api/status": lambda: state.cluster_status(addr),
            "/api/nodes": lambda: state.list_nodes(addr),
            "/api/actors": lambda: state.list_actors(addr),
            "/api/jobs": lambda: state.list_jobs(addr),
            "/api/workers": lambda: state.list_workers(addr),
            "/api/placement_groups": lambda: state.list_placement_groups(addr),
            "/api/timeline": lambda: state.timeline(addr),
            "/api/task_summary": lambda: state.task_summary(addr),
            "/api/request_summary": lambda: state.request_summary(addr),
            "/api/alerts": lambda: state.alerts(addr),
            "/api/profile": lambda: state.profile(
                duration_s=(
                    float(qs["duration_s"]) if qs.get("duration_s") else 5.0
                ),
                hz=float(qs["hz"]) if qs.get("hz") else 99.0,
                address=addr,
            ),
            "/api/stacks": lambda: state.stacks(
                address=addr, node=qs.get("node"),
            ),
            "/api/crash_reports": lambda: state.crash_reports(
                address=addr,
                pid=int(qs["pid"]) if qs.get("pid") else None,
                node=qs.get("node"),
            ),
            "/api/metrics/history": lambda: state.metrics_history(
                name=qs.get("name"),
                tags=json.loads(qs["tags"]) if qs.get("tags") else None,
                window_s=float(qs["window_s"]) if qs.get("window_s") else None,
                step_s=float(qs["step_s"]) if qs.get("step_s") else None,
                address=addr,
            ),
        }
        if path in apis:
            return (
                200, "application/json",
                json.dumps(apis[path](), default=str).encode(),
            )
        if path.startswith("/api/jobs/"):
            return self._route_job_get(path)
        if path == "/metrics":
            text = metrics_mod.prometheus_text(state.cluster_metrics(addr))
            return 200, "text/plain; version=0.0.4", text.encode()
        if path in ("/", "/index.html"):
            st = state.cluster_status(addr)
            page = _PAGE.format(
                status=html.escape(json.dumps(st, indent=2)),
                nodes=_table(
                    state.list_nodes(addr),
                    ["node_id", "address", "alive", "active_leases",
                     "pending_leases"],
                ),
                actors=_table(
                    state.list_actors(addr),
                    ["actor_id", "class_name", "state", "name"],
                ),
                jobs=_table(
                    state.list_jobs(addr), ["job_id", "alive"],
                ),
            )
            return 200, "text/html", page.encode()
        return 404, "application/json", b'{"error": "not found"}'

    # -- job submission REST API (reference dashboard job module:
    # python/ray/dashboard/modules/job/job_manager.py:62 — submit/
    # status/logs/stop over HTTP, so `curl` and CI drive jobs with no
    # in-process client) -----------------------------------------------

    def _job_client(self):
        from ray_tpu.job_submission import JobSubmissionClient

        if getattr(self, "_jobs_client", None) is None:
            self._jobs_client = JobSubmissionClient()
        return self._jobs_client

    def _route_job_get(self, path: str):
        parts = [p for p in path.split("/") if p]  # api, jobs, sid, [sub]
        client = self._job_client()
        if parts == ["api", "jobs", "submissions"]:
            return (
                200, "application/json",
                json.dumps(client.list_jobs(), default=str).encode(),
            )
        sid = parts[2]
        if len(parts) == 3:
            return (
                200, "application/json",
                json.dumps(client.get_job_info(sid), default=str).encode(),
            )
        if len(parts) == 4 and parts[3] == "logs":
            return (
                200, "application/json",
                json.dumps({"logs": client.get_job_logs(sid)}).encode(),
            )
        return 404, "application/json", b'{"error": "not found"}'

    def _route_post(self, path: str, payload: bytes):
        parts = [p for p in path.split("/") if p]
        if parts[:2] != ["api", "jobs"]:
            return 404, "application/json", b'{"error": "not found"}'
        client = self._job_client()
        if len(parts) == 2:  # POST /api/jobs — submit
            body = json.loads(payload or b"{}")
            entrypoint = body.get("entrypoint")
            if not entrypoint:
                return (
                    400, "application/json",
                    b'{"error": "entrypoint required"}',
                )
            sid = client.submit_job(
                entrypoint=entrypoint,
                submission_id=body.get("submission_id"),
                runtime_env=body.get("runtime_env"),
            )
            return (
                200, "application/json",
                json.dumps({"submission_id": sid}).encode(),
            )
        if len(parts) == 4 and parts[3] == "stop":
            stopped = client.stop_job(parts[2])
            return (
                200, "application/json",
                json.dumps({"stopped": bool(stopped)}).encode(),
            )
        if len(parts) == 4 and parts[3] == "delete":
            deleted = client.delete_job(parts[2])
            return (
                200, "application/json",
                json.dumps({"deleted": bool(deleted)}).encode(),
            )
        return 404, "application/json", b'{"error": "not found"}'


def start_dashboard(control_address: Optional[str] = None,
                    port: int = 0) -> Dashboard:
    if control_address is None:
        from ray_tpu.core import worker as worker_mod

        control_address = worker_mod.global_worker().control_address
    dash = Dashboard(control_address, port=port)
    dash.start()
    return dash
