"""Search spaces + trial generation.

Parity: ray.tune search-space API (reference python/ray/tune/search/ —
sample.py domains, BasicVariantGenerator grid/random expansion).
"""

from __future__ import annotations

import random
from typing import Any, Dict, List, Optional, Sequence


class Domain:
    def sample(self, rng: random.Random) -> Any:
        raise NotImplementedError


class Uniform(Domain):
    def __init__(self, low: float, high: float):
        self.low, self.high = low, high

    def sample(self, rng):
        return rng.uniform(self.low, self.high)


class LogUniform(Domain):
    def __init__(self, low: float, high: float):
        import math

        self._lo, self._hi = math.log(low), math.log(high)

    def sample(self, rng):
        import math

        return math.exp(rng.uniform(self._lo, self._hi))


class RandInt(Domain):
    def __init__(self, low: int, high: int):
        self.low, self.high = low, high

    def sample(self, rng):
        return rng.randrange(self.low, self.high)


class Choice(Domain):
    def __init__(self, options: Sequence[Any]):
        self.options = list(options)

    def sample(self, rng):
        return rng.choice(self.options)


class GridSearch:
    """Marker: expands the cross-product instead of sampling."""

    def __init__(self, values: Sequence[Any]):
        self.values = list(values)


def uniform(low: float, high: float) -> Uniform:
    return Uniform(low, high)


def loguniform(low: float, high: float) -> LogUniform:
    return LogUniform(low, high)


def randint(low: int, high: int) -> RandInt:
    return RandInt(low, high)


def choice(options: Sequence[Any]) -> Choice:
    return Choice(options)


def grid_search(values: Sequence[Any]) -> GridSearch:
    return GridSearch(values)


def generate_trials(
    param_space: Dict[str, Any],
    num_samples: int,
    seed: Optional[int] = None,
) -> List[Dict[str, Any]]:
    """Expand grid_search dims into their cross-product; sample Domain
    dims num_samples times per grid point (reference BasicVariantGenerator
    semantics)."""
    rng = random.Random(seed)
    grid_keys = [k for k, v in param_space.items() if isinstance(v, GridSearch)]
    grids: List[Dict[str, Any]] = [{}]
    for k in grid_keys:
        grids = [
            {**g, k: v} for g in grids for v in param_space[k].values
        ]
    trials = []
    for g in grids:
        for _ in range(num_samples):
            cfg = dict(g)
            for k, v in param_space.items():
                if k in cfg:
                    continue
                cfg[k] = v.sample(rng) if isinstance(v, Domain) else v
            trials.append(cfg)
    return trials
