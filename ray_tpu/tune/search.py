"""Search spaces + trial generation.

Parity: ray.tune search-space API (reference python/ray/tune/search/ —
sample.py domains, BasicVariantGenerator grid/random expansion).
"""

from __future__ import annotations

import math
import random
from typing import Any, Dict, List, Optional, Sequence


class Domain:
    def sample(self, rng: random.Random) -> Any:
        raise NotImplementedError


class Uniform(Domain):
    def __init__(self, low: float, high: float):
        self.low, self.high = low, high

    def sample(self, rng):
        return rng.uniform(self.low, self.high)


class LogUniform(Domain):
    def __init__(self, low: float, high: float):
        import math

        self._lo, self._hi = math.log(low), math.log(high)

    def sample(self, rng):
        import math

        return math.exp(rng.uniform(self._lo, self._hi))


class RandInt(Domain):
    def __init__(self, low: int, high: int):
        self.low, self.high = low, high

    def sample(self, rng):
        return rng.randrange(self.low, self.high)


class Choice(Domain):
    def __init__(self, options: Sequence[Any]):
        self.options = list(options)

    def sample(self, rng):
        return rng.choice(self.options)


class GridSearch:
    """Marker: expands the cross-product instead of sampling."""

    def __init__(self, values: Sequence[Any]):
        self.values = list(values)


def uniform(low: float, high: float) -> Uniform:
    return Uniform(low, high)


def loguniform(low: float, high: float) -> LogUniform:
    return LogUniform(low, high)


def randint(low: int, high: int) -> RandInt:
    return RandInt(low, high)


def choice(options: Sequence[Any]) -> Choice:
    return Choice(options)


def grid_search(values: Sequence[Any]) -> GridSearch:
    return GridSearch(values)


def generate_trials(
    param_space: Dict[str, Any],
    num_samples: int,
    seed: Optional[int] = None,
) -> List[Dict[str, Any]]:
    """Expand grid_search dims into their cross-product; sample Domain
    dims num_samples times per grid point (reference BasicVariantGenerator
    semantics)."""
    rng = random.Random(seed)
    grid_keys = [k for k, v in param_space.items() if isinstance(v, GridSearch)]
    grids: List[Dict[str, Any]] = [{}]
    for k in grid_keys:
        grids = [
            {**g, k: v} for g in grids for v in param_space[k].values
        ]
    trials = []
    for g in grids:
        for _ in range(num_samples):
            cfg = dict(g)
            for k, v in param_space.items():
                if k in cfg:
                    continue
                cfg[k] = v.sample(rng) if isinstance(v, Domain) else v
            trials.append(cfg)
    return trials


class TPESearcher:
    """Model-based search: Tree-structured Parzen Estimator, no external
    deps (the role optuna's TPESampler plays for the reference,
    python/ray/tune/search/optuna/optuna_search.py:1; algorithm per
    Bergstra et al. 2011, per-dimension independent factorization like
    hyperopt's default).

    After ``n_startup`` random trials, completed trials split at the
    ``gamma`` quantile into good/bad sets. Per dimension, candidates are
    drawn from a kernel density over the GOOD values (bad-set density in
    the denominator), and the candidate maximizing l(x)/g(x) is chosen —
    categorical dims use smoothed count ratios instead of kernels.
    """

    def __init__(self, metric: str, mode: str = "max",
                 n_startup: int = 10, gamma: float = 0.25,
                 n_candidates: int = 24, seed: Optional[int] = None):
        if mode not in ("min", "max"):
            raise ValueError("mode must be 'min' or 'max'")
        self.metric = metric
        self.mode = mode
        self.n_startup = n_startup
        self.gamma = gamma
        self.n_candidates = n_candidates
        self._rng = random.Random(seed)
        self._space: Dict[str, Any] = {}
        self._observed: List[Any] = []  # (score_minimized, config)
        self._suggested: Dict[str, Dict[str, Any]] = {}

    def set_search_space(self, param_space: Dict[str, Any]) -> None:
        bad = [k for k, v in param_space.items() if isinstance(v, GridSearch)]
        if bad:
            raise ValueError(
                f"TPESearcher cannot optimize grid_search dimensions {bad}; "
                "use a Domain (uniform/loguniform/randint/choice) instead"
            )
        self._space = dict(param_space)

    # -- searcher protocol ---------------------------------------------

    def suggest(self, trial_id: str) -> Dict[str, Any]:
        if len(self._observed) < self.n_startup:
            cfg = {
                k: (v.sample(self._rng) if isinstance(v, Domain) else v)
                for k, v in self._space.items()
            }
        else:
            cfg = self._suggest_tpe()
        self._suggested[trial_id] = cfg
        return cfg

    def on_trial_complete(self, trial_id: str,
                          result: Optional[Dict[str, Any]]) -> None:
        cfg = self._suggested.pop(trial_id, None)
        if cfg is None or not result or self.metric not in result:
            return
        score = float(result[self.metric])
        if self.mode == "max":
            score = -score  # normalize to minimization
        self._observed.append((score, cfg))

    # -- TPE core -------------------------------------------------------

    def _split(self):
        ordered = sorted(self._observed, key=lambda sc: sc[0])
        n_good = max(1, int(math.ceil(self.gamma * len(ordered))))
        good = [c for _, c in ordered[:n_good]]
        bad = [c for _, c in ordered[n_good:]] or good
        return good, bad

    def _suggest_tpe(self) -> Dict[str, Any]:
        good, bad = self._split()
        cfg: Dict[str, Any] = {}
        for key, dom in self._space.items():
            if isinstance(dom, Choice):
                cfg[key] = self._pick_categorical(key, dom, good, bad)
            elif isinstance(dom, (Uniform, LogUniform, RandInt)):
                cfg[key] = self._pick_numeric(key, dom, good, bad)
            elif isinstance(dom, Domain):
                cfg[key] = dom.sample(self._rng)
            else:
                cfg[key] = dom
        return cfg

    def _pick_categorical(self, key, dom: "Choice", good, bad):
        def weights(rows):
            counts = {i: 1.0 for i in range(len(dom.options))}  # +1 smooth
            for c in rows:
                try:
                    counts[dom.options.index(c[key])] += 1.0
                except (ValueError, KeyError):
                    pass
            total = sum(counts.values())
            return {i: v / total for i, v in counts.items()}

        wl, wg = weights(good), weights(bad)
        best = max(range(len(dom.options)), key=lambda i: wl[i] / wg[i])
        return dom.options[best]

    def _pick_numeric(self, key, dom, good, bad):
        to_x, from_x, lo, hi = self._transform(dom)
        if hi - lo <= 0:
            return from_x(lo)  # degenerate (pinned) dimension
        gx = [to_x(c[key]) for c in good if key in c]
        bx = [to_x(c[key]) for c in bad if key in c]
        if not gx:
            return dom.sample(self._rng)
        span = hi - lo
        bw_g = max(span / max(1.0, math.sqrt(len(gx))), 1e-6 * span)
        bw_b = max(span / max(1.0, math.sqrt(len(bx) or 1)), 1e-6 * span)

        def density(x, centers, bw):
            if not centers:
                return 1.0 / span  # uniform prior
            s = sum(
                math.exp(-0.5 * ((x - c) / bw) ** 2) for c in centers
            )
            return s / (len(centers) * bw * math.sqrt(2 * math.pi)) + 1e-12

        best_x, best_ratio = None, -1.0
        for _ in range(self.n_candidates):
            center = self._rng.choice(gx)
            x = min(max(self._rng.gauss(center, bw_g), lo), hi)
            ratio = density(x, gx, bw_g) / density(x, bx, bw_b)
            if ratio > best_ratio:
                best_x, best_ratio = x, ratio
        return from_x(best_x)

    def _transform(self, dom):
        if isinstance(dom, LogUniform):
            # LogUniform stores its bounds pre-logged (_lo/_hi)
            return (math.log, math.exp, dom._lo, dom._hi)
        if isinstance(dom, RandInt):
            return (
                float,
                lambda x: int(min(max(round(x), dom.low), dom.high - 1)),
                float(dom.low), float(dom.high - 1),
            )
        return (float, float, float(dom.low), float(dom.high))
