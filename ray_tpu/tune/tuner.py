"""Tuner + trial controller.

Parity: ray.tune Tuner (reference python/ray/tune/tuner.py:43) and
TuneController (tune/execution/tune_controller.py:67 — event-loop step
:665, trial-actor scheduling :963): trials run in actors, the controller
polls their buffered results, feeds the scheduler, and stops losers
early; per-trial checkpoints land under the run dir.
"""

from __future__ import annotations

import logging
import os
import time
import uuid
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.tune import schedulers as sched_mod
from ray_tpu.tune.search import generate_trials
from ray_tpu.utils import serialization

logger = logging.getLogger(__name__)


class TuneConfig:
    def __init__(
        self,
        metric: str = "score",
        mode: str = "max",
        num_samples: int = 1,
        max_concurrent_trials: int = 4,
        scheduler=None,
        seed: Optional[int] = None,
        search_alg=None,
        max_failures: int = 0,
    ):
        if mode not in ("min", "max"):
            raise ValueError("mode must be 'min' or 'max'")
        self.metric = metric
        self.mode = mode
        self.num_samples = num_samples
        self.max_concurrent_trials = max_concurrent_trials
        self.scheduler = scheduler or sched_mod.FIFOScheduler()
        self.seed = seed
        # model-based search (e.g. search.TPESearcher): configs are
        # SUGGESTED one at a time from completed-trial history instead of
        # pre-sampled (reference: tune search_alg / optuna integration)
        self.search_alg = search_alg
        # trial fault tolerance: a trial whose runner dies is relaunched
        # from its latest checkpoint up to this many times (reference
        # FailureConfig(max_failures))
        self.max_failures = max_failures


class TrialResult:
    def __init__(self, trial_id: str, config: Dict[str, Any]):
        self.trial_id = trial_id
        self.config = config
        self.metrics: Optional[Dict[str, Any]] = None  # last report
        self.all_reports: List[Dict[str, Any]] = []
        self.error: Optional[str] = None
        self.stopped_early = False
        self.checkpoint_path: Optional[str] = None
        self.exploited_from: Optional[str] = None  # PBT clone source

    def __repr__(self):
        return (
            f"TrialResult({self.trial_id}, metrics={self.metrics}, "
            f"stopped_early={self.stopped_early}, error={self.error})"
        )


class ResultGrid:
    def __init__(self, results: List[TrialResult], metric: str, mode: str):
        self._results = results
        self._metric = metric
        self._mode = mode

    def __iter__(self):
        return iter(self._results)

    def __len__(self):
        return len(self._results)

    def __getitem__(self, i):
        return self._results[i]

    def get_best_result(self) -> TrialResult:
        scored = [
            r for r in self._results
            if r.metrics and self._metric in r.metrics
        ]
        if not scored:
            raise ValueError("no trial reported the target metric")
        return (max if self._mode == "max" else min)(
            scored, key=lambda r: r.metrics[self._metric]
        )

    @property
    def num_errors(self) -> int:
        return sum(1 for r in self._results if r.error)


@ray_tpu.remote
class _TrialRunner:
    """Hosts one trial; buffers reports for the controller to drain."""

    def __init__(self, fn_blob: bytes, config: Dict[str, Any], trial_dir: str,
                 restore_from: Optional[str] = None):
        import threading

        from ray_tpu.tune import session

        self._fn = serialization.loads(fn_blob)
        self._config = config
        self._trial_dir = trial_dir
        self._restore_from = restore_from
        self._reports: List[Dict[str, Any]] = []
        self._lock = threading.Lock()
        self._done = False
        self._error: Optional[str] = None
        self._session = session

    def run(self) -> bool:
        """Executes the trainable to completion (or until killed)."""
        import inspect

        from ray_tpu.tune import session
        from ray_tpu.tune.trainable import Trainable

        session._set(
            self._on_report, self._trial_dir, self._config,
            restore_from=self._restore_from,
        )
        try:
            if inspect.isclass(self._fn) and issubclass(self._fn, Trainable):
                self._run_class_trainable()
            else:
                self._fn(self._config)
            return True
        except Exception as e:  # noqa: BLE001
            with self._lock:
                self._error = f"{type(e).__name__}: {e}"
            raise
        finally:
            with self._lock:
                self._done = True
            session._set(None, None, None)

    def _run_class_trainable(self) -> None:
        """Drive a Trainable subclass: setup → step loop, reporting each
        step with an auto-checkpoint (PBT exploits restore from these)."""
        from ray_tpu.tune import session

        inst = self._fn()
        inst.config = dict(self._config)
        inst.setup(self._config)
        if self._restore_from is not None:
            state = session.load_checkpoint(self._restore_from)
            inst.load_checkpoint(state.get("trainable_state", state))
            inst.iteration = state.get("_iteration", inst.iteration)
        try:
            while True:
                metrics = inst.step() or {}
                inst.iteration += 1
                session.report(
                    dict(metrics),
                    checkpoint={
                        "trainable_state": inst.save_checkpoint(),
                        "_iteration": inst.iteration,
                    },
                )
                if metrics.get("done"):
                    return
        finally:
            inst.cleanup()

    def _on_report(self, metrics: Dict[str, Any]) -> None:
        with self._lock:
            self._reports.append(metrics)

    def drain(self, cursor: int = 0):
        """Reports from `cursor` onward. NON-destructive: a reply that the
        controller times out on and discards is re-fetched by the next
        drain (the cursor only advances after a delivered reply)."""
        with self._lock:
            return {
                "reports": self._reports[cursor:],
                "done": self._done,
                "error": self._error,
            }


class Tuner:
    def __init__(
        self,
        trainable: Callable[[Dict[str, Any]], None],
        *,
        param_space: Dict[str, Any],
        tune_config: Optional[TuneConfig] = None,
        run_dir: Optional[str] = None,
    ):
        self._trainable = trainable
        self._param_space = param_space
        self._cfg = tune_config or TuneConfig()
        self._run_dir = run_dir or os.path.join(
            "/tmp/ray_tpu", "tune", f"run_{uuid.uuid4().hex[:8]}"
        )

    def _latest_checkpoint(self, tid: str) -> Optional[str]:
        trial_dir = os.path.join(self._run_dir, tid)
        if not os.path.isdir(trial_dir):
            return None
        ckpts = sorted(
            d for d in os.listdir(trial_dir) if d.startswith("checkpoint_")
        )
        return os.path.join(trial_dir, ckpts[-1]) if ckpts else None

    def fit(self) -> ResultGrid:
        cfg = self._cfg
        if cfg.search_alg is not None:
            cfg.search_alg.set_search_space(self._param_space)
            pending = [
                (f"trial_{i:04d}", None) for i in range(cfg.num_samples)
            ]
        else:
            configs = generate_trials(
                self._param_space, cfg.num_samples, seed=cfg.seed
            )
            pending = [
                (f"trial_{i:04d}", c) for i, c in enumerate(configs)
            ]
        fn_blob = serialization.dumps_function(self._trainable)
        results = {
            tid: TrialResult(tid, c) for tid, c in pending if c is not None
        }
        running: Dict[str, Dict[str, Any]] = {}  # tid -> {actor, run_ref}
        os.makedirs(self._run_dir, exist_ok=True)

        from ray_tpu.tune.trainable import trial_resources

        resources = trial_resources(self._trainable) or {}
        if hasattr(cfg.scheduler, "on_trial_add") and cfg.search_alg is None:
            for tid, c in pending:
                cfg.scheduler.on_trial_add(tid, c)

        def launch(tid: str, config: Dict[str, Any],
                   restore_from: Optional[str] = None,
                   prev_iter: int = 0) -> None:
            trial_dir = os.path.join(self._run_dir, tid)
            os.makedirs(trial_dir, exist_ok=True)
            # max_concurrency=2: run() occupies one execution thread for
            # the trial's lifetime; drain() needs the other.
            opts: Dict[str, Any] = {"max_concurrency": 2}
            if resources:
                cpus = resources.get("CPU")
                if cpus is not None:
                    opts["num_cpus"] = cpus
                tpus = resources.get("TPU")
                if tpus is not None:
                    opts["num_tpus"] = tpus
                custom = {
                    k: v for k, v in resources.items()
                    if k not in ("CPU", "TPU")
                }
                if custom:
                    opts["resources"] = custom
            actor = _TrialRunner.options(**opts).remote(
                fn_blob, config, trial_dir, restore_from
            )
            running[tid] = {
                "actor": actor,
                "run_ref": actor.run.remote(),
                "iter": prev_iter,
                "cursor": 0,
            }

        def finish(tid: str, stopped_early: bool = False,
                   error: Optional[str] = None) -> None:
            rec = running.pop(tid)
            res = results[tid]
            if cfg.search_alg is not None:
                cfg.search_alg.on_trial_complete(tid, res.metrics)
            res.stopped_early = stopped_early
            if error:
                res.error = error
            try:
                ray_tpu.kill(rec["actor"])
            except Exception:  # noqa: BLE001
                pass
            ckpts = sorted(
                d for d in os.listdir(os.path.join(self._run_dir, tid))
                if d.startswith("checkpoint_")
            ) if os.path.isdir(os.path.join(self._run_dir, tid)) else []
            if ckpts:
                res.checkpoint_path = os.path.join(self._run_dir, tid, ckpts[-1])

        while pending or running:
            while pending and len(running) < cfg.max_concurrent_trials:
                tid, config = pending.pop(0)
                if config is None:  # model-based: suggest from history
                    config = cfg.search_alg.suggest(tid)
                    results[tid] = TrialResult(tid, config)
                    if hasattr(cfg.scheduler, "on_trial_add"):
                        cfg.scheduler.on_trial_add(tid, config)
                launch(tid, config)
            time.sleep(0.1)
            for tid in list(running):
                rec = running[tid]
                try:
                    # Short per-poll timeout: a wedged runner must not
                    # head-of-line block the serial poll loop; the miss
                    # budget (~2min) decides wedged-vs-slow. The cursor
                    # makes a timed-out-then-completed drain harmless —
                    # its reports are re-fetched next round.
                    state = ray_tpu.get(
                        rec["actor"].drain.remote(rec["cursor"]), timeout=5
                    )
                    rec["drain_misses"] = 0
                    rec["cursor"] += len(state["reports"])
                except ray_tpu.exceptions.GetTimeoutError:
                    rec["drain_misses"] = rec.get("drain_misses", 0) + 1
                    if rec["drain_misses"] >= 24:
                        finish(tid, error="trial runner unresponsive")
                    continue
                except Exception as e:  # noqa: BLE001 — runner died
                    failures = rec.get("failures", 0)
                    ckpt = self._latest_checkpoint(tid)
                    if failures < cfg.max_failures:
                        # trial FT (reference FailureConfig + tune
                        # controller restore, tune_controller.py:1691):
                        # relaunch from the latest checkpoint
                        logger.warning(
                            "trial %s runner died (%s); restoring from %s "
                            "(failure %d/%d)",
                            tid, e, ckpt, failures + 1, cfg.max_failures,
                        )
                        # no checkpoint yet -> fresh restart (reference
                        # FailureConfig restarts from scratch then)
                        prev_iter = rec["iter"] if ckpt is not None else 0
                        running.pop(tid)
                        try:
                            ray_tpu.kill(rec["actor"])
                        except Exception:  # noqa: BLE001
                            pass
                        launch(tid, results[tid].config,
                               restore_from=ckpt, prev_iter=prev_iter)
                        running[tid]["failures"] = failures + 1
                        continue
                    finish(tid, error=f"trial runner died: {e}")
                    continue
                res = results[tid]
                decision = sched_mod.CONTINUE
                for report in state["reports"]:
                    rec["iter"] += 1
                    report.setdefault("training_iteration", rec["iter"])
                    res.all_reports.append(report)
                    res.metrics = report
                    decision = cfg.scheduler.on_result(tid, report)
                    if decision != sched_mod.CONTINUE:
                        break
                if isinstance(decision, tuple) and decision[0] == "EXPLOIT":
                    # PBT: clone a top trial's checkpoint, restart with
                    # mutated hyperparameters (reference pbt.py exploit)
                    _, src_tid, new_config = decision
                    src_ckpt = self._latest_checkpoint(src_tid)
                    if src_ckpt is not None:
                        logger.info(
                            "PBT: trial %s exploits %s (new config %s)",
                            tid, src_tid, new_config,
                        )
                        prev_iter = rec["iter"]
                        rec_old = running.pop(tid)
                        try:
                            ray_tpu.kill(rec_old["actor"])
                        except Exception:  # noqa: BLE001
                            pass
                        res.config = dict(new_config)
                        res.exploited_from = src_tid
                        launch(tid, new_config, restore_from=src_ckpt,
                               prev_iter=prev_iter)
                        continue
                    decision = sched_mod.CONTINUE  # no ckpt yet: carry on
                if state["done"] or state["error"]:
                    # drain any error; natural completion
                    finish(tid, error=state["error"])
                elif decision == sched_mod.STOP:
                    logger.info("early-stopping trial %s", tid)
                    finish(tid, stopped_early=True)
        return ResultGrid(
            list(results.values()), cfg.metric, cfg.mode
        )
