"""Class Trainable API (parity: reference python/ray/tune/trainable/).

A ``Trainable`` subclass gives the controller step-level control: the
trial runner drives ``setup → step → step → ...``, reporting each step's
metrics, checkpointing via ``save_checkpoint`` (used by PBT exploits),
and restoring via ``load_checkpoint`` when a trial is cloned or resumed.
"""

from __future__ import annotations

from typing import Any, Dict, Optional


class Trainable:
    def __init__(self):
        self.config: Dict[str, Any] = {}
        self.iteration = 0

    # -- subclass surface (reference Trainable API) ---------------------

    def setup(self, config: Dict[str, Any]) -> None:
        """One-time initialization with the trial's (possibly mutated)
        hyperparameters."""

    def step(self) -> Dict[str, Any]:
        """One training iteration; returns the metrics to report. Return
        a dict containing ``{"done": True}`` to finish the trial."""
        raise NotImplementedError

    def save_checkpoint(self) -> Dict[str, Any]:
        """Serializable trial state (weights + counters)."""
        return {}

    def load_checkpoint(self, state: Dict[str, Any]) -> None:
        """Restore from ``save_checkpoint`` output."""

    def cleanup(self) -> None:
        """Teardown before the trial actor exits."""


def with_resources(trainable: Any, resources: Dict[str, float]) -> Any:
    """Attach per-trial resource requirements (parity:
    tune.with_resources): the trial actor leases these resources, so
    trial concurrency is bounded by cluster capacity, not just
    max_concurrent_trials."""
    trainable.__rt_trial_resources__ = dict(resources)
    return trainable


def trial_resources(trainable: Any) -> Optional[Dict[str, float]]:
    return getattr(trainable, "__rt_trial_resources__", None)
