"""In-trial session API: tune.report(...) / tune.get_trial_dir().

Parity: ray.tune.report (reference tune/trainable/session-style API).
"""

from __future__ import annotations

import os
import threading
from typing import Any, Callable, Dict, Optional

_local = threading.local()


def _set(report_cb: Optional[Callable], trial_dir: Optional[str],
         config: Optional[Dict[str, Any]],
         restore_from: Optional[str] = None) -> None:
    _local.report_cb = report_cb
    _local.trial_dir = trial_dir
    _local.config = config
    _local.restore_from = restore_from


def get_checkpoint() -> Optional[str]:
    """Checkpoint dir to resume from, if this trial was cloned (PBT
    exploit) or restored; None for a fresh trial. Parity:
    ray.tune.get_checkpoint."""
    return getattr(_local, "restore_from", None)


def report(metrics: Optional[Dict[str, Any]] = None,
           checkpoint: Optional[Dict[str, Any]] = None,
           **kwargs: Any) -> None:
    """Record one result for this trial (and optionally persist a
    checkpoint dict under the trial dir). Accepts a metrics dict, bare
    keyword metrics, or both (reference: both tune.report styles)."""
    metrics = {**(metrics or {}), **kwargs}
    cb = getattr(_local, "report_cb", None)
    if cb is None:
        raise RuntimeError("tune.report() called outside a tune trial")
    if checkpoint is not None:
        import pickle

        trial_dir = _local.trial_dir
        step = len(os.listdir(trial_dir)) if os.path.isdir(trial_dir) else 0
        ckpt_dir = os.path.join(trial_dir, f"checkpoint_{step:06d}")
        os.makedirs(ckpt_dir, exist_ok=True)
        with open(os.path.join(ckpt_dir, "state.pkl"), "wb") as f:
            pickle.dump(checkpoint, f)
        metrics = {**metrics, "_checkpoint": ckpt_dir}
    cb(dict(metrics))


def get_trial_dir() -> str:
    d = getattr(_local, "trial_dir", None)
    if d is None:
        raise RuntimeError("not inside a tune trial")
    return d


def load_checkpoint(ckpt_dir: str) -> Dict[str, Any]:
    import pickle

    with open(os.path.join(ckpt_dir, "state.pkl"), "rb") as f:
        return pickle.load(f)
