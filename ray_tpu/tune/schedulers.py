"""Trial schedulers.

Parity: ray.tune schedulers (reference python/ray/tune/schedulers/ —
FIFOScheduler, AsyncHyperBandScheduler/ASHA async_hyperband.py).
"""

from __future__ import annotations

from typing import Dict, List

CONTINUE = "CONTINUE"
STOP = "STOP"


class FIFOScheduler:
    """No early stopping: every trial runs to its own completion."""

    def on_result(self, trial_id: str, result: Dict) -> str:
        return CONTINUE


class ASHAScheduler:
    """Asynchronous Successive Halving (reference async_hyperband.py):
    rungs at grace_period * reduction_factor^k; when a trial first reports
    at/past a rung, its metric joins the rung's record and the trial stops
    unless it is in the rung's top 1/reduction_factor fraction."""

    def __init__(
        self,
        metric: str,
        mode: str = "max",
        max_t: int = 100,
        grace_period: int = 1,
        reduction_factor: int = 3,
        time_attr: str = "training_iteration",
    ):
        if mode not in ("min", "max"):
            raise ValueError(f"mode must be min|max, got {mode!r}")
        self.metric = metric
        self.mode = mode
        self.max_t = max_t
        self.grace_period = grace_period
        self.rf = reduction_factor
        self.time_attr = time_attr
        self.rungs: List[int] = []
        t = grace_period
        while t < max_t:
            self.rungs.append(t)
            t *= reduction_factor
        # rung milestone -> list of recorded metric values
        self._rung_records: Dict[int, List[float]] = {r: [] for r in self.rungs}
        # trial_id -> highest rung already judged
        self._judged: Dict[str, int] = {}

    def on_result(self, trial_id: str, result: Dict) -> str:
        t = result.get(self.time_attr)
        value = result.get(self.metric)
        if t is None or value is None:
            return CONTINUE
        if t >= self.max_t:
            return STOP  # ran to the horizon
        for rung in reversed(self.rungs):
            if t < rung or self._judged.get(trial_id, -1) >= rung:
                continue
            self._judged[trial_id] = rung
            records = self._rung_records[rung]
            records.append(float(value))
            if len(records) < self.rf:
                return CONTINUE  # not enough peers to judge yet
            ordered = sorted(records, reverse=(self.mode == "max"))
            k = max(1, len(ordered) // self.rf)
            cutoff = ordered[k - 1]
            good = value >= cutoff if self.mode == "max" else value <= cutoff
            return CONTINUE if good else STOP
        return CONTINUE
