"""Trial schedulers.

Parity: ray.tune schedulers (reference python/ray/tune/schedulers/ —
FIFOScheduler, AsyncHyperBandScheduler/ASHA async_hyperband.py).
"""

from __future__ import annotations

from typing import Dict, List

CONTINUE = "CONTINUE"
STOP = "STOP"


class FIFOScheduler:
    """No early stopping: every trial runs to its own completion."""

    def on_result(self, trial_id: str, result: Dict) -> str:
        return CONTINUE


class ASHAScheduler:
    """Asynchronous Successive Halving (reference async_hyperband.py):
    rungs at grace_period * reduction_factor^k; when a trial first reports
    at/past a rung, its metric joins the rung's record and the trial stops
    unless it is in the rung's top 1/reduction_factor fraction."""

    def __init__(
        self,
        metric: str,
        mode: str = "max",
        max_t: int = 100,
        grace_period: int = 1,
        reduction_factor: int = 3,
        time_attr: str = "training_iteration",
    ):
        if mode not in ("min", "max"):
            raise ValueError(f"mode must be min|max, got {mode!r}")
        self.metric = metric
        self.mode = mode
        self.max_t = max_t
        self.grace_period = grace_period
        self.rf = reduction_factor
        self.time_attr = time_attr
        self.rungs: List[int] = []
        t = grace_period
        while t < max_t:
            self.rungs.append(t)
            t *= reduction_factor
        # rung milestone -> list of recorded metric values
        self._rung_records: Dict[int, List[float]] = {r: [] for r in self.rungs}
        # trial_id -> highest rung already judged
        self._judged: Dict[str, int] = {}

    def on_result(self, trial_id: str, result: Dict) -> str:
        t = result.get(self.time_attr)
        value = result.get(self.metric)
        if t is None or value is None:
            return CONTINUE
        if t >= self.max_t:
            return STOP  # ran to the horizon
        for rung in reversed(self.rungs):
            if t < rung or self._judged.get(trial_id, -1) >= rung:
                continue
            self._judged[trial_id] = rung
            records = self._rung_records[rung]
            records.append(float(value))
            if len(records) < self.rf:
                return CONTINUE  # not enough peers to judge yet
            ordered = sorted(records, reverse=(self.mode == "max"))
            k = max(1, len(ordered) // self.rf)
            cutoff = ordered[k - 1]
            good = value >= cutoff if self.mode == "max" else value <= cutoff
            return CONTINUE if good else STOP
        return CONTINUE


class PopulationBasedTraining:
    """PBT (parity: reference python/ray/tune/schedulers/pbt.py):
    at each perturbation interval a bottom-quantile trial EXPLOITS a
    top-quantile trial — it restarts from the winner's latest checkpoint
    with EXPLORED (mutated) hyperparameters. Decisions are returned to
    the controller as ("EXPLOIT", source_trial_id, mutated_config); the
    controller performs the clone/restart (tuner.py)."""

    def __init__(
        self,
        metric: str,
        mode: str = "max",
        perturbation_interval: int = 4,
        hyperparam_mutations: Dict = None,
        quantile_fraction: float = 0.25,
        resample_probability: float = 0.25,
        time_attr: str = "training_iteration",
        seed: int = 0,
    ):
        if mode not in ("min", "max"):
            raise ValueError(f"mode must be min|max, got {mode!r}")
        if not hyperparam_mutations:
            raise ValueError("hyperparam_mutations is required for PBT")
        import random as _random

        self.metric = metric
        self.mode = mode
        self.interval = perturbation_interval
        self.mutations = dict(hyperparam_mutations)
        self.quantile = quantile_fraction
        self.resample_p = resample_probability
        self.time_attr = time_attr
        self._rng = _random.Random(seed)
        self._configs: Dict[str, Dict] = {}
        self._scores: Dict[str, float] = {}
        self._last_perturb: Dict[str, int] = {}
        self.exploit_count = 0  # observability / tests

    # controller hooks ---------------------------------------------------

    def on_trial_add(self, trial_id: str, config: Dict) -> None:
        self._configs[trial_id] = dict(config)
        self._last_perturb[trial_id] = 0

    def _explore(self, config: Dict) -> Dict:
        out = dict(config)
        for key, spec in self.mutations.items():
            if isinstance(spec, list):
                out[key] = self._rng.choice(spec)
            elif callable(spec) and not hasattr(spec, "sample"):
                out[key] = spec()
            elif hasattr(spec, "sample"):  # search-space Domain
                out[key] = spec.sample(self._rng)
            elif isinstance(out.get(key), (int, float)):
                factor = 1.2 if self._rng.random() > 0.5 else 0.8
                out[key] = type(out[key])(out[key] * factor)
        return out

    def on_result(self, trial_id: str, result: Dict):
        t = result.get(self.time_attr)
        value = result.get(self.metric)
        if t is None or value is None:
            return CONTINUE
        self._scores[trial_id] = float(value)
        if t - self._last_perturb.get(trial_id, 0) < self.interval:
            return CONTINUE
        self._last_perturb[trial_id] = t
        peers = sorted(
            self._scores.items(), key=lambda kv: kv[1],
            reverse=(self.mode == "max"),
        )
        if len(peers) < 2:
            return CONTINUE
        k = max(1, int(len(peers) * self.quantile))
        top = [tid for tid, _ in peers[:k]]
        bottom = {tid for tid, _ in peers[-k:]}
        if trial_id not in bottom or trial_id in top:
            return CONTINUE
        source = self._rng.choice(top)
        base = self._configs.get(source, self._configs.get(trial_id, {}))
        if self._rng.random() < self.resample_p:
            new_config = self._explore(self._explore(base))
        else:
            new_config = self._explore(base)
        self._configs[trial_id] = dict(new_config)
        self.exploit_count += 1
        return ("EXPLOIT", source, new_config)


class HyperBandScheduler:
    """HyperBand (asynchronous-bracket formulation): trials are assigned
    round-robin to brackets whose grace periods span
    ``grace_period * rf^k`` up to max_t, and each bracket runs ASHA-style
    successive halving at its own rungs. This is the multi-bracket
    generalization of ASHA the HyperBand paper reduces to under async
    arrival (the role the reference's hyperband.py / hb_bohb.py family
    plays; the synchronous cohort barrier is deliberately dropped — it
    wastes cluster time waiting for stragglers and can deadlock with
    early-stopped trials)."""

    def __init__(
        self,
        metric: str,
        mode: str = "max",
        max_t: int = 81,
        grace_period: int = 1,
        reduction_factor: int = 3,
        time_attr: str = "training_iteration",
    ):
        self.brackets: List[ASHAScheduler] = []
        t = grace_period
        while t <= max_t:
            self.brackets.append(
                ASHAScheduler(
                    metric, mode=mode, max_t=max_t, grace_period=t,
                    reduction_factor=reduction_factor, time_attr=time_attr,
                )
            )
            t *= reduction_factor
        self._assignment: Dict[str, int] = {}
        self._next = 0

    def on_trial_add(self, trial_id: str, config: Dict) -> None:
        self._assignment[trial_id] = self._next % len(self.brackets)
        self._next += 1

    def _bracket(self, trial_id: str) -> ASHAScheduler:
        idx = self._assignment.get(trial_id)
        if idx is None:  # trial added without on_trial_add (restore path)
            idx = self._next % len(self.brackets)
            self._assignment[trial_id] = idx
            self._next += 1
        return self.brackets[idx]

    def on_result(self, trial_id: str, result: Dict) -> str:
        return self._bracket(trial_id).on_result(trial_id, result)
