"""ray_tpu.tune — hyperparameter tuning.

Parity target: Ray Tune (reference python/ray/tune — Tuner + trial
controller over actors, search spaces, ASHA early stopping, per-trial
checkpoints).
"""

from ray_tpu.tune.schedulers import (
    ASHAScheduler,
    FIFOScheduler,
    HyperBandScheduler,
    PopulationBasedTraining,
)
from ray_tpu.tune.trainable import Trainable, with_resources
from ray_tpu.tune.search import (
    TPESearcher,
    choice,
    grid_search,
    loguniform,
    randint,
    uniform,
)
from ray_tpu.tune.session import (
    get_checkpoint,
    get_trial_dir,
    load_checkpoint,
    report,
)
from ray_tpu.tune.tuner import ResultGrid, TrialResult, TuneConfig, Tuner

__all__ = [
    "ASHAScheduler",
    "HyperBandScheduler",
    "TPESearcher",
    "PopulationBasedTraining",
    "Trainable",
    "get_checkpoint",
    "with_resources",
    "FIFOScheduler",
    "ResultGrid",
    "TrialResult",
    "TuneConfig",
    "Tuner",
    "choice",
    "get_trial_dir",
    "grid_search",
    "load_checkpoint",
    "loguniform",
    "randint",
    "report",
    "uniform",
]
