"""Request router — power-of-two-choices replica selection.

Parity: the reference Router + PowerOfTwoChoicesRequestRouter
(python/ray/serve/_private/router.py:473, request_router/pow_2_router.py):
sample two replicas, pick the one with the smaller known queue; queue
lengths come from the controller's routing table, refreshed by version
polling (long-poll-lite) plus a local in-flight delta so bursts spread
before the next refresh.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Any, Dict, Optional

import ray_tpu
from ray_tpu.core.actor import ActorHandle
from ray_tpu.observability import core_metrics

ROUTE_REFRESH_S = 1.0


class Router:
    def __init__(self, controller: Any):
        self._controller = controller
        self._lock = threading.Lock()
        self._version = -1
        self._table: Dict[str, Dict[str, Any]] = {}
        self._last_refresh = 0.0
        # replica_id -> locally-issued in-flight count (delta on top of
        # the controller-reported ongoing count)
        self._local_inflight: Dict[str, int] = {}
        self._stopped = threading.Event()
        # TOPOLOGY long-poll: replica add/remove/death propagates in ~ms
        # (the controller holds the reply until its version changes)
        # instead of the 1 s ongoing-count refresh cadence — the round-3
        # "router thrashes between refreshes" weakness.
        threading.Thread(
            target=self._topology_longpoll, name="router-longpoll",
            daemon=True,
        ).start()

    def _topology_longpoll(self) -> None:
        while not self._stopped.is_set():
            with self._lock:
                version = self._version
            try:
                reply = ray_tpu.get(
                    self._controller.get_routing_table.remote(version, 20.0),
                    timeout=40,
                )
            except Exception:  # noqa: BLE001 — controller briefly away
                self._stopped.wait(1.0)
                continue
            if reply.get("table") is not None:
                with self._lock:
                    if reply["version"] != self._version:
                        self._version = reply["version"]
                        self._table = reply["table"]
                        self._last_refresh = time.monotonic()

    def _refresh(self, force: bool = False) -> None:
        now = time.monotonic()
        with self._lock:
            if not force and now - self._last_refresh < ROUTE_REFRESH_S:
                return
            version = self._version
        reply = ray_tpu.get(
            self._controller.get_routing_table.remote(version, 0.0),
            timeout=10,
        )
        with self._lock:
            self._last_refresh = time.monotonic()
            if reply["table"] is not None:
                self._version = reply["version"]
                self._table = reply["table"]
                # fresh controller-observed ongoing counts supersede the
                # local deltas (callers that never report completion decay
                # here) — wait_s=0 polls always return a table, so this
                # runs every ROUTE_REFRESH_S
                self._local_inflight.clear()

    def deployment_for_route(self, path: str) -> Optional[str]:
        self._refresh()
        with self._lock:
            best = None
            for name, dep in self._table.items():
                prefix = dep["route_prefix"]
                if path == prefix or path.startswith(prefix.rstrip("/") + "/"):
                    if best is None or len(prefix) > len(
                        self._table[best]["route_prefix"]
                    ):
                        best = name
            return best

    def choose_replica(self, deployment: str, timeout_s: float = 30.0,
                       model_id: Optional[str] = None):
        """Pow-2 choice; blocks (re-polling) until a replica exists.
        With a multiplexed ``model_id``, replicas already holding that
        model are preferred (reference multiplex routing hint) — traffic
        for one model stays warm on one replica instead of thrashing
        every replica's LRU; when nobody holds it, normal pow-2 picks the
        replica that will load it."""
        t0 = time.monotonic()
        deadline = t0 + timeout_s
        while True:
            self._refresh()
            with self._lock:
                dep = self._table.get(deployment)
                replicas = list(dep["replicas"]) if dep else []
                if replicas and model_id:
                    holding = [
                        r for r in replicas
                        if model_id in r.get("model_ids", [])
                    ]
                    if holding:
                        replicas = holding
                if replicas:
                    if len(replicas) == 1:
                        chosen = replicas[0]
                    else:
                        a, b = random.sample(replicas, 2)
                        chosen = min(
                            (a, b),
                            key=lambda r: r["ongoing"]
                            + self._local_inflight.get(r["replica_id"], 0),
                        )
                    rid = chosen["replica_id"]
                    self._local_inflight[rid] = (
                        self._local_inflight.get(rid, 0) + 1
                    )
                    if core_metrics.ENABLED:
                        core_metrics.serve_router_requests.inc(
                            tags={"deployment": deployment}
                        )
                        core_metrics.serve_router_queue_wait_s.observe(
                            time.monotonic() - t0
                        )
                    return rid, ActorHandle(*chosen["handle_info"])
            if time.monotonic() >= deadline:
                raise TimeoutError(
                    f"no replicas available for deployment {deployment!r}"
                )
            self._refresh(force=True)
            time.sleep(0.1)

    def request_finished(self, replica_id: str) -> None:
        with self._lock:
            n = self._local_inflight.get(replica_id, 0) - 1
            if n <= 0:
                self._local_inflight.pop(replica_id, None)
            else:
                self._local_inflight[replica_id] = n

    def assign(self, deployment: str, payload: Any,
               method: Optional[str] = None, timeout_s: float = 30.0,
               model_id: Optional[str] = None):
        """Route one request; returns (replica_id, result ObjectRef)."""
        rid, handle = self.choose_replica(deployment, timeout_s, model_id)
        if method:
            return rid, handle.handle_request.remote(payload, method=method)
        return rid, handle.handle_request.remote(payload)

    def call_streaming(self, deployment: str, payload: Any,
                       method: Optional[str] = None,
                       timeout_s: float = 60.0):
        """Route one request to the replica's streaming entry point and
        yield items as they are produced (core actor streaming
        generators). The in-flight delta is held until the stream is
        exhausted or abandoned."""
        rid, handle = self.choose_replica(deployment, timeout_s)
        try:
            gen = handle.handle_request_streaming.remote(
                payload, method=method
            )
            for item_ref in gen:
                yield ray_tpu.get(item_ref, timeout=timeout_s)
        finally:
            self.request_finished(rid)

    def call(self, deployment: str, payload: Any,
             method: Optional[str] = None, timeout_s: float = 60.0,
             model_id: Optional[str] = None) -> Any:
        """Route + get with retry on replica death: the routing table lags
        replica failures by up to a health-check period, so a request that
        lands on a corpse is transparently re-routed (reference: the
        router's queue-probe failures trigger re-selection)."""
        from ray_tpu.core.exceptions import (
            ActorDiedError,
            ActorUnavailableError,
        )

        deadline = time.monotonic() + timeout_s
        last_exc: Optional[BaseException] = None
        for _ in range(4):
            remaining = max(0.5, deadline - time.monotonic())
            rid, ref = self.assign(
                deployment, payload, method, remaining, model_id
            )
            try:
                return ray_tpu.get(ref, timeout=remaining)
            except (ActorDiedError, ActorUnavailableError) as e:
                last_exc = e
                self._refresh(force=True)
            finally:
                self.request_finished(rid)
            if time.monotonic() >= deadline:
                break
        raise last_exc
