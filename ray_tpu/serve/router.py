"""Request router — power-of-two-choices replica selection.

Parity: the reference Router + PowerOfTwoChoicesRequestRouter
(python/ray/serve/_private/router.py:473, request_router/pow_2_router.py):
sample two replicas, pick the one with the smaller known queue; queue
lengths come from the controller's routing table, refreshed by version
polling (long-poll-lite) plus a local in-flight delta so bursts spread
before the next refresh.

Affinity tiers on top of pow-2:
  - ``model_id``: replicas already holding a multiplexed model are
    preferred (warm-engine affinity, reference multiplex routing);
  - ``session_key``: rendezvous (highest-random-weight) hashing pins a
    session to ONE replica while the replica set is stable — the serve
    LLM path uses the OpenAI ``user`` field so a conversation keeps
    hitting the replica whose KV slots hold its prefix. Replica death
    re-pins only the sessions that lived on the dead replica (the HRW
    property), unlike mod-N hashing which reshuffles everyone.

``call_direct`` is the proxy's hot path: one RPC to the replica's
hosting worker (rpc_actor_direct_call) on PR 3's multi-segment frames +
cached dispatcher pool — no TaskSpec, no return-object round trip
through the owner's memory store. It falls back to the ordinary
actor-task path when the target worker predates the direct handler or
the feature is switched off (config.serve_direct_rpc).
"""

from __future__ import annotations

import random
import threading
import time
import zlib
from typing import Any, Dict, Optional

import ray_tpu
from ray_tpu.core.actor import ActorHandle
from ray_tpu.observability import core_metrics, tracing
from ray_tpu.utils.config import config

ROUTE_REFRESH_S = 1.0


def _trace_id_of(payload: Any) -> Optional[str]:
    """Trace id the proxy injected into the request headers, if the
    payload is header-bearing (serve Request) and tracing stamped one."""
    headers = getattr(payload, "headers", None)
    if headers:
        return headers.get(tracing.TRACE_HEADER)
    return None


class Router:
    def __init__(self, controller: Any):
        self._controller = controller
        self._lock = threading.Lock()
        self._version = -1
        self._table: Dict[str, Dict[str, Any]] = {}
        self._last_refresh = 0.0
        # replica_id -> locally-issued in-flight count (delta on top of
        # the controller-reported ongoing count)
        self._local_inflight: Dict[str, int] = {}
        self._stopped = threading.Event()
        # TOPOLOGY long-poll: replica add/remove/death propagates in ~ms
        # (the controller holds the reply until its version changes)
        # instead of the 1 s ongoing-count refresh cadence — the round-3
        # "router thrashes between refreshes" weakness.
        threading.Thread(
            target=self._topology_longpoll, name="router-longpoll",
            daemon=True,
        ).start()

    def _topology_longpoll(self) -> None:
        while not self._stopped.is_set():
            with self._lock:
                version = self._version
            try:
                reply = ray_tpu.get(
                    self._controller.get_routing_table.remote(version, 20.0),
                    timeout=40,
                )
            except Exception:  # noqa: BLE001 — controller briefly away
                self._stopped.wait(1.0)
                continue
            if reply.get("table") is not None:
                with self._lock:
                    if reply["version"] != self._version:
                        self._version = reply["version"]
                        self._table = reply["table"]
                        self._last_refresh = time.monotonic()

    def _refresh(self, force: bool = False) -> None:
        now = time.monotonic()
        with self._lock:
            if not force and now - self._last_refresh < ROUTE_REFRESH_S:
                return
            version = self._version
        reply = ray_tpu.get(
            self._controller.get_routing_table.remote(version, 0.0),
            timeout=10,
        )
        with self._lock:
            self._last_refresh = time.monotonic()
            if reply["table"] is not None:
                self._version = reply["version"]
                self._table = reply["table"]
                # fresh controller-observed ongoing counts supersede the
                # local deltas (callers that never report completion decay
                # here) — wait_s=0 polls always return a table, so this
                # runs every ROUTE_REFRESH_S
                self._local_inflight.clear()

    def deployment_for_route(self, path: str) -> Optional[str]:
        self._refresh()
        with self._lock:
            best = None
            for name, dep in self._table.items():
                prefix = dep["route_prefix"]
                if path == prefix or path.startswith(prefix.rstrip("/") + "/"):
                    if best is None or len(prefix) > len(
                        self._table[best]["route_prefix"]
                    ):
                        best = name
            return best

    def max_queued_requests(self, deployment: str) -> Optional[int]:
        """Per-deployment admission bound from the routing table
        (@serve.deployment(max_queued_requests=...)); None means the
        global RT_SERVE_ADMISSION_MAX_INFLIGHT applies. Table-shipped so
        every proxy enforces the deploy-time bound without a config
        round-trip."""
        with self._lock:
            dep = self._table.get(deployment)
            if dep is None:
                return None
            return dep.get("max_queued_requests")

    @staticmethod
    def _rendezvous(session_key: str, replicas):
        """Highest-random-weight choice: stable per (session, replica
        set), minimal re-pinning when the set changes."""
        return max(
            replicas,
            key=lambda r: zlib.crc32(
                f"{session_key}\x00{r['replica_id']}".encode()
            ),
        )

    def choose_replica(self, deployment: str, timeout_s: float = 30.0,
                       model_id: Optional[str] = None,
                       session_key: Optional[str] = None,
                       prefix_hint: Optional[str] = None):
        """Pow-2 choice; blocks (re-polling) until a replica exists.
        With a multiplexed ``model_id``, replicas already holding that
        model are preferred (reference multiplex routing hint) — traffic
        for one model stays warm on one replica instead of thrashing
        every replica's LRU; when nobody holds it, normal pow-2 picks the
        replica that will load it. A ``session_key`` overrides both with
        rendezvous hashing over the FULL replica set (KV/session
        affinity): sessions spread across every replica — each loading
        the model on its first session — rather than piling onto
        whichever replica warmed the model first. ``prefix_hint`` (a
        digest of the request's leading prompt text, computed by the
        proxy) rendezvous-hashes the same way when no session pins the
        request: requests sharing a system prompt land on the replica
        whose engine already holds those prefix KV blocks."""
        affinity = session_key or prefix_hint
        t0 = time.monotonic()
        deadline = t0 + timeout_s
        while True:
            self._refresh()
            with self._lock:
                dep = self._table.get(deployment)
                replicas = list(dep["replicas"]) if dep else []
                if replicas and model_id and not affinity:
                    holding = [
                        r for r in replicas
                        if model_id in r.get("model_ids", [])
                    ]
                    if holding:
                        replicas = holding
                if replicas:
                    if affinity:
                        chosen = self._rendezvous(affinity, replicas)
                    elif len(replicas) == 1:
                        chosen = replicas[0]
                    else:
                        a, b = random.sample(replicas, 2)
                        chosen = min(
                            (a, b),
                            key=lambda r: r["ongoing"]
                            + self._local_inflight.get(r["replica_id"], 0),
                        )
                    rid = chosen["replica_id"]
                    self._local_inflight[rid] = (
                        self._local_inflight.get(rid, 0) + 1
                    )
                    if core_metrics.ENABLED:
                        core_metrics.serve_router_requests.inc(
                            tags={"deployment": deployment}
                        )
                        core_metrics.serve_router_queue_wait_s.observe(
                            time.monotonic() - t0
                        )
                    return rid, ActorHandle(*chosen["handle_info"])
            if time.monotonic() >= deadline:
                raise TimeoutError(
                    f"no replicas available for deployment {deployment!r}"
                )
            self._refresh(force=True)
            time.sleep(0.1)

    def try_pick_nowait(self, path: str,
                        model_id: Optional[str] = None,
                        session_key: Optional[str] = None,
                        prefix_hint: Optional[str] = None):
        """Event-loop-safe replica pick: route-match + selection against
        the CURRENT table only — no refresh RPC, no polling, no sleeps.
        Returns (deployment, replica_id, handle) or None when the table
        is stale or has no match (the caller takes the blocking pool
        path, whose choose_replica refreshes for everyone). Staleness
        gating doubles as the ongoing-count refresh driver: at least one
        request per ROUTE_REFRESH_S goes through the refreshing path."""
        with self._lock:
            if time.monotonic() - self._last_refresh >= ROUTE_REFRESH_S:
                return None
            best = None
            for name, dep in self._table.items():
                prefix = dep["route_prefix"]
                if path == prefix or path.startswith(prefix.rstrip("/") + "/"):
                    if best is None or len(prefix) > len(
                        self._table[best]["route_prefix"]
                    ):
                        best = name
            if best is None:
                return None
            replicas = list(self._table[best]["replicas"])
            if not replicas:
                return None
            affinity = session_key or prefix_hint
            if model_id and not affinity:
                holding = [
                    r for r in replicas
                    if model_id in r.get("model_ids", [])
                ]
                if holding:
                    replicas = holding
            if affinity:
                chosen = self._rendezvous(affinity, replicas)
            elif len(replicas) == 1:
                chosen = replicas[0]
            else:
                a, b = random.sample(replicas, 2)
                chosen = min(
                    (a, b),
                    key=lambda r: r["ongoing"]
                    + self._local_inflight.get(r["replica_id"], 0),
                )
            rid = chosen["replica_id"]
            self._local_inflight[rid] = self._local_inflight.get(rid, 0) + 1
            if core_metrics.ENABLED:
                core_metrics.serve_router_requests.inc(
                    tags={"deployment": best}
                )
            return best, rid, ActorHandle(*chosen["handle_info"])

    def request_finished(self, replica_id: str) -> None:
        with self._lock:
            n = self._local_inflight.get(replica_id, 0) - 1
            if n <= 0:
                self._local_inflight.pop(replica_id, None)
            else:
                self._local_inflight[replica_id] = n

    def assign(self, deployment: str, payload: Any,
               method: Optional[str] = None, timeout_s: float = 30.0,
               model_id: Optional[str] = None,
               session_key: Optional[str] = None,
               prefix_hint: Optional[str] = None):
        """Route one request; returns (replica_id, result ObjectRef)."""
        rid, handle = self.choose_replica(
            deployment, timeout_s, model_id, session_key, prefix_hint
        )
        if method:
            return rid, handle.handle_request.remote(payload, method=method)
        return rid, handle.handle_request.remote(payload)

    def call_streaming(self, deployment: str, payload: Any,
                       method: Optional[str] = None,
                       timeout_s: float = 60.0,
                       model_id: Optional[str] = None,
                       session_key: Optional[str] = None,
                       prefix_hint: Optional[str] = None):
        """Route one request to the replica's streaming entry point and
        yield items as they are produced (core actor streaming
        generators). The in-flight delta is held until the stream is
        exhausted or abandoned; an ABANDONED stream (the HTTP client
        disconnected and the proxy closed this generator) cancels the
        replica-side task so the deployment's generator unwinds and the
        LLM engine frees the request's KV slot."""
        tid = _trace_id_of(payload) if tracing.ENABLED else None
        t0u = tracing.now_us() if tid else 0
        rid, handle = self.choose_replica(
            deployment, timeout_s, model_id, session_key, prefix_hint
        )
        if tid and tracing.ENABLED:
            tracing.emit(tracing.request_span(
                tid, tracing.ROUTER, deployment, t0u,
                tracing.now_us() - t0u, replica=rid,
            ))
        gen = None
        exhausted = False
        try:
            gen = handle.handle_request_streaming.remote(
                payload, method=method
            )
            for item_ref in gen:
                yield ray_tpu.get(item_ref, timeout=timeout_s)
            exhausted = True
        finally:
            self.request_finished(rid)
            if gen is not None and not exhausted:
                self._cancel_streaming(handle, gen)

    @staticmethod
    def _cancel_streaming(handle: ActorHandle, gen) -> None:
        """Interrupt an abandoned streaming task on its replica (oneway;
        best effort — a dead replica freed everything anyway)."""
        from ray_tpu.core import worker as worker_mod

        try:
            w = worker_mod.global_worker()
            addr = w._resolve_actor_address(handle._actor_id, timeout_s=5.0)
            w.workers.get(addr).call_oneway(
                "cancel_task", task_id_hex=gen._task_id.hex(), force=False
            )
        except Exception:  # noqa: BLE001 — cancellation is advisory
            pass

    def call(self, deployment: str, payload: Any,
             method: Optional[str] = None, timeout_s: float = 60.0,
             model_id: Optional[str] = None,
             session_key: Optional[str] = None,
             prefix_hint: Optional[str] = None) -> Any:
        """Route + get with retry on replica death: the routing table lags
        replica failures by up to a health-check period, so a request that
        lands on a corpse is transparently re-routed (reference: the
        router's queue-probe failures trigger re-selection)."""
        from ray_tpu.core.exceptions import (
            ActorDiedError,
            ActorUnavailableError,
        )

        deadline = time.monotonic() + timeout_s
        last_exc: Optional[BaseException] = None
        tid = _trace_id_of(payload) if tracing.ENABLED else None
        for _ in range(4):
            remaining = max(0.5, deadline - time.monotonic())
            t0u = tracing.now_us() if tid else 0
            rid, ref = self.assign(
                deployment, payload, method, remaining, model_id,
                session_key, prefix_hint,
            )
            if tid and tracing.ENABLED:
                tracing.emit(tracing.request_span(
                    tid, tracing.ROUTER, deployment, t0u,
                    tracing.now_us() - t0u, replica=rid,
                ))
            try:
                return ray_tpu.get(ref, timeout=remaining)
            except (ActorDiedError, ActorUnavailableError) as e:
                last_exc = e
                self._refresh(force=True)
            finally:
                self.request_finished(rid)
            if time.monotonic() >= deadline:
                break
        raise last_exc

    # -- proxy hot path --------------------------------------------------

    def call_direct(self, deployment: str, payload: Any,
                    method: Optional[str] = None, timeout_s: float = 60.0,
                    model_id: Optional[str] = None,
                    session_key: Optional[str] = None,
                    prefix_hint: Optional[str] = None) -> Any:
        """One-hop request: proxy → the replica's hosting worker over a
        single RPC (rpc_actor_direct_call) instead of the actor-task
        machinery (TaskSpec + submit/reply threads + owner memory store).
        The reply rides the multi-segment wire format, so a Frame-wrapped
        response body ≥32 KiB travels as a raw out-of-band segment.

        Falls back to the ordinary path per-request when the feature is
        off or the target worker predates the handler; connection-level
        failures re-route like call()."""
        from ray_tpu.core import worker as worker_mod
        from ray_tpu.utils.rpc import (
            RpcConnectionError,
            RpcError,
            RpcTimeout,
        )

        if not config.serve_direct_rpc:
            return self.call(
                deployment, payload, method, timeout_s, model_id,
                session_key, prefix_hint,
            )
        w = worker_mod.global_worker()
        deadline = time.monotonic() + timeout_s
        last_exc: Optional[BaseException] = None
        tid = _trace_id_of(payload) if tracing.ENABLED else None
        for _ in range(4):
            remaining = max(0.5, deadline - time.monotonic())
            t0u = tracing.now_us() if tid else 0
            rid, handle = self.choose_replica(
                deployment, remaining, model_id, session_key, prefix_hint
            )
            if tid and tracing.ENABLED:
                tracing.emit(tracing.request_span(
                    tid, tracing.ROUTER, deployment, t0u,
                    tracing.now_us() - t0u, replica=rid,
                ))
            addr = None
            try:
                addr = w._resolve_actor_address(
                    handle._actor_id, timeout_s=remaining
                )
                reply = w.workers.get(addr).call(
                    "actor_direct_call",
                    target="handle_request_direct",
                    args=(payload,),
                    kwargs={"method": method} if method else None,
                    timeout_s=remaining,
                )
            except RpcTimeout:
                # the request may STILL be executing on the replica: do
                # not re-submit (duplicate execution) and do not tear
                # down the shared worker connection — surface it, like
                # the actor-task path's get-timeout
                raise
            except RpcConnectionError as e:
                # replica/worker died (same re-route semantics as
                # call()'s ActorDied/ActorUnavailable retry)
                last_exc = e
                w._actor_addr_cache.pop(handle._actor_id, None)
                if addr is not None:
                    w.workers.drop(addr)
                self._refresh(force=True)
                continue
            except RpcError:
                raise
            finally:
                self.request_finished(rid)
            if reply[0] == "no_actor":
                # mid-restart or pre-direct worker: serve THIS request on
                # the ordinary path (its retry ladder handles the rest)
                return self.call(
                    deployment, payload, method,
                    max(0.5, deadline - time.monotonic()), model_id,
                    session_key, prefix_hint,
                )
            return self._unwrap_direct(reply[1])
        raise last_exc

    @staticmethod
    def _unwrap_direct(wrapped: Any) -> Any:
        """Invert replica.handle_request_direct's wrapping; Frame bodies
        come back as zero-copy memoryviews."""
        from ray_tpu.utils import serialization

        kind, value = wrapped
        if kind == "raw":
            return serialization.as_view(value)
        if kind == "http":
            status, ctype, body = value
            return status, ctype, serialization.as_view(body)
        return value
