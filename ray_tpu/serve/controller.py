"""Serve controller — desired-state reconciler.

Parity: the reference ServeController actor
(python/ray/serve/_private/controller.py:123) with its
DeploymentStateManager reconcile loop (deployment_state.py:2203,3627),
SLO-driven autoscaling (serve/autoscale/policy.py replaces the naive
requests-per-replica count), and replica health checking. Routing
tables are served with a version number so routers poll cheaply
(long-poll-lite, reference long_poll.py:253).

Scale-down is session-aware: a victim replica moves to the
deployment's ``draining`` set — out of the routing table (the HRW
session router re-pins its sessions to survivors on the next refresh)
but still probed — and is killed only once its in-flight work,
streaming included, hits zero (plus a settle period covering requests
already routed) or the drain deadline fires.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Any, Dict, List, Optional

import ray_tpu
from ray_tpu.serve.replica import ServeReplica

logger = logging.getLogger(__name__)

CONTROLLER_NAME = "SERVE_CONTROLLER"
RECONCILE_PERIOD_S = 0.5
# A drained replica must stay up at least this long after leaving the
# table: routers refresh within ROUTE_REFRESH_S (1 s) and requests they
# routed in the stale window still have to land and count in the next
# health probe before "ongoing == 0" means quiescent.
DRAIN_SETTLE_S = 2.0


@ray_tpu.remote
class ServeController:
    def __init__(self, http_port: Optional[int] = None):
        # name -> deployment record
        self._deployments: Dict[str, Dict[str, Any]] = {}
        self._lock = threading.Lock()
        self._version = 0
        self._http_port = http_port
        self._proxies: Dict[str, Any] = {}  # node_id -> proxy handle
        self._policy = None  # SLOPolicy, built lazily on first tick
        self._collector = None  # SignalCollector, ditto
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._reconcile_loop, name="serve-reconcile", daemon=True
        )
        self._thread.start()

    # ------------------------------------------------------------------
    # control API
    # ------------------------------------------------------------------

    def deploy(
        self,
        name: str,
        callable_blob: bytes,
        init_args: tuple,
        init_kwargs: dict,
        num_replicas: int,
        route_prefix: Optional[str],
        max_concurrency: int,
        autoscaling: Optional[Dict[str, Any]],
        resources: Optional[Dict[str, float]],
        max_queued_requests: Optional[int] = None,
    ) -> bool:
        old_replicas = []
        with self._lock:
            existing = self._deployments.get(name)
            next_replica = 0
            if existing is not None:
                # Redeploy: new code/config replaces the old replicas.
                # Keep the replica counter so actor names never collide,
                # and kill the old replicas (outside the lock) so the
                # reconciler starts fresh ones from the new blob.
                next_replica = existing["next_replica"]
                old_replicas = list(existing["replicas"].values())
                old_replicas.extend(existing["draining"].values())
            self._deployments[name] = {
                "name": name,
                "callable_blob": callable_blob,
                "init_args": init_args,
                "init_kwargs": init_kwargs,
                "target_replicas": num_replicas,
                "route_prefix": route_prefix or f"/{name}",
                "max_concurrency": max_concurrency,
                # {min_replicas, max_replicas, target_ongoing_requests}
                "autoscaling": autoscaling,
                "resources": resources or {},
                # per-deployment proxy admission bound (None = global
                # RT_SERVE_ADMISSION_MAX_INFLIGHT); ships in the routing
                # table so every proxy enforces it without a config hop
                "max_queued_requests": max_queued_requests,
                "replicas": {},  # replica_id -> {handle, healthy}
                "stats": {},  # replica_id -> last stats
                # replica_id -> {handle, handle_info, since, deadline,
                # ongoing}: out of the table, finishing live streams
                "draining": {},
                "drain_deadline_s": None,  # per-deployment override
                "last_decision": None,  # last up/down autoscale decision
                "last_signals": None,  # most recent Signals.describe()
                "next_replica": next_replica,
                "deleting": False,
            }
            self._version += 1
        if self._policy is not None:
            self._policy.forget(name)  # fresh hysteresis for new code
        for rec in old_replicas:
            self._kill_silently(rec["handle"])
        return True

    def delete_deployment(self, name: str) -> bool:
        with self._lock:
            dep = self._deployments.get(name)
            if dep is None:
                return False
            dep["deleting"] = True
            dep["target_replicas"] = 0
            self._version += 1
        if self._policy is not None:
            self._policy.forget(name)
        return True

    def get_routing_table(self, known_version: int = -1, wait_s: float = 0.0):
        """Routing table + version. With wait_s > 0, blocks until the
        TOPOLOGY version changes (long-poll-lite). With wait_s == 0 the
        current table is always returned — replica `ongoing` counts change
        continuously without bumping the version, and routers need them
        fresh (pow-2 would otherwise route on frozen queue lengths).

        The wait is SLICED server-side (dispatcher-block discipline):
        routers re-issue slices forever (router._topology_longpoll), so a
        long caller deadline must not hold an actor thread here."""
        from ray_tpu.utils.config import config

        wait_s = min(wait_s, float(config.dispatch_wait_slice_s))
        deadline = time.monotonic() + wait_s
        while True:
            with self._lock:
                if self._version != known_version or wait_s <= 0:
                    table = {
                        name: {
                            "route_prefix": dep["route_prefix"],
                            "max_queued_requests": dep["max_queued_requests"],
                            "replicas": [
                                {
                                    "replica_id": rid,
                                    "ongoing": dep["stats"].get(rid, {}).get(
                                        "ongoing", 0
                                    ),
                                    "model_ids": dep["stats"].get(
                                        rid, {}
                                    ).get("model_ids", []),
                                    "handle_info": rec["handle_info"],
                                }
                                for rid, rec in dep["replicas"].items()
                                if rec["healthy"]
                            ],
                        }
                        for name, dep in self._deployments.items()
                        if not dep["deleting"]
                    }
                    return {"version": self._version, "table": table}
            if time.monotonic() >= deadline:
                return {"version": known_version, "table": None}
            time.sleep(0.05)

    def status(self) -> Dict[str, Any]:
        with self._lock:
            return {
                name: {
                    "target": dep["target_replicas"],
                    "running": sum(
                        1 for r in dep["replicas"].values() if r["healthy"]
                    ),
                    "draining": len(dep["draining"]),
                    "route_prefix": dep["route_prefix"],
                    "autoscaling": dep["autoscaling"],
                    "last_decision": dep["last_decision"],
                }
                for name, dep in self._deployments.items()
            }

    def set_target_replicas(
        self,
        name: str,
        num_replicas: int,
        drain_deadline_s: Optional[float] = None,
    ) -> bool:
        """Manual scale (`serve.scale`). On an autoscaling deployment the
        policy re-evaluates from here next tick; on a manual one this IS
        the desired state. ``drain_deadline_s`` overrides the
        RT_SERVE_AUTOSCALE_DRAIN_DEADLINE_S force-kill bound for this
        deployment's subsequent drains."""
        with self._lock:
            dep = self._deployments.get(name)
            if dep is None or dep["deleting"]:
                return False
            old = dep["target_replicas"]
            dep["target_replicas"] = max(0, int(num_replicas))
            if drain_deadline_s is not None:
                dep["drain_deadline_s"] = float(drain_deadline_s)
            new = dep["target_replicas"]
        if new != old:
            direction = "up" if new > old else "down"
            self._record_decision(name, old, new, direction, "manual")
        return True

    def autoscale_status(self) -> Dict[str, Any]:
        """Control-loop visibility (`state.autoscale_status`, `rt top`):
        per-deployment replica counts, drain progress, the last scale
        decision and the signals behind it."""
        now = time.monotonic()
        with self._lock:
            return {
                name: {
                    "target": dep["target_replicas"],
                    "running": sum(
                        1 for r in dep["replicas"].values() if r["healthy"]
                    ),
                    "draining": {
                        rid: {
                            "ongoing": rec["ongoing"],
                            "age_s": round(now - rec["since"], 3),
                            "deadline_in_s": round(rec["deadline"] - now, 3),
                        }
                        for rid, rec in dep["draining"].items()
                    },
                    "autoscaling": dep["autoscaling"],
                    "last_decision": dep["last_decision"],
                    "last_signals": dep["last_signals"],
                }
                for name, dep in self._deployments.items()
                if not dep["deleting"]
            }

    def ready(self, name: str, timeout_s: float = 60.0) -> bool:
        """Sliced like get_routing_table: returns False at the slice
        bound and clients (serve.run) re-issue until their own
        deadline."""
        from ray_tpu.utils.config import config

        timeout_s = min(timeout_s, float(config.dispatch_wait_slice_s))
        deadline = time.monotonic() + timeout_s
        while True:
            with self._lock:
                dep = self._deployments.get(name)
                if dep is not None:
                    healthy = sum(
                        1 for r in dep["replicas"].values() if r["healthy"]
                    )
                    if healthy >= max(1, dep["target_replicas"]):
                        return True
            if time.monotonic() >= deadline:
                return False
            time.sleep(0.05)

    def shutdown(self) -> bool:
        self._stop.set()
        with self._lock:
            deps = list(self._deployments.values())
            proxies = list(self._proxies.values())
            self._deployments.clear()
            self._proxies.clear()
        for dep in deps:
            for rec in dep["replicas"].values():
                self._kill_silently(rec["handle"])
            for rec in dep["draining"].values():
                self._kill_silently(rec["handle"])
        for p in proxies:
            self._kill_silently(p)
        return True

    @staticmethod
    def _kill_silently(handle) -> None:
        try:
            ray_tpu.kill(handle)
        except Exception:  # noqa: BLE001
            pass

    # ------------------------------------------------------------------
    # reconcile loop
    # ------------------------------------------------------------------

    def _reconcile_loop(self) -> None:
        from ray_tpu.utils.config import config

        last_autoscale = 0.0
        while not self._stop.wait(RECONCILE_PERIOD_S):
            try:
                self._check_health()
                now = time.monotonic()
                interval = float(config.serve_autoscale_interval_s)
                if now - last_autoscale >= max(interval, RECONCILE_PERIOD_S):
                    self._autoscale()
                    self._publish_status()
                    last_autoscale = now
                self._reconcile()
                self._ensure_proxies()
            except Exception:  # noqa: BLE001 — keep the loop alive
                logger.exception("serve reconcile iteration failed")

    def _check_health(self) -> None:
        """Probe replicas; collect queue stats; drop dead ones.

        Probes hit the hosting worker's RPC layer (rpc_actor_queue_stats),
        NOT the replica's execution queue, so a saturated replica still
        answers instantly and `ongoing` counts queued + running requests —
        the reference replica's out-of-band queue-length probe. Transient
        RPC timeouts tolerate several misses; a dead worker (connection
        refused / actor lookup failure) removes the replica immediately."""
        from ray_tpu.core import worker as worker_mod
        from ray_tpu.core.exceptions import ActorDiedError
        from ray_tpu.utils.rpc import RpcConnectionError, RpcError

        w = worker_mod.global_worker()
        with self._lock:
            probes = [
                (dep, rid, rec, False)
                for dep in self._deployments.values()
                for rid, rec in list(dep["replicas"].items())
            ]
            # draining replicas stay probed: "ongoing == 0" is the drain
            # completion signal, and a drainer that dies mid-drain must
            # be reaped, not waited on until its deadline
            probes.extend(
                (dep, rid, rec, True)
                for dep in self._deployments.values()
                for rid, rec in list(dep["draining"].items())
            )
        for dep, rid, rec, draining in probes:
            dead = False
            try:
                addr = w._resolve_actor_address(
                    rec["handle"]._actor_id, timeout_s=5.0
                )
                stats = w.workers.get(addr).call(
                    "actor_queue_stats", timeout_s=5.0
                )
                if stats is None:
                    raise RpcConnectionError("worker hosts no actor")
                with self._lock:
                    if draining:
                        rec["ongoing"] = stats["queued"] + stats["running"]
                        continue
                    dep["stats"][rid] = {
                        "ongoing": stats["queued"] + stats["running"],
                        "model_ids": stats.get("multiplexed_model_ids", []),
                    }
                    rec["probe_misses"] = 0
                    if not rec["healthy"]:
                        rec["healthy"] = True
                        self._version += 1
                continue
            except ActorDiedError:
                dead = True  # control plane confirms death: remove now
            except RpcConnectionError:
                # connection loss is ambiguous (worker rebinding, network
                # blip, or real death) — weigh it heavier than a timeout
                # but do not kill a healthy replica on one strike. Drop the
                # cached address so the next probe re-resolves: a replica
                # that restarted at a NEW address must not be probed at the
                # old one forever (and a truly dead one resolves to
                # ActorDiedError next round for immediate removal).
                w._actor_addr_cache.pop(rec["handle"]._actor_id, None)
                with self._lock:
                    rec["probe_misses"] = rec.get("probe_misses", 0) + 3
                    dead = rec["probe_misses"] >= 6
            except Exception:  # noqa: BLE001 — slow or dying
                with self._lock:
                    rec["probe_misses"] = rec.get("probe_misses", 0) + 1
                    dead = rec["probe_misses"] >= 6  # ~30s unresponsive
            if not dead:
                continue
            with self._lock:
                if draining:
                    dep["draining"].pop(rid, None)
                else:
                    if rec["healthy"]:
                        rec["healthy"] = False
                    self._version += 1
                    dep["replicas"].pop(rid, None)
                    dep["stats"].pop(rid, None)
            self._kill_silently(rec["handle"])
            logger.warning(
                "replica %s of %s failed health check; removed%s",
                rid, dep["name"], " (was draining)" if draining else "",
            )

    def _autoscale(self) -> None:
        """SLO-driven policy (serve/autoscale/policy.py): windowed TTFT
        p95 / KV occupancy / queue depth from the head's metrics history
        plus the burn-rate alert state, folded over the ongoing-count
        baseline with hysteresis, cooldowns and min/max bounds. Every
        up/down decision is stamped as a timeline event, counted in
        rt_serve_autoscale_decisions_total, and published to the head KV
        for state.autoscale_status() / `rt top`."""
        from ray_tpu.core import worker as worker_mod
        from ray_tpu.serve.autoscale.policy import SignalCollector, SLOPolicy

        if self._policy is None:
            self._policy = SLOPolicy()
        if self._collector is None:
            self._collector = SignalCollector(
                worker_mod.global_worker().control.call
            )
        with self._lock:
            deps = list(self._deployments.values())
        for dep in deps:
            auto = dep["autoscaling"]
            if not auto or dep["deleting"]:
                continue
            name = dep["name"]
            with self._lock:
                total_ongoing = sum(
                    s.get("ongoing", 0) for s in dep["stats"].values()
                )
                model_ids = sorted({
                    m
                    for s in dep["stats"].values()
                    for m in s.get("model_ids", [])
                })
                current = dep["target_replicas"]
            signals = self._collector.collect(name, model_ids, total_ongoing)
            decision = self._policy.decide(name, current, signals, auto)
            with self._lock:
                # re-read under the lock: a set_target_replicas/redeploy
                # may have moved the target while signals were collected
                if self._deployments.get(name) is not dep:
                    continue
                dep["last_signals"] = signals.describe()
                if dep["target_replicas"] != current:
                    continue
                if decision.direction == "hold":
                    continue
                dep["target_replicas"] = decision.target
            logger.info(
                "autoscaling %s: %d -> %d (%s)",
                name, current, decision.target, decision.reason,
            )
            self._record_decision(
                name, current, decision.target, decision.direction,
                decision.reason,
            )

    def _record_decision(
        self, name: str, old: int, new: int, direction: str, reason: str
    ) -> None:
        """One scale decision: dep record (for status), timeline instant
        (for `rt timeline`), decision counter (for history/alerts)."""
        from ray_tpu.observability import core_metrics, tracing

        decision = {
            "from": old, "to": new, "direction": direction,
            "reason": reason, "ts": time.time(),
        }
        with self._lock:
            dep = self._deployments.get(name)
            if dep is not None:
                dep["last_decision"] = decision
        if tracing.ENABLED:
            tracing.emit({
                "type": "autoscale",
                "deployment": name,
                "from": old,
                "to": new,
                "direction": direction,
                "reason": reason,
                "ts_us": tracing.now_us(),
                "pid": os.getpid(),
            })
        if core_metrics.ENABLED:
            core_metrics.serve_autoscale_decisions.inc(
                tags={"deployment": name, "direction": direction}
            )

    def _publish_status(self) -> None:
        """Replica gauges + the autoscale_status snapshot into the head
        KV (ns="serve"), the same side channel the cluster autoscaler
        uses for infeasible demand: state.autoscale_status() and `rt
        top` read it without an extra controller round-trip."""
        from ray_tpu.core import worker as worker_mod
        from ray_tpu.observability import core_metrics

        status = self.autoscale_status()
        if core_metrics.ENABLED:
            for name, st in status.items():
                tags = {"deployment": name}
                core_metrics.serve_replicas_running.set(
                    float(st["running"]), tags=tags
                )
                core_metrics.serve_replicas_target.set(
                    float(st["target"]), tags=tags
                )
                core_metrics.serve_replicas_draining.set(
                    float(len(st["draining"])), tags=tags
                )
        try:
            worker_mod.global_worker().control.call(
                "kv_put", ns="serve", key="autoscale_status",
                value=json.dumps(  # inband: ok — ~1 KiB status record
                    {"deployments": status, "ts": time.time()}
                ).encode(),
                timeout_s=5.0,
            )
        except Exception:  # noqa: BLE001 — status publish must not kill the loop
            pass

    def _reconcile(self) -> None:
        """Start/drain/stop replicas to match target."""
        from ray_tpu.utils.config import config

        with self._lock:
            deps = list(self._deployments.values())
        for dep in deps:
            now = time.monotonic()
            with self._lock:
                current = len(dep["replicas"])
                target = dep["target_replicas"]
                deleting = dep["deleting"]
                # scale-up resurrects drainers first: their KV cache and
                # prefix blocks are hot, and un-draining is free — back
                # into the table, sessions re-pin to them again
                while current < target and dep["draining"] and not deleting:
                    rid, rec = max(
                        dep["draining"].items(), key=lambda kv: kv[1]["since"]
                    )
                    dep["draining"].pop(rid)
                    dep["replicas"][rid] = {
                        "handle": rec["handle"],
                        "handle_info": rec["handle_info"],
                        "healthy": True,
                    }
                    self._version += 1
                    current += 1
                    logger.info("replica %s un-drained (scale-up)", rid)
            for _ in range(current, target):
                self._start_replica(dep)
            if deleting:
                # teardown is not a drain: delete_deployment means stop
                # now, streams included (old behavior)
                with self._lock:
                    victims = list(dep["replicas"].items())
                    victims += list(dep["draining"].items())
                    dep["replicas"].clear()
                    dep["draining"].clear()
                    dep["stats"].clear()
                    if victims:
                        self._version += 1
                for _, rec in victims:
                    self._kill_silently(rec["handle"])
            elif current > target:
                with self._lock:
                    # session-aware drain: victims leave the table
                    # (routers re-pin within ROUTE_REFRESH_S) but keep
                    # running until their streams finish. Fewest-ongoing
                    # first: drains finish fastest and the fewest
                    # sessions remap.
                    ranked = sorted(
                        dep["replicas"].items(),
                        key=lambda kv: dep["stats"].get(kv[0], {}).get(
                            "ongoing", 0
                        ),
                    )
                    deadline_s = dep["drain_deadline_s"]
                    if deadline_s is None:
                        deadline_s = float(
                            config.serve_autoscale_drain_deadline_s
                        )
                    for rid, rec in ranked[: current - target]:
                        dep["replicas"].pop(rid, None)
                        stats = dep["stats"].pop(rid, None) or {}
                        dep["draining"][rid] = {
                            "handle": rec["handle"],
                            "handle_info": rec["handle_info"],
                            "since": now,
                            "deadline": now + deadline_s,
                            "ongoing": stats.get("ongoing", 0),
                        }
                        logger.info(
                            "replica %s draining (ongoing=%d, "
                            "deadline %.1fs)",
                            rid, stats.get("ongoing", 0), deadline_s,
                        )
                    self._version += 1
            # drain completion: quiescent (after the settle period that
            # covers requests routed from a stale table) or past the
            # deadline — then, and only then, the actor dies
            finished = []
            with self._lock:
                for rid, rec in list(dep["draining"].items()):
                    if (
                        rec["ongoing"] <= 0
                        and now - rec["since"] >= DRAIN_SETTLE_S
                    ):
                        finished.append((rid, rec, "drained"))
                    elif now >= rec["deadline"]:
                        finished.append((rid, rec, "drain deadline"))
                for rid, _rec, _why in finished:
                    dep["draining"].pop(rid, None)
            for rid, rec, why in finished:
                self._kill_silently(rec["handle"])
                logger.info("replica %s stopped (%s)", rid, why)
            if deleting:
                with self._lock:
                    empty = not dep["replicas"] and not dep["draining"]
                    name = dep["name"]
                if empty:
                    with self._lock:
                        self._deployments.pop(name, None)
                        self._version += 1

    def _start_replica(self, dep: Dict[str, Any]) -> None:
        with self._lock:
            rid = f"{dep['name']}#{dep['next_replica']}"
            dep["next_replica"] += 1
        res = dict(dep["resources"])
        handle = ServeReplica.options(
            name=f"SERVE_REPLICA::{rid}",
            max_concurrency=dep["max_concurrency"],
            num_cpus=res.pop("CPU", 1),
            num_tpus=res.pop("TPU", 0) or None,
            resources=res or None,
        ).remote(
            dep["name"], dep["callable_blob"], dep["init_args"],
            dep["init_kwargs"],
        )
        with self._lock:
            # A redeploy may have replaced the record while this replica
            # was starting: registering into the orphaned dict would leak
            # a live actor nothing tracks.
            if self._deployments.get(dep["name"]) is not dep:
                stale = True
            else:
                stale = False
                dep["replicas"][rid] = {
                    "handle": handle,
                    # (actor_id, class_name, method_meta): routers rebuild
                    # a borrower ActorHandle from this (handles are plain
                    # pickleable records, actor.py __reduce__)
                    "handle_info": (
                        handle._actor_id, handle._class_name,
                        handle._method_meta,
                    ),
                    "healthy": True,
                }
                self._version += 1
        if stale:
            self._kill_silently(handle)
            return
        logger.info("started replica %s", rid)

    # ------------------------------------------------------------------
    # proxies (one per node, reference proxy.py:1176)
    # ------------------------------------------------------------------

    def _ensure_proxies(self) -> None:
        if self._http_port is None:
            return
        from ray_tpu.core.api import NodeAffinitySchedulingStrategy, nodes
        from ray_tpu.serve.proxy import ServeProxy

        alive = {n["node_id"]: n for n in nodes() if n.get("alive", True)}
        with self._lock:
            missing = [nid for nid in alive if nid not in self._proxies]
            gone = [nid for nid in self._proxies if nid not in alive]
            for nid in gone:
                self._proxies.pop(nid, None)
        for nid in missing:
            proxy = ServeProxy.options(
                name=f"SERVE_PROXY::{nid[:8]}",
                scheduling_strategy=NodeAffinitySchedulingStrategy(nid),
                num_cpus=0,
            ).remote(self._http_port)
            with self._lock:
                self._proxies[nid] = proxy

    def proxy_addresses(self) -> List[str]:
        with self._lock:
            proxies = list(self._proxies.values())
        addrs = []
        for p in proxies:
            try:
                addrs.append(ray_tpu.get(p.address.remote(), timeout=10))
            except Exception:  # noqa: BLE001
                pass
        return addrs
