"""Serve controller — desired-state reconciler.

Parity: the reference ServeController actor
(python/ray/serve/_private/controller.py:123) with its
DeploymentStateManager reconcile loop (deployment_state.py:2203,3627),
requests-per-replica autoscaling (autoscaling_policy.py), and replica
health checking. Routing tables are served with a version number so
routers poll cheaply (long-poll-lite, reference long_poll.py:253).
"""

from __future__ import annotations

import logging
import math
import threading
import time
from typing import Any, Dict, List, Optional

import ray_tpu
from ray_tpu.serve.replica import ServeReplica

logger = logging.getLogger(__name__)

CONTROLLER_NAME = "SERVE_CONTROLLER"
RECONCILE_PERIOD_S = 0.5
AUTOSCALE_WINDOW_S = 2.0


@ray_tpu.remote
class ServeController:
    def __init__(self, http_port: Optional[int] = None):
        # name -> deployment record
        self._deployments: Dict[str, Dict[str, Any]] = {}
        self._lock = threading.Lock()
        self._version = 0
        self._http_port = http_port
        self._proxies: Dict[str, Any] = {}  # node_id -> proxy handle
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._reconcile_loop, name="serve-reconcile", daemon=True
        )
        self._thread.start()

    # ------------------------------------------------------------------
    # control API
    # ------------------------------------------------------------------

    def deploy(
        self,
        name: str,
        callable_blob: bytes,
        init_args: tuple,
        init_kwargs: dict,
        num_replicas: int,
        route_prefix: Optional[str],
        max_concurrency: int,
        autoscaling: Optional[Dict[str, Any]],
        resources: Optional[Dict[str, float]],
    ) -> bool:
        old_replicas = []
        with self._lock:
            existing = self._deployments.get(name)
            next_replica = 0
            if existing is not None:
                # Redeploy: new code/config replaces the old replicas.
                # Keep the replica counter so actor names never collide,
                # and kill the old replicas (outside the lock) so the
                # reconciler starts fresh ones from the new blob.
                next_replica = existing["next_replica"]
                old_replicas = list(existing["replicas"].values())
            self._deployments[name] = {
                "name": name,
                "callable_blob": callable_blob,
                "init_args": init_args,
                "init_kwargs": init_kwargs,
                "target_replicas": num_replicas,
                "route_prefix": route_prefix or f"/{name}",
                "max_concurrency": max_concurrency,
                # {min_replicas, max_replicas, target_ongoing_requests}
                "autoscaling": autoscaling,
                "resources": resources or {},
                "replicas": {},  # replica_id -> {handle, healthy}
                "stats": {},  # replica_id -> last stats
                "next_replica": next_replica,
                "deleting": False,
            }
            self._version += 1
        for rec in old_replicas:
            self._kill_silently(rec["handle"])
        return True

    def delete_deployment(self, name: str) -> bool:
        with self._lock:
            dep = self._deployments.get(name)
            if dep is None:
                return False
            dep["deleting"] = True
            dep["target_replicas"] = 0
            self._version += 1
        return True

    def get_routing_table(self, known_version: int = -1, wait_s: float = 0.0):
        """Routing table + version. With wait_s > 0, blocks until the
        TOPOLOGY version changes (long-poll-lite). With wait_s == 0 the
        current table is always returned — replica `ongoing` counts change
        continuously without bumping the version, and routers need them
        fresh (pow-2 would otherwise route on frozen queue lengths)."""
        deadline = time.monotonic() + wait_s
        while True:
            with self._lock:
                if self._version != known_version or wait_s <= 0:
                    table = {
                        name: {
                            "route_prefix": dep["route_prefix"],
                            "replicas": [
                                {
                                    "replica_id": rid,
                                    "ongoing": dep["stats"].get(rid, {}).get(
                                        "ongoing", 0
                                    ),
                                    "model_ids": dep["stats"].get(
                                        rid, {}
                                    ).get("model_ids", []),
                                    "handle_info": rec["handle_info"],
                                }
                                for rid, rec in dep["replicas"].items()
                                if rec["healthy"]
                            ],
                        }
                        for name, dep in self._deployments.items()
                        if not dep["deleting"]
                    }
                    return {"version": self._version, "table": table}
            if time.monotonic() >= deadline:
                return {"version": known_version, "table": None}
            time.sleep(0.05)

    def status(self) -> Dict[str, Any]:
        with self._lock:
            return {
                name: {
                    "target": dep["target_replicas"],
                    "running": sum(
                        1 for r in dep["replicas"].values() if r["healthy"]
                    ),
                    "route_prefix": dep["route_prefix"],
                    "autoscaling": dep["autoscaling"],
                }
                for name, dep in self._deployments.items()
            }

    def ready(self, name: str, timeout_s: float = 60.0) -> bool:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with self._lock:
                dep = self._deployments.get(name)
                if dep is not None:
                    healthy = sum(
                        1 for r in dep["replicas"].values() if r["healthy"]
                    )
                    if healthy >= max(1, dep["target_replicas"]):
                        return True
            time.sleep(0.05)
        return False

    def shutdown(self) -> bool:
        self._stop.set()
        with self._lock:
            deps = list(self._deployments.values())
            proxies = list(self._proxies.values())
            self._deployments.clear()
            self._proxies.clear()
        for dep in deps:
            for rec in dep["replicas"].values():
                self._kill_silently(rec["handle"])
        for p in proxies:
            self._kill_silently(p)
        return True

    @staticmethod
    def _kill_silently(handle) -> None:
        try:
            ray_tpu.kill(handle)
        except Exception:  # noqa: BLE001
            pass

    # ------------------------------------------------------------------
    # reconcile loop
    # ------------------------------------------------------------------

    def _reconcile_loop(self) -> None:
        last_autoscale = 0.0
        while not self._stop.wait(RECONCILE_PERIOD_S):
            try:
                self._check_health()
                now = time.monotonic()
                if now - last_autoscale >= AUTOSCALE_WINDOW_S:
                    self._autoscale()
                    last_autoscale = now
                self._reconcile()
                self._ensure_proxies()
            except Exception:  # noqa: BLE001 — keep the loop alive
                logger.exception("serve reconcile iteration failed")

    def _check_health(self) -> None:
        """Probe replicas; collect queue stats; drop dead ones.

        Probes hit the hosting worker's RPC layer (rpc_actor_queue_stats),
        NOT the replica's execution queue, so a saturated replica still
        answers instantly and `ongoing` counts queued + running requests —
        the reference replica's out-of-band queue-length probe. Transient
        RPC timeouts tolerate several misses; a dead worker (connection
        refused / actor lookup failure) removes the replica immediately."""
        from ray_tpu.core import worker as worker_mod
        from ray_tpu.core.exceptions import ActorDiedError
        from ray_tpu.utils.rpc import RpcConnectionError, RpcError

        w = worker_mod.global_worker()
        with self._lock:
            probes = [
                (dep, rid, rec)
                for dep in self._deployments.values()
                for rid, rec in list(dep["replicas"].items())
            ]
        for dep, rid, rec in probes:
            dead = False
            try:
                addr = w._resolve_actor_address(
                    rec["handle"]._actor_id, timeout_s=5.0
                )
                stats = w.workers.get(addr).call(
                    "actor_queue_stats", timeout_s=5.0
                )
                if stats is None:
                    raise RpcConnectionError("worker hosts no actor")
                with self._lock:
                    dep["stats"][rid] = {
                        "ongoing": stats["queued"] + stats["running"],
                        "model_ids": stats.get("multiplexed_model_ids", []),
                    }
                    rec["probe_misses"] = 0
                    if not rec["healthy"]:
                        rec["healthy"] = True
                        self._version += 1
                continue
            except ActorDiedError:
                dead = True  # control plane confirms death: remove now
            except RpcConnectionError:
                # connection loss is ambiguous (worker rebinding, network
                # blip, or real death) — weigh it heavier than a timeout
                # but do not kill a healthy replica on one strike. Drop the
                # cached address so the next probe re-resolves: a replica
                # that restarted at a NEW address must not be probed at the
                # old one forever (and a truly dead one resolves to
                # ActorDiedError next round for immediate removal).
                w._actor_addr_cache.pop(rec["handle"]._actor_id, None)
                with self._lock:
                    rec["probe_misses"] = rec.get("probe_misses", 0) + 3
                    dead = rec["probe_misses"] >= 6
            except Exception:  # noqa: BLE001 — slow or dying
                with self._lock:
                    rec["probe_misses"] = rec.get("probe_misses", 0) + 1
                    dead = rec["probe_misses"] >= 6  # ~30s unresponsive
            if not dead:
                continue
            with self._lock:
                if rec["healthy"]:
                    rec["healthy"] = False
                self._version += 1
                dep["replicas"].pop(rid, None)
                dep["stats"].pop(rid, None)
            self._kill_silently(rec["handle"])
            logger.warning(
                "replica %s of %s failed health check; removed",
                rid, dep["name"],
            )

    def _autoscale(self) -> None:
        """requests-per-replica policy (reference autoscaling_policy.py):
        desired = ceil(total_ongoing / target_ongoing_requests)."""
        with self._lock:
            deps = list(self._deployments.values())
        for dep in deps:
            auto = dep["autoscaling"]
            if not auto or dep["deleting"]:
                continue
            with self._lock:
                total_ongoing = sum(
                    s.get("ongoing", 0) for s in dep["stats"].values()
                )
                target_per = max(1e-9, float(auto.get("target_ongoing_requests", 1)))
                desired = math.ceil(total_ongoing / target_per)
                desired = max(int(auto.get("min_replicas", 1)), desired)
                desired = min(int(auto.get("max_replicas", 8)), desired)
                if desired != dep["target_replicas"]:
                    logger.info(
                        "autoscaling %s: %d -> %d (ongoing=%d)",
                        dep["name"], dep["target_replicas"], desired,
                        total_ongoing,
                    )
                    dep["target_replicas"] = desired

    def _reconcile(self) -> None:
        """Start/stop replicas to match target."""
        with self._lock:
            deps = list(self._deployments.values())
        for dep in deps:
            with self._lock:
                current = len(dep["replicas"])
                target = dep["target_replicas"]
                deleting = dep["deleting"]
            for _ in range(current, target):
                self._start_replica(dep)
            if current > target:
                with self._lock:
                    victims = list(dep["replicas"].items())[target - current:]
                    for rid, rec in victims:
                        dep["replicas"].pop(rid, None)
                        dep["stats"].pop(rid, None)
                    self._version += 1
                for _, rec in victims:
                    self._kill_silently(rec["handle"])
            if deleting:
                with self._lock:
                    empty = not dep["replicas"]
                    name = dep["name"]
                if empty:
                    with self._lock:
                        self._deployments.pop(name, None)
                        self._version += 1

    def _start_replica(self, dep: Dict[str, Any]) -> None:
        with self._lock:
            rid = f"{dep['name']}#{dep['next_replica']}"
            dep["next_replica"] += 1
        res = dict(dep["resources"])
        handle = ServeReplica.options(
            name=f"SERVE_REPLICA::{rid}",
            max_concurrency=dep["max_concurrency"],
            num_cpus=res.pop("CPU", 1),
            num_tpus=res.pop("TPU", 0) or None,
            resources=res or None,
        ).remote(
            dep["name"], dep["callable_blob"], dep["init_args"],
            dep["init_kwargs"],
        )
        with self._lock:
            # A redeploy may have replaced the record while this replica
            # was starting: registering into the orphaned dict would leak
            # a live actor nothing tracks.
            if self._deployments.get(dep["name"]) is not dep:
                stale = True
            else:
                stale = False
                dep["replicas"][rid] = {
                    "handle": handle,
                    # (actor_id, class_name, method_meta): routers rebuild
                    # a borrower ActorHandle from this (handles are plain
                    # pickleable records, actor.py __reduce__)
                    "handle_info": (
                        handle._actor_id, handle._class_name,
                        handle._method_meta,
                    ),
                    "healthy": True,
                }
                self._version += 1
        if stale:
            self._kill_silently(handle)
            return
        logger.info("started replica %s", rid)

    # ------------------------------------------------------------------
    # proxies (one per node, reference proxy.py:1176)
    # ------------------------------------------------------------------

    def _ensure_proxies(self) -> None:
        if self._http_port is None:
            return
        from ray_tpu.core.api import NodeAffinitySchedulingStrategy, nodes
        from ray_tpu.serve.proxy import ServeProxy

        alive = {n["node_id"]: n for n in nodes() if n.get("alive", True)}
        with self._lock:
            missing = [nid for nid in alive if nid not in self._proxies]
            gone = [nid for nid in self._proxies if nid not in alive]
            for nid in gone:
                self._proxies.pop(nid, None)
        for nid in missing:
            proxy = ServeProxy.options(
                name=f"SERVE_PROXY::{nid[:8]}",
                scheduling_strategy=NodeAffinitySchedulingStrategy(nid),
                num_cpus=0,
            ).remote(self._http_port)
            with self._lock:
                self._proxies[nid] = proxy

    def proxy_addresses(self) -> List[str]:
        with self._lock:
            proxies = list(self._proxies.values())
        addrs = []
        for p in proxies:
            try:
                addrs.append(ray_tpu.get(p.address.remote(), timeout=10))
            except Exception:  # noqa: BLE001
                pass
        return addrs
