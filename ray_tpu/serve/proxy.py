"""Per-node HTTP proxy actor.

Parity: the reference ProxyActor/HTTPProxy (python/ray/serve/_private/
proxy.py:1176,827): one proxy per node accepts HTTP, matches the route
prefix, routes to a replica (pow-2 router) and returns the response.
Implemented on the stdlib ThreadingHTTPServer — request handling threads
block on the replica call, the actor's own RPC threads stay free.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qsl, urlparse

import ray_tpu
from ray_tpu.serve.replica import Request


@ray_tpu.remote
class ServeProxy:
    def __init__(self, port: int = 0, controller_name: str = "SERVE_CONTROLLER"):
        from ray_tpu.serve.router import Router

        controller = ray_tpu.get_actor(controller_name)
        self._router = Router(controller)
        proxy = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):  # quiet
                pass

            def _handle(self, method: str):
                parsed = urlparse(self.path)
                query = dict(parse_qsl(parsed.query))
                length = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(length) if length else b""
                if query.get("stream") in ("1", "true"):
                    return self._handle_streaming(method, parsed.path,
                                                  query, body)
                try:
                    status, payload = proxy._dispatch(
                        method, parsed.path, query,
                        dict(self.headers), body,
                    )
                except TimeoutError as e:
                    status, payload = 503, json.dumps(
                        {"error": str(e)}
                    ).encode()
                except Exception as e:  # noqa: BLE001 — app errors -> 500
                    status, payload = 500, json.dumps(
                        {"error": f"{type(e).__name__}: {e}"}
                    ).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def _handle_streaming(self, method, path, query, body):
                """?stream=1: chunked transfer encoding, one JSON line per
                streamed item (the reference proxy's streaming response
                path over starlette; here raw HTTP/1.1 chunks)."""
                deployment = proxy._router.deployment_for_route(path)
                if deployment is None:
                    payload = json.dumps({"error": f"no route for {path}"}).encode()
                    self.send_response(404)
                    self.send_header("Content-Length", str(len(payload)))
                    self.end_headers()
                    self.wfile.write(payload)
                    return
                self.send_response(200)
                self.send_header("Content-Type", "application/x-ndjson")
                self.send_header("Transfer-Encoding", "chunked")
                self.end_headers()

                def chunk(data: bytes):
                    self.wfile.write(f"{len(data):x}\r\n".encode())
                    self.wfile.write(data + b"\r\n")

                try:
                    request = Request(method, path, body, {}, query)
                    for item in proxy._router.call_streaming(
                        deployment, request, timeout_s=300
                    ):
                        line = (
                            item if isinstance(item, bytes)
                            else json.dumps(item).encode()
                        )
                        chunk(line + b"\n")
                        self.wfile.flush()
                except Exception as e:  # noqa: BLE001 — trailer chunk
                    chunk(json.dumps(
                        {"error": f"{type(e).__name__}: {e}"}
                    ).encode() + b"\n")
                self.wfile.write(b"0\r\n\r\n")

            def do_GET(self):
                self._handle("GET")

            def do_POST(self):
                self._handle("POST")

            def do_PUT(self):
                self._handle("PUT")

            def do_DELETE(self):
                self._handle("DELETE")

        self._server = ThreadingHTTPServer(("0.0.0.0", port), Handler)
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="serve-http", daemon=True
        )
        self._thread.start()

    def _dispatch(self, method: str, path: str, query, headers, body: bytes):
        if path == "/-/routes":
            self._router._refresh(force=True)
            return 200, json.dumps(
                {
                    name: dep["route_prefix"]
                    for name, dep in self._router._table.items()
                }
            ).encode()
        if path == "/-/healthz":
            return 200, b'"ok"'
        deployment = self._router.deployment_for_route(path)
        if deployment is None:
            return 404, json.dumps({"error": f"no route for {path}"}).encode()
        request = Request(method, path, body, headers, query)
        result = self._router.call(deployment, request, timeout_s=120)
        if isinstance(result, bytes):
            return 200, result
        return 200, json.dumps(result).encode()

    def address(self) -> str:
        from ray_tpu.core import worker as worker_mod

        port = self._server.server_address[1]
        # the node's routable address, not loopback: multi-node clients
        # must be able to reach every node's proxy
        host = worker_mod.global_worker().node_agent_address.split(":")[0]
        return f"{host}:{port}"

    def health(self) -> bool:
        return True
