"""Per-node HTTP proxy actor.

Parity: the reference ProxyActor/HTTPProxy (python/ray/serve/_private/
proxy.py:1176,827): one proxy per node accepts HTTP, matches the route
prefix, routes to a replica (pow-2 router) and returns the response.

Data plane: asyncio (ray_tpu/serve/http_server.py) — the reference's
proxy is ASGI/asyncio (proxy.py:732), and the round-4 review flagged the
previous thread-per-request stdlib server as the gap. Connections are
event-driven with keep-alive; the blocking replica call runs on a
bounded pool; ?stream=1 responses ride chunked transfer encoding.

Model multiplexing: a request carrying a ``serve_multiplexed_model_id``
header (or ``model_id`` query param) is routed preferentially to a
replica that already holds that model (reference multiplex routing).
"""

from __future__ import annotations

import json
from typing import Optional

import ray_tpu
from ray_tpu.serve.http_server import AioHttpServer
from ray_tpu.serve.replica import Request

_MODEL_ID_HEADER = "serve_multiplexed_model_id"


@ray_tpu.remote
class ServeProxy:
    def __init__(self, port: int = 0, controller_name: str = "SERVE_CONTROLLER"):
        from ray_tpu.serve.router import Router

        controller = ray_tpu.get_actor(controller_name)
        self._router = Router(controller)
        self._server = AioHttpServer(self._handle, port=port)

    # -- request path (runs on the server's executor pool) --------------

    def _handle(self, method: str, path: str, query, headers, body: bytes):
        if query.get("stream") in ("1", "true"):
            return self._handle_streaming(method, path, query, headers, body)
        try:
            status, payload = self._dispatch(method, path, query, headers, body)
        except TimeoutError as e:
            status, payload = 503, json.dumps({"error": str(e)}).encode()
        except Exception as e:  # noqa: BLE001 — app errors -> 500
            status, payload = 500, json.dumps(
                {"error": f"{type(e).__name__}: {e}"}
            ).encode()
        return status, "application/json", payload

    def _handle_streaming(self, method, path, query, headers, body):
        """?stream=1: a generator — the asyncio server turns each yielded
        item into one chunk (reference proxy's streaming response path)."""
        deployment = self._router.deployment_for_route(path)
        if deployment is None:
            return 404, "application/json", json.dumps(
                {"error": f"no route for {path}"}
            ).encode()
        request = Request(method, path, body, headers, query)

        def gen():
            try:
                for item in self._router.call_streaming(
                    deployment, request, timeout_s=300
                ):
                    line = (
                        item if isinstance(item, bytes)
                        else json.dumps(item).encode()
                    )
                    yield line + b"\n"
            except Exception as e:  # noqa: BLE001 — trailer chunk
                yield json.dumps(
                    {"error": f"{type(e).__name__}: {e}"}
                ).encode() + b"\n"

        return gen()

    def _dispatch(self, method: str, path: str, query, headers, body: bytes):
        if path == "/-/routes":
            self._router._refresh(force=True)
            return 200, json.dumps(
                {
                    name: dep["route_prefix"]
                    for name, dep in self._router._table.items()
                }
            ).encode()
        if path == "/-/healthz":
            return 200, b'"ok"'
        deployment = self._router.deployment_for_route(path)
        if deployment is None:
            return 404, json.dumps({"error": f"no route for {path}"}).encode()
        model_id: Optional[str] = (
            headers.get(_MODEL_ID_HEADER) or query.get("model_id") or None
        )
        request = Request(method, path, body, headers, query)
        result = self._router.call(
            deployment, request, timeout_s=120, model_id=model_id
        )
        if isinstance(result, bytes):
            return 200, result
        return 200, json.dumps(result).encode()

    def address(self) -> str:
        from ray_tpu.core import worker as worker_mod

        # the node's routable address, not loopback: multi-node clients
        # must be able to reach every node's proxy
        host = worker_mod.global_worker().node_agent_address.split(":")[0]
        return f"{host}:{self._server.port}"

    def health(self) -> bool:
        return True
