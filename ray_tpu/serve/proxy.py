"""Per-node HTTP proxy actor.

Parity: the reference ProxyActor/HTTPProxy (python/ray/serve/_private/
proxy.py:1176,827): one proxy per node accepts HTTP, matches the route
prefix, routes to a replica (pow-2 router) and returns the response.

Data plane: asyncio (ray_tpu/serve/http_server.py) — the reference's
proxy is ASGI/asyncio (proxy.py:732), and the round-4 review flagged the
previous thread-per-request stdlib server as the gap. Connections are
event-driven with keep-alive; the blocking replica call runs on a
bounded pool; streamed responses ride chunked transfer encoding.

Hot path: replica calls go over ONE direct RPC to the replica's hosting
worker (router.call_direct → rpc_actor_direct_call) on the multi-segment
wire format + cached dispatcher pool — no TaskSpec, no owner-side object
store (PROFILE.md "Serve no-op front-door budget"). config.serve_direct_rpc
switches the old actor-task path back on.

OpenAI front door: paths shaped like `/v1/completions`,
`/v1/chat/completions` and `/v1/models` get a cheap body probe
(serve/openai/protocol.py) for the routing hints that live in the JSON
body — the ``stream`` flag (SSE, not ?stream=1), the ``model`` id
(multiplexed warm-engine affinity) and the ``user`` session key
(rendezvous KV affinity). Errors on those routes are OpenAI-shaped.

Model multiplexing: a request carrying a ``serve_multiplexed_model_id``
header (or ``model_id`` query param) is routed preferentially to a
replica that already holds that model (reference multiplex routing).
"""

from __future__ import annotations

import asyncio
import json
from typing import Optional

import ray_tpu
from ray_tpu.serve.http_server import AioHttpServer, FallbackToPool
from ray_tpu.serve.openai import protocol as oai
from ray_tpu.serve.replica import Request
from ray_tpu.utils.rpc import RpcError, RpcTimeout

# NOTE: this class is cloudpickled BY VALUE (the @ray_tpu.remote wrapper
# shadows the module attribute, so by-reference lookup fails): methods
# must not reference module globals that hold _thread.locks — the config
# registry is imported at call time for exactly that reason.

_MODEL_ID_HEADER = "serve_multiplexed_model_id"
# bodies past this stay off the fast path: the request frame is sent on
# the event loop thread, which must never sit in a long sendmsg
_FAST_MAX_BODY = 64 * 1024


@ray_tpu.remote
class ServeProxy:
    def __init__(self, port: int = 0, controller_name: str = "SERVE_CONTROLLER"):
        from ray_tpu.serve.autoscale.admission import AdmissionController
        from ray_tpu.serve.router import Router

        controller = ray_tpu.get_actor(controller_name)
        self._router = Router(controller)
        self._admission = AdmissionController()
        self._server = AioHttpServer(
            self._handle, port=port, fast_handler=self._try_fast
        )

    # -- admission control (serve/autoscale/admission.py) ----------------

    def _admit(self, deployment: str, model_id: Optional[str]):
        """One admission attempt: None = admitted (caller owns exactly
        one release), or a Shed to return. The per-deployment bound comes
        from the routing table (deploy-time max_queued_requests) with
        RT_SERVE_ADMISSION_MAX_INFLIGHT as the default."""
        cap = self._router.max_queued_requests(deployment)
        return self._admission.try_acquire(
            deployment, model_id=model_id, max_inflight=cap
        )

    @staticmethod
    def _shed_response(shed, openai: bool):
        """429/503 + Retry-After: the overload contract. OpenAI routes
        get an OpenAI-shaped error body; everything else plain JSON."""
        if openai:
            body = oai.error_body(
                shed.message, err_type=shed.err_type, code=shed.reason
            )
        else:
            body = json.dumps({
                "error": shed.message,
                "reason": shed.reason,
                "retry_after_s": shed.retry_after_s,
            }).encode()
        return shed.status, "application/json", body, shed.headers()

    # -- fast path (runs ON the event loop; must never block) ------------

    def _try_fast(self, method, path, query, headers, body: bytes):
        """Zero-executor-hop dispatch for unary requests whose replica is
        instantly routable: pick from the router's cached table, fire the
        direct RPC asynchronously, and await the reply as a loop future.
        Anything not instantly serviceable (streaming, stale table, cold
        actor-address cache, oversized body, feature off) returns None —
        the ordinary pool handler takes it."""
        from ray_tpu.utils.config import config

        if not config.serve_direct_rpc or len(body) > _FAST_MAX_BODY:
            return None
        if query.get("stream") in ("1", "true"):
            return None
        if path.startswith("/-/"):
            return None  # admin endpoints touch router internals
        probe = oai.probe(method, path, body, headers)
        if probe is not None and probe.stream:
            return None
        if probe is not None:
            model_id, session_key = probe.model, probe.session_key
            prefix_hint = (
                probe.prefix_hint if config.serve_prefix_cache else None
            )
        else:
            model_id = (
                headers.get(_MODEL_ID_HEADER) or query.get("model_id") or None
            )
            session_key = None
            prefix_hint = None
        from ray_tpu.observability import tracing

        trace = None
        if tracing.ENABLED:
            trace_id = (headers.get(tracing.TRACE_HEADER)
                        or tracing.new_trace_id())
            headers[tracing.TRACE_HEADER] = trace_id
            trace = (trace_id, None, tracing.now_us())
        picked = self._router.try_pick_nowait(
            path, model_id, session_key, prefix_hint
        )
        if picked is None:
            return None
        deployment, rid, handle = picked
        shed = self._admit(deployment, model_id)
        if shed is not None:
            # shed BEFORE the replica RPC: overload never reaches an
            # engine, and the reply is a plain tuple (no pool hop)
            self._router.request_finished(rid)
            if trace is not None:
                self._trace_end(
                    (trace[0], deployment, trace[2]), shed.status
                )
            return self._shed_response(shed, openai=probe is not None)
        if trace is not None:
            # fill in the deployment the pick resolved; stamp the pick
            # itself as the (sub-ms) router leg of this trace
            trace = (trace[0], deployment, trace[2])
            if tracing.ENABLED:
                tracing.emit(tracing.request_span(
                    trace[0], tracing.ROUTER, deployment, trace[2],
                    tracing.now_us() - trace[2], replica=rid,
                ))
        from ray_tpu.core import worker as worker_mod

        w = worker_mod.global_worker()
        addr = w._actor_addr_cache.get(handle._actor_id)
        client = w.workers.get(addr) if addr is not None else None
        if client is None or client._sock is None:
            # cold address/connection: resolving would block the loop
            self._router.request_finished(rid)
            self._admission.release(deployment, model_id)
            return None
        request = Request(method, path, body, headers, query)
        try:
            pending = client.call_async(
                "actor_direct_call", target="handle_request_direct",
                args=(request,),
            )
        except RpcError:
            self._router.request_finished(rid)
            self._admission.release(deployment, model_id)
            return None  # connection just dropped: pool path re-routes
        return self._await_direct(pending, rid, openai=probe is not None,
                                  trace=trace,
                                  admitted=(deployment, model_id))

    def _trace_begin(self, headers, deployment):
        """Mint (or adopt) the trace id, inject it into the request
        headers, and return (trace_id, deployment, t0_us) — or None when
        tracing is off, so downstream stamp sites short-circuit."""
        from ray_tpu.observability import tracing

        if not tracing.ENABLED:
            return None
        trace_id = (headers.get(tracing.TRACE_HEADER)
                    or tracing.new_trace_id())
        headers[tracing.TRACE_HEADER] = trace_id
        return (trace_id, deployment, tracing.now_us())

    def _trace_end(self, trace, status: int = 200) -> None:
        """Stamp the proxy (end-to-end) span for a request begun with
        _trace_begin."""
        if trace is None:
            return
        from ray_tpu.observability import tracing

        if tracing.ENABLED:
            trace_id, deployment, t0 = trace
            tracing.emit(tracing.request_span(
                trace_id, tracing.PROXY, deployment or "?",
                t0, tracing.now_us() - t0, status=status,
            ))

    async def _await_direct(self, pending, rid: str, openai: bool,
                            trace=None, admitted=None):
        from ray_tpu.serve.router import Router
        from ray_tpu.utils.rpc import RemoteError

        loop = asyncio.get_running_loop()
        fut = loop.create_future()

        def _deliver(p):
            loop.call_soon_threadsafe(
                lambda: fut.set_result(p) if not fut.done() else None
            )

        pending.add_done_callback(_deliver)
        status = None  # None at exit = fell back to pool: no proxy span
        try:
            try:
                p = await asyncio.wait_for(fut, timeout=120)
            except asyncio.TimeoutError:
                status = 503
                return 503, "application/json", (
                    oai.error_body("request timed out",
                                   err_type="overloaded_error")
                    if openai else b'{"error":"request timed out"}'
                )
            if not p.ok:
                if isinstance(p.payload, RemoteError):
                    # the request EXECUTED and raised: a real 500, never
                    # re-dispatched (double execution)
                    msg = f"RemoteError: {p.payload}"
                    status = 500
                    return 500, "application/json", (
                        oai.error_body(msg, err_type="internal_error")
                        if openai else json.dumps({"error": msg}).encode()
                    )
                # connection lost: re-route on the pool path (same
                # retry-on-replica-death semantics as router.call)
                raise FallbackToPool
            reply = p.payload
            if reply[0] == "no_actor":
                raise FallbackToPool  # mid-restart: pool path re-routes
            result = Router._unwrap_direct(reply[1])
            if openai:
                out = oai.split_http_result(result)
                status = out[0]
                return out
            status = 200
            if isinstance(result, (bytes, bytearray, memoryview)):
                return 200, "application/json", result
            if (
                isinstance(result, tuple) and len(result) == 3
                and isinstance(result[0], int)
            ):
                status = result[0]
                return result
            return 200, "application/json", json.dumps(result).encode()
        finally:
            self._router.request_finished(rid)
            if admitted is not None:
                self._admission.release(*admitted)
            if status is not None:
                self._trace_end(trace, status)

    # -- request path (runs on the server's executor pool) --------------

    def _handle(self, method: str, path: str, query, headers, body: bytes):
        probe = oai.probe(method, path, body, headers)
        if probe is not None:
            return self._handle_openai(method, path, query, headers, body,
                                       probe)
        if query.get("stream") in ("1", "true"):
            return self._handle_streaming(method, path, query, headers, body)
        try:
            return self._dispatch(method, path, query, headers, body)
        except (TimeoutError, RpcTimeout) as e:
            return 503, "application/json", json.dumps(
                {"error": str(e)}
            ).encode()
        except Exception as e:  # noqa: BLE001 — app errors -> 500
            return 500, "application/json", json.dumps(
                {"error": f"{type(e).__name__}: {e}"}
            ).encode()

    def _handle_streaming(self, method, path, query, headers, body):
        """?stream=1: a generator — the asyncio server turns each yielded
        item into one chunk (reference proxy's streaming response path)."""
        deployment = self._router.deployment_for_route(path)
        if deployment is None:
            return 404, "application/json", json.dumps(
                {"error": f"no route for {path}"}
            ).encode()
        model_id: Optional[str] = (
            headers.get(_MODEL_ID_HEADER) or query.get("model_id") or None
        )
        shed = self._admit(deployment, model_id)
        if shed is not None:
            # shed is a unary reply even on a would-be stream: the
            # client gets headers + body + Retry-After, never a hung
            # half-open chunked response
            return self._shed_response(shed, openai=False)
        trace = self._trace_begin(headers, deployment)
        request = Request(method, path, body, headers, query)

        def gen():
            try:
                for item in self._router.call_streaming(
                    deployment, request, timeout_s=300
                ):
                    line = (
                        item if isinstance(item, bytes)
                        else json.dumps(item).encode()
                    )
                    yield line + b"\n"
            except Exception as e:  # noqa: BLE001 — trailer chunk
                yield json.dumps(
                    {"error": f"{type(e).__name__}: {e}"}
                ).encode() + b"\n"
            finally:
                self._admission.release(deployment, model_id)
                self._trace_end(trace, 200)

        return gen()

    # -- OpenAI front door ----------------------------------------------

    def _handle_openai(self, method, path, query, headers, body,
                       probe: "oai.Probe"):
        """`/v1/*`-shaped requests: body-probed routing hints, SSE when
        the body says ``stream: true``, OpenAI-shaped errors."""
        deployment = self._router.deployment_for_route(path)
        if deployment is None:
            return 404, "application/json", oai.error_body(
                f"no route for {path}", err_type="invalid_request_error",
                code="route_not_found",
            )
        from ray_tpu.utils.config import config

        shed = self._admit(deployment, probe.model)
        if shed is not None:
            # one unary 429/503 + Retry-After whether the request wanted
            # SSE or not: overload must never open a stream
            return self._shed_response(shed, openai=True)
        trace = self._trace_begin(headers, deployment)
        request = Request(method, path, body, headers, query)
        if probe.stream:
            # the stream generator owns the admission slot from here
            return self._openai_stream(deployment, request, probe, trace)
        try:
            result = self._router.call_direct(
                deployment, request, timeout_s=300,
                model_id=probe.model, session_key=probe.session_key,
                prefix_hint=(
                    probe.prefix_hint if config.serve_prefix_cache else None
                ),
            )
        except (TimeoutError, RpcTimeout) as e:
            self._trace_end(trace, 503)
            return 503, "application/json", oai.error_body(
                str(e), err_type="overloaded_error"
            )
        except Exception as e:  # noqa: BLE001
            self._trace_end(trace, 500)
            return 500, "application/json", oai.error_body(
                f"{type(e).__name__}: {e}", err_type="internal_error"
            )
        finally:
            self._admission.release(deployment, probe.model)
        out = oai.split_http_result(result)
        self._trace_end(trace, out[0])
        return out

    def _openai_stream(self, deployment: str, request: Request,
                       probe: "oai.Probe", trace=None):
        """SSE response: each yielded ``data: {...}\\n\\n`` event is one
        chunk; closing the connection closes this generator, which
        cancels the replica-side stream and frees the engine's KV slot.
        The proxy span closes when the generator does, so its duration
        covers the whole stream (the e2e number request_summary rolls
        up)."""

        from ray_tpu.utils.config import config

        hint = probe.prefix_hint if config.serve_prefix_cache else None

        def gen():
            try:
                for item in self._router.call_streaming(
                    deployment, request, timeout_s=600,
                    model_id=probe.model, session_key=probe.session_key,
                    prefix_hint=hint,
                ):
                    yield item if isinstance(item, bytes) else oai.sse_event(
                        item
                    )
            except Exception as e:  # noqa: BLE001 — mid-stream trailer
                yield oai.sse_error(f"{type(e).__name__}: {e}")
            finally:
                # admission slot acquired by _handle_openai: a stream
                # occupies replica capacity until it closes, so it holds
                # its slot just as long
                self._admission.release(deployment, probe.model)
                self._trace_end(trace, 200)

        return 200, oai.SSE_CONTENT_TYPE, gen()

    # -- generic dispatch ------------------------------------------------

    def _dispatch(self, method: str, path: str, query, headers, body: bytes):
        if path == "/-/routes":
            self._router._refresh(force=True)
            return 200, "application/json", json.dumps(
                {
                    name: dep["route_prefix"]
                    for name, dep in self._router._table.items()
                }
            ).encode()
        if path == "/-/healthz":
            return 200, "application/json", b'"ok"'
        deployment = self._router.deployment_for_route(path)
        if deployment is None:
            return 404, "application/json", json.dumps(
                {"error": f"no route for {path}"}
            ).encode()
        model_id: Optional[str] = (
            headers.get(_MODEL_ID_HEADER) or query.get("model_id") or None
        )
        shed = self._admit(deployment, model_id)
        if shed is not None:
            return self._shed_response(shed, openai=False)
        trace = self._trace_begin(headers, deployment)
        request = Request(method, path, body, headers, query)
        try:
            result = self._router.call_direct(
                deployment, request, timeout_s=120, model_id=model_id
            )
        finally:
            self._admission.release(deployment, model_id)
        if isinstance(result, (bytes, bytearray, memoryview)):
            self._trace_end(trace, 200)
            return 200, "application/json", result
        if (
            isinstance(result, tuple) and len(result) == 3
            and isinstance(result[0], int)
        ):
            self._trace_end(trace, result[0])
            return result
        self._trace_end(trace, 200)
        return 200, "application/json", json.dumps(result).encode()

    def address(self) -> str:
        from ray_tpu.core import worker as worker_mod

        # the node's routable address, not loopback: multi-node clients
        # must be able to reach every node's proxy
        host = worker_mod.global_worker().node_agent_address.split(":")[0]
        return f"{host}:{self._server.port}"

    def health(self) -> bool:
        return True
