"""Minimal asyncio HTTP/1.1 server for the serve data plane.

Parity rationale: the reference proxy is ASGI/asyncio (uvicorn +
starlette, python/ray/serve/_private/proxy.py:732) — connection handling
is event-driven, so thousands of keep-alive connections cost one loop,
not one thread each. This is the same design without external deps: a
hand-rolled HTTP/1.1 parser over ``asyncio.start_server``, keep-alive by
default, chunked transfer for streaming handlers, and a bounded thread
pool for the (blocking) replica calls.

Handlers are plain callables (run in the pool, NOT on the loop):

    handler(method, path, query, headers, body)
      -> (status:int, content_type:str, payload:bytes)        # unary
      -> (status:int, content_type:str, payload:bytes,
          extra_headers:dict)           # unary with extra response
                                        # headers (admission control
                                        # sheds attach Retry-After)
      -> generator yielding bytes                             # streaming
      -> (status:int, content_type:str, generator)            # streaming
                                       with explicit status/content-type
                                       (SSE: "text/event-stream")

A client disconnect mid-stream CLOSES the handler's generator (on the
pool), so producers can release held resources — the serve LLM path
relies on this to cancel the replica-side stream and free its engine
KV slot.

Fast path: an optional ``fast_handler`` runs ON THE EVENT LOOP before
the pool dispatch. It must never block; it returns None (take the pool
path), a ready result, or an awaitable resolving to a result. Raising
``FallbackToPool`` from the awaitable re-dispatches the request to the
ordinary pool handler. The serve proxy uses this to issue the
replica RPC asynchronously — the request then costs zero executor
hops and no parked pool thread (PROFILE.md serve budget).
"""

from __future__ import annotations

import asyncio
import inspect
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Dict, Optional, Tuple
from urllib.parse import parse_qsl, unquote, urlparse

_MAX_HEADER = 64 * 1024
_MAX_BODY = 256 * 1024 * 1024


class FallbackToPool(Exception):
    """Raised by a fast-path awaitable: re-dispatch on the pool handler
    (only safe when the request provably did NOT execute yet)."""


class AioHttpServer:
    def __init__(self, handler: Callable, port: int = 0,
                 host: str = "0.0.0.0", pool_size: int = 32,
                 fast_handler: Optional[Callable] = None):
        self._handler = handler
        self._fast = fast_handler
        self._host = host
        self._port = port
        self._pool = ThreadPoolExecutor(
            max_workers=pool_size, thread_name_prefix="serve-call"
        )
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._started = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="serve-aio", daemon=True
        )
        self._thread.start()
        if not self._started.wait(timeout=30):
            raise RuntimeError("asyncio HTTP server failed to start")

    @property
    def port(self) -> int:
        return self._port

    def _run(self) -> None:
        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        self._loop = loop

        async def boot():
            server = await asyncio.start_server(
                self._serve_conn, self._host, self._port,
            )
            self._port = server.sockets[0].getsockname()[1]
            self._started.set()
            async with server:
                await server.serve_forever()

        try:
            loop.run_until_complete(boot())
        except asyncio.CancelledError:
            pass
        finally:
            loop.close()

    def stop(self) -> None:
        if self._loop is not None:
            self._loop.call_soon_threadsafe(
                lambda: [t.cancel() for t in asyncio.all_tasks(self._loop)]
            )
        self._pool.shutdown(wait=False)

    # -- connection handling -------------------------------------------

    async def _serve_conn(self, reader: asyncio.StreamReader,
                          writer: asyncio.StreamWriter) -> None:
        try:
            while True:
                try:
                    head = await reader.readuntil(b"\r\n\r\n")
                except (asyncio.IncompleteReadError, ConnectionError):
                    return
                except asyncio.LimitOverrunError:
                    await self._simple(writer, 431, b'{"error":"headers too large"}')
                    return
                if len(head) > _MAX_HEADER:
                    await self._simple(writer, 431, b'{"error":"headers too large"}')
                    return
                try:
                    method, target, headers = self._parse_head(head)
                except ValueError:
                    await self._simple(writer, 400, b'{"error":"bad request"}')
                    return
                try:
                    length = int(headers.get("content-length") or 0)
                    if length < 0:
                        raise ValueError
                except ValueError:
                    await self._simple(writer, 400, b'{"error":"bad content-length"}')
                    return
                if length > _MAX_BODY:
                    await self._simple(writer, 413, b'{"error":"body too large"}')
                    return
                body = await reader.readexactly(length) if length else b""
                parsed = urlparse(target)
                path = unquote(parsed.path)
                query = dict(parse_qsl(parsed.query))
                keep = headers.get("connection", "keep-alive").lower() != "close"
                loop = asyncio.get_running_loop()
                result = None
                if self._fast is not None:
                    try:
                        fast = self._fast(method, path, query, headers, body)
                    except Exception:  # noqa: BLE001 — probe bug: pool path
                        fast = None
                    if fast is not None:
                        try:
                            result = (
                                await fast if inspect.isawaitable(fast)
                                else fast
                            )
                        except FallbackToPool:
                            result = None
                        except Exception as e:  # noqa: BLE001
                            await self._simple(
                                writer, 500,
                                f'{{"error":"{type(e).__name__}"}}'.encode(),
                                keep,
                            )
                            if not keep:
                                return
                            continue
                if result is None:
                    try:
                        result = await loop.run_in_executor(
                            self._pool, self._handler, method, path, query,
                            headers, body,
                        )
                    except Exception as e:  # noqa: BLE001 — crash -> 500
                        await self._simple(
                            writer, 500,
                            f'{{"error":"{type(e).__name__}"}}'.encode(),
                            keep,
                        )
                        if not keep:
                            return
                        continue
                if hasattr(result, "__next__"):  # streaming generator
                    ok = await self._stream(writer, result, loop)
                    # chunked responses end the exchange cleanly; keep
                    # the connection for the next request
                    if not ok:
                        return  # client went away mid-stream
                elif (
                    isinstance(result, tuple) and len(result) == 3
                    and hasattr(result[2], "__next__")
                ):  # streaming with explicit status/content-type (SSE)
                    status, ctype, gen = result
                    ok = await self._stream(
                        writer, gen, loop, status=status, ctype=ctype
                    )
                    if not ok:
                        return
                else:
                    extra = None
                    if len(result) == 4:
                        status, ctype, payload, extra = result
                    else:
                        status, ctype, payload = result
                    await self._respond(
                        writer, status, ctype, payload, keep, extra
                    )
                if not keep:
                    return
        finally:
            try:
                writer.close()
            except Exception:  # noqa: BLE001
                pass

    @staticmethod
    def _parse_head(head: bytes) -> Tuple[str, str, Dict[str, str]]:
        lines = head.decode("latin-1").split("\r\n")
        parts = lines[0].split(" ")
        if len(parts) != 3:
            raise ValueError("bad request line")
        method, target, _version = parts
        headers: Dict[str, str] = {}
        for line in lines[1:]:
            if not line:
                continue
            k, _, v = line.partition(":")
            headers[k.strip().lower()] = v.strip()
        return method.upper(), target, headers

    async def _respond(self, writer, status: int, ctype: str,
                       payload: bytes, keep: bool,
                       extra: Optional[Dict[str, str]] = None) -> None:
        extra_lines = b""
        if extra:
            extra_lines = b"".join(
                b"%s: %s\r\n" % (k.encode("latin-1"), v.encode("latin-1"))
                for k, v in extra.items()
            )
        writer.write(
            b"HTTP/1.1 %d %s\r\n"
            b"Content-Type: %s\r\n"
            b"Content-Length: %d\r\n"
            b"%s"
            b"Connection: %s\r\n\r\n"
            % (
                status, _REASONS.get(status, b"OK"), ctype.encode(),
                len(payload), extra_lines,
                b"keep-alive" if keep else b"close",
            )
        )
        writer.write(payload)
        await writer.drain()

    async def _simple(self, writer, status: int, payload: bytes,
                      keep: bool = False) -> None:
        await self._respond(
            writer, status, "application/json", payload, keep
        )

    async def _stream(self, writer, gen, loop, status: int = 200,
                      ctype: str = "application/x-ndjson") -> bool:
        """Chunked transfer encoding: one chunk per yielded bytes item.
        The (blocking) generator advances on the pool, the writes on the
        loop. Returns False when the client disconnected mid-stream —
        the generator is CLOSED either way (its finally blocks release
        producer resources, e.g. the LLM engine's KV slot)."""
        writer.write(
            b"HTTP/1.1 %d %s\r\n"
            b"Content-Type: %s\r\n"
            b"Transfer-Encoding: chunked\r\n"
            b"Connection: keep-alive\r\n\r\n"
            % (status, _REASONS.get(status, b"OK"), ctype.encode())
        )
        alive = True
        try:
            while True:
                item = await loop.run_in_executor(self._pool, _next_or_done, gen)
                if item is _DONE:
                    break
                writer.write(b"%x\r\n%s\r\n" % (len(item), item))
                await writer.drain()
        except (ConnectionError, OSError):
            alive = False  # client went away: stop producing NOW
        finally:
            # close on the pool: generator finally blocks may issue
            # (blocking) cancel RPCs and must not run on the event loop
            await loop.run_in_executor(self._pool, _close_gen, gen)
            if alive:
                try:
                    writer.write(b"0\r\n\r\n")
                    await writer.drain()
                except (ConnectionError, OSError):
                    alive = False
        return alive


_DONE = object()


def _next_or_done(gen):
    try:
        return next(gen)
    except StopIteration:
        return _DONE


def _close_gen(gen):
    try:
        gen.close()
    except Exception:  # noqa: BLE001 — producer cleanup is best-effort
        pass


_REASONS = {
    200: b"OK", 400: b"Bad Request", 404: b"Not Found",
    413: b"Payload Too Large", 429: b"Too Many Requests",
    431: b"Request Header Fields Too Large",
    500: b"Internal Server Error", 503: b"Service Unavailable",
}
