"""Disaggregated prefill/decode: KV handoff over the zero-copy plane.

The serving split the reference implements with NIXL-backed tensor
transport (nixl_tensor_transport.py): prefill and decode run as
SEPARATE deployments so compute-bound prefill can scale independently
of latency-bound decode. Here the handoff rides the repo's own data
plane — the decode-side ingress mints an RpcChannel handle (its own
worker is the reader), calls the prefill deployment with it, and the
prefill replica ships the prompt's KV rows back through
``write_value`` (scatter-gather multiseg frames: the KV tensors travel
as raw out-of-band segments, never in-band pickles — the first
production consumer of the PR-3/8 zero-copy path outside benchmarks).

Flow per request (trace id rides every leg, so state.timeline() shows
prefill → transfer → decode as one request):

    ingress (decode replica)                 prefill replica
      mint rpc channel handle  ──payload──►  prefix-aware prefill
      resp.result()  ◄────────────ack──────  write_value(KV shipment)
      recv_kv(reader)                        [PREFILL span]
      [TRANSFER span]
      engine admit imports KV rows, decodes

Failure contract: the prefill call carries a deadline
(RT_SERVE_DISAGG_TIMEOUT_S); a SIGKILLed prefill replica surfaces as
ActorDied/Timeout on the ack or a channel-read timeout — the request
FAILS within the budget, decode never hangs on a half-open channel.
Kill switch: RT_SERVE_DISAGG=0 (ingress prefills locally as before).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, List, Optional

from ray_tpu.observability import core_metrics, tracing


def channel_capacity(model_cfg) -> int:
    """Upper bound for one KV shipment: full-length K+V rows in f32
    plus slack for the frame header/meta."""
    row = (
        model_cfg.n_layer * model_cfg.n_positions
        * model_cfg.n_head * model_cfg.head_dim * 4
    )
    return 2 * row + (1 << 20)


class PrefillEngine:
    """Prefill-only engine: one working sequence, no decode loop.
    Shares the LLMServer's weights recipe (same PRNGKey(0) init /
    checkpoint), so at temperature=0 the first token and KV rows are
    exactly what the monolithic engine would have produced.

    With the paged pool on (RT_SERVE_PAGED_KV, the engine default) the
    prefill tier runs on the SAME PagedKVPool + paged kernels as the
    decode engine — prefix KV and working KV live in one device pool
    and the shipment is a gather of the sequence's pages, eliminating
    the third KV representation disagg used to maintain (slot row +
    host BlockPool + wire tensors). The slot/BlockPool path survives
    behind the kill switch."""

    def __init__(self, cfg) -> None:
        import jax

        from ray_tpu.models import gpt2
        from ray_tpu.serve import prefix_cache
        from ray_tpu.utils.config import config

        self.cfg = cfg
        self.model_cfg = gpt2.CONFIGS[cfg.model_id]
        if cfg.checkpoint_path:
            import pickle

            with open(cfg.checkpoint_path, "rb") as f:
                self.params = pickle.load(f)
        else:
            self.params = gpt2.init(jax.random.PRNGKey(0), self.model_cfg)
        self._rng = jax.random.PRNGKey(1)
        self._paged = (
            bool(cfg.paged_kv)
            if getattr(cfg, "paged_kv", None) is not None
            else bool(config.serve_paged_kv)
        )
        if self._paged:
            B = int(config.serve_prefix_block_tokens)
            max_pages = -(-self.model_cfg.n_positions // B)
            # resident-prefix capacity matching BlockPool's budget, plus
            # one full working reservation (+ the scratch page 0), so
            # alloc can always cover a prompt by evicting LRU residents
            self._pool = prefix_cache.PagedKVPool(
                cfg.model_id,
                num_pages=(
                    int(config.serve_prefix_pool_blocks) + max_pages + 1
                ),
                page_tokens=B,
            )
        else:
            self._pool = prefix_cache.BlockPool(cfg.model_id)
        self._lock = threading.Lock()
        # slot path: [L, 1, T, H, Dh]; paged path: [L, N, B, H, Dh]
        self._cache_k = self._cache_v = None  # lazy

    def prefill(self, prompt_tokens: List[int],
                temperature: float) -> Dict[str, Any]:
        """Run (prefix-cache-aware) prefill of the prompt into the
        engine's single KV row, sample the first token, and return the
        shipment dict the decode engine's ``kv_import`` path expects."""
        import jax.numpy as jnp
        import numpy as np

        from ray_tpu.models import gpt2_decode as dec
        from ray_tpu.serve import prefix_cache
        from ray_tpu.utils.config import config

        mcfg = self.model_cfg
        T_max = mcfg.n_positions
        prompt = list(prompt_tokens)[-(T_max - 1):] or [0]

        def bucket(n: int, cap: int) -> int:
            p = 16
            while p < n:
                p *= 2
            return min(p, cap)

        if self._paged:
            return self._prefill_paged(prompt, temperature, bucket)

        with self._lock:
            if self._cache_k is None:
                self._cache_k, self._cache_v = dec.init_cache(mcfg, 1, T_max)
            pool = self._pool if config.serve_prefix_cache else None
            held: List[str] = []
            digests: List[str] = []
            cached = 0
            try:
                if pool is not None:
                    digests = prefix_cache.hash_blocks(
                        prompt, pool.block_tokens
                    )
                    held, ks, vs = pool.match(
                        digests, max_tokens=len(prompt) - 1
                    )
                    cached = len(held) * pool.block_tokens
                slot = jnp.int32(0)
                if cached:
                    self._cache_k, self._cache_v = dec.write_prefix(
                        jnp.asarray(np.concatenate(ks, axis=1)),
                        jnp.asarray(np.concatenate(vs, axis=1)),
                        self._cache_k, self._cache_v, slot,
                    )
                    tail = prompt[cached:]
                    tok = np.zeros(
                        (1, bucket(len(tail), T_max - cached)), np.int32
                    )
                    tok[0, : len(tail)] = tail
                    logits, self._cache_k, self._cache_v = dec.prefill_extend(
                        mcfg, self.params, jnp.asarray(tok),
                        jnp.int32(cached), jnp.int32(len(tail)),
                        self._cache_k, self._cache_v, slot,
                    )
                else:
                    tok = np.zeros((1, bucket(len(prompt), T_max)), np.int32)
                    tok[0, : len(prompt)] = prompt
                    logits, self._cache_k, self._cache_v = dec.prefill(
                        mcfg, self.params, jnp.asarray(tok),
                        jnp.int32(len(prompt)), self._cache_k, self._cache_v,
                        slot,
                    )
                first = self._sample_one(logits, temperature)
                # host copy of the freshly-filled row; the shipment (and
                # the pool blocks) slice it
                row_k = np.asarray(self._cache_k[:, 0])
                row_v = np.asarray(self._cache_v[:, 0])
                if pool is not None and len(digests) > len(held):
                    B = pool.block_tokens
                    for j in range(len(held), len(digests)):
                        pool.insert(
                            digests[j],
                            row_k[:, j * B:(j + 1) * B].copy(),
                            row_v[:, j * B:(j + 1) * B].copy(),
                        )
                    held = list(digests)
            except Exception:
                # prefill/write donate the caches: a post-dispatch error
                # leaves them deleted — rebuild lazily next call
                self._cache_k = self._cache_v = None
                raise
            finally:
                if pool is not None and held:
                    pool.release(held)
        n = len(prompt)
        return {
            "k": np.ascontiguousarray(row_k[:, :n]),
            "v": np.ascontiguousarray(row_v[:, :n]),
            "first_token": first,
            "prompt_len": n,
            "cached_tokens": cached,
        }

    def _prefill_paged(self, prompt: List[int], temperature: float,
                       bucket) -> Dict[str, Any]:
        """Paged-pool prefill: match resident prefix pages (refcount
        bump, zero copies), prefill only the tail into freshly
        allocated pages, seal the new full blocks, and gather the
        sequence's pages into the host shipment. Wire format is
        IDENTICAL to the slot path — the decode side never knows which
        engine produced the rows."""
        import jax.numpy as jnp
        import numpy as np

        from ray_tpu.models import gpt2_decode as dec
        from ray_tpu.serve import prefix_cache
        from ray_tpu.utils.config import config

        mcfg = self.model_cfg
        T_max = mcfg.n_positions
        pool = self._pool
        B = pool.page_tokens
        max_pages = -(-T_max // B)
        with self._lock:
            if self._cache_k is None:
                self._cache_k, self._cache_v = dec.init_paged_cache(
                    mcfg, pool.num_pages, B
                )
                pool.reset()
            use_prefix = bool(config.serve_prefix_cache)
            digests = (
                prefix_cache.hash_blocks(prompt, B) if use_prefix else []
            )
            held_pages: List[int] = []
            new_pages: List[int] = []
            try:
                # keep >=1 prompt token uncached: the tail prefill
                # produces the first-token logits
                _, held_pages = pool.match_pages(
                    digests, max_tokens=len(prompt) - 1
                )
                cached = len(held_pages) * B
                n_pages = -(-len(prompt) // B)
                alloc = pool.alloc(n_pages - len(held_pages))
                if alloc is None:
                    raise RuntimeError(
                        f"prefill page pool exhausted: need "
                        f"{n_pages - len(held_pages)} pages"
                    )
                new_pages = alloc
                pages = held_pages + new_pages
                table = np.zeros((max_pages,), np.int32)
                table[: len(pages)] = pages
                tail = prompt[cached:]
                tok = np.zeros(
                    (1, bucket(len(tail), max_pages * B - cached)), np.int32
                )
                tok[0, : len(tail)] = tail
                logits, self._cache_k, self._cache_v = dec.prefill_paged(
                    mcfg, self.params, jnp.asarray(tok), jnp.int32(cached),
                    jnp.int32(len(tail)), self._cache_k, self._cache_v,
                    jnp.asarray(table),
                )
                first = self._sample_one(logits, temperature)
                # shipment = gather of this sequence's pages (device
                # gather + ONE host copy; no per-block host pool copies)
                n = len(prompt)
                row_k = np.asarray(
                    self._cache_k[:, jnp.asarray(table[:n_pages])]
                ).reshape(mcfg.n_layer, n_pages * B, mcfg.n_head,
                          mcfg.head_dim)
                row_v = np.asarray(
                    self._cache_v[:, jnp.asarray(table[:n_pages])]
                ).reshape(mcfg.n_layer, n_pages * B, mcfg.n_head,
                          mcfg.head_dim)
                n_full = n // B
                for j in range(len(held_pages), min(n_full, len(digests))):
                    pool.seal(digests[j], int(pages[j]))
            except Exception:
                # prefill donates the caches: a post-dispatch error
                # leaves them deleted — rebuild (and reset the pool,
                # whose sealed pages pointed into them) lazily next call
                self._cache_k = self._cache_v = None
                raise
            finally:
                pool.release_pages(held_pages + new_pages)
        return {
            "k": np.ascontiguousarray(row_k[:, :n]),
            "v": np.ascontiguousarray(row_v[:, :n]),
            "first_token": first,
            "prompt_len": n,
            "cached_tokens": cached,
        }

    def _sample_one(self, logits, temperature: float) -> int:
        import jax
        import jax.numpy as jnp

        if temperature <= 0:
            return int(jnp.argmax(logits))
        self._rng, sub = jax.random.split(self._rng)
        return int(jax.random.categorical(sub, logits / temperature))

    def batch_stats(self, _payload=None) -> Dict[str, Any]:
        return {"prefix": self._pool.stats(), "pid": os.getpid()}

    def unload(self) -> None:
        """Multiplex eviction: the prefix pool dies with the engine."""
        self._pool.close()
        self._cache_k = self._cache_v = None


class PrefillServer:
    """The prefill deployment callable: receives
    ``{model, prompt_tokens, temperature, chan, trace_id}`` payloads
    from decode-side ingress replicas, runs prefill, and ships the KV
    rows back through the caller's channel handle."""

    def __init__(self, models, max_engines_per_replica: int = 2):
        from ray_tpu.serve import multiplex
        from ray_tpu.serve.openai.ingress import _normalize_models

        self._models = _normalize_models(models)
        self._engines = multiplex.make_multiplexer(
            lambda model: self._load_engine(model),
            max_models=max_engines_per_replica,
        )

    def _load_engine(self, model: str) -> PrefillEngine:
        cfg = self._models.get(model)
        if cfg is None:
            raise ValueError(f"model {model!r} does not exist")
        return PrefillEngine(cfg)

    def __call__(self, payload: Dict[str, Any]) -> Dict[str, Any]:
        if payload.get("op") == "info":
            # test/ops hook: which process serves this replica
            return {"pid": os.getpid(),
                    "models": sorted(self._models)}
        model = payload["model"]
        trace_id = payload.get("trace_id")
        t0u = tracing.now_us() if (tracing.ENABLED and trace_id) else 0
        engine = self._engines.get(model)
        shipment = engine.prefill(
            payload["prompt_tokens"], float(payload.get("temperature", 0.0))
        )
        nbytes = shipment["k"].nbytes + shipment["v"].nbytes
        send_kv(payload["chan"], shipment,
                timeout_s=float(payload.get("timeout_s", 30.0)))
        if core_metrics.ENABLED:
            core_metrics.serve_kv_transfer_bytes.inc(
                nbytes, tags={"deployment": model}
            )
        if tracing.ENABLED and trace_id:
            tracing.emit(tracing.request_span(
                trace_id, tracing.PREFILL, model, t0u,
                tracing.now_us() - t0u,
                tokens=shipment["prompt_len"],
                cached=shipment["cached_tokens"] > 0,
                kv_bytes=nbytes,
            ))
        return {
            "ok": True,
            "prompt_len": shipment["prompt_len"],
            "cached_tokens": shipment["cached_tokens"],
            "kv_bytes": nbytes,
        }


def send_kv(handle: Dict[str, Any], shipment: Dict[str, Any],
            timeout_s: float = 30.0) -> None:
    """Writer leg: serialize the shipment scatter-gather (the KV
    ndarrays become out-of-band segments; the ≥32 KiB frame rides the
    multiseg wire format, pinned by tools/check_inband_payloads.py)."""
    from ray_tpu.core import channels

    chan = channels.open_channel(handle, "write")
    try:
        chan.write_value(shipment, timeout_s=timeout_s)
    finally:
        chan.close()


def recv_kv(reader, timeout_s: float = 30.0) -> Dict[str, Any]:
    """Reader leg: one shipment off the channel (zero-copy frame)."""
    return reader.read_value(timeout_s=timeout_s)


def prefill_remote(deployment: str, model: str, eng_req: Dict[str, Any],
                   model_cfg) -> Dict[str, Any]:
    """Decode-side orchestration: run ``eng_req``'s prefill on the
    ``deployment`` prefill tier and return the ``kv_import`` dict for
    the local engine's admission. Raises within the
    RT_SERVE_DISAGG_TIMEOUT_S budget when the prefill tier is dead."""
    from ray_tpu import serve
    from ray_tpu.core import channels
    from ray_tpu.core import worker as worker_mod
    from ray_tpu.utils.config import config

    deadline = time.monotonic() + config.serve_disagg_timeout_s
    w = worker_mod.global_worker()
    handle = channels.rpc_channel_handle(
        w.address, channel_capacity(model_cfg), slots=2
    )
    reader = channels.open_channel(handle, "read")
    trace_id = eng_req.get("trace_id")
    try:
        h = serve.get_deployment_handle(deployment)
        resp = h.remote({
            "model": model,
            "prompt_tokens": eng_req["prompt_tokens"],
            "temperature": eng_req.get("temperature", 0.0),
            "chan": handle,
            "trace_id": trace_id,
            "timeout_s": max(1.0, deadline - time.monotonic()),
        })
        ack = resp.result(
            timeout_s=max(1.0, deadline - time.monotonic())
        )
        if not isinstance(ack, dict) or not ack.get("ok"):
            raise RuntimeError(f"prefill deployment returned {ack!r}")
        t0u = tracing.now_us() if (tracing.ENABLED and trace_id) else 0
        shipment = recv_kv(
            reader, timeout_s=max(1.0, deadline - time.monotonic())
        )
        if tracing.ENABLED and trace_id:
            tracing.emit(tracing.request_span(
                trace_id, tracing.TRANSFER, model, t0u,
                tracing.now_us() - t0u,
                kv_bytes=int(ack.get("kv_bytes", 0)),
            ))
        return {
            "k": shipment["k"],
            "v": shipment["v"],
            "first_token": shipment["first_token"],
            "prompt_len": shipment["prompt_len"],
        }
    finally:
        reader.close()
