"""@serve.batch — transparent request batching inside a deployment.

Parity: the reference's ``ray.serve.batch`` (python/ray/serve/batching.py:1
_BatchQueue + @serve.batch decorator): concurrent calls to the decorated
method are gathered into one list-in/list-out invocation, amortizing
per-call overhead (tokenization, device dispatch) across the batch.

Thread-based (not asyncio): replicas execute requests on actor
max_concurrency threads, so the accumulator collects across those threads
— the first caller of an empty batch becomes the *flusher* and waits out
``batch_wait_timeout_s`` (or until ``max_batch_size`` arrives), everyone
else parks on their item's event. Matches the reference's semantics:

- the wrapped function receives a LIST of requests and must return a
  list of equal length (ValueError otherwise, delivered to every caller);
- per-item exceptions: if the batch fn raises, every batched caller gets
  the error;
- ``max_batch_size`` / ``batch_wait_timeout_s`` are tunable at decoration
  time and via ``set_max_batch_size`` / ``set_batch_wait_timeout_s``
  handles (reference batching.py set_* parity).

State is created LAZILY and PER INSTANCE (method case): deployments ship
to replicas via pickle, so threading primitives must not live in the
decorator closure — and two instances of one class must never share a
queue (a batch would execute with the wrong ``self``). The config dict is
read live by the queue, so driver-side ``set_*`` calls before deployment
never materialize unpicklable state.
"""

from __future__ import annotations

import functools
import threading
import time
from typing import Any, Callable, List, Optional

from ray_tpu.observability import core_metrics

_LAZY_LOCK = threading.Lock()


class _Item:
    __slots__ = ("value", "event", "result", "error", "enq_ts")

    def __init__(self, value):
        self.value = value
        self.event = threading.Event()
        self.result = None
        self.error: Optional[BaseException] = None
        self.enq_ts = time.monotonic()


class _BatchQueue:
    def __init__(self, fn: Callable[..., List[Any]], cfg: dict):
        self.fn = fn
        self.cfg = cfg  # read live: set_* updates apply to running queues
        self._lock = threading.Lock()
        self._items: List[_Item] = []
        self._flusher_active = False
        self._arrived = threading.Condition(self._lock)
        # observability (reference exposes batch utilization metrics)
        self.num_batches = 0
        self.batch_sizes: List[int] = []

    @property
    def max_batch_size(self) -> int:
        return int(self.cfg["max_batch_size"])

    @property
    def batch_wait_timeout_s(self) -> float:
        return float(self.cfg["batch_wait_timeout_s"])

    def call(self, instance, value) -> Any:
        item = _Item(value)
        with self._lock:
            self._items.append(item)
            self._arrived.notify_all()
            if not self._flusher_active:
                self._flusher_active = True
                flusher = True
            else:
                flusher = False
        if flusher:
            self._flush_when_ready(instance)
        if not item.event.wait(timeout=300.0):
            raise TimeoutError("batched call timed out")
        if item.error is not None:
            raise item.error
        return item.result

    def _flush_when_ready(self, instance) -> None:
        deadline = time.monotonic() + self.batch_wait_timeout_s
        with self._lock:
            while (
                len(self._items) < self.max_batch_size
                and time.monotonic() < deadline
            ):
                self._arrived.wait(
                    max(0.0, min(deadline - time.monotonic(), 0.05))
                )
            batch, self._items = (
                self._items[: self.max_batch_size],
                self._items[self.max_batch_size:],
            )
            self._flusher_active = False
            if self._items:
                # leftovers: promote a new flusher thread (same instance —
                # one queue serves exactly one instance)
                self._flusher_active = True
                threading.Thread(
                    target=self._flush_when_ready, args=(instance,),
                    daemon=True,
                ).start()
        if not batch:
            return
        self.num_batches += 1
        self.batch_sizes.append(len(batch))
        if len(self.batch_sizes) > 100:
            del self.batch_sizes[:-100]
        if core_metrics.ENABLED:
            core_metrics.serve_batch_size.observe(len(batch))
            now = time.monotonic()
            for it in batch:
                core_metrics.serve_batch_wait_s.observe(now - it.enq_ts)
        try:
            args = [it.value for it in batch]
            results = (
                self.fn(instance, args) if instance is not None
                else self.fn(args)
            )
            if not isinstance(results, (list, tuple)) or len(results) != len(batch):
                raise ValueError(
                    f"@serve.batch function {self.fn.__name__} must return "
                    f"a list of length {len(batch)}, got {type(results)}"
                )
            for it, r in zip(batch, results):
                it.result = r
                it.event.set()
        except BaseException as e:  # noqa: BLE001 — fan the error out
            for it in batch:
                it.error = e
                it.event.set()


def batch(_fn: Optional[Callable] = None, *, max_batch_size: int = 8,
          batch_wait_timeout_s: float = 0.01):
    """Decorator: gather concurrent calls into one list-in/list-out call.

    Usage (reference @serve.batch parity)::

        @serve.deployment(max_concurrency=16)
        class Model:
            @serve.batch(max_batch_size=32, batch_wait_timeout_s=0.02)
            def __call__(self, requests):   # receives a LIST
                return [self.net(r) for r in requests]
    """

    def wrap(fn):
        cfg = {
            "max_batch_size": max_batch_size,
            "batch_wait_timeout_s": batch_wait_timeout_s,
        }
        attr = f"_rt_batch_queue__{fn.__name__}"
        state: dict = {}  # free-function case only

        def queue_for(instance) -> _BatchQueue:
            # import-at-call: referencing module globals directly would
            # drag a _thread.lock into this (pickled-by-value) closure
            from ray_tpu.serve import batching as _mod

            holder = instance.__dict__ if instance is not None else state
            q = holder.get(attr)
            if q is None:
                with _mod._LAZY_LOCK:
                    q = holder.get(attr)
                    if q is None:
                        q = holder[attr] = _mod._BatchQueue(fn, cfg)
            return q

        @functools.wraps(fn)
        def inner(self_or_first, *rest):
            # method: inner(self, request); free function: inner(request)
            if rest:
                return queue_for(self_or_first).call(self_or_first, rest[0])
            return queue_for(None).call(None, self_or_first)

        def set_max_batch_size(n):
            cfg["max_batch_size"] = int(n)

        def set_batch_wait_timeout_s(s):
            cfg["batch_wait_timeout_s"] = float(s)

        inner._rt_batch_cfg = cfg
        inner._rt_batch_queue_for = queue_for
        inner.set_max_batch_size = set_max_batch_size
        inner.set_batch_wait_timeout_s = set_batch_wait_timeout_s
        return inner

    if _fn is not None:
        return wrap(_fn)
    return wrap
