"""@serve.batch — transparent request batching inside a deployment.

Parity: the reference's ``ray.serve.batch`` (python/ray/serve/batching.py:1
_BatchQueue + @serve.batch decorator): concurrent calls to the decorated
method are gathered into one list-in/list-out invocation, amortizing
per-call overhead (tokenization, device dispatch) across the batch.

Thread-based (not asyncio): replicas execute requests on actor
max_concurrency threads, so the accumulator collects across those threads
— the first caller of an empty batch becomes the *flusher* and waits out
``batch_wait_timeout_s`` (or until ``max_batch_size`` arrives), everyone
else parks on their item's event. Matches the reference's semantics:

- the wrapped function receives a LIST of requests and must return a
  list of equal length (ValueError otherwise, delivered to every caller);
- per-item exceptions: if the batch fn raises, every batched caller gets
  the error;
- ``max_batch_size`` / ``batch_wait_timeout_s`` are tunable at decoration
  time and via ``set_max_batch_size`` / ``set_batch_wait_timeout_s``
  handles (reference batching.py set_* parity).
"""

from __future__ import annotations

import functools
import threading
import time
from typing import Any, Callable, List, Optional


class _Item:
    __slots__ = ("value", "event", "result", "error")

    def __init__(self, value):
        self.value = value
        self.event = threading.Event()
        self.result = None
        self.error: Optional[BaseException] = None


class _BatchQueue:
    def __init__(self, fn: Callable[[List[Any]], List[Any]],
                 max_batch_size: int, batch_wait_timeout_s: float):
        self.fn = fn
        self.max_batch_size = max_batch_size
        self.batch_wait_timeout_s = batch_wait_timeout_s
        self._lock = threading.Lock()
        self._items: List[_Item] = []
        self._flusher_active = False
        self._arrived = threading.Condition(self._lock)
        # observability (reference exposes batch utilization metrics)
        self.num_batches = 0
        self.batch_sizes: List[int] = []

    def call(self, instance, value) -> Any:
        item = _Item(value)
        with self._lock:
            self._items.append(item)
            self._arrived.notify_all()
            if not self._flusher_active:
                self._flusher_active = True
                flusher = True
            else:
                flusher = False
        if flusher:
            self._flush_when_ready(instance)
        if not item.event.wait(timeout=300.0):
            raise TimeoutError("batched call timed out")
        if item.error is not None:
            raise item.error
        return item.result

    def _flush_when_ready(self, instance) -> None:
        deadline = time.monotonic() + self.batch_wait_timeout_s
        with self._lock:
            while (
                len(self._items) < self.max_batch_size
                and time.monotonic() < deadline
            ):
                self._arrived.wait(
                    max(0.0, min(deadline - time.monotonic(), 0.05))
                )
            batch, self._items = (
                self._items[: self.max_batch_size],
                self._items[self.max_batch_size:],
            )
            self._flusher_active = False
            if self._items:
                # leftovers: promote a new flusher via the next call —
                # wake a parked caller so ITS thread takes over
                self._flusher_active = True
                threading.Thread(
                    target=self._flush_when_ready, args=(instance,),
                    daemon=True,
                ).start()
        if not batch:
            return
        self.num_batches += 1
        self.batch_sizes.append(len(batch))
        if len(self.batch_sizes) > 100:
            del self.batch_sizes[:-100]
        try:
            args = [it.value for it in batch]
            results = (
                self.fn(instance, args) if instance is not None
                else self.fn(args)
            )
            if not isinstance(results, (list, tuple)) or len(results) != len(batch):
                raise ValueError(
                    f"@serve.batch function {self.fn.__name__} must return "
                    f"a list of length {len(batch)}, got {type(results)}"
                )
            for it, r in zip(batch, results):
                it.result = r
                it.event.set()
        except BaseException as e:  # noqa: BLE001 — fan the error out
            for it in batch:
                it.error = e
                it.event.set()


def batch(_fn: Optional[Callable] = None, *, max_batch_size: int = 8,
          batch_wait_timeout_s: float = 0.01):
    """Decorator: gather concurrent calls into one list-in/list-out call.

    Usage (reference @serve.batch parity)::

        @serve.deployment(max_concurrency=16)
        class Model:
            @serve.batch(max_batch_size=32, batch_wait_timeout_s=0.02)
            def __call__(self, requests):   # receives a LIST
                return [self.net(r) for r in requests]
    """

    def wrap(fn):
        queue = _BatchQueue(fn, max_batch_size, batch_wait_timeout_s)

        @functools.wraps(fn)
        def inner(self_or_first, *rest):
            # method: inner(self, request); free function: inner(request)
            if rest:
                return queue.call(self_or_first, rest[0])
            return queue.call(None, self_or_first)

        inner._rt_batch_queue = queue
        inner.set_max_batch_size = (
            lambda n: setattr(queue, "max_batch_size", int(n))
        )
        inner.set_batch_wait_timeout_s = (
            lambda s: setattr(queue, "batch_wait_timeout_s", float(s))
        )
        return inner

    if _fn is not None:
        return wrap(_fn)
    return wrap
