"""@serve.multiplexed — N model variants per replica with LRU eviction.

Parity: the reference's model multiplexing (python/ray/serve/multiplex.py:1
_ModelMultiplexWrapper + @serve.multiplexed): one replica hosts up to
``max_num_models_per_replica`` models, loaded on demand by ``model_id``
and evicted least-recently-used; the router prefers replicas that already
hold the requested model (routing hint via the controller's replica
stats), so repeated traffic for one model stays warm on one replica.

The decorated loader must be a method taking ``model_id`` and returning
the loaded model. Consumers call ``get_model(model_id)`` — here the
decorated function IS the getter (call it with the id), matching the
reference's ``self.get_model(model_id)`` shape.

The per-replica loaded set is reported to the controller through the
replica's stats (replica.py attaches ``multiplexed_model_ids``), and the
router's pow-2 choice is filtered to model-holding replicas first
(router.py), falling back to any replica (which then loads + maybe
evicts).
"""

from __future__ import annotations

import functools
import threading
from collections import OrderedDict
from typing import Callable, Optional

# replica-process-global registry: replica.py reads it to report loaded
# model ids; keyed by wrapper id so several multiplexed loaders coexist
_REGISTRY = {}
_REGISTRY_LOCK = threading.Lock()


def loaded_model_ids():
    """All model ids currently loaded in this process (for replica
    stats)."""
    with _REGISTRY_LOCK:
        wrappers = list(_REGISTRY.values())
    out = []
    for w in wrappers:
        out.extend(w.model_ids())
    return out


class _Multiplexer:
    def __init__(self, loader: Callable, max_models: int):
        self.loader = loader
        self.max_models = max_models
        self._lock = threading.Lock()
        self._models: "OrderedDict[str, object]" = OrderedDict()
        self._loading: dict = {}  # model_id -> Event (single-flight)

    def model_ids(self):
        with self._lock:
            return list(self._models)

    def peek(self, model_id: str):
        """Loaded model or None — never loads, never touches the LRU."""
        with self._lock:
            return self._models.get(model_id)

    def get(self, instance, model_id: str):
        while True:
            with self._lock:
                if model_id in self._models:
                    self._models.move_to_end(model_id)
                    return self._models[model_id]
                ev = self._loading.get(model_id)
                if ev is None:
                    ev = threading.Event()
                    self._loading[model_id] = ev
                    break
            # another thread is loading this model: wait for it
            ev.wait(timeout=300.0)
        try:
            model = (
                self.loader(instance, model_id) if instance is not None
                else self.loader(model_id)
            )
            from ray_tpu.observability import core_metrics

            if core_metrics.ENABLED:
                core_metrics.serve_multiplex_loads.inc(
                    tags={"model": model_id}
                )
            with self._lock:
                self._models[model_id] = model
                self._models.move_to_end(model_id)
                evicted = []
                evicted_ids = []
                while len(self._models) > self.max_models:
                    old_id, old = self._models.popitem(last=False)  # LRU out
                    evicted.append(old)
                    evicted_ids.append(old_id)
            if core_metrics.ENABLED:
                for old_id in evicted_ids:
                    core_metrics.serve_multiplex_evictions.inc(
                        tags={"model": old_id}
                    )
            for old in evicted:
                # reference calls __del__/model cleanup hooks if present
                unload = getattr(old, "unload", None)
                if callable(unload):
                    try:
                        unload()
                    except Exception:  # noqa: BLE001 — eviction best-effort
                        pass
            return model
        finally:
            with self._lock:
                self._loading.pop(model_id, None)
            ev.set()


class ModelMultiplexer:
    """Imperative multiplexer for callers that configure ``max_models``
    at runtime (the decorator form fixes it at class-definition time).
    ``loader(model_id)`` loads a model; get() caches it LRU-bounded and
    the loaded set feeds the replica's multiplexed-model stats like the
    decorator does. Build with :func:`make_multiplexer` INSIDE the
    replica (init, not module scope): the registry entry must land in
    the replica process for the router's warm-model affinity to see it."""

    def __init__(self, mux: _Multiplexer):
        self._mux = mux

    def get(self, model_id: str):
        return self._mux.get(None, model_id)

    def peek(self, model_id: str):
        return self._mux.peek(model_id)

    def model_ids(self):
        return self._mux.model_ids()


def make_multiplexer(loader: Callable, max_models: int = 3) -> ModelMultiplexer:
    mux = _Multiplexer(loader, max_models)
    with _REGISTRY_LOCK:
        _REGISTRY[id(mux)] = mux
    return ModelMultiplexer(mux)


def multiplexed(_fn: Optional[Callable] = None, *,
                max_num_models_per_replica: int = 3):
    """Decorator for a model-loading method; calling the decorated method
    returns the (cached) model for ``model_id``::

        @serve.deployment
        class Multi:
            @serve.multiplexed(max_num_models_per_replica=2)
            def get_model(self, model_id: str):
                return load_weights(model_id)   # expensive, runs once

            def __call__(self, req):
                model = self.get_model(req.query["model_id"])
                return model(req.body)
    """

    def wrap(fn):
        # The multiplexer (with its locks) is created LAZILY and PER
        # INSTANCE: deployments ship to replicas via pickle (threading
        # primitives must stay out of the closure), and two instances of
        # one class must not share an LRU — a model loaded with r1's self
        # must never be served for r2. Lazy creation also lands the
        # _REGISTRY entry in the REPLICA process, where the loaded-model
        # stats belong.
        attr = f"_rt_multiplexer__{fn.__name__}"
        state: dict = {}  # free-function case only

        def mux_for(instance) -> _Multiplexer:
            # import-at-call: referencing the module lock directly would
            # drag a _thread.lock into this (pickled-by-value) closure
            from ray_tpu.serve import multiplex as _mod

            holder = instance.__dict__ if instance is not None else state
            m = holder.get(attr)
            if m is None:
                with _mod._REGISTRY_LOCK:
                    m = holder.get(attr)
                    if m is None:
                        m = holder[attr] = _mod._Multiplexer(
                            fn, max_num_models_per_replica
                        )
                        _mod._REGISTRY[id(m)] = m
            return m

        @functools.wraps(fn)
        def inner(self_or_id, *rest):
            if rest:
                return mux_for(self_or_id).get(self_or_id, rest[0])
            return mux_for(None).get(None, self_or_id)

        inner._rt_multiplexer_for = mux_for
        return inner

    if _fn is not None:
        return wrap(_fn)
    return wrap
