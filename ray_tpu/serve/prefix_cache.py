"""Block-granular prefix KV cache for the LLM engine.

vLLM-style automatic prefix caching ported to the slot-cache engine
(serve/llm.py): prompts are chopped into fixed-size token blocks, each
block is identified by a CHAIN hash (its own tokens + the parent
block's digest, so a digest names an entire prefix, not just 64 loose
tokens), and the host-side K/V for every full block a request prefills
is parked in a per-engine refcounted pool. The next request sharing
that prefix copies the matched blocks straight into its slot
(gpt2_decode.write_prefix) and prefills only the uncached tail
(gpt2_decode.prefill_extend) — TTFT stops paying for the shared system
prompt.

Lifecycle contract: ``match`` and ``insert`` both leave the caller
holding ONE ref per returned/inserted digest; the engine releases them
when the request leaves its slot (finish/cancel/fail/unload). Only
refcount-0 blocks are LRU-evictable; ``close()`` drops everything
regardless of refcounts — a multiplex eviction must not strand
resident blocks (the pool is gone with the engine).

Kill switch: RT_SERVE_PREFIX_CACHE=0 (checked at admission, so it
doubles as bench_core's A/B lever at runtime).
"""

from __future__ import annotations

import hashlib
import os
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ray_tpu.observability import core_metrics
from ray_tpu.utils.config import config

# Live pools in this process (engine model_id -> pool), for unload
# accounting and tests. An engine owns at most one pool (BlockPool for
# the slot engine, PagedKVPool for the paged engine).
_POOLS: Dict[int, Any] = {}
_POOLS_LOCK = threading.Lock()


def hash_blocks(tokens: Sequence[int], block_tokens: int) -> List[str]:
    """Chained content digests of the prompt's FULL blocks.

    digest_i = blake2b(digest_{i-1} || int32 tokens of block i), so two
    prompts share digest_i iff they share the entire first (i+1) blocks
    — a pool lookup never has to compare token lists, and the digests
    are stable across processes/replicas (pure content, no pid/seed).
    The trailing partial block is never hashed: only full blocks are
    cacheable."""
    n_full = len(tokens) // block_tokens
    if n_full <= 0:
        return []
    arr = np.asarray(tokens[: n_full * block_tokens], dtype=np.int32)
    out: List[str] = []
    parent = b""
    for i in range(n_full):
        h = hashlib.blake2b(digest_size=16)
        h.update(parent)
        h.update(arr[i * block_tokens : (i + 1) * block_tokens].tobytes())
        parent = h.digest()
        out.append(parent.hex())
    return out


class _Block:
    __slots__ = ("digest", "k", "v", "refs", "tick")

    def __init__(self, digest: str, k: np.ndarray, v: np.ndarray):
        self.digest = digest
        self.k = k  # [L, B, H, Dh] host copy, engine compute dtype
        self.v = v
        self.refs = 0
        self.tick = 0


class BlockPool:
    """Refcounted, LRU-evicted pool of prefix KV blocks for one engine."""

    def __init__(self, model_id: str, block_tokens: Optional[int] = None,
                 max_blocks: Optional[int] = None):
        self.model_id = model_id
        self.block_tokens = int(
            block_tokens or config.serve_prefix_block_tokens
        )
        self.max_blocks = int(max_blocks or config.serve_prefix_pool_blocks)
        self._lock = threading.Lock()
        self._blocks: Dict[str, _Block] = {}
        self._tick = 0
        self._closed = False
        # plain counters independent of the metrics kill switch, for
        # engine stats()/bench assertions
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self._node_tag = f"pid{os.getpid()}"
        with _POOLS_LOCK:
            _POOLS[id(self)] = self

    # -- lookup / insert / release ------------------------------------

    def match(
        self, digests: Sequence[str], max_tokens: int
    ) -> Tuple[List[str], List[np.ndarray], List[np.ndarray]]:
        """Longest resident chain prefix of ``digests``, capped so at
        most ``max_tokens`` tokens come from cache (the engine keeps at
        least one prompt token for the tail prefill — a fully-cached
        prompt would have nothing to produce first-token logits from).
        Increfs every matched block; caller must release()."""
        cap = max(0, int(max_tokens)) // self.block_tokens
        held: List[str] = []
        ks: List[np.ndarray] = []
        vs: List[np.ndarray] = []
        with self._lock:
            if not self._closed:
                for d in digests[:cap]:
                    blk = self._blocks.get(d)
                    if blk is None:
                        break
                    blk.refs += 1
                    self._tick += 1
                    blk.tick = self._tick
                    held.append(d)
                    ks.append(blk.k)
                    vs.append(blk.v)
            hits = len(held)
            misses = len(digests) - hits
            self.hits += hits
            self.misses += misses
            if core_metrics.ENABLED:
                tags = {"deployment": self.model_id}
                if hits:
                    core_metrics.serve_prefix_cache_hits.inc(hits, tags=tags)
                if misses:
                    core_metrics.serve_prefix_cache_misses.inc(
                        misses, tags=tags
                    )
        return held, ks, vs

    def insert(self, digest: str, k: np.ndarray, v: np.ndarray) -> None:
        """Park one block's host K/V ``[L, B, H, Dh]``; a block already
        resident is just touched (re-insert after a capped match). The
        caller holds one ref either way until release()."""
        with self._lock:
            if self._closed:
                return
            blk = self._blocks.get(digest)
            if blk is None:
                blk = _Block(digest, k, v)
                self._blocks[digest] = blk
            blk.refs += 1
            self._tick += 1
            blk.tick = self._tick
            self._evict_locked()
            self._publish_resident_locked()

    def release(self, digests: Sequence[str]) -> None:
        """Drop the caller's refs (request left its slot); newly
        refcount-0 blocks become LRU-evictable but stay resident —
        that residency IS the cache."""
        if not digests:
            return
        with self._lock:
            for d in digests:
                blk = self._blocks.get(d)
                if blk is not None and blk.refs > 0:
                    blk.refs -= 1
            self._evict_locked()
            self._publish_resident_locked()

    # -- maintenance ---------------------------------------------------

    def _evict_locked(self) -> None:
        while len(self._blocks) > self.max_blocks:
            victim = None
            for blk in self._blocks.values():
                if blk.refs == 0 and (
                    victim is None or blk.tick < victim.tick
                ):
                    victim = blk
            if victim is None:
                return  # everything pinned by in-flight requests
            del self._blocks[victim.digest]
            self.evictions += 1
            if core_metrics.ENABLED:
                core_metrics.serve_prefix_cache_evictions.inc(
                    tags={"deployment": self.model_id}
                )

    def _publish_resident_locked(self) -> None:
        if core_metrics.ENABLED:
            core_metrics.serve_prefix_blocks_resident.set(
                len(self._blocks),
                tags={"deployment": self.model_id, "node": self._node_tag},
            )

    def resident(self) -> int:
        with self._lock:
            return len(self._blocks)

    def ref_count(self, digest: str) -> int:
        with self._lock:
            blk = self._blocks.get(digest)
            return blk.refs if blk is not None else 0

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "blocks": len(self._blocks),
                "block_tokens": self.block_tokens,
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
            }

    def close(self) -> None:
        """Unconditionally drop every block (engine unload/eviction):
        outstanding refs die with the engine's slots, so honoring them
        would strand the blocks forever."""
        with self._lock:
            self._blocks.clear()
            self._closed = True
            self._publish_resident_locked()
        with _POOLS_LOCK:
            _POOLS.pop(id(self), None)


class _Page:
    """Metadata for one device-resident KV page. The page's K/V content
    lives in the engine's paged device cache (gpt2_decode.init_paged_cache
    row ``idx``); the pool only tracks who may read it."""

    __slots__ = ("idx", "refs", "digest", "tick")

    def __init__(self, idx: int):
        self.idx = idx
        self.refs = 0
        # set when the page is SEALED as a prefix block: its content is
        # the KV of a full prompt block named by this chain digest
        self.digest: Optional[str] = None
        self.tick = 0


class PagedKVPool:
    """Refcounted allocator over ONE device-resident page pool shared by
    generation KV and prefix KV (vLLM-style paged attention, metadata
    side). Unlike :class:`BlockPool` it holds NO host tensor copies —
    a prefix hit is a refcount bump on pages already sitting in the
    device cache, zero block copies.

    Page 0 is a reserved scratch page, never allocated: inactive decode
    rows scatter their junk K/V there (their page tables are all-zero),
    so the jitted decode step needs no per-row validity branch.

    Lifecycle: ``alloc`` returns pages with one ref each (the admitting
    request's pin). ``seal`` registers a written page under its chain
    digest so later ``match_pages`` calls can pin it too (one more ref
    per reader). ``release_pages`` drops refs; a ref-0 UNSEALED page
    goes straight back to the free list, a ref-0 sealed page stays
    resident as cache and is reclaimed by global LRU only when ``alloc``
    runs dry — that residency IS the prefix cache, and eviction order is
    strictly least-recently-matched over everything not pinned by a
    live request."""

    def __init__(self, model_id: str, num_pages: int,
                 page_tokens: Optional[int] = None):
        self.model_id = model_id
        self.page_tokens = int(
            page_tokens or config.serve_prefix_block_tokens
        )
        self.num_pages = int(num_pages)
        if self.num_pages < 2:
            raise ValueError("paged pool needs >= 2 pages (page 0 is scratch)")
        self._lock = threading.Lock()
        self._pages: List[_Page] = [_Page(i) for i in range(self.num_pages)]
        # page 0 reserved as scratch: never on the free list
        self._free: List[int] = list(range(self.num_pages - 1, 0, -1))
        self._sealed: Dict[str, int] = {}  # digest -> page idx
        self._tick = 0
        self._closed = False
        # plain counters independent of the metrics kill switch, for
        # engine stats()/bench/test assertions
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        # block copies performed at admission on this pool's behalf
        # (KV-import page writes; a prefix hit must contribute ZERO) —
        # incremented by the engine next to each device copy it issues
        self.copies = 0
        self._node_tag = f"pid{os.getpid()}"
        with _POOLS_LOCK:
            _POOLS[id(self)] = self

    # -- allocation ----------------------------------------------------

    def alloc(self, n: int) -> Optional[List[int]]:
        """Allocate ``n`` pages (refs=1 each), evicting least-recently-
        used ref-0 sealed pages if the free list runs dry. Returns None
        — and takes nothing — when even eviction can't cover the ask:
        admission defers, it never half-allocates."""
        if n <= 0:
            return []
        with self._lock:
            if self._closed:
                return None
            while len(self._free) < n and self._evict_one_locked():
                pass
            if len(self._free) < n:
                return None
            out = [self._free.pop() for _ in range(n)]
            for idx in out:
                pg = self._pages[idx]
                pg.refs = 1
                pg.digest = None
                self._tick += 1
                pg.tick = self._tick
            return out

    def _evict_one_locked(self) -> bool:
        victim: Optional[_Page] = None
        for d, idx in self._sealed.items():
            pg = self._pages[idx]
            if pg.refs == 0 and (victim is None or pg.tick < victim.tick):
                victim = pg
        if victim is None:
            return False  # every sealed page pinned by a live request
        del self._sealed[victim.digest]
        victim.digest = None
        self._free.append(victim.idx)
        self.evictions += 1
        if core_metrics.ENABLED:
            core_metrics.serve_prefix_cache_evictions.inc(
                tags={"deployment": self.model_id}
            )
        return True

    # -- prefix matching / sealing ------------------------------------

    def match_pages(
        self, digests: Sequence[str], max_tokens: int
    ) -> Tuple[List[str], List[int]]:
        """Longest resident chain prefix of ``digests`` (capped so at
        most ``max_tokens`` tokens come from cache — the engine keeps at
        least one prompt token for the tail prefill). Increfs every
        matched page; caller must release_pages(). ZERO copies: the
        returned page indices go straight into the request's page table."""
        cap = max(0, int(max_tokens)) // self.page_tokens
        held: List[str] = []
        pages: List[int] = []
        with self._lock:
            if not self._closed:
                for d in digests[:cap]:
                    idx = self._sealed.get(d)
                    if idx is None:
                        break
                    pg = self._pages[idx]
                    pg.refs += 1
                    self._tick += 1
                    pg.tick = self._tick
                    held.append(d)
                    pages.append(idx)
            hits = len(held)
            misses = len(digests) - hits
            self.hits += hits
            self.misses += misses
            if core_metrics.ENABLED:
                tags = {"deployment": self.model_id}
                if hits:
                    core_metrics.serve_prefix_cache_hits.inc(hits, tags=tags)
                if misses:
                    core_metrics.serve_prefix_cache_misses.inc(
                        misses, tags=tags
                    )
        return held, pages

    def seal(self, digest: str, page: int) -> bool:
        """Register an already-written page as the prefix block named by
        ``digest`` — no copy, the KV is already in the device cache.
        Returns False (page stays private to its request, freed on
        release) when the digest is already sealed elsewhere: two
        racing requests with the same prompt must converge on ONE
        canonical page."""
        with self._lock:
            if self._closed or digest in self._sealed:
                return False
            pg = self._pages[page]
            pg.digest = digest
            self._sealed[digest] = page
            self._tick += 1
            pg.tick = self._tick
            self._publish_resident_locked()
            return True

    # -- release / maintenance ----------------------------------------

    def release_pages(self, pages: Sequence[int]) -> None:
        """Drop the caller's pins. Ref-0 unsealed pages return to the
        free list immediately; ref-0 sealed pages stay resident (LRU-
        evictable) — that residency is the cache."""
        if not pages:
            return
        with self._lock:
            for idx in pages:
                pg = self._pages[idx]
                if pg.refs > 0:
                    pg.refs -= 1
                if pg.refs == 0 and pg.digest is None and not self._closed:
                    self._free.append(idx)
            self._publish_resident_locked()

    def reset(self) -> None:
        """Drop ALL metadata (poisoned engine round rebuilt the device
        cache with zeros, so every sealed page's content is gone — the
        BlockPool could survive this because it held host copies; this
        pool cannot)."""
        with self._lock:
            if self._closed:
                return
            for pg in self._pages:
                pg.refs = 0
                pg.digest = None
                pg.tick = 0
            self._sealed.clear()
            self._free = list(range(self.num_pages - 1, 0, -1))
            self._tick = 0
            self._publish_resident_locked()

    def _publish_resident_locked(self) -> None:
        if core_metrics.ENABLED:
            core_metrics.serve_prefix_blocks_resident.set(
                len(self._sealed),
                tags={"deployment": self.model_id, "node": self._node_tag},
            )

    # -- introspection -------------------------------------------------

    def free_pages(self) -> int:
        with self._lock:
            return len(self._free)

    def resident(self) -> int:
        """Sealed prefix pages resident (BlockPool-compatible name)."""
        with self._lock:
            return len(self._sealed)

    def ref_count(self, digest: str) -> int:
        with self._lock:
            idx = self._sealed.get(digest)
            return self._pages[idx].refs if idx is not None else 0

    def page_refs(self, page: int) -> int:
        with self._lock:
            return self._pages[page].refs

    def stats(self) -> Dict[str, int]:
        with self._lock:
            free = len(self._free)
            return {
                "blocks": len(self._sealed),
                "block_tokens": self.page_tokens,
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
                "copies": self.copies,
                "pages_total": self.num_pages - 1,  # scratch excluded
                "pages_free": free,
                "pages_occupied": self.num_pages - 1 - free,
                "prefix_resident": len(self._sealed),
            }

    def close(self) -> None:
        """Engine unload/eviction: drop everything regardless of refs —
        outstanding pins die with the engine's sequences."""
        with self._lock:
            for pg in self._pages:
                pg.refs = 0
                pg.digest = None
            self._sealed.clear()
            self._free = []
            self._closed = True
            self._publish_resident_locked()
        with _POOLS_LOCK:
            _POOLS.pop(id(self), None)


def live_pools() -> List[Any]:
    """Pools not yet close()d in this process (test/debug hook)."""
    with _POOLS_LOCK:
        return list(_POOLS.values())
