"""ray_tpu.serve.openai — OpenAI-compatible serving front door.

Parity target: the reference's serve/llm ingress
(python/ray/llm/_internal/serve/deployments/routers/router.py +
serve/llm/openai_api_models.py): `/v1/completions`,
`/v1/chat/completions` and `/v1/models` speaking the OpenAI wire
protocol — JSON request/response bodies, SSE streaming
(``data: {...}\\n\\n`` frames, ``data: [DONE]`` terminator), `usage`
token accounting, and OpenAI-shaped error bodies — in front of the
native KV-cache engine (`serve/llm.py`).

Layers:
  protocol.py   request/response dataclasses, validation, SSE framing
  tokenizer.py  pluggable tokenizer registry + byte-level fallback
  ingress.py    the OpenAIServer deployment (multiplexed engines)

Deploy with ``ray_tpu.serve.llm.deploy(...)``.
"""

from ray_tpu.serve.openai.ingress import OpenAIServer, build_openai_deployment
from ray_tpu.serve.openai.protocol import (
    ChatCompletionRequest,
    CompletionRequest,
    OpenAIError,
    error_body,
    probe,
    sse_event,
    SSE_DONE,
)
from ray_tpu.serve.openai.tokenizer import (
    ByteTokenizer,
    get_tokenizer,
    register_tokenizer,
)

__all__ = [
    "ByteTokenizer",
    "ChatCompletionRequest",
    "CompletionRequest",
    "OpenAIError",
    "OpenAIServer",
    "SSE_DONE",
    "build_openai_deployment",
    "error_body",
    "get_tokenizer",
    "probe",
    "register_tokenizer",
    "sse_event",
]
