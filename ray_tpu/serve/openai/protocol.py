"""OpenAI wire protocol: request/response models, SSE framing, errors.

Parity: the reference's serve/llm OpenAI models
(python/ray/llm/_internal/serve/configs/openai_api_models.py — itself a
vLLM-protocol mirror): `/v1/completions` and `/v1/chat/completions`
request bodies validated into dataclasses, response/chunk dataclasses
serialized back to the exact field shapes the `openai` python client
parses, `usage` accounting, SSE framing (``data: {json}\n\n`` with a
``data: [DONE]\n\n`` terminator) and OpenAI-shaped error envelopes
(``{"error": {"message", "type", "param", "code"}}``).

Everything here is transport-agnostic pure data: the ingress deployment
(ingress.py) builds these from engine output, and the proxy only probes
(``probe()``) the body for routing hints (stream flag, model id,
session key) without interpreting the rest.
"""

from __future__ import annotations

import hashlib
import json
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

# ---------------------------------------------------------------------------
# Errors
# ---------------------------------------------------------------------------


class OpenAIError(Exception):
    """Validation/lookup failure that maps to an OpenAI error body."""

    def __init__(self, message: str, status: int = 400,
                 err_type: str = "invalid_request_error",
                 param: Optional[str] = None, code: Optional[str] = None):
        super().__init__(message)
        self.status = status
        self.err_type = err_type
        self.param = param
        self.code = code

    def body(self) -> bytes:
        return error_body(
            str(self), err_type=self.err_type, param=self.param,
            code=self.code,
        )


def error_body(message: str, err_type: str = "invalid_request_error",
               param: Optional[str] = None,
               code: Optional[str] = None) -> bytes:
    return json.dumps({
        "error": {
            "message": message, "type": err_type,
            "param": param, "code": code,
        }
    }).encode()


# ---------------------------------------------------------------------------
# SSE framing
# ---------------------------------------------------------------------------

SSE_DONE = b"data: [DONE]\n\n"
SSE_CONTENT_TYPE = "text/event-stream"


def sse_event(obj: Any) -> bytes:
    """One server-sent event carrying a JSON payload (the only event
    shape the OpenAI streaming protocol uses)."""
    return b"data: " + json.dumps(obj, separators=(",", ":")).encode() + b"\n\n"


def sse_error(message: str, err_type: str = "internal_error") -> bytes:
    """Mid-stream failure: the status line already went out as 200, so
    the error travels as a data event (the openai client surfaces it as
    a malformed-chunk error, matching reference behavior)."""
    return b"data: " + error_body(message, err_type=err_type) + b"\n\n"


# ---------------------------------------------------------------------------
# Requests
# ---------------------------------------------------------------------------


def _require(body: Dict[str, Any], key: str) -> Any:
    if key not in body or body[key] is None:
        raise OpenAIError(
            f"you must provide a {key!r} parameter", param=key,
            code="missing_field",
        )
    return body[key]


def _opt_number(body: Dict[str, Any], key: str, default, lo, hi):
    v = body.get(key, default)
    if v is None:
        return default
    try:
        v = float(v)
    except (TypeError, ValueError):
        raise OpenAIError(
            f"{key!r} must be a number, got {v!r}", param=key
        ) from None
    if not lo <= v <= hi:
        raise OpenAIError(
            f"{key!r} must be between {lo} and {hi}, got {v}", param=key
        )
    return v


@dataclass
class CompletionRequest:
    model: str
    prompt: str
    max_tokens: int = 16
    temperature: float = 1.0
    stream: bool = False
    n: int = 1
    user: Optional[str] = None
    echo: bool = False

    @classmethod
    def from_body(cls, body: Any) -> "CompletionRequest":
        if not isinstance(body, dict):
            raise OpenAIError("request body must be a JSON object")
        prompt = _require(body, "prompt")
        if isinstance(prompt, list):
            # the API accepts a batch of prompts; a single-element list is
            # common client behavior, larger batches are out of scope here
            if len(prompt) != 1 or not isinstance(prompt[0], str):
                raise OpenAIError(
                    "only a single string prompt is supported", param="prompt"
                )
            prompt = prompt[0]
        if not isinstance(prompt, str):
            raise OpenAIError("'prompt' must be a string", param="prompt")
        n = int(body.get("n") or 1)
        if n != 1:
            raise OpenAIError("only n=1 is supported", param="n")
        return cls(
            model=str(_require(body, "model")),
            prompt=prompt,
            max_tokens=int(_opt_number(body, "max_tokens", 16, 0, 1 << 20)),
            temperature=_opt_number(body, "temperature", 1.0, 0.0, 2.0),
            stream=bool(body.get("stream")),
            n=1,
            user=body.get("user"),
            echo=bool(body.get("echo")),
        )


@dataclass
class ChatMessage:
    role: str
    content: str

    def as_dict(self) -> Dict[str, str]:
        return {"role": self.role, "content": self.content}


@dataclass
class ChatCompletionRequest:
    model: str
    messages: List[ChatMessage]
    max_tokens: int = 16
    temperature: float = 1.0
    stream: bool = False
    user: Optional[str] = None

    @classmethod
    def from_body(cls, body: Any) -> "ChatCompletionRequest":
        if not isinstance(body, dict):
            raise OpenAIError("request body must be a JSON object")
        raw = _require(body, "messages")
        if not isinstance(raw, list) or not raw:
            raise OpenAIError(
                "'messages' must be a non-empty array", param="messages"
            )
        messages = []
        for i, m in enumerate(raw):
            if not isinstance(m, dict) or "role" not in m:
                raise OpenAIError(
                    f"messages[{i}] must be an object with a 'role'",
                    param="messages",
                )
            content = m.get("content")
            if not isinstance(content, str):
                raise OpenAIError(
                    f"messages[{i}].content must be a string", param="messages"
                )
            messages.append(ChatMessage(str(m["role"]), content))
        # both spellings: max_completion_tokens superseded max_tokens
        max_tokens = body.get("max_completion_tokens", body.get("max_tokens", 16))
        return cls(
            model=str(_require(body, "model")),
            messages=messages,
            max_tokens=int(_opt_number(
                {"max_tokens": max_tokens}, "max_tokens", 16, 0, 1 << 20
            )),
            temperature=_opt_number(body, "temperature", 1.0, 0.0, 2.0),
            stream=bool(body.get("stream")),
            user=body.get("user"),
        )


# ---------------------------------------------------------------------------
# Responses
# ---------------------------------------------------------------------------


@dataclass
class UsageInfo:
    prompt_tokens: int = 0
    completion_tokens: int = 0

    def as_dict(self) -> Dict[str, int]:
        return {
            "prompt_tokens": self.prompt_tokens,
            "completion_tokens": self.completion_tokens,
            "total_tokens": self.prompt_tokens + self.completion_tokens,
        }


def _new_id(prefix: str) -> str:
    return f"{prefix}-{uuid.uuid4().hex[:24]}"


@dataclass
class CompletionResponse:
    model: str
    text: str
    finish_reason: str
    usage: UsageInfo
    system_fingerprint: Optional[str] = None
    id: str = field(default_factory=lambda: _new_id("cmpl"))
    created: int = field(default_factory=lambda: int(time.time()))

    def as_dict(self) -> Dict[str, Any]:
        return {
            "id": self.id,
            "object": "text_completion",
            "created": self.created,
            "model": self.model,
            "system_fingerprint": self.system_fingerprint,
            "choices": [{
                "index": 0, "text": self.text, "logprobs": None,
                "finish_reason": self.finish_reason,
            }],
            "usage": self.usage.as_dict(),
        }

    def json_bytes(self) -> bytes:
        return json.dumps(self.as_dict()).encode()


@dataclass
class ChatCompletionResponse:
    model: str
    content: str
    finish_reason: str
    usage: UsageInfo
    system_fingerprint: Optional[str] = None
    id: str = field(default_factory=lambda: _new_id("chatcmpl"))
    created: int = field(default_factory=lambda: int(time.time()))

    def as_dict(self) -> Dict[str, Any]:
        return {
            "id": self.id,
            "object": "chat.completion",
            "created": self.created,
            "model": self.model,
            "system_fingerprint": self.system_fingerprint,
            "choices": [{
                "index": 0,
                "message": {"role": "assistant", "content": self.content},
                "logprobs": None,
                "finish_reason": self.finish_reason,
            }],
            "usage": self.usage.as_dict(),
        }

    def json_bytes(self) -> bytes:
        return json.dumps(self.as_dict()).encode()


def completion_chunk(rid: str, created: int, model: str, text: str,
                     finish_reason: Optional[str] = None,
                     usage: Optional[UsageInfo] = None,
                     system_fingerprint: Optional[str] = None) -> Dict[str, Any]:
    out: Dict[str, Any] = {
        "id": rid, "object": "text_completion", "created": created,
        "model": model, "system_fingerprint": system_fingerprint,
        "choices": [{
            "index": 0, "text": text, "logprobs": None,
            "finish_reason": finish_reason,
        }],
    }
    if usage is not None:
        out["usage"] = usage.as_dict()
    return out


def chat_chunk(rid: str, created: int, model: str,
               delta: Dict[str, Any],
               finish_reason: Optional[str] = None,
               usage: Optional[UsageInfo] = None,
               system_fingerprint: Optional[str] = None) -> Dict[str, Any]:
    out: Dict[str, Any] = {
        "id": rid, "object": "chat.completion.chunk", "created": created,
        "model": model, "system_fingerprint": system_fingerprint,
        "choices": [{
            "index": 0, "delta": delta, "logprobs": None,
            "finish_reason": finish_reason,
        }],
    }
    if usage is not None:
        out["usage"] = usage.as_dict()
    return out


def model_list(model_ids: List[str]) -> Dict[str, Any]:
    return {
        "object": "list",
        "data": [
            {
                "id": mid, "object": "model", "created": 0,
                "owned_by": "ray_tpu",
            }
            for mid in model_ids
        ],
    }


# ---------------------------------------------------------------------------
# Proxy-side body probe (routing hints only)
# ---------------------------------------------------------------------------


class Probe:
    """Routing hints the HTTP proxy extracts from an OpenAI request
    without fully interpreting it: whether the response streams (the
    stream flag lives in the JSON body, not the query string), which
    model it targets (multiplex warm-engine affinity), the session
    key (same `user` sticks to the replica holding its warm KV slots)
    and the prefix hint (requests sharing leading prompt text land on
    the replica whose engine holds those prefix KV blocks)."""

    __slots__ = ("endpoint", "stream", "model", "session_key",
                 "prefix_hint")

    def __init__(self, endpoint: str, stream: bool,
                 model: Optional[str], session_key: Optional[str],
                 prefix_hint: Optional[str] = None):
        self.endpoint = endpoint
        self.stream = stream
        self.model = model
        self.session_key = session_key
        self.prefix_hint = prefix_hint


_SESSION_HEADER = "x-session-id"

# Prefix-hint contract (must match across proxies; the engine's block
# pool is what the hint targets, so the geometry tracks the default
# serve_prefix_block_tokens=64 under the 1-byte-per-token tokenizer):
# hash the first <=256 chars of the rendered prompt, but only when at
# least 64 chars exist — shorter prompts share no full 64-token block,
# and pinning them all to one rendezvous replica would just hotspot it.
_PREFIX_HINT_MAX_CHARS = 256
_PREFIX_HINT_MIN_CHARS = 64


def _prefix_hint(obj: Dict[str, Any]) -> Optional[str]:
    """Content digest of the request's leading prompt text. Pure
    function of the body (no pid/salt) so every proxy maps a shared
    system prompt to the same rendezvous key. Chat bodies reuse the
    tokenizer's chat template rendering for the leading messages so the
    hinted text is exactly what the engine will tokenize."""
    if isinstance(obj.get("prompt"), str):
        lead = obj["prompt"]
    elif isinstance(obj.get("messages"), list):
        parts = []
        for m in obj["messages"]:
            if not isinstance(m, dict):
                return None
            parts.append(f"<|{m.get('role')}|>{m.get('content')}")
            if sum(len(p) for p in parts) >= _PREFIX_HINT_MAX_CHARS:
                break
        lead = "\n".join(parts)
    else:
        return None
    if len(lead) < _PREFIX_HINT_MIN_CHARS:
        return None
    return hashlib.blake2b(
        lead[:_PREFIX_HINT_MAX_CHARS].encode("utf-8", "replace"),
        digest_size=8,
    ).hexdigest()


def probe(method: str, path: str, body: bytes,
          headers: Dict[str, str]) -> Optional[Probe]:
    """Classify an OpenAI front-door request. Conservative on purpose:
    path shape alone is not enough (a pre-existing user deployment at
    ``/api/models`` or ``/foo/completions`` must keep its generic
    behavior), so completions/chat additionally require an OpenAI-shaped
    JSON object body carrying ``model``, and the models listing requires
    the canonical ``/v1/models`` tail. Returns None for everything
    else — the proxy's generic paths."""
    if path.endswith("/chat/completions"):
        endpoint = "chat"
    elif path.endswith("/completions"):
        endpoint = "completions"
    elif path.endswith("/v1/models") or path == "/v1/models":
        return Probe("models", False, None, None)
    else:
        return None
    try:
        obj = json.loads(body) if body else {}
    except ValueError:
        return None
    if not isinstance(obj, dict) or "model" not in obj:
        return None
    model = obj.get("model")
    user = obj.get("user") or headers.get(_SESSION_HEADER)
    return Probe(
        endpoint, bool(obj.get("stream")),
        str(model) if model is not None else None,
        str(user) if user is not None else None,
        _prefix_hint(obj),
    )


def finish_reason(produced: int, max_tokens: int) -> str:
    return "length" if produced >= max_tokens else "stop"


def split_http_result(result: Any) -> Tuple[int, str, Any]:
    """Normalize an ingress return value to (status, content_type, body).
    Bytes-like bodies (incl. zero-copy memoryviews off the direct RPC
    path) pass through unchanged."""
    if isinstance(result, tuple) and len(result) == 3:
        return result
    if isinstance(result, (bytes, bytearray, memoryview)):
        return 200, "application/json", result
    return 200, "application/json", json.dumps(result).encode()
