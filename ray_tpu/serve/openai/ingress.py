"""OpenAI ingress deployment: HTTP surface of the LLM engine.

Parity: the reference's LLMRouter/LLMServer ingress
(python/ray/llm/_internal/serve/deployments/routers/router.py): one
deployment class that terminates `/v1/completions`,
`/v1/chat/completions` and `/v1/models`, translates them to the
engine's token-id interface through the tokenizer layer, and emits
OpenAI response bodies — SSE chunks when ``stream: true``.

Each replica hosts its engines IN-PROCESS through the multiplex layer
(one ``LLMServer`` continuous-batching engine per served model id,
LRU-bounded), so the OpenAI ``model`` field doubles as the multiplexed
model id: the controller's replica stats report loaded engines, the
router prefers replicas already holding the model, and the session key
(OpenAI ``user``) rendezvous-pins a conversation to one replica's warm
KV slots.

Concurrency: requests execute on the hosting worker's RPC dispatcher
threads (direct path) or the replica's executor threads; the engine's
continuous batcher coalesces them into shared decode steps, so the
ingress itself is thread-safe by construction (no mutable state past
init beyond the engine multiplexer).
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, Iterator, List, Optional, Union

from ray_tpu.serve.openai import protocol
from ray_tpu.serve.openai.protocol import (
    ChatCompletionRequest,
    CompletionRequest,
    OpenAIError,
    UsageInfo,
)
from ray_tpu.serve.openai import tokenizer as tokenizer_mod
from ray_tpu.observability import tracing


def _normalize_models(models) -> Dict[str, Any]:
    """Accept str | LLMConfig | {name: str|dict|LLMConfig} and return
    {openai model name: LLMConfig}."""
    from ray_tpu.serve.llm import LLMConfig

    def to_cfg(name: str, v) -> LLMConfig:
        if isinstance(v, LLMConfig):
            return v
        if isinstance(v, str):
            return LLMConfig(model_id=v)
        if isinstance(v, dict):
            return LLMConfig(**v)
        raise TypeError(f"model {name!r}: cannot build LLMConfig from {v!r}")

    if isinstance(models, str):
        return {models: to_cfg(models, models)}
    if isinstance(models, dict):
        return {name: to_cfg(name, v) for name, v in models.items()}
    from ray_tpu.serve.llm import LLMConfig as _C

    if isinstance(models, _C):
        return {models.model_id: models}
    raise TypeError(f"unsupported models spec: {models!r}")


class OpenAIServer:
    """The `/v1` deployment callable (one instance per replica)."""

    def __init__(self, models, tokenizer: Optional[str] = None,
                 max_engines_per_replica: int = 2,
                 prefill_deployment: Optional[str] = None):
        from ray_tpu.serve import multiplex

        self._models = _normalize_models(models)
        self._tokenizer_name = tokenizer
        # disaggregated serving: name of the prefill-tier deployment
        # (serve/kv_transfer.py PrefillServer); None = monolithic
        self._prefill_deployment = prefill_deployment
        # engines load lazily per model id and evict LRU — the multiplex
        # registry also feeds the replica's loaded-model stats, which the
        # router's warm-engine affinity reads
        self._engines = multiplex.make_multiplexer(
            lambda model: self._load_engine(model),
            max_models=max_engines_per_replica,
        )
        # replica identity surfaced as system_fingerprint so clients (and
        # the affinity tests) can observe which replica answered
        self._fingerprint = f"rt-replica-{os.getpid()}"

    def _load_engine(self, model: str):
        from ray_tpu.serve.llm import LLMServer

        cfg = self._models.get(model)
        if cfg is None:
            raise OpenAIError(
                f"model {model!r} does not exist", status=404,
                err_type="invalid_request_error", param="model",
                code="model_not_found",
            )
        return LLMServer(cfg)

    def _tokenizer_for(self, model: str):
        return tokenizer_mod.get_tokenizer(self._tokenizer_name or model)

    # -- request entry ---------------------------------------------------

    def __call__(self, request: Any):
        """Route one front-door request. ``request`` is the proxy's
        Request (method/path/body) or a plain dict (handle calls in
        tests)."""
        try:
            return self._route(request)
        except OpenAIError as e:
            return e.status, "application/json", e.body()

    def _route(self, request: Any):
        trace_id = None
        if isinstance(request, dict):  # handle.remote() / test calls
            body = request
            path = request.get("__path__", "/v1/completions")
        else:
            path = getattr(request, "path", "") or ""
            if tracing.ENABLED:
                trace_id = (getattr(request, "headers", None)
                            or {}).get(tracing.TRACE_HEADER)
            if path.endswith("/models"):
                return self.list_models()
            try:
                body = request.json()
            except ValueError:
                raise OpenAIError("request body is not valid JSON") from None
        if path.endswith("/chat/completions"):
            return self.chat_completion(body, trace_id=trace_id)
        if path.endswith("/completions"):
            return self.completion(body, trace_id=trace_id)
        if path.endswith("/models"):
            return self.list_models()
        raise OpenAIError(f"no OpenAI route for {path!r}", status=404,
                          err_type="invalid_request_error")

    # -- endpoints -------------------------------------------------------

    def list_models(self):
        return 200, "application/json", json.dumps(
            protocol.model_list(sorted(self._models))
        ).encode()

    def _error_stream(self, e: OpenAIError) -> Iterator[bytes]:
        """A stream=true request that failed before decoding began: the
        error travels as the stream's only SSE event (the proxy already
        committed to the streaming response path from its body probe)."""
        yield b"data: " + e.body() + b"\n\n"
        yield protocol.SSE_DONE

    def completion(self, body: Any, trace_id: Optional[str] = None):
        try:
            req = CompletionRequest.from_body(body)
            tok = self._tokenizer_for(req.model)
            prompt_tokens = tok.encode(req.prompt)
            engine, eng_req = self._engine_request(
                req.model, prompt_tokens, req.max_tokens, req.temperature,
                trace_id=trace_id,
            )
        except OpenAIError as e:
            if isinstance(body, dict) and body.get("stream"):
                return self._error_stream(e)
            raise
        if req.stream:
            return self._stream_completion(engine, eng_req, req, tok)
        out = engine(eng_req)
        produced: List[int] = out["tokens"]
        text = tok.decode(produced)
        if req.echo:
            text = req.prompt + text
        resp = protocol.CompletionResponse(
            model=req.model, text=text,
            finish_reason=protocol.finish_reason(len(produced), req.max_tokens),
            usage=UsageInfo(len(prompt_tokens), len(produced)),
            system_fingerprint=self._fingerprint,
        )
        return 200, "application/json", resp.json_bytes()

    def chat_completion(self, body: Any, trace_id: Optional[str] = None):
        try:
            req = ChatCompletionRequest.from_body(body)
            tok = self._tokenizer_for(req.model)
            prompt_tokens = tokenizer_mod.encode_chat(req.messages, tok)
            engine, eng_req = self._engine_request(
                req.model, prompt_tokens, req.max_tokens, req.temperature,
                trace_id=trace_id,
            )
        except OpenAIError as e:
            if isinstance(body, dict) and body.get("stream"):
                return self._error_stream(e)
            raise
        if req.stream:
            return self._stream_chat(engine, eng_req, req, tok)
        out = engine(eng_req)
        produced: List[int] = out["tokens"]
        resp = protocol.ChatCompletionResponse(
            model=req.model, content=tok.decode(produced),
            finish_reason=protocol.finish_reason(len(produced), req.max_tokens),
            usage=UsageInfo(len(prompt_tokens), len(produced)),
            system_fingerprint=self._fingerprint,
        )
        return 200, "application/json", resp.json_bytes()

    def _engine_request(self, model: str, prompt_tokens: List[int],
                        max_tokens: int, temperature: float,
                        trace_id: Optional[str] = None):
        engine = self._engines.get(model)
        vocab = engine.model_cfg.vocab_size
        eng_req = {
            # out-of-vocab tokens (a non-byte tokenizer against a tiny
            # test vocab) clamp instead of faulting the gather
            "prompt_tokens": [min(int(t), vocab - 1) for t in prompt_tokens],
            "max_new_tokens": int(max_tokens),
            "temperature": float(temperature),
        }
        if trace_id is not None:
            # rides the engine-request dict: the proxy-minted trace id
            # reaches the engine span without a header-bearing object
            eng_req["trace_id"] = trace_id
        return engine, self._maybe_disaggregate(model, engine, eng_req)

    def _maybe_disaggregate(self, model: str, engine,
                            eng_req: Dict[str, Any]) -> Dict[str, Any]:
        """Disaggregated serving: run the prefill leg on the prefill
        deployment and attach the shipped KV rows as ``kv_import`` so
        the local engine only decodes. No-op without a prefill tier or
        with RT_SERVE_DISAGG=0. A dead prefill tier fails the request
        within RT_SERVE_DISAGG_TIMEOUT_S (never a decode hang)."""
        from ray_tpu.utils.config import config

        if self._prefill_deployment is None or not config.serve_disagg:
            return eng_req
        from ray_tpu.serve import kv_transfer

        try:
            imp = kv_transfer.prefill_remote(
                self._prefill_deployment, model, eng_req, engine.model_cfg
            )
        except OpenAIError:
            raise
        except Exception as e:  # noqa: BLE001 — OpenAI-shaped surface
            raise OpenAIError(
                f"disaggregated prefill failed: {type(e).__name__}: {e}",
                status=500, err_type="internal_error",
            ) from e
        return {**eng_req, "kv_import": imp}

    # -- SSE streaming ---------------------------------------------------

    def _stream_completion(self, engine, eng_req: Dict[str, Any],
                           req: CompletionRequest, tok) -> Iterator[bytes]:
        """SSE chunks for /v1/completions. Closing the generator (client
        disconnect) closes the engine stream, which cancels the request
        and frees its KV slot."""
        rid = protocol._new_id("cmpl")
        created = int(time.time())
        n_prompt = len(eng_req["prompt_tokens"])

        def gen():
            eng_gen = engine({**eng_req, "stream": True})
            dec = tok.incremental_decoder()
            produced = 0
            try:
                if req.echo:
                    yield protocol.sse_event(protocol.completion_chunk(
                        rid, created, req.model, req.prompt,
                        system_fingerprint=self._fingerprint,
                    ))
                for item in eng_gen:
                    produced += 1
                    text = dec.feed(item["token"])
                    if text:
                        yield protocol.sse_event(protocol.completion_chunk(
                            rid, created, req.model, text,
                            system_fingerprint=self._fingerprint,
                        ))
                tail = dec.flush()
                if tail:
                    yield protocol.sse_event(protocol.completion_chunk(
                        rid, created, req.model, tail,
                        system_fingerprint=self._fingerprint,
                    ))
                yield protocol.sse_event(protocol.completion_chunk(
                    rid, created, req.model, "",
                    finish_reason=protocol.finish_reason(
                        produced, req.max_tokens
                    ),
                    usage=UsageInfo(n_prompt, produced),
                    system_fingerprint=self._fingerprint,
                ))
                yield protocol.SSE_DONE
            finally:
                eng_gen.close()  # disconnect mid-stream frees the KV slot

        return gen()

    def _stream_chat(self, engine, eng_req: Dict[str, Any],
                     req: ChatCompletionRequest, tok) -> Iterator[bytes]:
        rid = protocol._new_id("chatcmpl")
        created = int(time.time())
        n_prompt = len(eng_req["prompt_tokens"])

        def gen():
            eng_gen = engine({**eng_req, "stream": True})
            dec = tok.incremental_decoder()
            produced = 0
            try:
                # the role announcement chunk the openai client expects
                yield protocol.sse_event(protocol.chat_chunk(
                    rid, created, req.model,
                    {"role": "assistant", "content": ""},
                    system_fingerprint=self._fingerprint,
                ))
                for item in eng_gen:
                    produced += 1
                    text = dec.feed(item["token"])
                    if text:
                        yield protocol.sse_event(protocol.chat_chunk(
                            rid, created, req.model, {"content": text},
                            system_fingerprint=self._fingerprint,
                        ))
                tail = dec.flush()
                if tail:
                    yield protocol.sse_event(protocol.chat_chunk(
                        rid, created, req.model, {"content": tail},
                        system_fingerprint=self._fingerprint,
                    ))
                yield protocol.sse_event(protocol.chat_chunk(
                    rid, created, req.model, {},
                    finish_reason=protocol.finish_reason(
                        produced, req.max_tokens
                    ),
                    usage=UsageInfo(n_prompt, produced),
                    system_fingerprint=self._fingerprint,
                ))
                yield protocol.SSE_DONE
            finally:
                eng_gen.close()

        return gen()

    # -- introspection (tests / ops) ------------------------------------

    def engine_stats(self, model: Optional[str] = None) -> Dict[str, Any]:
        """Stats of a loaded engine WITHOUT loading it (None when the
        model has no engine on this replica)."""
        for mid in self._engines.model_ids():
            if model is None or mid == model:
                eng = self._engines.peek(mid)
                if eng is not None:
                    stats = eng.batch_stats()
                    stats["model"] = mid
                    stats["fingerprint"] = self._fingerprint
                    return stats
        return {"model": model, "fingerprint": self._fingerprint,
                "batches": 0, "occupied": 0}


def build_openai_deployment(
    models: Union[str, Dict[str, Any]],
    *,
    name: str = "openai-llm",
    num_replicas: int = 1,
    route_prefix: str = "/v1",
    tokenizer: Optional[str] = None,
    max_engines_per_replica: int = 2,
    max_concurrency: int = 16,
    autoscaling_config: Optional[Dict[str, Any]] = None,
    ray_actor_options: Optional[Dict[str, float]] = None,
    prefill_deployment: Optional[str] = None,
    max_queued_requests: Optional[int] = None,
):
    """Bind the multi-replica OpenAI front door (use serve.llm.deploy to
    also run it)."""
    from ray_tpu import serve

    _normalize_models(models)  # validate early, in the driver
    dep = serve.deployment(
        OpenAIServer,
        name=name,
        num_replicas=num_replicas,
        route_prefix=route_prefix,
        max_concurrency=max_concurrency,
        autoscaling_config=autoscaling_config,
        ray_actor_options=ray_actor_options,
        max_queued_requests=max_queued_requests,
    )
    return dep.bind(
        models, tokenizer=tokenizer,
        max_engines_per_replica=max_engines_per_replica,
        prefill_deployment=prefill_deployment,
    )
