"""Pluggable tokenizer layer for the OpenAI front door.

The reference resolves a HuggingFace tokenizer per served model
(vllm's get_tokenizer); this image ships no vocab files, so the
default is a deterministic BYTE-LEVEL tokenizer: token i is byte i
(0..255), which maps exactly onto the gpt2-tiny test config's
vocab_size=256 and round-trips any UTF-8 text. Real deployments
register their tokenizer under the model name::

    from ray_tpu.serve.openai import register_tokenizer
    register_tokenizer("my-model", lambda: MyBPETokenizer(...))

and the ingress resolves it with ``get_tokenizer(name)`` (falling back
to the byte tokenizer so tests and dryruns never need vocab files).

A tokenizer is any object with ``encode(text) -> List[int]``,
``decode(tokens) -> str`` and ``incremental_decoder() -> obj`` where
``obj.feed(token) -> str`` yields the newly-decodable text (UTF-8
multibyte sequences must not be split mid-character across SSE chunks).
"""

from __future__ import annotations

import codecs
import threading
from typing import Callable, Dict, List, Optional

# ---------------------------------------------------------------------------
# Byte-level fallback
# ---------------------------------------------------------------------------


class _ByteIncrementalDecoder:
    """Streams tokens to text without splitting multibyte characters:
    a UTF-8 continuation byte buffers until its sequence completes, so
    each feed() returns only fully-decodable text."""

    def __init__(self):
        self._dec = codecs.getincrementaldecoder("utf-8")("replace")

    def feed(self, token: int) -> str:
        return self._dec.decode(bytes([int(token) & 0xFF]))

    def flush(self) -> str:
        return self._dec.decode(b"", final=True)


class ByteTokenizer:
    """Deterministic byte-level tokenizer: token i == byte i. Vocab size
    256 — exactly the gpt2-tiny test config's vocabulary."""

    vocab_size = 256

    def encode(self, text: str) -> List[int]:
        return list(text.encode("utf-8"))

    def decode(self, tokens: List[int]) -> str:
        return bytes(int(t) & 0xFF for t in tokens).decode(
            "utf-8", errors="replace"
        )

    def incremental_decoder(self) -> _ByteIncrementalDecoder:
        return _ByteIncrementalDecoder()


# ---------------------------------------------------------------------------
# Registry
# ---------------------------------------------------------------------------

_registry: Dict[str, Callable[[], object]] = {}
_instances: Dict[str, object] = {}
_lock = threading.Lock()


def register_tokenizer(name: str, factory: Callable[[], object]) -> None:
    """Register a tokenizer factory under a model (or tokenizer) name."""
    with _lock:
        _registry[name] = factory
        _instances.pop(name, None)


def get_tokenizer(name: Optional[str] = None):
    """Resolve a tokenizer by name; unknown names fall back to the byte
    tokenizer (this image has no vocab files — the serving machinery,
    not text quality, is the parity surface)."""
    key = name or "byte"
    with _lock:
        inst = _instances.get(key)
        if inst is None:
            factory = _registry.get(key, ByteTokenizer)
            inst = _instances[key] = factory()
        return inst


# ---------------------------------------------------------------------------
# Chat template
# ---------------------------------------------------------------------------

# Flattens a message list into one prompt string; role sentinels keep
# turns distinguishable to the model and the trailing assistant cue asks
# for the next turn (the minimal analogue of a HF chat_template).
_ROLE_OPEN = "<|{role}|>"
_ASSISTANT_CUE = "<|assistant|>"


def render_chat(messages) -> str:
    parts = []
    for m in messages:
        role = m.role if hasattr(m, "role") else m["role"]
        content = m.content if hasattr(m, "content") else m["content"]
        parts.append(_ROLE_OPEN.format(role=role) + content)
    parts.append(_ASSISTANT_CUE)
    return "\n".join(parts)


def encode_chat(messages, tokenizer) -> List[int]:
    """Flatten messages through the chat template into the engine's
    token-id stream."""
    return tokenizer.encode(render_chat(messages))
