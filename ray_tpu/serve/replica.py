"""Serve replica actor.

Parity: the reference Replica/UserCallableWrapper
(python/ray/serve/_private/replica.py:1688,2679): hosts one instance of
the user's deployment callable, tracks ongoing-request count (the signal
the pow-2 router and the autoscaler consume), and exposes a health probe.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, Optional, Tuple

import ray_tpu


class Request:
    """Minimal HTTP-ish request object handed to deployments called via
    the proxy (parity: starlette Request in the reference)."""

    def __init__(self, method: str, path: str, body: bytes,
                 headers: Optional[Dict[str, str]] = None,
                 query: Optional[Dict[str, str]] = None):
        self.method = method
        self.path = path
        self.body = body
        self.headers = headers or {}
        self.query = query or {}

    def json(self) -> Any:
        import json

        return json.loads(self.body or b"null")

    def text(self) -> str:
        return (self.body or b"").decode("utf-8", errors="replace")


@ray_tpu.remote
class ServeReplica:
    """One replica of a deployment. max_concurrency on the actor lets
    multiple requests execute concurrently in threads; _ongoing tracks
    in-flight requests for routing/autoscaling."""

    def __init__(self, deployment_name: str, callable_blob: bytes,
                 init_args: Tuple, init_kwargs: Dict[str, Any]):
        from ray_tpu.utils import serialization

        self.deployment_name = deployment_name
        cls_or_fn = serialization.loads(callable_blob)
        if isinstance(cls_or_fn, type):
            self._callable = cls_or_fn(*init_args, **init_kwargs)
        else:
            self._callable = cls_or_fn
        self._ongoing = 0
        self._total = 0
        self._lock = threading.Lock()
        self._started = time.time()

    def _trace_id_of(self, payload: Any) -> Optional[str]:
        from ray_tpu.observability import tracing

        headers = getattr(payload, "headers", None)
        if headers:
            return headers.get(tracing.TRACE_HEADER)
        return None

    def _stamp(self, trace_id: Optional[str], t0_us: int) -> None:
        from ray_tpu.observability import tracing

        if trace_id and tracing.ENABLED:
            tracing.emit(tracing.request_span(
                trace_id, tracing.REPLICA, self.deployment_name,
                t0_us, tracing.now_us() - t0_us,
            ))

    def handle_request(self, payload: Any, *, method: Optional[str] = None):
        from ray_tpu.observability import tracing

        trace_id = self._trace_id_of(payload) if tracing.ENABLED else None
        t0_us = tracing.now_us() if trace_id else 0
        with self._lock:
            self._ongoing += 1
            self._total += 1
        try:
            target = self._callable
            if method:
                target = getattr(self._callable, method)
            return target(payload)
        finally:
            with self._lock:
                self._ongoing -= 1
            self._stamp(trace_id, t0_us)

    def handle_request_direct(self, payload: Any, *,
                              method: Optional[str] = None):
        """Proxy hot-path entry (worker rpc_actor_direct_call): same
        semantics as handle_request, but the result is wrapped so bulk
        response bodies ride the RPC reply as out-of-band multi-segment
        frames instead of being re-pickled in-band:

          ("raw",  body)                  bytes-like response
          ("http", (status, ctype, body)) explicit HTTP triple
          ("obj",  value)                 anything else (JSON-encoded by
                                          the proxy)

        where ``body`` is serialization.maybe_frame output — a Frame
        once it crosses the 32 KiB out-of-band floor."""
        from ray_tpu.utils import serialization

        result = self.handle_request(payload, method=method)
        if isinstance(result, (bytes, bytearray)):
            return ("raw", serialization.maybe_frame(result))
        if (
            isinstance(result, tuple) and len(result) == 3
            and isinstance(result[0], int)
            and isinstance(result[2], (bytes, bytearray))
        ):
            status, ctype, body = result
            return ("http", (status, ctype, serialization.maybe_frame(body)))
        return ("obj", result)

    @ray_tpu.method(num_returns="streaming")
    def handle_request_streaming(self, payload: Any, *,
                                 method: Optional[str] = None):
        """Streaming variant: the deployment returns an iterable and each
        item reaches the caller as it is produced (core streaming
        generators; parity: reference streaming deployment responses
        through the proxy's chunked transfer)."""
        from ray_tpu.observability import tracing

        trace_id = self._trace_id_of(payload) if tracing.ENABLED else None
        t0_us = tracing.now_us() if trace_id else 0
        with self._lock:
            self._ongoing += 1
            self._total += 1
        try:
            target = self._callable
            if method:
                target = getattr(self._callable, method)
            result = target(payload)
            if result is None:
                return
            if isinstance(result, (bytes, str, dict, tuple)):
                # non-iterable response (a tuple is an HTTP triple, not a
                # stream): one chunk
                yield result
                return
            yield from result
        finally:
            with self._lock:
                self._ongoing -= 1
            self._stamp(trace_id, t0_us)

    def health(self) -> bool:
        return True

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "ongoing": self._ongoing,
                "total": self._total,
                "uptime_s": time.time() - self._started,
            }
