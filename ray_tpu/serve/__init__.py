"""ray_tpu.serve — online model serving.

Parity target: Ray Serve (reference python/ray/serve — controller
reconciler, per-node HTTP proxies, pow-2 request router, replica
autoscaling, deployment handles).
"""

from ray_tpu.serve.api import (
    DeploymentResponse,
    Deployment,
    DeploymentHandle,
    autoscale_status,
    delete,
    deployment,
    get_deployment_handle,
    proxy_addresses,
    run,
    scale,
    shutdown,
    start,
    status,
)
from ray_tpu.serve.batching import batch
from ray_tpu.serve.multiplex import make_multiplexer, multiplexed
from ray_tpu.serve.replica import Request

# submodules, imported LAST (they import this package's API above):
# serve.llm.deploy(...) is the OpenAI front-door entrypoint and
# serve.openai holds its protocol/tokenizer/ingress layers
from ray_tpu.serve import llm, openai  # noqa: E402  (cycle-safe tail import)

__all__ = [
    "llm",
    "make_multiplexer",
    "openai",
    "Deployment",
    "DeploymentHandle",
    "DeploymentResponse",
    "Request",
    "autoscale_status",
    "batch",
    "delete",
    "deployment",
    "get_deployment_handle",
    "multiplexed",
    "proxy_addresses",
    "run",
    "scale",
    "shutdown",
    "start",
    "status",
]
