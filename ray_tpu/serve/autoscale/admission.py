"""Proxy-side admission control + load shedding.

Bounds the work a proxy will hold instead of letting overload collapse
the whole serving path: past the per-deployment in-flight bound the
proxy sheds with ``503`` (the deployment is overloaded — retry after
backoff), past the per-model concurrency cap with ``429`` (this model
is rate-limited — slow down). Every shed carries ``Retry-After`` so
well-behaved clients back off instead of hammering, and is counted in
``rt_serve_shed_total`` (by deployment and reason) which feeds the
``serve_shed_rate`` alert rule.

Counts are per-proxy (one proxy per node): the bound is "work THIS
proxy has admitted and not yet finished", covering both the fast
direct-RPC path and the pool paths, streaming included (a stream holds
its slot until the generator closes — in-flight is what occupies
replicas, not just what is queued).

This runs on the proxy's HTTP event loop (the fast-path handler), so
everything here must be non-blocking: plain dict bookkeeping under an
uncontended ``threading.Lock``, no RPCs, no sleeps. The rtlint
blocking-async pass pins that (ON_LOOP_FUNCTIONS).
"""

from __future__ import annotations

import math
import os
import threading
from dataclasses import dataclass
from typing import Dict, Optional, Tuple


@dataclass(frozen=True)
class Shed:
    """A rejected admission: everything the HTTP layer needs to answer.
    ``status`` is 503 for deployment overload, 429 for a per-model
    concurrency cap."""

    status: int
    reason: str  # metric tag: "deployment_overload" | "model_concurrency"
    err_type: str  # OpenAI-style error.type for /v1 responses
    retry_after_s: float
    message: str

    def headers(self) -> Dict[str, str]:
        return {"Retry-After": str(max(1, math.ceil(self.retry_after_s)))}


class AdmissionController:
    """Per-proxy admission bookkeeping. ``try_acquire`` either admits
    (returns None; the caller MUST ``release`` exactly once when the
    request — including any streaming body — finishes) or shed
    (returns a ``Shed``; nothing to release)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._node_tag = f"pid{os.getpid()}"
        self._by_dep: Dict[str, int] = {}
        self._by_model: Dict[Tuple[str, str], int] = {}

    def inflight(self, deployment: str) -> int:
        with self._lock:
            return self._by_dep.get(deployment, 0)

    def try_acquire(
        self,
        deployment: str,
        model_id: Optional[str] = None,
        max_inflight: Optional[int] = None,
    ) -> Optional[Shed]:
        from ray_tpu.observability import core_metrics
        from ray_tpu.utils.config import config

        enabled = bool(config.serve_admission_enabled)
        cap = (
            int(max_inflight)
            if max_inflight is not None
            else int(config.serve_admission_max_inflight)
        )
        model_cap = int(config.serve_admission_model_concurrency)
        retry_after = float(config.serve_admission_retry_after_s)
        shed = None
        with self._lock:
            cur = self._by_dep.get(deployment, 0)
            if enabled and cap > 0 and cur >= cap:
                shed = Shed(
                    status=503,
                    reason="deployment_overload",
                    err_type="overloaded_error",
                    retry_after_s=retry_after,
                    message=(
                        f"deployment {deployment!r} is at its in-flight "
                        f"bound ({cap}); retry after backoff"
                    ),
                )
            elif (
                enabled
                and model_id
                and model_cap > 0
                and self._by_model.get((deployment, model_id), 0) >= model_cap
            ):
                shed = Shed(
                    status=429,
                    reason="model_concurrency",
                    err_type="rate_limit_error",
                    retry_after_s=retry_after,
                    message=(
                        f"model {model_id!r} is at its concurrency cap "
                        f"({model_cap}); slow down"
                    ),
                )
            else:
                # Admit. Counting even when disabled keeps acquire/release
                # pairing consistent if the kill switch flips mid-flight.
                self._by_dep[deployment] = cur + 1
                if model_id:
                    key = (deployment, model_id)
                    self._by_model[key] = self._by_model.get(key, 0) + 1
                cur += 1
        if core_metrics.ENABLED:
            if shed is not None:
                core_metrics.serve_shed.inc(
                    tags={"deployment": deployment, "reason": shed.reason}
                )
            else:
                core_metrics.serve_admission_inflight.set(
                    float(cur),
                    tags={"deployment": deployment, "node": self._node_tag},
                )
        return shed

    def release(
        self, deployment: str, model_id: Optional[str] = None
    ) -> None:
        from ray_tpu.observability import core_metrics

        with self._lock:
            cur = self._by_dep.get(deployment, 0) - 1
            if cur <= 0:
                self._by_dep.pop(deployment, None)
                cur = 0
            else:
                self._by_dep[deployment] = cur
            if model_id:
                key = (deployment, model_id)
                n = self._by_model.get(key, 0) - 1
                if n <= 0:
                    self._by_model.pop(key, None)
                else:
                    self._by_model[key] = n
        if core_metrics.ENABLED:
            core_metrics.serve_admission_inflight.set(
                float(cur),
                tags={"deployment": deployment, "node": self._node_tag},
            )
