"""SLO-driven autoscaling policy.

Replaces the naive requests-per-replica autoscaler with decisions
driven by the signals that actually predict SLO violation:

- windowed TTFT p95 from the head's metrics history (the serving
  north-star, same series the burn-rate alert watches),
- KV-slot occupancy (occupied/total) and queue-depth gauges,
- the ``serve_ttft_p95_burn`` alert state itself — firing is a
  scale-up hint even when raw counts look tame.

Split in two so the decision logic stays unit-testable without a
cluster:

- ``SignalCollector`` does the RPCs (metrics_history / alerts against
  the head) and degrades gracefully: any signal it cannot compute —
  sampler off, no samples in the window, RPC failure — comes back
  ``None``/``False`` and the policy falls back to the ongoing-count
  baseline.
- ``SLOPolicy`` is pure: (current replicas, Signals, autoscaling
  config, now) -> Decision, with hysteresis (separate high/low
  watermarks), cooldowns (scale-up can jump straight to the desired
  count after ``serve_autoscale_up_cooldown_s``; scale-down steps ONE
  replica at a time and only after every signal stayed quiet for
  ``serve_autoscale_down_cooldown_s``, re-armed after each step — with
  sustained FULL idleness overriding windowed echoes of handled
  traffic) and min/max replica bounds.

Tag fallback: engine metrics (serve/llm.py) tag series with the MODEL
id, not the serve deployment name, so the collector tries the
deployment name, then each multiplexed model id seen in replica stats,
then the untagged aggregate.
"""

from __future__ import annotations

import logging
import math
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, Iterable, List, Optional

from ray_tpu.utils.config import config
from ray_tpu.utils.metrics import hist_quantile

logger = logging.getLogger(__name__)

BURN_RULE = "serve_ttft_p95_burn"


@dataclass
class Signals:
    """One autoscale tick's view of a deployment. ``None`` means "no
    data" (never "zero") — the policy treats missing signals as quiet
    for scale-up and as non-blocking for scale-down."""

    ongoing: int = 0  # queued + running across replicas (always known)
    ttft_p95_s: Optional[float] = None
    kv_occupancy: Optional[float] = None  # occupied/total, 0..1
    queue_depth: Optional[float] = None  # windowed avg queued requests
    burn_firing: bool = False

    def describe(self) -> Dict[str, Any]:
        return {
            "ongoing": self.ongoing,
            "ttft_p95_s": self.ttft_p95_s,
            "kv_occupancy": self.kv_occupancy,
            "queue_depth": self.queue_depth,
            "burn_firing": self.burn_firing,
        }


@dataclass
class Decision:
    target: int
    direction: str  # "up" | "down" | "hold"
    reason: str

    def describe(self) -> Dict[str, Any]:
        return {
            "target": self.target,
            "direction": self.direction,
            "reason": self.reason,
        }


class SLOPolicy:
    """Pure decision engine; one instance per controller, per-deployment
    hysteresis state keyed by deployment name."""

    def __init__(self):
        # name -> {"last_up_ts", "last_down_ts", "ok_since"}
        self._state: Dict[str, Dict[str, Optional[float]]] = {}

    def forget(self, name: str) -> None:
        self._state.pop(name, None)

    def decide(
        self,
        name: str,
        current: int,
        signals: Signals,
        auto: Dict[str, Any],
        now: Optional[float] = None,
    ) -> Decision:
        now = time.monotonic() if now is None else now
        st = self._state.setdefault(
            name, {"last_up_ts": None, "last_down_ts": None, "ok_since": None}
        )
        lo = int(auto.get("min_replicas", 1))
        hi = int(auto.get("max_replicas", 8))
        target_per = max(
            1e-9, float(auto.get("target_ongoing_requests", 1))
        )
        ttft_target = max(1e-9, float(config.alerts_ttft_target_s))
        queue_max = float(config.alerts_queue_depth_max)

        # Baseline: the requests-per-replica count the old policy used.
        # It reacts instantly to a burst, before any windowed series has
        # samples, and keeps behavior on metric-less deployments intact.
        base = math.ceil(signals.ongoing / target_per)

        pressure: List[str] = []
        if signals.burn_firing:
            pressure.append("ttft_burn_firing")
        if (
            signals.ttft_p95_s is not None
            and signals.ttft_p95_s
            > ttft_target * float(config.serve_autoscale_ttft_high_frac)
        ):
            pressure.append(f"ttft_p95={signals.ttft_p95_s:.3f}s")
        if (
            signals.kv_occupancy is not None
            and signals.kv_occupancy
            > float(config.serve_autoscale_kv_high_frac)
        ):
            pressure.append(f"kv_occupancy={signals.kv_occupancy:.2f}")
        if (
            signals.queue_depth is not None
            and signals.queue_depth > queue_max
        ):
            pressure.append(f"queue_depth={signals.queue_depth:.1f}")

        desired = base
        if pressure and signals.ongoing > 0:
            # SLO pressure asks for at least one more replica even when
            # the ongoing count alone would not. With ZERO in-flight
            # work the pressure signals are windowed echoes of traffic
            # already handled — another replica can't serve requests
            # that no longer exist.
            desired = max(desired, current + 1)
        desired = max(lo, min(hi, desired))

        if desired > current:
            st["ok_since"] = None
            last_up = st["last_up_ts"]
            cooldown = float(config.serve_autoscale_up_cooldown_s)
            if last_up is not None and now - last_up < cooldown:
                return Decision(current, "hold", "up_cooldown")
            st["last_up_ts"] = now
            why = pressure[0] if pressure else f"ongoing={signals.ongoing}"
            return Decision(desired, "up", why)

        # Scale-down candidate: every signal must be quiet — below the
        # LOW watermarks, not merely below the high ones (hysteresis) —
        # and stay quiet for the whole down-cooldown before one replica
        # drains. Missing signals don't block (None = no data), and a
        # FULLY idle deployment (zero queued + running at every tick of
        # the cooldown) is quiet regardless: the windowed series and the
        # global burn alert lag by their window lengths, and echoes of a
        # burst that was already handled must not pin replicas up.
        idle = signals.ongoing == 0
        quiet = desired < current and (
            idle
            or (
                not pressure
                and not signals.burn_firing
                and (
                    signals.ttft_p95_s is None
                    or signals.ttft_p95_s
                    < ttft_target
                    * float(config.serve_autoscale_ttft_low_frac)
                )
                and (
                    signals.kv_occupancy is None
                    or signals.kv_occupancy
                    < float(config.serve_autoscale_kv_low_frac)
                )
                and (
                    signals.queue_depth is None
                    or signals.queue_depth < 1.0
                )
            )
        )
        if not quiet:
            st["ok_since"] = None
            return Decision(current, "hold", "steady")
        if st["ok_since"] is None:
            st["ok_since"] = now
        held = now - st["ok_since"]
        cooldown = float(config.serve_autoscale_down_cooldown_s)
        if held < cooldown:
            return Decision(
                current, "hold", f"sustained_ok {held:.0f}s/{cooldown:.0f}s"
            )
        # One step at a time, re-armed: draining is deliberate.
        st["ok_since"] = now
        st["last_down_ts"] = now
        return Decision(
            max(lo, current - 1), "down",
            f"sustained_ok>{cooldown:.0f}s ongoing={signals.ongoing}",
        )


class SignalCollector:
    """Pulls policy signals from the head over an existing control-store
    RPC client. ``call`` is ``client.call``-shaped:
    ``call(method, timeout_s=..., **kwargs) -> result``."""

    def __init__(self, call: Callable[..., Any]):
        self._call = call

    # -- RPC wrappers (each degrades to None on any failure) ----------

    def _history(
        self,
        metric: str,
        tags: Optional[Dict[str, str]],
        window_s: float,
    ) -> Optional[Dict[str, Any]]:
        try:
            out = self._call(
                "metrics_history", name=metric, tags=tags,
                window_s=window_s, timeout_s=5.0,
            )
        except Exception:  # noqa: BLE001 — head restarting, sampler off
            return None
        if not isinstance(out, dict) or not out.get("points"):
            return None
        return out

    def _tag_candidates(
        self, name: str, model_ids: Iterable[str]
    ) -> List[Optional[Dict[str, str]]]:
        cands: List[Optional[Dict[str, str]]] = [{"deployment": name}]
        cands.extend({"deployment": m} for m in dict.fromkeys(model_ids))
        cands.append(None)
        return cands

    def hist_p95(
        self, metric: str, name: str, model_ids: Iterable[str],
        window_s: float,
    ) -> Optional[float]:
        for tags in self._tag_candidates(name, model_ids):
            out = self._history(metric, tags, window_s)
            if out is None or out.get("kind") != "histogram":
                continue
            bounds = out.get("boundaries")
            pts = [p for p in out["points"] if "buckets" in p]
            if not bounds or not pts:
                continue
            buckets = [0.0] * (len(bounds) + 1)
            for p in pts:
                for i, b in enumerate(p["buckets"]):
                    buckets[i] += b
            q = hist_quantile(bounds, buckets, 0.95)
            if q is not None:
                return float(q)
        return None

    def gauge_avg(
        self, metric: str, name: str, model_ids: Iterable[str],
        window_s: float,
    ) -> Optional[float]:
        for tags in self._tag_candidates(name, model_ids):
            out = self._history(metric, tags, window_s)
            if out is None or out.get("kind") != "gauge":
                continue
            vals = [
                p["value"] for p in out["points"] if p.get("value") is not None
            ]
            if vals:
                return float(sum(vals) / len(vals))
        return None

    def burn_firing(self) -> bool:
        try:
            rep = self._call("alerts", timeout_s=5.0)
        except Exception:  # noqa: BLE001
            return False
        for a in (rep or {}).get("alerts", []) or []:
            if a.get("name") == BURN_RULE and a.get("state") == "firing":
                return True
        return False

    # -- the one call the controller makes per deployment per tick ----

    def history_enabled(self) -> bool:
        try:
            inv = self._call("metrics_history", name=None, timeout_s=5.0)
        except Exception:  # noqa: BLE001
            return False
        return bool((inv or {}).get("enabled"))

    def collect(
        self, name: str, model_ids: Iterable[str], ongoing: int
    ) -> Signals:
        if not self.history_enabled():
            # Sampler off (tests, bare clusters): degrade to the
            # ongoing-count baseline + alert state, skip 4×3 doomed RPCs.
            return Signals(
                ongoing=int(ongoing), burn_firing=self.burn_firing()
            )
        window_s = float(config.serve_autoscale_window_s)
        model_ids = list(model_ids)
        ttft = self.hist_p95("rt_serve_ttft_s", name, model_ids, window_s)
        # KV signal: page occupancy (paged engine) preferred — pages
        # track actual KV bytes pinned, where slot occupancy saturated
        # at "every slot holds a request" even with most rows unused.
        # Slot gauges remain the fallback for RT_SERVE_PAGED_KV=0
        # engines (the paged engine also aliases its page numbers onto
        # the slot names for one release, so either branch works).
        occupied = self.gauge_avg(
            "rt_serve_kv_pages_occupied", name, model_ids, window_s
        )
        total = self.gauge_avg(
            "rt_serve_kv_pages_total", name, model_ids, window_s
        )
        if occupied is None or not total:
            occupied = self.gauge_avg(
                "rt_serve_kv_slots_occupied", name, model_ids, window_s
            )
            total = self.gauge_avg(
                "rt_serve_kv_slots_total", name, model_ids, window_s
            )
        occupancy = None
        if occupied is not None and total:
            occupancy = occupied / total
        queue = self.gauge_avg(
            "rt_serve_queued_requests", name, model_ids, window_s
        )
        return Signals(
            ongoing=int(ongoing),
            ttft_p95_s=ttft,
            kv_occupancy=occupancy,
            queue_depth=queue,
            burn_firing=self.burn_firing(),
        )
