"""Serving control loop — the acting half of the serving story.

PR 15 built the sensing half (metrics history, SLO burn-rate alerts,
``bench_serve.py``); this package closes the loop:

- ``policy``: the SLO-driven autoscaling policy. ``SignalCollector``
  reads windowed TTFT p95 / KV-slot occupancy / queue depth from the
  head's metrics history plus the burn-rate alert state; ``SLOPolicy``
  turns those into replica-count decisions with hysteresis, cooldowns
  and min/max bounds. Consumed by ``serve/controller.py:_autoscale``.
- ``admission``: proxy-side admission control + load shedding —
  bounded per-deployment in-flight work and per-model concurrency
  caps, shedding 429/503 + ``Retry-After`` instead of collapsing.

Session-aware drain (the third leg) lives in the controller's replica
lifecycle: a scale-down victim leaves the routing table (HRW re-pins
its sessions), finishes its in-flight streams, and only then exits.
"""

from ray_tpu.serve.autoscale.admission import AdmissionController, Shed
from ray_tpu.serve.autoscale.policy import (
    Decision,
    SignalCollector,
    Signals,
    SLOPolicy,
)

__all__ = [
    "AdmissionController",
    "Decision",
    "Shed",
    "SignalCollector",
    "Signals",
    "SLOPolicy",
]
