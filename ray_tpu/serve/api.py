"""Serve public API.

Parity: ray.serve (reference python/ray/serve/api.py): @serve.deployment,
Deployment.bind, serve.run, DeploymentHandle, serve.status/shutdown.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, Optional, Union

import ray_tpu
from ray_tpu.serve.controller import CONTROLLER_NAME, ServeController
from ray_tpu.utils import serialization

_lock = threading.Lock()
_controller = None
_local_router = None


class Deployment:
    def __init__(
        self,
        func_or_class: Union[Callable, type],
        name: str,
        num_replicas: int = 1,
        route_prefix: Optional[str] = None,
        max_concurrency: int = 8,
        autoscaling_config: Optional[Dict[str, Any]] = None,
        ray_actor_options: Optional[Dict[str, float]] = None,
        max_queued_requests: Optional[int] = None,
    ):
        self.func_or_class = func_or_class
        self.name = name
        self.num_replicas = num_replicas
        self.route_prefix = route_prefix
        self.max_concurrency = max_concurrency
        self.autoscaling_config = autoscaling_config
        self.ray_actor_options = ray_actor_options
        # per-deployment proxy admission bound (in-flight requests per
        # proxy; None = RT_SERVE_ADMISSION_MAX_INFLIGHT)
        self.max_queued_requests = max_queued_requests
        self.init_args: tuple = ()
        self.init_kwargs: dict = {}

    def bind(self, *args, **kwargs) -> "Deployment":
        clone = Deployment(
            self.func_or_class, self.name, self.num_replicas,
            self.route_prefix, self.max_concurrency, self.autoscaling_config,
            self.ray_actor_options, self.max_queued_requests,
        )
        clone.init_args = args
        clone.init_kwargs = kwargs
        return clone

    def options(self, **kwargs) -> "Deployment":
        clone = self.bind(*self.init_args, **self.init_kwargs)
        for k, v in kwargs.items():
            if not hasattr(clone, k):
                raise TypeError(f"unknown deployment option {k!r}")
            setattr(clone, k, v)
        return clone


def deployment(
    _func_or_class=None,
    *,
    name: Optional[str] = None,
    num_replicas: int = 1,
    route_prefix: Optional[str] = None,
    max_concurrency: int = 8,
    autoscaling_config: Optional[Dict[str, Any]] = None,
    ray_actor_options: Optional[Dict[str, float]] = None,
    max_queued_requests: Optional[int] = None,
):
    """@serve.deployment decorator (reference api.py deployment)."""

    def wrap(obj):
        return Deployment(
            obj,
            name or getattr(obj, "__name__", "deployment"),
            num_replicas=num_replicas,
            route_prefix=route_prefix,
            max_concurrency=max_concurrency,
            autoscaling_config=autoscaling_config,
            ray_actor_options=ray_actor_options,
            max_queued_requests=max_queued_requests,
        )

    if _func_or_class is not None:
        return wrap(_func_or_class)
    return wrap


def start(http_port: Optional[int] = 0, detached: bool = False):
    """Start (or connect to) the Serve controller."""
    global _controller
    with _lock:
        if _controller is not None:
            return _controller
        try:
            _controller = ray_tpu.get_actor(CONTROLLER_NAME)
        except ValueError:
            _controller = ServeController.options(
                name=CONTROLLER_NAME,
                lifetime="detached" if detached else None,
                num_cpus=0,
                max_concurrency=16,
            ).remote(http_port)
        return _controller


def run(dep: Deployment, *, wait_ready: bool = True,
        ready_timeout_s: float = 120.0) -> "DeploymentHandle":
    """Deploy (or redeploy) and return a handle."""
    controller = start()
    blob = serialization.dumps_function(dep.func_or_class)
    ray_tpu.get(
        controller.deploy.remote(
            dep.name, blob, dep.init_args, dep.init_kwargs,
            dep.num_replicas, dep.route_prefix, dep.max_concurrency,
            dep.autoscaling_config, dep.ray_actor_options,
            dep.max_queued_requests,
        )
    )
    if wait_ready and not _wait_ready(controller, dep.name, ready_timeout_s):
        raise TimeoutError(f"deployment {dep.name!r} did not become ready")
    return DeploymentHandle(dep.name)


def _wait_ready(controller, name: str, timeout_s: float) -> bool:
    """Client side of the sliced controller.ready(): the controller
    answers each call within config.dispatch_wait_slice_s (dispatcher-
    block discipline), so the client re-issues slices until its own
    deadline."""
    import time

    deadline = time.monotonic() + timeout_s
    while True:
        left = deadline - time.monotonic()
        if left <= 0:
            return False
        if ray_tpu.get(
            controller.ready.remote(name, left), timeout=left + 30
        ):
            return True


class DeploymentResponse:
    """Future-like result of handle.remote() (reference handle.py
    DeploymentResponse): submitted eagerly; .result() blocks, and retries
    on a replica that died after routing."""

    def __init__(self, router, deployment: str, payload: Any,
                 method: Optional[str], replica_id: str, ref):
        self._router = router
        self._deployment = deployment
        self._payload = payload
        self._method = method
        self._replica_id = replica_id
        self._ref = ref
        self._done = False
        self._value: Any = None
        self._error: Optional[BaseException] = None

    @property
    def ref(self):
        return self._ref

    def result(self, timeout_s: float = 60.0) -> Any:
        from ray_tpu.core.exceptions import (
            ActorDiedError,
            ActorUnavailableError,
        )

        from ray_tpu.core.exceptions import GetTimeoutError

        if not self._done:
            try:
                self._value = ray_tpu.get(self._ref, timeout=timeout_s)
            except GetTimeoutError:
                # the request is still running: NOT a terminal outcome —
                # the response stays live (in-flight count included) and
                # the caller may retry result() with a longer timeout
                raise
            except (ActorDiedError, ActorUnavailableError):
                # replica died under us: re-route the request
                try:
                    self._value = self._router.call(
                        self._deployment, self._payload, self._method,
                        timeout_s,
                    )
                except BaseException as e:  # noqa: BLE001
                    self._error = e
            except BaseException as e:  # noqa: BLE001
                self._error = e
            self._done = True
            self._router.request_finished(self._replica_id)
        if self._error is not None:
            raise self._error
        return self._value


class DeploymentHandle:
    """Python-level calls into a deployment (reference handle.py:757)."""

    def __init__(self, deployment_name: str):
        self.deployment_name = deployment_name
        self._model_id: Optional[str] = None

    def options(self, *, multiplexed_model_id: Optional[str] = None
                ) -> "DeploymentHandle":
        """Reference handle.options(multiplexed_model_id=...) parity:
        route the call to a replica already holding this model."""
        h = DeploymentHandle(self.deployment_name)
        h._model_id = multiplexed_model_id
        return h

    def _router(self):
        global _local_router
        with _lock:
            if _local_router is None:
                from ray_tpu.serve.router import Router

                _local_router = Router(ray_tpu.get_actor(CONTROLLER_NAME))
            return _local_router

    def remote(self, payload: Any = None, *,
               method: Optional[str] = None) -> DeploymentResponse:
        router = self._router()
        rid, ref = router.assign(
            self.deployment_name, payload, method,
            model_id=self._model_id,
        )
        return DeploymentResponse(
            router, self.deployment_name, payload, method, rid, ref
        )

    def __repr__(self):
        return f"DeploymentHandle({self.deployment_name!r})"


def get_deployment_handle(name: str) -> DeploymentHandle:
    return DeploymentHandle(name)


def status() -> Dict[str, Any]:
    controller = start()
    return ray_tpu.get(controller.status.remote())


def scale(name: str, num_replicas: int,
          drain_deadline_s: Optional[float] = None) -> bool:
    """Manually set a deployment's target replica count. Scale-down is
    session-aware: surplus replicas drain (no new sessions, live SSE
    streams finish) and exit, force-killed only at ``drain_deadline_s``
    (default RT_SERVE_AUTOSCALE_DRAIN_DEADLINE_S). On an autoscaling
    deployment the policy re-evaluates from the new target next tick."""
    controller = start()
    return ray_tpu.get(
        controller.set_target_replicas.remote(
            name, num_replicas, drain_deadline_s
        )
    )


def autoscale_status() -> Dict[str, Any]:
    """Live control-loop state straight from the controller: replica
    counts (target/running/draining with per-drainer progress), the last
    scale decision, and the signals behind it. `state.autoscale_status()`
    reads the same snapshot from the head KV without needing the
    controller handle."""
    controller = start()
    return ray_tpu.get(controller.autoscale_status.remote())


def delete(name: str) -> None:
    controller = start()
    ray_tpu.get(controller.delete_deployment.remote(name))


def proxy_addresses():
    controller = start()
    return ray_tpu.get(controller.proxy_addresses.remote())


def shutdown() -> None:
    global _controller, _local_router
    with _lock:
        controller = _controller
        _controller = None
        _local_router = None
    if controller is not None:
        try:
            ray_tpu.get(controller.shutdown.remote(), timeout=30)
            ray_tpu.kill(controller)
        except Exception:  # noqa: BLE001
            pass
