"""Serve LLM — autoregressive model deployments.

Parity: the reference serve.llm stack (python/ray/serve/llm — deployment
+ engine wrapper + OpenAI-ish request shape) with a JAX engine instead of
vLLM: the replica holds GPT-2 weights, jits one batched decode step, and
a dynamic micro-batcher (the reference's @serve.batch role) coalesces
concurrent requests into one padded batched generation so replicas
saturate the chip instead of decoding one request at a time.

Token-level API (this image has no tokenizer vocab files): requests are
{"prompt_tokens": [int], "max_new_tokens": N, "temperature": T};
responses are {"tokens": [int]}. Weights are randomly initialized unless
a checkpoint path of gpt2.init-compatible arrays is given — the serving
machinery, not the text quality, is the parity surface.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

from ray_tpu import serve


class LLMConfig:
    def __init__(
        self,
        model_id: str = "gpt2-tiny",
        num_replicas: int = 1,
        max_batch_size: int = 8,
        batch_wait_timeout_s: float = 0.02,
        max_new_tokens_cap: int = 256,
        checkpoint_path: Optional[str] = None,
        route_prefix: Optional[str] = "/llm",
        max_concurrency: int = 16,
    ):
        self.model_id = model_id
        self.num_replicas = num_replicas
        self.max_batch_size = max_batch_size
        self.batch_wait_timeout_s = batch_wait_timeout_s
        self.max_new_tokens_cap = max_new_tokens_cap
        self.checkpoint_path = checkpoint_path
        self.route_prefix = route_prefix
        self.max_concurrency = max_concurrency


class _Request:
    __slots__ = ("prompt", "max_new", "temperature", "event", "result",
                 "error")

    def __init__(self, prompt, max_new, temperature):
        self.prompt = prompt
        self.max_new = max_new
        self.temperature = temperature
        self.event = threading.Event()
        self.result: Optional[List[int]] = None
        self.error: Optional[BaseException] = None


class LLMServer:
    """The deployment callable: micro-batched greedy/temperature decode."""

    def __init__(self, config: LLMConfig):
        import jax
        import jax.numpy as jnp

        from ray_tpu.models import gpt2

        self.cfg = config
        self.model_cfg = gpt2.CONFIGS[config.model_id]
        if config.checkpoint_path:
            import pickle

            with open(config.checkpoint_path, "rb") as f:
                self.params = pickle.load(f)
        else:
            self.params = gpt2.init(jax.random.PRNGKey(0), self.model_cfg)
        self._jnp = jnp
        mcfg = self.model_cfg

        def next_logits(params, tokens, lengths):
            # tokens [B, T] right-padded; take each row's last real logit
            logits = gpt2.forward(params, tokens, mcfg)
            idx = jnp.maximum(lengths - 1, 0)
            last = jnp.take_along_axis(
                logits, idx[:, None, None], axis=1
            )[:, 0, :]
            return last[:, : mcfg.vocab_size]

        self._next_logits = jax.jit(next_logits)
        self._rng = jax.random.PRNGKey(1)
        import collections

        self._queue: List[_Request] = []
        self._lock = threading.Lock()
        # bounded: a long-lived replica serves millions of batches
        self._batch_sizes = collections.deque(maxlen=1000)
        self._total_batches = 0
        self._max_batch_seen = 0
        self._stop = threading.Event()
        threading.Thread(
            target=self._batch_loop, name="llm-batcher", daemon=True
        ).start()

    # -- request path ---------------------------------------------------

    def __call__(self, request: Any) -> Dict[str, Any]:
        if hasattr(request, "json"):  # HTTP proxy path
            request = request.json()
        prompt = list(request.get("prompt_tokens") or [0])
        max_new = min(
            int(request.get("max_new_tokens", 16)),
            self.cfg.max_new_tokens_cap,
        )
        temperature = float(request.get("temperature", 0.0))
        req = _Request(prompt, max_new, temperature)
        with self._lock:
            self._queue.append(req)
        if not req.event.wait(timeout=300):
            raise TimeoutError("generation timed out")
        if req.error is not None:
            raise req.error
        return {"tokens": req.result}

    def batch_stats(self, _payload=None) -> Dict[str, Any]:
        with self._lock:
            sizes = list(self._batch_sizes)
            total = self._total_batches
            mx = self._max_batch_seen
        return {
            "batches": total,
            "max_batch": mx,
            "mean_batch": sum(sizes) / len(sizes) if sizes else 0,
        }

    # -- batcher --------------------------------------------------------

    def _take_batch(self) -> List[_Request]:
        deadline = time.monotonic() + self.cfg.batch_wait_timeout_s
        while not self._stop.is_set():
            with self._lock:
                if len(self._queue) >= self.cfg.max_batch_size or (
                    self._queue and time.monotonic() >= deadline
                ):
                    batch = self._queue[: self.cfg.max_batch_size]
                    del self._queue[: len(batch)]
                    return batch
                if not self._queue:
                    deadline = time.monotonic() + self.cfg.batch_wait_timeout_s
            time.sleep(0.002)
        return []

    def _batch_loop(self) -> None:
        while not self._stop.is_set():
            batch = self._take_batch()
            if not batch:
                continue
            try:
                self._generate(batch)
            except Exception as e:  # noqa: BLE001
                # fail THIS batch's callers with the error and keep the
                # batcher alive — one poisoned request must not turn the
                # replica into a black hole
                for r in batch:
                    r.error = e
                    r.event.set()

    def _generate(self, batch: List[_Request]) -> None:
        import jax
        import numpy as np

        jnp = self._jnp
        with self._lock:
            self._batch_sizes.append(len(batch))
            self._total_batches += 1
            self._max_batch_seen = max(self._max_batch_seen, len(batch))
        B = len(batch)
        max_new = max(r.max_new for r in batch)
        max_prompt = max(len(r.prompt) for r in batch)
        total = min(max_prompt + max_new, self.model_cfg.n_positions)
        tokens = np.zeros((B, total), np.int32)
        lengths = np.zeros((B,), np.int32)
        for i, r in enumerate(batch):
            p = r.prompt[-self.model_cfg.n_positions:]
            tokens[i, : len(p)] = p
            lengths[i] = len(p)
        tokens = jnp.asarray(tokens)
        lengths = jnp.asarray(lengths)
        outs: List[List[int]] = [[] for _ in range(B)]
        for _ in range(max_new):
            logits = self._next_logits(self.params, tokens, lengths)
            greedy = jnp.argmax(logits, axis=-1)
            self._rng, sub = jax.random.split(self._rng)
            temps = jnp.asarray(
                [max(r.temperature, 1e-6) for r in batch], jnp.float32
            )
            sampled = jax.random.categorical(sub, logits / temps[:, None])
            use_greedy = jnp.asarray(
                [r.temperature <= 0 for r in batch]
            )
            nxt = jnp.where(use_greedy, greedy, sampled).astype(jnp.int32)
            nxt_np = np.asarray(nxt)
            len_np = np.asarray(lengths)
            for i, r in enumerate(batch):
                if len(outs[i]) < r.max_new and len_np[i] < total:
                    outs[i].append(int(nxt_np[i]))
            # append in place where there is room
            can = lengths < total
            tokens = tokens.at[jnp.arange(B), jnp.minimum(lengths, total - 1)].set(
                jnp.where(can, nxt, tokens[jnp.arange(B), total - 1])
            )
            lengths = jnp.minimum(lengths + 1, total)
        for i, r in enumerate(batch):
            r.result = outs[i][: r.max_new]
            r.event.set()


def build_llm_deployment(config: Optional[LLMConfig] = None) -> Any:
    """Deployment for an LLM server (parity: serve.llm build_llm_deployment)."""
    config = config or LLMConfig()
    dep = serve.deployment(
        LLMServer,
        name=f"llm-{config.model_id}",
        num_replicas=config.num_replicas,
        route_prefix=config.route_prefix,
        max_concurrency=config.max_concurrency,
    )
    return dep.bind(config)
