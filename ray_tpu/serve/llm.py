"""Serve LLM — autoregressive model deployments on a KV-cache engine.

Parity: the reference serve.llm stack (python/ray/serve/llm — deployment
+ engine wrapper + OpenAI-ish request shape) whose engine tier is vLLM
(/root/reference/python/ray/llm/_internal/serve/engines/vllm/). Here the
engine is native JAX (models/gpt2_decode.py): a prefill/decode split
over a slot-based static-shape KV cache with CONTINUOUS BATCHING — new
requests are admitted into free slots between decode steps, so a long
generation never blocks short ones and every decode step runs all
occupied slots in one jitted call. Generating N tokens costs N
single-token forwards over cached K/V, not N full-prefix recomputes
(the round-3 engine's O(N·T·model) flaw).

Token-level API (this image has no tokenizer vocab files): requests are
{"prompt_tokens": [int], "max_new_tokens": N, "temperature": T};
responses are {"tokens": [int]}. Weights are randomly initialized unless
a checkpoint path of gpt2.init-compatible arrays is given — the serving
machinery, not the text quality, is the parity surface.
"""

from __future__ import annotations

import collections
import os
import threading
import time
from typing import Any, Dict, List, Optional

from ray_tpu import serve
from ray_tpu.observability import core_metrics, tracing


class LLMConfig:
    def __init__(
        self,
        model_id: str = "gpt2-tiny",
        num_replicas: int = 1,
        max_batch_size: int = 8,
        batch_wait_timeout_s: float = 0.02,
        max_new_tokens_cap: int = 256,
        checkpoint_path: Optional[str] = None,
        route_prefix: Optional[str] = "/llm",
        max_concurrency: int = 16,
        engine: str = "kv",  # "kv" (cached decode) | "recompute" (legacy)
        paged_kv: Optional[bool] = None,  # None = RT_SERVE_PAGED_KV
        async_decode: Optional[bool] = None,  # None = RT_SERVE_ASYNC_DECODE
    ):
        self.model_id = model_id
        self.num_replicas = num_replicas
        self.max_batch_size = max_batch_size
        self.batch_wait_timeout_s = batch_wait_timeout_s
        self.max_new_tokens_cap = max_new_tokens_cap
        self.checkpoint_path = checkpoint_path
        self.route_prefix = route_prefix
        self.max_concurrency = max_concurrency
        if engine not in ("kv", "recompute"):
            raise ValueError(f"unknown engine {engine!r}")
        self.engine = engine
        # Paged KV pool vs legacy slot cache for the kv engine. An
        # explicit bool here overrides the RT_SERVE_PAGED_KV env flag —
        # the config field travels in the pickled deployment spec, so
        # bench_serve's interleaved A/B arms can pick their engine
        # without touching replica-process environments.
        self.paged_kv = paged_kv
        # Async decode pipeline (one-step lookahead): an explicit bool
        # overrides RT_SERVE_ASYNC_DECODE the same way, so bench_serve's
        # asyncdecode leg can A/B it per arm through the pickled spec.
        self.async_decode = async_decode


class _Request:
    __slots__ = ("prompt", "max_new", "temperature", "event", "result",
                 "error", "token_q", "cancelled", "trace_id", "t_enqueue",
                 "t0_us", "kv_import")

    def __init__(self, prompt, max_new, temperature, stream=False):
        self.prompt = prompt
        self.max_new = max_new
        self.temperature = temperature
        self.event = threading.Event()
        self.result: Optional[List[int]] = None
        self.error: Optional[BaseException] = None
        # disaggregated decode: prefill already ran elsewhere and shipped
        # {"k", "v", "first_token", "prompt_len"} over an RpcChannel
        # (serve/kv_transfer.py) — admission imports the KV rows instead
        # of prefilling
        self.kv_import: Optional[Dict[str, Any]] = None
        # observability (set at enqueue only when the switches are on):
        # trace id propagated from the proxy, wall/monotonic enqueue
        # stamps for the engine span and the TTFT histogram
        self.trace_id: Optional[str] = None
        self.t_enqueue: Optional[float] = None
        self.t0_us = 0
        # set when the consumer abandoned the request (client disconnect
        # mid-stream): the engine frees the KV slot at the next round
        # instead of decoding to max_new for nobody
        self.cancelled = False
        # streaming consumers read tokens here as the engine produces
        # them; None marks the end of the stream
        self.token_q: Optional["queue.Queue"] = None
        if stream:
            import queue

            self.token_q = queue.Queue()


class _Chunk:
    """One dispatched-but-unharvested decode chunk (the async pipeline's
    in-flight lookahead). The engine dispatches chunk N+1 from chunk N's
    device-resident outputs BEFORE materializing chunk N's tokens; this
    record carries everything the later harvest needs: the device token
    array, the (row, seq, finish_pending) set captured at dispatch, rows
    cancelled while the chunk was in flight (their tokens are dropped on
    the host), and pages whose free is deferred until this chunk — the
    last one that can scatter into them — has completed."""

    __slots__ = ("toks_dev", "n_steps", "rows", "by_row", "dropped",
                 "free_after")

    def __init__(self, toks_dev, n_steps: int):
        self.toks_dev = toks_dev  # [K, S] (or [S] when K == 1) on device
        self.n_steps = n_steps
        self.rows: List[tuple] = []  # (row, seq, finish_pending)
        self.by_row: Dict[int, Any] = {}
        self.dropped: set = set()  # rows cancelled mid-flight
        self.free_after: List[int] = []  # pages released at harvest


class _PagedSeq:
    """One live sequence in the paged engine: the request it serves,
    its page pins, and its prefill/decode cursors. Admission reserves
    EVERY page the sequence can ever touch (ceil(min(prompt+max_new,
    T_max)/page_tokens)), so the page-table row never changes while the
    sequence is in flight."""

    __slots__ = ("req", "prompt", "pages", "released", "digests", "n_hit",
                 "table", "cached_tokens", "prefill_pos", "length",
                 "produced", "last_token", "t_last", "ttft_us", "active",
                 "budget_left")

    def __init__(self, req: _Request, prompt: List[int]):
        self.req = req
        self.prompt = prompt
        # page pins held in the engine's PagedKVPool: matched prefix
        # pages first, then freshly allocated ones. Released EXACTLY
        # once (the ``released`` latch) when the request leaves the
        # engine — finish, cancel, fail, or unload may race, and a
        # double release would corrupt another sequence's refcounts.
        self.pages: List[int] = []
        self.released = False
        self.digests: List[str] = []
        self.n_hit = 0  # leading pages that came from the prefix cache
        self.table = None  # np [MaxPages] page-table row
        self.cached_tokens = 0
        self.prefill_pos = 0  # prompt tokens already in the pool
        self.length = 0  # tokens in KV once active
        self.produced: List[int] = []
        self.last_token = 0
        self.t_last: Optional[float] = None
        self.ttft_us = 0
        self.active = False  # prefill complete, decoding
        # decode steps this sequence may still be dispatched for;
        # decremented AT DISPATCH (not harvest) so the pipelined loop
        # knows deterministically, before any token materializes, which
        # rows finish in the chunk it just launched
        self.budget_left = 0


class _Slot:
    """One occupied KV-cache row: the request it serves + its cursor."""

    __slots__ = ("req", "length", "produced", "last_token", "t_last",
                 "pool", "pool_refs", "cached", "ttft_us", "budget_left")

    def __init__(self, req: _Request, length: int, first_token: int):
        self.req = req
        self.length = length          # tokens currently in the cache row
        self.produced = [first_token]
        self.last_token = first_token
        self.t_last: Optional[float] = None  # last token delivery stamp
        # prefix-cache bookkeeping: block refs this slot holds in the
        # engine's BlockPool (released when the request leaves the slot),
        # and whether admission skipped any prefill work (cache hit or
        # disaggregated KV import) — tags the engine span's TTFT split
        self.pool = None
        self.pool_refs: List[str] = []
        self.cached = False
        self.ttft_us = 0
        self.budget_left = 0  # see _PagedSeq.budget_left


class LLMServer:
    """The deployment callable: continuous-batched KV-cached decode."""

    def __init__(self, config: LLMConfig):
        import jax

        from ray_tpu.models import gpt2

        self.cfg = config
        self.model_cfg = gpt2.CONFIGS[config.model_id]
        if config.checkpoint_path:
            import pickle

            with open(config.checkpoint_path, "rb") as f:
                self.params = pickle.load(f)
        else:
            self.params = gpt2.init(jax.random.PRNGKey(0), self.model_cfg)
        self._rng = jax.random.PRNGKey(1)

        self._queue: collections.deque = collections.deque()
        self._lock = threading.Lock()
        self._work = threading.Event()
        self._batch_sizes = collections.deque(maxlen=1000)
        self._total_batches = 0
        self._max_batch_seen = 0
        self._occupied = 0  # KV slots held after the last engine round
        # per-process gauge label (the cluster merge keeps the latest
        # value PER SERIES; distinct tags keep every engine process)
        self._node_tag = f"pid{os.getpid()}"
        self._stop = threading.Event()
        if config.engine == "kv":
            from ray_tpu.serve import prefix_cache
            from ray_tpu.utils.config import config as rtcfg

            self._paged = (
                bool(config.paged_kv) if config.paged_kv is not None
                else bool(rtcfg.serve_paged_kv)
            )
            # one-step lookahead pipeline; RT_SERVE_ASYNC_DECODE=0 (or
            # async_decode=False in the spec) restores the synchronous
            # dispatch->harvest loop
            self._async_decode = (
                bool(config.async_decode)
                if config.async_decode is not None
                else bool(rtcfg.serve_async_decode)
            )
            if self._paged:
                # ONE page pool holds generation and prefix KV. Default
                # size is MATCHED MEMORY with the slot engine: the slot
                # cache is [L, S, T_max, H, Dh]; S*ceil(T_max/B) pages
                # of B tokens hold the same element count (+1 reserved
                # scratch page that inactive rows scatter into).
                B = int(rtcfg.serve_prefix_block_tokens)
                max_pages = -(-self.model_cfg.n_positions // B)
                pool_pages = int(rtcfg.serve_kv_pool_pages) or (
                    config.max_batch_size * max_pages
                )
                self._prefix_pool = prefix_cache.PagedKVPool(
                    config.model_id, num_pages=pool_pages + 1,
                    page_tokens=B,
                )
                target = self._engine_loop_paged
            else:
                # legacy slot engine (RT_SERVE_PAGED_KV=0 kill switch):
                # block pool always exists for a kv engine; the
                # RT_SERVE_PREFIX_CACHE kill switch is checked per
                # admission so it doubles as a runtime A/B lever
                self._prefix_pool = prefix_cache.BlockPool(config.model_id)
                target = self._engine_loop_kv
        else:
            self._paged = False
            self._prefix_pool = None
            target = self._engine_loop_recompute
        threading.Thread(
            target=target, name="llm-engine", daemon=True
        ).start()

    # -- request path ---------------------------------------------------

    def _parse(self, request: Any) -> "_Request":
        trace_id = None
        if hasattr(request, "json"):  # HTTP proxy path
            if tracing.ENABLED:
                trace_id = request.headers.get(tracing.TRACE_HEADER)
            body = request.json()
            stream = (
                bool(body.get("stream"))
                or request.query.get("stream") in ("1", "true")
            )
            request = body
        else:
            stream = bool(request.get("stream"))
            if tracing.ENABLED:
                trace_id = request.get("trace_id")
        prompt = list(request.get("prompt_tokens") or [0])
        max_new = min(
            int(request.get("max_new_tokens", 16)),
            self.cfg.max_new_tokens_cap,
        )
        temperature = float(request.get("temperature", 0.0))
        req = _Request(prompt, max_new, temperature, stream=stream)
        req.trace_id = trace_id
        req.kv_import = request.get("kv_import")
        return req

    def __call__(self, request: Any):
        req = self._parse(request)
        if req.token_q is not None and self.cfg.engine != "kv":
            # validate BEFORE enqueue: the engine would otherwise decode a
            # request whose caller already got the ValueError
            raise ValueError("stream=True requires the kv engine")
        if core_metrics.ENABLED or tracing.ENABLED:
            req.t_enqueue = time.monotonic()
            if tracing.ENABLED and req.trace_id:
                req.t0_us = tracing.now_us()
        with self._lock:
            self._queue.append(req)
        self._work.set()
        if req.token_q is not None:
            return self._stream_tokens(req)
        if not req.event.wait(timeout=300):
            raise TimeoutError("generation timed out")
        if req.error is not None:
            raise req.error
        return {"tokens": req.result}

    def _stream_tokens(self, req: "_Request"):
        """Token-by-token generator (continuous batching pushes each
        decoded token as its step completes; parity: vLLM's streaming
        generate in the reference's serve.llm engine). Closing the
        generator before exhaustion — the client disconnected — cancels
        the request so the engine frees its KV slot."""
        import queue as queue_mod

        produced = 0
        done = False
        try:
            while True:
                try:
                    tok = req.token_q.get(timeout=300)
                except queue_mod.Empty:
                    raise TimeoutError("generation stalled") from None
                if tok is None:
                    done = True
                    if req.error is not None:
                        raise req.error
                    return
                produced += 1
                yield {"token": int(tok), "index": produced - 1}
        finally:
            if not done:
                req.cancelled = True
                self._work.set()  # wake the engine to reap the slot

    def batch_stats(self, _payload=None) -> Dict[str, Any]:
        with self._lock:
            sizes = list(self._batch_sizes)
            total = self._total_batches
            mx = self._max_batch_seen
        return {
            "batches": total,
            "max_batch": mx,
            "mean_batch": sum(sizes) / len(sizes) if sizes else 0,
            "occupied": self._occupied,
            "prefix": (
                self._prefix_pool.stats() if self._prefix_pool else None
            ),
        }

    def unload(self) -> None:
        """Multiplex eviction hook: stop the engine thread so an evicted
        engine doesn't keep a decode loop (and its KV cache) alive.
        Queued requests fail HERE and in-flight ones fail in the engine
        loop's exit path — callers get an immediate error, not a 300s
        timeout wait."""
        self._stop.set()
        self._work.set()
        err = RuntimeError(f"engine {self.cfg.model_id!r} was unloaded")
        while True:
            with self._lock:
                req = self._queue.popleft() if self._queue else None
            if req is None:
                break
            self._fail_request(req, err)
        # the prefix-block pool dies with the engine: close() drops every
        # resident block regardless of refcounts (in-flight slots fail in
        # the loop's exit path; their refs would otherwise strand blocks)
        if self._prefix_pool is not None:
            self._prefix_pool.close()

    @staticmethod
    def _fail_request(req: "_Request", err: BaseException) -> None:
        req.error = err
        req.event.set()
        if req.token_q is not None:
            req.token_q.put(None)

    def _record_step(self, occupancy: int) -> None:
        with self._lock:
            self._batch_sizes.append(occupancy)
            self._total_batches += 1
            self._max_batch_seen = max(self._max_batch_seen, occupancy)
            queued = len(self._queue)
        if core_metrics.ENABLED:
            dep = self.cfg.model_id
            core_metrics.serve_batch_fill.observe(
                occupancy, tags={"deployment": dep}
            )
            ntags = {"deployment": dep, "node": self._node_tag}
            core_metrics.serve_kv_slots_occupied.set(occupancy, tags=ntags)
            core_metrics.serve_kv_slots_total.set(
                self.cfg.max_batch_size, tags=ntags
            )
            core_metrics.serve_queued_requests.set(queued, tags=ntags)

    # -- KV engine (continuous batching over cache slots) ---------------

    def _engine_loop_kv(self) -> None:
        import jax
        import jax.numpy as jnp
        import numpy as np

        from ray_tpu.models import gpt2_decode as dec
        from ray_tpu.serve import prefix_cache
        from ray_tpu.utils.config import config

        mcfg = self.model_cfg
        S = self.cfg.max_batch_size
        T_max = mcfg.n_positions
        cache_k, cache_v = dec.init_cache(mcfg, S, T_max)
        slots: List[Optional[_Slot]] = [None] * S
        last = np.zeros((S,), np.int32)
        lengths = np.zeros((S,), np.int32)
        temps = np.zeros((S,), np.float32)
        greedy = np.ones((S,), bool)
        # device-resident copies of the step state: fully uploaded only
        # at (re)build; admissions/retirements push JUST their rows via
        # dec.update_rows, so steady-state churn never stalls the
        # pipeline behind four host->device transfers
        dev_state = None  # (last, lengths, temps, greedy) on device
        dirty: set = set()  # rows whose host state must reach the device
        rng_base = self._rng
        step_no = 0
        # async decode pipeline (RT_SERVE_ASYNC_DECODE): at most ONE
        # dispatched-but-unharvested chunk; None in sync mode or when
        # the pipeline is drained
        async_mode = self._async_decode
        inflight: Optional[_Chunk] = None
        # monotonic stamp of the moment the device ran dry with work
        # still active; the next dispatch observes the span as
        # rt_serve_decode_host_gap_s (0 when a lookahead kept it busy)
        gap_start: Optional[float] = None

        def _bucket(n: int, cap: int) -> int:
            # next power of two: one compile per bucket, and a short
            # prompt doesn't pay a full T_max-wide prefill
            p = 16
            while p < n:
                p *= 2
            return min(p, cap)

        def admit(i: int, req: _Request) -> None:
            nonlocal cache_k, cache_v
            prompt = req.prompt[-(T_max - 1):]
            pool = self._prefix_pool if config.serve_prefix_cache else None
            held: List[str] = []
            digests: List[str] = []
            cached = 0
            try:
                if req.kv_import is not None:
                    # disaggregated decode: the prefill deployment already
                    # computed this prompt's KV rows and first token —
                    # import them and skip prefill entirely
                    imp = req.kv_import
                    n = min(int(imp["prompt_len"]), T_max - 1)
                    C = _bucket(n, T_max)
                    L, H, Dh = mcfg.n_layer, mcfg.n_head, mcfg.head_dim
                    pk = np.zeros((L, C, H, Dh), np.float32)
                    pv = np.zeros((L, C, H, Dh), np.float32)
                    pk[:, :n] = np.asarray(imp["k"])[:, :n]
                    pv[:, :n] = np.asarray(imp["v"])[:, :n]
                    cache_k, cache_v = dec.write_prefix(
                        jnp.asarray(pk), jnp.asarray(pv),
                        cache_k, cache_v, jnp.int32(i),
                    )
                    first = int(imp["first_token"])
                    prompt_len = n
                    cached = n
                else:
                    if pool is not None:
                        digests = prefix_cache.hash_blocks(
                            prompt, pool.block_tokens
                        )
                        # keep >=1 prompt token uncached: the tail
                        # prefill produces the first-token logits
                        held, ks, vs = pool.match(
                            digests, max_tokens=len(prompt) - 1
                        )
                        cached = len(held) * pool.block_tokens
                    if cached:
                        cache_k, cache_v = dec.write_prefix(
                            jnp.asarray(np.concatenate(ks, axis=1)),
                            jnp.asarray(np.concatenate(vs, axis=1)),
                            cache_k, cache_v, jnp.int32(i),
                        )
                        tail = prompt[cached:]
                        tok = np.zeros(
                            (1, _bucket(len(tail), T_max - cached)), np.int32
                        )
                        tok[0, : len(tail)] = tail
                        logits, cache_k, cache_v = dec.prefill_extend(
                            mcfg, self.params, jnp.asarray(tok),
                            jnp.int32(cached), jnp.int32(len(tail)),
                            cache_k, cache_v, jnp.int32(i),
                        )
                    else:
                        tok = np.zeros(
                            (1, _bucket(len(prompt), T_max)), np.int32
                        )
                        tok[0, : len(prompt)] = prompt
                        logits, cache_k, cache_v = dec.prefill(
                            mcfg, self.params, jnp.asarray(tok),
                            jnp.int32(len(prompt)), cache_k, cache_v,
                            jnp.int32(i),
                        )
                    first = int(self._sample_one(logits, req.temperature))
                    prompt_len = len(prompt)
                    if pool is not None and len(digests) > len(held):
                        # park the blocks this request just prefilled for
                        # the next shared-prefix request (host copies of
                        # the slot's fresh K/V rows)
                        row_k = np.asarray(cache_k[:, i])
                        row_v = np.asarray(cache_v[:, i])
                        B = pool.block_tokens
                        for j in range(len(held), len(digests)):
                            pool.insert(
                                digests[j],
                                row_k[:, j * B:(j + 1) * B].copy(),
                                row_v[:, j * B:(j + 1) * B].copy(),
                            )
                        held = list(digests)
            except Exception as e:  # noqa: BLE001
                if pool is not None and held:
                    pool.release(held)
                req.error = e
                req.event.set()
                if req.token_q is not None:
                    req.token_q.put(None)
                # prefill donates the caches too: a post-dispatch failure
                # here deleted them, so every slot's state is garbage —
                # propagate so the outer handler fails in-flight requests
                # and marks the caches for rebuild (this request's error
                # is already set; fail_inflight won't see it in slots)
                raise
            slot = _Slot(req, prompt_len, first)
            slot.pool = pool
            slot.pool_refs = held
            slot.cached = cached > 0
            slots[i] = slot
            if tracing.ENABLED and req.t0_us:
                slot.ttft_us = tracing.now_us() - req.t0_us
            if core_metrics.ENABLED:
                now = time.monotonic()
                slot.t_last = now
                dep_tags = {"deployment": self.cfg.model_id}
                if req.t_enqueue is not None:
                    core_metrics.serve_ttft_s.observe(
                        now - req.t_enqueue, tags=dep_tags
                    )
                core_metrics.serve_tokens_generated.inc(tags=dep_tags)
            if req.token_q is not None and req.max_new >= 1:
                # zero-token completions must not leak the sampled-but-
                # unrequested first token into the stream
                req.token_q.put(first)
            slot.budget_left = min(req.max_new - 1, T_max - 1 - prompt_len)
            last[i] = first
            lengths[i] = prompt_len
            temps[i] = max(req.temperature, 1e-6)
            greedy[i] = req.temperature <= 0
            dirty.add(i)

        def release_refs(s: _Slot) -> None:
            # the request is leaving its slot: drop its prefix-block refs
            # (blocks stay resident, just become LRU-evictable)
            if s.pool is not None and s.pool_refs:
                s.pool.release(s.pool_refs)
                s.pool_refs = []

        def retire(i: int) -> None:
            """Row i leaves the decode batch: zero its host state so the
            next dispatch's incremental row push parks it on junk-safe
            values (length 0 => the junk token scatters at position 0 of
            a free row, overwritten by the next admission's prefill —
            which the device executes after any in-flight chunk)."""
            s = slots[i]
            slots[i] = None
            release_refs(s)
            last[i] = 0
            lengths[i] = 0
            temps[i] = 1e-6
            greedy[i] = True
            dirty.add(i)

        def complete(s: _Slot) -> None:
            s.req.result = s.produced[: s.req.max_new]
            if tracing.ENABLED and s.req.trace_id and s.req.t0_us:
                tracing.emit(tracing.request_span(
                    s.req.trace_id, tracing.ENGINE, self.cfg.model_id,
                    s.req.t0_us, tracing.now_us() - s.req.t0_us,
                    tokens=len(s.req.result),
                    cached=s.cached, ttft_us=s.ttft_us,
                ))
            s.req.event.set()
            if s.req.token_q is not None:
                s.req.token_q.put(None)  # end of stream

        def finish(i: int) -> None:
            s = slots[i]
            retire(i)
            complete(s)

        def fail_inflight(e: BaseException) -> None:
            # One poisoned round must not turn the replica into a black
            # hole (the guard the legacy _batch_loop had): fail every
            # occupied slot's request — including rows whose finish was
            # scheduled at dispatch but whose chunk never harvested —
            # and keep serving.
            nonlocal inflight
            for i in range(S):
                if slots[i] is not None:
                    s = slots[i]
                    retire(i)
                    self._fail_request(s.req, e)
            if inflight is not None:
                rec, inflight = inflight, None
                for _i, s, fin in rec.rows:
                    if fin:
                        self._fail_request(s.req, e)

        def harvest(rec: _Chunk, drained: bool) -> None:
            """Materialize a dispatched chunk's tokens and run all its
            host bookkeeping: fan-out, SSE queue puts, metric stamps,
            completions. In async mode this executes while the NEXT
            chunk (already dispatched) keeps the device busy —
            np.asarray is the only sync point."""
            nonlocal gap_start
            toks = np.asarray(rec.toks_dev)
            if toks.ndim == 1:
                toks = toks[None]  # [1, S]
            if drained and core_metrics.ENABLED:
                # no younger chunk in flight: the device just ran dry
                # and stays dry until the next dispatch — that span is
                # the host gap the async pipeline exists to hide
                gap_start = time.monotonic()
            n_new = rec.n_steps
            live = [r for r in rec.rows if r[0] not in rec.dropped]
            if core_metrics.ENABLED:
                # every live row receives exactly n_steps tokens (the
                # chunk was bounded by the minimum remaining budget)
                now = time.monotonic()
                dep_tags = {"deployment": self.cfg.model_id}
                core_metrics.serve_tokens_generated.inc(
                    n_new * len(live), tags=dep_tags
                )
                for _i, s, _fin in live:
                    if s.t_last is not None:
                        core_metrics.serve_inter_token_s.observe(
                            (now - s.t_last) / n_new, tags=dep_tags
                        )
                    s.t_last = now
            for k in range(n_new):
                for i, s, _fin in live:
                    s.length += 1
                    s.last_token = int(toks[k, i])
                    s.produced.append(s.last_token)
                    if (
                        s.req.token_q is not None
                        and not s.req.cancelled
                        and len(s.produced) > 1  # first token sent at admit
                        and len(s.produced) <= s.req.max_new
                    ):
                        s.req.token_q.put(s.last_token)
            for i, s, fin in live:
                if fin:
                    complete(s)
                elif slots[i] is s:
                    # keep the host mirror accurate for full rebuilds
                    last[i] = s.last_token
                    lengths[i] = s.length

        def dispatch(active: List[int], waiting: bool) -> _Chunk:
            nonlocal cache_k, cache_v, dev_state, step_no, gap_start
            if dev_state is None:
                dev_state = (
                    jnp.asarray(last), jnp.asarray(lengths),
                    jnp.asarray(temps), jnp.asarray(greedy),
                )
                dirty.clear()
            elif dirty:
                # incremental dev_state: scatter ONLY the changed rows
                # (admits/retires) into the device-resident step state
                # instead of re-uploading all four arrays
                idx = np.asarray(sorted(dirty), np.int32)
                d_last, d_len, d_temps, d_greedy = dev_state
                dev_state = dec.update_rows(
                    d_last, d_len, d_temps, d_greedy,
                    jnp.asarray(idx), jnp.asarray(last[idx]),
                    jnp.asarray(lengths[idx]), jnp.asarray(temps[idx]),
                    jnp.asarray(greedy[idx]),
                )
                dirty.clear()
            d_last, d_len, d_temps, d_greedy = dev_state
            # Chunk size: as many tokens as every active slot still
            # needs (bounded), but single-step whenever requests are
            # waiting so admission latency stays one step.
            K = 1
            if not waiting:
                K = max(1, min(8, min(
                    slots[i].budget_left for i in active
                )))
            if core_metrics.ENABLED:
                core_metrics.serve_decode_host_gap_s.observe(
                    (time.monotonic() - gap_start)
                    if gap_start is not None else 0.0,
                    tags={"deployment": self.cfg.model_id},
                )
            gap_start = None
            self._record_step(len(active))
            if K > 1:
                toks_dev, d_last2, d_len, cache_k, cache_v = (
                    dec.decode_multi(
                        mcfg, self.params, d_last, d_len, cache_k,
                        cache_v, d_temps, d_greedy, rng_base, K, step_no,
                    )
                )
                step_no += K
                dev_state = (d_last2, d_len, d_temps, d_greedy)
            else:
                step_no += 1
                toks_dev, d_len, cache_k, cache_v = dec.decode_and_sample(
                    mcfg, self.params, d_last, d_len, cache_k, cache_v,
                    d_temps, d_greedy, rng_base, step_no,
                )
                dev_state = (toks_dev, d_len, d_temps, d_greedy)
            rec = _Chunk(toks_dev, K)
            for i in active:
                s = slots[i]
                s.budget_left -= K
                fin = s.budget_left <= 0
                rec.rows.append((i, s, fin))
                rec.by_row[i] = s
                if fin:
                    # deterministic finish (budgets, not token values,
                    # end generations here): the row leaves the batch
                    # AT DISPATCH so the next chunk never includes it
                    # and its slot is immediately reusable; token
                    # fan-out and completion happen at harvest
                    retire(i)
            return rec

        def one_round() -> None:
            """One continuous-batching round: reap/admit -> dispatch the
            next chunk -> harvest the previous one (async lookahead) or
            this one (sync)."""
            nonlocal cache_k, cache_v, dev_state, inflight, gap_start
            if cache_k is None:  # rebuild after a poisoned (donated) round
                cache_k, cache_v = dec.init_cache(mcfg, S, T_max)
                dev_state = None
                dirty.clear()
            # consume the wake flag BEFORE the queue/cancel scans: a
            # set() landing after the scans stays pending for the idle
            # wait below, so an idle engine can never sleep through a
            # request that arrived between scan and wait (the old
            # wait-then-clear order could eat exactly that wakeup — up
            # to 500 ms of TTFT on an idle engine)
            self._work.clear()
            # reap abandoned requests (client disconnected mid-stream):
            # their KV rows go back to the free pool instead of decoding
            # to max_new for nobody
            for i in range(S):
                s = slots[i]
                if s is not None and s.req.cancelled:
                    if (
                        inflight is not None
                        and inflight.by_row.get(i) is s
                    ):
                        # mid-lookahead cancel: the in-flight chunk's
                        # tokens for this row drop at harvest
                        inflight.dropped.add(i)
                    retire(i)
                    s.req.event.set()
            # admit new requests into free slots (continuous batching)
            admitted = False
            for i in range(S):
                if slots[i] is not None:
                    continue
                while True:
                    with self._lock:
                        req = self._queue.popleft() if self._queue else None
                    if req is None or not req.cancelled:
                        break
                    req.event.set()  # cancelled while queued: never admit
                if req is None:
                    break
                admit(i, req)
                admitted = True
            active = [i for i in range(S) if slots[i] is not None]
            # single-token answers (or 0-token asks) finish immediately
            for i in list(active):
                s = slots[i]
                if len(s.produced) >= s.req.max_new or s.length >= T_max - 1:
                    finish(i)
            active = [i for i in range(S) if slots[i] is not None]
            self._occupied = len(active)
            if not active:
                if inflight is not None:
                    # drain the lookahead before idling: its tokens are
                    # real and its pending finishes must complete
                    rec, inflight = inflight, None
                    harvest(rec, True)
                elif not admitted:
                    self._work.wait(timeout=0.5)
                gap_start = None
                return
            with self._lock:
                waiting = bool(self._queue)
            rec = dispatch(active, waiting)
            if async_mode:
                # one-step lookahead: chunk N+1 is on the device; run
                # chunk N's host bookkeeping underneath it
                prev, inflight = inflight, rec
                if prev is not None:
                    harvest(prev, False)
            else:
                harvest(rec, True)

        while not self._stop.is_set():
            try:
                one_round()
            except Exception as e:  # noqa: BLE001 — engine must survive
                import logging

                logging.getLogger(__name__).exception(
                    "kv engine round failed; failing in-flight requests"
                )
                fail_inflight(e)
                dev_state = None
                dirty.clear()
                gap_start = None
                # prefill/decode donate the caches (donate_argnums): an
                # exception raised after dispatch leaves cache_k/cache_v
                # pointing at deleted buffers on TPU, so every later round
                # would fail too — mark them for rebuild (done inside the
                # next round's try so a failing rebuild — same OOM/device
                # error — can't kill the engine thread)
                cache_k = cache_v = None
                time.sleep(0.05)  # don't hot-spin on a persistent fault
        # stopped (unload): in-flight slots must fail NOW, not strand
        # their callers until the 300s wait times out (unload() drains
        # the queue; slots are this thread's to fail)
        fail_inflight(
            RuntimeError(f"engine {self.cfg.model_id!r} was unloaded")
        )
        self._occupied = 0

    # -- paged KV engine (one refcounted page pool, chunked prefill) -----

    def _record_step_paged(self, fill: int, pst: Dict[str, int]) -> None:
        with self._lock:
            self._batch_sizes.append(fill)
            self._total_batches += 1
            self._max_batch_seen = max(self._max_batch_seen, fill)
            queued = len(self._queue)
        if core_metrics.ENABLED:
            dep = self.cfg.model_id
            core_metrics.serve_batch_fill.observe(
                fill, tags={"deployment": dep}
            )
            ntags = {"deployment": dep, "node": self._node_tag}
            core_metrics.serve_kv_pages_total.set(
                pst["pages_total"], tags=ntags
            )
            core_metrics.serve_kv_pages_occupied.set(
                pst["pages_occupied"], tags=ntags
            )
            core_metrics.serve_kv_pages_prefix_resident.set(
                pst["prefix_resident"], tags=ntags
            )
            # one-release aliases: page occupancy published under the
            # slot-gauge names keeps the serve_kv_occupancy alert rule
            # and pre-paged dashboards evaluating unchanged
            core_metrics.serve_kv_slots_occupied.set(
                pst["pages_occupied"], tags=ntags
            )
            core_metrics.serve_kv_slots_total.set(
                pst["pages_total"], tags=ntags
            )
            core_metrics.serve_queued_requests.set(queued, tags=ntags)

    def _engine_loop_paged(self) -> None:
        """Continuous batching over ONE paged KV pool: generation and
        prefix pages coexist, a prefix hit is a refcount bump (zero
        copies), admission is page-granular (free pages, not free
        slots), and long prompts prefill in RT_SERVE_PREFILL_CHUNK_TOKENS
        chunks interleaved with decode so in-flight streams keep a
        bounded ITL."""
        import jax
        import jax.numpy as jnp
        import numpy as np

        from ray_tpu.models import gpt2_decode as dec
        from ray_tpu.serve import prefix_cache
        from ray_tpu.utils.config import config

        mcfg = self.model_cfg
        T_max = mcfg.n_positions
        pool = self._prefix_pool
        B = pool.page_tokens
        max_pages = -(-T_max // B)  # page-table width per sequence
        n_phys = pool.num_pages
        # decode rows: page-granular admission packs more short
        # sequences than the slot engine had slots, bounded by the pool
        # itself (every live sequence pins >= 1 page)
        S = int(config.serve_paged_max_seqs) or min(
            pool.num_pages - 1, 4 * self.cfg.max_batch_size
        )
        S = max(1, min(S, pool.num_pages - 1))
        cache_k, cache_v = dec.init_paged_cache(mcfg, n_phys, B)
        seqs: List[Optional[_PagedSeq]] = [None] * S
        tables = np.zeros((S, max_pages), np.int32)  # 0 rows -> scratch
        last = np.zeros((S,), np.int32)
        lengths = np.zeros((S,), np.int32)
        temps = np.zeros((S,), np.float32)
        greedy = np.ones((S,), bool)
        # device-resident step state (incl. page tables): fully uploaded
        # only at (re)build; admissions/retirements push JUST their rows
        # via dec.update_rows_paged
        dev_state = None
        dirty: set = set()  # rows whose host state must reach the device
        rng_base = self._rng
        step_no = 0
        # async decode pipeline (RT_SERVE_ASYNC_DECODE): at most ONE
        # dispatched-but-unharvested chunk; None in sync mode or when
        # the pipeline is drained
        async_mode = self._async_decode
        inflight: Optional[_Chunk] = None
        # monotonic stamp of the moment the device ran dry with work
        # still active; the next dispatch observes the span as
        # rt_serve_decode_host_gap_s (0 when a lookahead kept it busy)
        gap_start: Optional[float] = None

        def _bucket(n: int, cap: int) -> int:
            p = 16
            while p < n:
                p *= 2
            return min(p, cap)

        def take_pages(s: _PagedSeq) -> List[int]:
            # a sequence's pages leave it EXACTLY once, however many of
            # finish/cancel/fail/unload race for it — a second release
            # would decref pages another sequence may already have
            # re-allocated
            if s.released:
                return []
            s.released = True
            pages, s.pages = s.pages, []
            return pages

        def retire(i: int, rec: Optional[_Chunk] = None) -> None:
            """Row i leaves the decode batch. Its pages free NOW unless
            an in-flight chunk still scatters into them (``rec``): then
            the free is DEFERRED until that chunk is harvested — one
            step — so a lookahead never reads (or writes) a freed page
            that admission re-allocated underneath it."""
            s = seqs[i]
            seqs[i] = None
            tables[i] = 0  # this row's junk scatters -> scratch page
            lengths[i] = 0
            last[i] = 0
            dirty.add(i)
            pages = take_pages(s)
            if rec is not None:
                rec.free_after.extend(pages)
            elif pages:
                pool.release_pages(pages)

        def activate(i: int, s: _PagedSeq, first: int, kv_len: int) -> None:
            """Prefill (or import) complete: the sequence joins the
            decode batch at position ``kv_len`` with ``first`` sampled."""
            s.active = True
            s.length = kv_len
            s.produced = [first]
            s.last_token = first
            s.budget_left = min(s.req.max_new - 1, T_max - 1 - kv_len)
            tables[i] = s.table
            last[i] = first
            lengths[i] = kv_len
            temps[i] = max(s.req.temperature, 1e-6)
            greedy[i] = s.req.temperature <= 0
            dirty.add(i)
            if tracing.ENABLED and s.req.t0_us:
                s.ttft_us = tracing.now_us() - s.req.t0_us
            if core_metrics.ENABLED:
                now = time.monotonic()
                s.t_last = now
                dep_tags = {"deployment": self.cfg.model_id}
                if s.req.t_enqueue is not None:
                    core_metrics.serve_ttft_s.observe(
                        now - s.req.t_enqueue, tags=dep_tags
                    )
                core_metrics.serve_tokens_generated.inc(tags=dep_tags)
            if s.req.token_q is not None and s.req.max_new >= 1:
                # zero-token completions must not leak the sampled-but-
                # unrequested first token into the stream
                s.req.token_q.put(first)

        def import_kv(i: int, s: _PagedSeq, imp: Dict[str, Any]) -> None:
            """Disaggregated decode: the prefill tier shipped this
            prompt's KV rows + first token. Blocks already resident in
            the pool were matched at admission (zero-copy ref bump);
            only the rest is device-written, then full blocks seal so
            the NEXT import of this prefix copies nothing at all."""
            nonlocal cache_k, cache_v
            n = min(int(imp["prompt_len"]), T_max - 1)
            skip = min(s.cached_tokens, n)  # pool-resident prefix
            if n > skip:
                L, H, Dh = mcfg.n_layer, mcfg.n_head, mcfg.head_dim
                nblk = -(-(n - skip) // B)
                kb = np.zeros((L, nblk * B, H, Dh), np.float32)
                vb = np.zeros((L, nblk * B, H, Dh), np.float32)
                kb[:, : n - skip] = np.asarray(imp["k"])[:, skip:n]
                vb[:, : n - skip] = np.asarray(imp["v"])[:, skip:n]
                first_pg = skip // B
                pages = np.asarray(
                    s.pages[first_pg : first_pg + nblk], np.int32
                )
                cache_k, cache_v = dec.write_pages(
                    jnp.asarray(kb.reshape(L, nblk, B, H, Dh)),
                    jnp.asarray(vb.reshape(L, nblk, B, H, Dh)),
                    cache_k, cache_v, jnp.asarray(pages),
                )
                pool.copies += nblk
                if core_metrics.ENABLED:
                    core_metrics.serve_kv_block_copies.inc(
                        nblk, tags={"deployment": self.cfg.model_id}
                    )
                for j in range(first_pg, min(n // B, len(s.digests))):
                    pool.seal(s.digests[j], int(s.pages[j]))
            s.prefill_pos = len(s.prompt)
            s.cached_tokens = n
            activate(i, s, int(imp["first_token"]), n)

        def admit(i: int, req: _Request) -> bool:
            """Page-based admission: reserve EVERY page the sequence
            can ever touch up front (tables never change mid-flight,
            decode can never OOM mid-generation). Returns False — and
            takes nothing — when the pool can't cover the reservation:
            the caller requeues the request until pages free up."""
            nonlocal cache_k, cache_v
            prompt = req.prompt[-(T_max - 1):]
            use_prefix = bool(config.serve_prefix_cache)
            total_tokens = min(len(prompt) + req.max_new, T_max)
            n_pages = -(-total_tokens // B)
            if n_pages > pool.num_pages - 1:
                self._fail_request(req, RuntimeError(
                    f"request needs {n_pages} KV pages; pool has "
                    f"{pool.num_pages - 1}"
                ))
                return True  # consumed (failed); keep admitting
            digests = (
                prefix_cache.hash_blocks(prompt, B) if use_prefix else []
            )
            if req.kv_import is not None:
                cap = int(req.kv_import["prompt_len"])
            else:
                # keep >=1 prompt token uncached: the tail prefill
                # produces the first-token logits
                cap = len(prompt) - 1
            _, hit_pages = pool.match_pages(digests, max_tokens=cap)
            new_pages = pool.alloc(n_pages - len(hit_pages))
            if new_pages is None:
                pool.release_pages(hit_pages)
                return False
            s = _PagedSeq(req, prompt)
            s.pages = hit_pages + new_pages
            s.digests = digests
            s.n_hit = len(hit_pages)
            s.cached_tokens = len(hit_pages) * B
            s.prefill_pos = s.cached_tokens
            row = np.zeros((max_pages,), np.int32)
            row[: len(s.pages)] = s.pages
            s.table = row
            seqs[i] = s
            try:
                if req.kv_import is not None:
                    import_kv(i, s, req.kv_import)
            except Exception as e:  # noqa: BLE001
                retire(i)
                self._fail_request(req, e)
                # write_pages donates the caches: a post-dispatch
                # failure here deleted them — propagate so the outer
                # handler fails in-flight requests and rebuilds
                raise
            return True

        def run_prefill() -> None:
            """Chunked prefill: at most RT_SERVE_PREFILL_CHUNK_TOKENS
            prompt tokens per engine round (0 = unchunked), so a long
            prompt prefills across rounds interleaved with decode steps
            and in-flight streams keep a bounded ITL."""
            nonlocal cache_k, cache_v
            chunk = int(config.serve_prefill_chunk_tokens)
            budget = chunk if chunk > 0 else (1 << 30)
            for i in range(S):
                s = seqs[i]
                if s is None or s.active or s.req.cancelled:
                    continue
                if budget <= 0:
                    break
                logits = None
                while s.prefill_pos < len(s.prompt) and budget > 0:
                    start = s.prefill_pos
                    n = min(len(s.prompt) - start, budget)
                    width = _bucket(n, max_pages * B - start)
                    n = min(n, width)
                    tok = np.zeros((1, width), np.int32)
                    tok[0, :n] = s.prompt[start : start + n]
                    logits, cache_k, cache_v = dec.prefill_paged(
                        mcfg, self.params, jnp.asarray(tok),
                        jnp.int32(start), jnp.int32(n),
                        cache_k, cache_v, jnp.asarray(s.table),
                    )
                    s.prefill_pos = start + n
                    budget -= n
                if s.prefill_pos >= len(s.prompt) and logits is not None:
                    # full prompt blocks this sequence just wrote become
                    # shareable prefix pages: seal registers the page
                    # under its chain digest with NO copy
                    n_full = len(s.prompt) // B
                    for j in range(s.n_hit, min(n_full, len(s.digests))):
                        pool.seal(s.digests[j], int(s.pages[j]))
                    first = self._sample_one(logits, s.req.temperature)
                    activate(i, s, int(first), len(s.prompt))

        def complete(s: _PagedSeq) -> None:
            s.req.result = s.produced[: s.req.max_new]
            if tracing.ENABLED and s.req.trace_id and s.req.t0_us:
                tracing.emit(tracing.request_span(
                    s.req.trace_id, tracing.ENGINE, self.cfg.model_id,
                    s.req.t0_us, tracing.now_us() - s.req.t0_us,
                    tokens=len(s.req.result),
                    cached=s.cached_tokens > 0, ttft_us=s.ttft_us,
                ))
            s.req.event.set()
            if s.req.token_q is not None:
                s.req.token_q.put(None)  # end of stream

        def finish(i: int) -> None:
            s = seqs[i]
            retire(i)
            complete(s)

        def fail_inflight(e: BaseException) -> None:
            nonlocal inflight
            for i in range(S):
                if seqs[i] is not None:
                    s = seqs[i]
                    retire(i)
                    self._fail_request(s.req, e)
            if inflight is not None:
                # the lookahead chunk dies unharvested: release its
                # deferred pages (the pool resets with the cache rebuild
                # anyway — this keeps occupancy honest even if the
                # rebuild itself keeps failing) and fail the requests
                # whose finish was scheduled at its dispatch
                rec, inflight = inflight, None
                if rec.free_after:
                    pool.release_pages(rec.free_after)
                    rec.free_after = []
                for _i, s, fin in rec.rows:
                    if fin:
                        self._fail_request(s.req, e)

        def harvest(rec: _Chunk, drained: bool) -> None:
            """Materialize a dispatched chunk's tokens and run all its
            host bookkeeping: fan-out, SSE queue puts, metric stamps,
            completions, deferred page frees. In async mode this
            executes while the NEXT chunk (already dispatched) keeps
            the device busy — np.asarray is the only sync point."""
            nonlocal gap_start
            toks = np.asarray(rec.toks_dev)
            if toks.ndim == 1:
                toks = toks[None]  # [1, S]
            if drained and core_metrics.ENABLED:
                # no younger chunk in flight: the device just ran dry
                # and stays dry until the next dispatch — that span is
                # the host gap the async pipeline exists to hide
                gap_start = time.monotonic()
            n_new = rec.n_steps
            live = [r for r in rec.rows if r[0] not in rec.dropped]
            if core_metrics.ENABLED:
                now = time.monotonic()
                dep_tags = {"deployment": self.cfg.model_id}
                core_metrics.serve_tokens_generated.inc(
                    n_new * len(live), tags=dep_tags
                )
                for _i, s, _fin in live:
                    if s.t_last is not None:
                        core_metrics.serve_inter_token_s.observe(
                            (now - s.t_last) / n_new, tags=dep_tags
                        )
                    s.t_last = now
            for k in range(n_new):
                for i, s, _fin in live:
                    s.length += 1
                    s.last_token = int(toks[k, i])
                    s.produced.append(s.last_token)
                    if (
                        s.req.token_q is not None
                        and not s.req.cancelled
                        and len(s.produced) > 1  # first sent at activate
                        and len(s.produced) <= s.req.max_new
                    ):
                        s.req.token_q.put(s.last_token)
            for i, s, fin in live:
                if fin:
                    complete(s)
                elif seqs[i] is s:
                    # keep the host mirror accurate for full rebuilds
                    last[i] = s.last_token
                    lengths[i] = s.length
            if rec.free_after:
                # deferred frees: this chunk was the last dispatch that
                # could scatter into these pages — they are now safe to
                # re-allocate
                pool.release_pages(rec.free_after)
                rec.free_after = []

        def dispatch(active: List[int], waiting: bool,
                     prefilling: bool) -> _Chunk:
            nonlocal cache_k, cache_v, dev_state, step_no, gap_start
            if dev_state is None:
                dev_state = (
                    jnp.asarray(last), jnp.asarray(lengths),
                    jnp.asarray(temps), jnp.asarray(greedy),
                    jnp.asarray(tables),
                )
                dirty.clear()
            elif dirty:
                # incremental dev_state: scatter ONLY the changed rows
                # (admits/retires) into the device-resident step state
                # instead of re-uploading all five arrays
                idx = np.asarray(sorted(dirty), np.int32)
                d_last, d_len, d_temps, d_greedy, d_tables = dev_state
                dev_state = dec.update_rows_paged(
                    d_last, d_len, d_temps, d_greedy, d_tables,
                    jnp.asarray(idx), jnp.asarray(last[idx]),
                    jnp.asarray(lengths[idx]), jnp.asarray(temps[idx]),
                    jnp.asarray(greedy[idx]), jnp.asarray(tables[idx]),
                )
                dirty.clear()
            d_last, d_len, d_temps, d_greedy, d_tables = dev_state
            # Chunk size: single-step while requests wait for admission
            # OR any sequence is mid-prefill (the next prefill chunk
            # must interleave after ONE decode step, or ITL for live
            # streams would stretch by the whole chunk).
            K = 1
            if not waiting and not prefilling:
                K = max(1, min(8, min(
                    seqs[i].budget_left for i in active
                )))
            if core_metrics.ENABLED:
                core_metrics.serve_decode_host_gap_s.observe(
                    (time.monotonic() - gap_start)
                    if gap_start is not None else 0.0,
                    tags={"deployment": self.cfg.model_id},
                )
            gap_start = None
            self._record_step_paged(len(active), pool.stats())
            if K > 1:
                toks_dev, d_last2, d_len, cache_k, cache_v = (
                    dec.decode_multi_paged(
                        mcfg, self.params, d_last, d_len, cache_k,
                        cache_v, d_tables, d_temps, d_greedy, rng_base,
                        K, step_no,
                    )
                )
                step_no += K
                dev_state = (d_last2, d_len, d_temps, d_greedy, d_tables)
            else:
                step_no += 1
                toks_dev, d_len, cache_k, cache_v = (
                    dec.decode_paged_and_sample(
                        mcfg, self.params, d_last, d_len, cache_k,
                        cache_v, d_tables, d_temps, d_greedy, rng_base,
                        step_no,
                    )
                )
                dev_state = (toks_dev, d_len, d_temps, d_greedy, d_tables)
            rec = _Chunk(toks_dev, K)
            for i in active:
                s = seqs[i]
                s.budget_left -= K
                fin = s.budget_left <= 0
                rec.rows.append((i, s, fin))
                rec.by_row[i] = s
                if fin:
                    # deterministic finish (budgets, not token values,
                    # end generations here): the row leaves the batch
                    # AT DISPATCH so the next chunk never includes it;
                    # its pages free when THIS chunk — the last one
                    # scattering into them — is harvested
                    retire(i, rec)
            return rec

        def one_round() -> None:
            nonlocal cache_k, cache_v, dev_state, inflight, gap_start
            if cache_k is None:
                # rebuild after a poisoned (donated) round. The pool's
                # sealed pages pointed into the deleted cache, so ALL
                # pool metadata resets with it (the BlockPool kept host
                # copies and could survive this; the page pool cannot)
                cache_k, cache_v = dec.init_paged_cache(mcfg, n_phys, B)
                pool.reset()
                dev_state = None
                dirty.clear()
            # consume the wake flag BEFORE the queue/cancel scans: a
            # set() landing after the scans stays pending for the idle
            # wait below, so an idle engine can never sleep through a
            # request that arrived between scan and wait (the old
            # wait-then-clear order could eat exactly that wakeup — up
            # to 500 ms of TTFT on an idle engine)
            self._work.clear()
            # reap abandoned requests: their pages go back to the pool
            # instead of decoding to max_new for nobody
            for i in range(S):
                s = seqs[i]
                if s is not None and s.req.cancelled:
                    rec = (
                        inflight
                        if inflight is not None
                        and inflight.by_row.get(i) is s
                        else None
                    )
                    if rec is not None:
                        # mid-lookahead cancel: the in-flight chunk's
                        # tokens for this row drop at harvest, and its
                        # pages free only once that chunk completes
                        rec.dropped.add(i)
                    retire(i, rec)
                    s.req.event.set()
            admitted = False
            for i in range(S):
                if seqs[i] is not None:
                    continue
                while True:
                    with self._lock:
                        req = self._queue.popleft() if self._queue else None
                    if req is None or not req.cancelled:
                        break
                    req.event.set()  # cancelled while queued: never admit
                if req is None:
                    break
                if not admit(i, req):
                    # page pressure: requeue at the FRONT (FIFO order
                    # holds) and stop admitting until pages free up
                    with self._lock:
                        self._queue.appendleft(req)
                    break
                admitted = True
            run_prefill()
            prefilling = any(
                s is not None and not s.active for s in seqs
            )
            active = [
                i for i in range(S)
                if seqs[i] is not None and seqs[i].active
            ]
            # single-token answers (and 0-token asks) finish immediately
            for i in list(active):
                s = seqs[i]
                if len(s.produced) >= s.req.max_new or s.length >= T_max - 1:
                    finish(i)
            active = [
                i for i in range(S)
                if seqs[i] is not None and seqs[i].active
            ]
            self._occupied = len(active)
            if not active:
                if inflight is not None:
                    # drain the lookahead before idling: its tokens are
                    # real and its pending finishes must complete
                    rec, inflight = inflight, None
                    harvest(rec, True)
                elif not admitted and not prefilling:
                    self._work.wait(timeout=0.5)
                gap_start = None
                return
            with self._lock:
                waiting = bool(self._queue)
            rec = dispatch(active, waiting, prefilling)
            if async_mode:
                # one-step lookahead: chunk N+1 is on the device; run
                # chunk N's host bookkeeping underneath it
                prev, inflight = inflight, rec
                if prev is not None:
                    harvest(prev, False)
            else:
                harvest(rec, True)

        while not self._stop.is_set():
            try:
                one_round()
            except Exception as e:  # noqa: BLE001 — engine must survive
                import logging

                logging.getLogger(__name__).exception(
                    "paged kv engine round failed; failing in-flight"
                    " requests"
                )
                fail_inflight(e)
                dev_state = None
                dirty.clear()
                gap_start = None
                # prefill/decode/write donate the caches: an exception
                # after dispatch leaves them deleted — mark for rebuild
                # (done inside the next round's try, with a pool.reset
                # alongside, so a failing rebuild can't kill the thread)
                cache_k = cache_v = None
                time.sleep(0.05)  # don't hot-spin on a persistent fault
        fail_inflight(
            RuntimeError(f"engine {self.cfg.model_id!r} was unloaded")
        )
        self._occupied = 0

    def _sample_one(self, logits, temperature: float) -> int:
        import jax
        import jax.numpy as jnp

        if temperature <= 0:
            return int(jnp.argmax(logits))
        self._rng, sub = jax.random.split(self._rng)
        return int(jax.random.categorical(sub, logits / temperature))

    # -- legacy engine (full-prefix recompute; kept for comparison) ------

    def _engine_loop_recompute(self) -> None:
        import jax
        import jax.numpy as jnp

        from ray_tpu.models import gpt2

        mcfg = self.model_cfg

        def next_logits(params, tokens, lengths):
            logits = gpt2.forward(params, tokens, mcfg)
            idx = jnp.maximum(lengths - 1, 0)
            lastl = jnp.take_along_axis(
                logits, idx[:, None, None], axis=1
            )[:, 0, :]
            return lastl[:, : mcfg.vocab_size]

        next_logits = jax.jit(next_logits)
        while not self._stop.is_set():
            batch = self._take_batch()
            if not batch:
                continue
            try:
                self._generate_recompute(batch, next_logits)
            except Exception as e:  # noqa: BLE001 — fail this batch only
                for r in batch:
                    r.error = e
                    r.event.set()

    def _take_batch(self) -> List[_Request]:
        deadline = time.monotonic() + self.cfg.batch_wait_timeout_s
        while not self._stop.is_set():
            with self._lock:
                if len(self._queue) >= self.cfg.max_batch_size or (
                    self._queue and time.monotonic() >= deadline
                ):
                    batch = []
                    while self._queue and len(batch) < self.cfg.max_batch_size:
                        batch.append(self._queue.popleft())
                    return batch
                if not self._queue:
                    deadline = time.monotonic() + self.cfg.batch_wait_timeout_s
            time.sleep(0.002)
        return []

    def _generate_recompute(self, batch: List[_Request], next_logits) -> None:
        import jax
        import jax.numpy as jnp
        import numpy as np

        self._record_step(len(batch))
        B = len(batch)
        max_new = max(r.max_new for r in batch)
        max_prompt = max(len(r.prompt) for r in batch)
        total = min(max_prompt + max_new, self.model_cfg.n_positions)
        tokens = np.zeros((B, total), np.int32)
        lengths = np.zeros((B,), np.int32)
        for i, r in enumerate(batch):
            p = r.prompt[-self.model_cfg.n_positions:]
            tokens[i, : len(p)] = p
            lengths[i] = len(p)
        tokens = jnp.asarray(tokens)
        lengths = jnp.asarray(lengths)
        outs: List[List[int]] = [[] for _ in range(B)]
        for _ in range(max_new):
            logits = next_logits(self.params, tokens, lengths)
            greedy = jnp.argmax(logits, axis=-1)
            self._rng, sub = jax.random.split(self._rng)
            temps = jnp.asarray(
                [max(r.temperature, 1e-6) for r in batch], jnp.float32
            )
            sampled = jax.random.categorical(sub, logits / temps[:, None])
            use_greedy = jnp.asarray([r.temperature <= 0 for r in batch])
            nxt = jnp.where(use_greedy, greedy, sampled).astype(jnp.int32)
            nxt_np = np.asarray(nxt)
            len_np = np.asarray(lengths)
            for i, r in enumerate(batch):
                if len(outs[i]) < r.max_new and len_np[i] < total:
                    outs[i].append(int(nxt_np[i]))
            can = lengths < total
            tokens = tokens.at[
                jnp.arange(B), jnp.minimum(lengths, total - 1)
            ].set(jnp.where(can, nxt, tokens[jnp.arange(B), total - 1]))
            lengths = jnp.minimum(lengths + 1, total)
        for i, r in enumerate(batch):
            r.result = outs[i][: r.max_new]
            r.event.set()


def build_llm_deployment(config: Optional[LLMConfig] = None) -> Any:
    """Deployment for an LLM server (parity: serve.llm build_llm_deployment)."""
    config = config or LLMConfig()
    dep = serve.deployment(
        LLMServer,
        name=f"llm-{config.model_id}",
        num_replicas=config.num_replicas,
        route_prefix=config.route_prefix,
        max_concurrency=config.max_concurrency,
    )
    return dep.bind(config)


def deploy(
    models: Any = "gpt2-tiny",
    *,
    name: str = "openai-llm",
    num_replicas: int = 1,
    route_prefix: str = "/v1",
    tokenizer: Optional[str] = None,
    max_engines_per_replica: int = 2,
    max_concurrency: int = 16,
    autoscaling_config: Optional[Dict[str, Any]] = None,
    ray_actor_options: Optional[Dict[str, float]] = None,
    max_queued_requests: Optional[int] = None,
    wait_ready: bool = True,
    ready_timeout_s: float = 300.0,
    disaggregated: bool = False,
    prefill_replicas: int = 1,
):
    """Run the OpenAI-compatible front door (parity: the reference's
    ``serve.llm build_openai_app`` + ``serve.run``): a multi-replica
    ingress deployment under ``route_prefix`` serving
    ``/v1/completions``, ``/v1/chat/completions`` (both with SSE
    streaming) and ``/v1/models`` over every node's HTTP proxy.

    ``models`` maps OpenAI model names to engine configs — a model id
    string, an :class:`LLMConfig`, or ``{name: LLMConfig | model_id |
    kwargs-dict}``. Each replica loads engines lazily per model
    (LRU-bounded at ``max_engines_per_replica``) and the router prefers
    replicas already holding the requested model; the OpenAI ``user``
    field pins a session to one replica's warm KV slots.

    ``disaggregated=True`` additionally runs a ``<name>-prefill``
    deployment (serve/kv_transfer.py): ingress replicas send every
    prompt there for prefill and import the KV rows over an RpcChannel,
    keeping only decode local (kill switch RT_SERVE_DISAGG=0 reverts to
    local prefill without redeploying).

    Returns the DeploymentHandle."""
    from ray_tpu.serve.openai.ingress import build_openai_deployment

    prefill_name = None
    if disaggregated:
        from ray_tpu.serve.kv_transfer import PrefillServer

        prefill_name = f"{name}-prefill"
        prefill_dep = serve.deployment(
            PrefillServer,
            name=prefill_name,
            num_replicas=prefill_replicas,
            route_prefix=None,  # internal tier: no HTTP surface
            max_concurrency=max_concurrency,
        ).bind(models, max_engines_per_replica=max_engines_per_replica)
        serve.run(
            prefill_dep, wait_ready=wait_ready,
            ready_timeout_s=ready_timeout_s,
        )
    app = build_openai_deployment(
        models,
        name=name,
        num_replicas=num_replicas,
        route_prefix=route_prefix,
        tokenizer=tokenizer,
        max_engines_per_replica=max_engines_per_replica,
        max_concurrency=max_concurrency,
        autoscaling_config=autoscaling_config,
        ray_actor_options=ray_actor_options,
        prefill_deployment=prefill_name,
        max_queued_requests=max_queued_requests,
    )
    return serve.run(
        app, wait_ready=wait_ready, ready_timeout_s=ready_timeout_s
    )
