"""ray_tpu — a TPU-native distributed AI framework.

A brand-new framework with the capabilities of Ray (reference:
``/root/reference``), designed JAX/XLA-first: a control store + per-host
scheduler agents + per-process core workers provide tasks, actors, objects
and placement groups (reference layer map: SURVEY.md §1); TPU chips and ICI
slice topology are first-class scheduler resources; collectives are XLA mesh
operations; and parallelism strategies (DP/FSDP/TP/PP/CP) are provided
natively via pjit/shard_map rather than delegated to external engines.

Public core API parity target: ``ray.init/remote/get/put/wait/kill/cancel``
(reference: python/ray/_private/worker.py:1388,2831,2982,3053,3233,3277 and
``@ray.remote`` worker.py:3453).
"""

from ray_tpu._version import __version__

# Core public API (lazily bound to keep `import ray_tpu` light — no JAX
# import unless a JAX-facing subpackage is used).
from ray_tpu.core.api import (
    available_resources,
    cancel,
    cluster_resources,
    get,
    get_actor,
    get_runtime_context,
    init,
    is_initialized,
    kill,
    method,
    nodes,
    put,
    remote,
    shutdown,
    wait,
)
from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.core.placement import (
    PlacementGroup,
    placement_group,
    remove_placement_group,
)
from ray_tpu.core import exceptions


def __getattr__(name):
    # Lazy subpackage access (`ray_tpu.data` after `import ray_tpu`)
    # without importing heavyweight libraries at top level.
    if name in ("data", "train", "serve", "tune", "collective", "dag"):
        import importlib

        try:
            mod = importlib.import_module(f"ray_tpu.{name}")
        except ImportError as e:
            # AttributeError keeps hasattr()-style feature probes working.
            raise AttributeError(
                f"module 'ray_tpu' has no attribute {name!r}"
            ) from e
        globals()[name] = mod
        return mod
    raise AttributeError(f"module 'ray_tpu' has no attribute {name!r}")

__all__ = [
    "__version__",
    "ObjectRef",
    "PlacementGroup",
    "available_resources",
    "cluster_resources",
    "nodes",
    "cancel",
    "exceptions",
    "get",
    "get_actor",
    "get_runtime_context",
    "init",
    "is_initialized",
    "kill",
    "method",
    "placement_group",
    "put",
    "remote",
    "remove_placement_group",
    "shutdown",
    "wait",
]
