"""Ring attention — context parallelism over the mesh "cp" axis.

Sequence-parallel exact attention for sequences too long for one chip:
each device holds a T/n slice of Q, K, V; K/V blocks rotate around the
ring via lax.ppermute (nearest-neighbor ICI hops) while every device
accumulates its queries' attention over all blocks with streaming-softmax
(running max/sum) merging — numerically identical to full attention.

The reference has NO equivalent (SURVEY.md §5 "long-context": it
delegates sequence scaling to vLLM/DeepSpeed); this is a required
capability-parity addition, built TPU-first: the rotation is compiled to
collective-permute on ICI and overlaps with the block computation.

Round-1 block computation is the einsum form (differentiable end-to-end
through the ring; per-shard score blocks are [B, H, T/n, T/n]); swapping
in the Pallas flash kernel per block is a planned optimization.

Usage: inside shard_map with q, k, v sharded on T over axis_name, or via
ring_attention_sharded() which applies the shard_map given a mesh.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

_NEG = -1e30


def _block_scores(q, kb, q_off, k_off, causal):
    """Masked scores for one (q-shard, k-block) pair, global positions."""
    d = q.shape[-1]
    s = jnp.einsum(
        "bthd,bshd->bhts", q, kb, preferred_element_type=jnp.float32
    ) * (1.0 / d**0.5)
    if causal:
        Tq, Tk = q.shape[1], kb.shape[1]
        row = jax.lax.broadcasted_iota(jnp.int32, (Tq, Tk), 0) + q_off
        col = jax.lax.broadcasted_iota(jnp.int32, (Tq, Tk), 1) + k_off
        s = jnp.where((col <= row)[None, None], s, _NEG)
    return s  # [B, H, Tq, Tk] fp32


def ring_attention(
    q: jax.Array,  # local shard [B, Tl, H, Dh]
    k: jax.Array,
    v: jax.Array,
    axis_name: str = "cp",
    causal: bool = True,
) -> jax.Array:
    """Exact attention across the ring; call under shard_map with the
    sequence dim sharded over `axis_name`."""
    n = lax.psum(1, axis_name)
    my = lax.axis_index(axis_name)
    B, Tl, H, D = q.shape

    def step(carry, s):
        acc, m_run, l_run, kk, vv = carry
        # kk/vv currently hold the block originally owned by rank (my - s)
        src = (my - s) % n
        scores = _block_scores(q, kk, my * Tl, src * Tl, causal)
        m_b = jnp.max(scores, axis=-1, keepdims=True)  # [B,H,Tq,1]
        m_b = jnp.maximum(m_b, _NEG)  # keep fully-masked rows finite
        p = jnp.exp(scores - m_b)
        # re-zero fully-masked entries (exp(-1e30 - -1e30) = 1)
        if causal:
            p = jnp.where(scores <= _NEG / 2, 0.0, p)
        l_b = jnp.sum(p, axis=-1, keepdims=True)
        o_b = jnp.einsum("bhts,bshd->bthd", p.astype(vv.dtype), vv)

        m_new = jnp.maximum(m_run, m_b)
        scale_run = jnp.exp(m_run - m_new)
        scale_b = jnp.exp(m_b - m_new)
        # [B,H,T,1] -> [B,T,H,1] for the output layout
        tr = lambda x: x.transpose(0, 2, 1, 3)
        acc = acc * tr(scale_run) + o_b.astype(jnp.float32) * tr(scale_b)
        l_run = l_run * scale_run + l_b * scale_b
        m_run = m_new
        # rotate kv to the next rank (nearest-neighbor ring on ICI)
        perm = [(i, (i + 1) % n) for i in range(n)]
        kk = lax.ppermute(kk, axis_name, perm)
        vv = lax.ppermute(vv, axis_name, perm)
        return (acc, m_run, l_run, kk, vv), None

    acc0 = jnp.zeros((B, Tl, H, D), jnp.float32)
    m0 = jnp.full((B, H, Tl, 1), _NEG, jnp.float32)
    l0 = jnp.zeros((B, H, Tl, 1), jnp.float32)
    (acc, m_run, l_run, _, _), _ = lax.scan(
        step, (acc0, m0, l0, k, v), jnp.arange(n)
    )
    l_safe = jnp.maximum(l_run, 1e-30).transpose(0, 2, 1, 3)
    return (acc / l_safe).astype(q.dtype)


def ring_attention_sharded(
    q: jax.Array,  # global [B, T, H, Dh]
    k: jax.Array,
    v: jax.Array,
    mesh,
    causal: bool = True,
    cp_axis: str = "cp",
    batch_axes=("dcn", "dp", "fsdp"),
    head_axis: Optional[str] = "tp",
) -> jax.Array:
    """shard_map wrapper: T over cp, batch over data axes, heads over tp."""
    from jax.sharding import PartitionSpec as P

    try:
        from jax import shard_map as _shard_map  # jax >= 0.8 export
    except ImportError:  # pragma: no cover
        from jax.experimental.shard_map import shard_map as _shard_map

    batch = tuple(a for a in batch_axes if a in mesh.shape)
    spec = P(batch if batch else None, cp_axis, head_axis, None)
    fn = functools.partial(ring_attention, axis_name=cp_axis, causal=causal)
    return _shard_map(
        fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    )(q, k, v)
