"""Ring attention — context parallelism over the mesh "cp" axis.

Sequence-parallel exact attention for sequences too long for one chip:
each device holds a T/n slice of Q, K, V; K/V blocks rotate around the
ring via lax.ppermute (nearest-neighbor ICI hops) while every device
accumulates its queries' attention over all blocks, merging block
results through their log-sum-exp — numerically identical to full
attention.

The reference has NO equivalent (SURVEY.md §5 "long-context": it
delegates sequence scaling to vLLM/DeepSpeed); this is a required
capability-parity addition, built TPU-first.

Block math runs in the Pallas flash kernel (ops/flash_attention.py
flash_fwd_block / flash_bwd_block): no [Tq, Tk] score tensor ever hits
HBM. The whole ring is a jax.custom_vjp: the forward ring saves (q, k,
v, o, global lse); the backward runs a second ring in which each
visiting block's (dk, dv) accumulators travel WITH the block, so after a
full rotation every block arrives home carrying gradient contributions
from every rank's queries (the standard ring-attention backward).

Ring-step visibility under causal masking (global positions):
  src == my  -> the diagonal block: causal flash kernel
  src <  my  -> fully visible: non-causal flash kernel
  src >  my  -> fully masked: skipped (zero output, -inf lse)

Usage: inside shard_map with q, k, v sharded on T over axis_name, or via
ring_attention_sharded() which applies the shard_map given a mesh.
`block_impl="einsum"` keeps the readable einsum block math as a numerics
oracle for tests.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from ray_tpu.ops import flash_attention as fa

_NEG = -1e30


# ---------------------------------------------------------------------------
# flash-block ring (custom VJP)
# ---------------------------------------------------------------------------


def _lse_to_btH1(lse, B, H):
    """[B*H, 8, Tl] sublane-layout lse -> [B, Tl, H, 1] merge weights."""
    Tl = lse.shape[-1]
    return lse[:, 0, :].reshape(B, H, Tl).transpose(0, 2, 1)[..., None]


def _ring_cases(src, my, causal, diag_fn, full_fn, skip_fn):
    if not causal:
        return full_fn()
    return lax.cond(
        src == my,
        diag_fn,
        lambda: lax.cond(src < my, full_fn, skip_fn),
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def ring_attention(
    q: jax.Array,  # local shard [B, Tl, H, Dh]
    k: jax.Array,
    v: jax.Array,
    axis_name: str = "cp",
    causal: bool = True,
) -> jax.Array:
    """Exact attention across the ring; call under shard_map with the
    sequence dim sharded over `axis_name`."""
    out, _ = _ring_fwd(q, k, v, axis_name, causal)
    return out


def _ring_fwd(q, k, v, axis_name, causal):
    n = lax.psum(1, axis_name)
    my = lax.axis_index(axis_name)
    B, Tl, H, D = q.shape
    BH = B * H
    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(carry, s):
        o_acc, lse_run, kk, vv = carry
        src = (my - s) % n

        def diag():
            return fa.flash_fwd_block(q, kk, vv, causal=True)

        def full():
            return fa.flash_fwd_block(q, kk, vv, causal=False)

        def skip():
            return (
                jnp.zeros((B, Tl, H, D), jnp.float32),
                jnp.full((BH, 8, Tl), _NEG, jnp.float32),
            )

        o_b, lse_b = _ring_cases(src, my, causal, diag, full, skip)
        # merge via lse: o = sum_b o_b * exp(lse_b - lse_global)
        lse_new = jnp.logaddexp(lse_run, lse_b)
        w_run = jnp.exp(lse_run - lse_new)
        w_b = jnp.exp(lse_b - lse_new)
        o_acc = (
            o_acc * _lse_to_btH1(w_run, B, H)
            + o_b * _lse_to_btH1(w_b, B, H)
        )
        kk = lax.ppermute(kk, axis_name, perm)
        vv = lax.ppermute(vv, axis_name, perm)
        return (o_acc, lse_new, kk, vv), None

    o0 = jnp.zeros((B, Tl, H, D), jnp.float32)
    lse0 = jnp.full((BH, 8, Tl), _NEG, jnp.float32)
    (o_acc, lse, _, _), _ = lax.scan(step, (o0, lse0, k, v), jnp.arange(n))
    out = o_acc.astype(q.dtype)
    return out, (q, k, v, out, lse)


def _ring_bwd(axis_name, causal, res, do):
    q, k, v, out, lse = res
    n = lax.psum(1, axis_name)
    my = lax.axis_index(axis_name)
    B, Tl, H, D = q.shape
    BH = B * H
    perm = [(i, (i + 1) % n) for i in range(n)]
    # delta = rowsum(dO * O) in the kernel's 8-row sublane layout
    delta = jnp.sum(
        do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1
    )  # [B, Tl, H]
    delta = delta.transpose(0, 2, 1).reshape(BH, Tl)
    delta = jnp.broadcast_to(delta[:, None, :], (BH, 8, Tl))

    def step(carry, s):
        dq_acc, kk, vv, dk_acc, dv_acc = carry
        src = (my - s) % n

        def diag():
            return fa.flash_bwd_block(q, kk, vv, do, lse, delta, causal=True)

        def full():
            return fa.flash_bwd_block(q, kk, vv, do, lse, delta, causal=False)

        def skip():
            z = jnp.zeros((B, Tl, H, D), jnp.float32)
            return z, z, z

        dq_b, dk_b, dv_b = _ring_cases(src, my, causal, diag, full, skip)
        dq_acc = dq_acc + dq_b
        dk_acc = dk_acc + dk_b
        dv_acc = dv_acc + dv_b
        # the visiting block AND its gradient accumulators rotate together;
        # after n steps each block is home with every rank's contribution
        kk = lax.ppermute(kk, axis_name, perm)
        vv = lax.ppermute(vv, axis_name, perm)
        dk_acc = lax.ppermute(dk_acc, axis_name, perm)
        dv_acc = lax.ppermute(dv_acc, axis_name, perm)
        return (dq_acc, kk, vv, dk_acc, dv_acc), None

    dq0 = jnp.zeros((B, Tl, H, D), jnp.float32)
    dkv0 = jnp.zeros((B, Tl, H, D), jnp.float32)
    (dq, _, _, dk, dv), _ = lax.scan(
        step, (dq0, k, v, dkv0, dkv0), jnp.arange(n)
    )
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


ring_attention.defvjp(
    lambda q, k, v, axis_name, causal: _ring_fwd(q, k, v, axis_name, causal),
    _ring_bwd,
)


# ---------------------------------------------------------------------------
# einsum block math (numerics oracle; differentiable end-to-end via autodiff)
# ---------------------------------------------------------------------------


def _block_scores(q, kb, q_off, k_off, causal):
    """Masked scores for one (q-shard, k-block) pair, global positions."""
    d = q.shape[-1]
    s = jnp.einsum(
        "bthd,bshd->bhts", q, kb, preferred_element_type=jnp.float32
    ) * (1.0 / d**0.5)
    if causal:
        Tq, Tk = q.shape[1], kb.shape[1]
        row = jax.lax.broadcasted_iota(jnp.int32, (Tq, Tk), 0) + q_off
        col = jax.lax.broadcasted_iota(jnp.int32, (Tq, Tk), 1) + k_off
        s = jnp.where((col <= row)[None, None], s, _NEG)
    return s  # [B, H, Tq, Tk] fp32


def ring_attention_einsum(
    q: jax.Array,  # local shard [B, Tl, H, Dh]
    k: jax.Array,
    v: jax.Array,
    axis_name: str = "cp",
    causal: bool = True,
) -> jax.Array:
    n = lax.psum(1, axis_name)
    my = lax.axis_index(axis_name)
    B, Tl, H, D = q.shape

    def step(carry, s):
        acc, m_run, l_run, kk, vv = carry
        # kk/vv currently hold the block originally owned by rank (my - s)
        src = (my - s) % n
        scores = _block_scores(q, kk, my * Tl, src * Tl, causal)
        m_b = jnp.max(scores, axis=-1, keepdims=True)  # [B,H,Tq,1]
        m_b = jnp.maximum(m_b, _NEG)  # keep fully-masked rows finite
        p = jnp.exp(scores - m_b)
        # re-zero fully-masked entries (exp(-1e30 - -1e30) = 1)
        if causal:
            p = jnp.where(scores <= _NEG / 2, 0.0, p)
        l_b = jnp.sum(p, axis=-1, keepdims=True)
        o_b = jnp.einsum("bhts,bshd->bthd", p.astype(vv.dtype), vv)

        m_new = jnp.maximum(m_run, m_b)
        scale_run = jnp.exp(m_run - m_new)
        scale_b = jnp.exp(m_b - m_new)
        # [B,H,T,1] -> [B,T,H,1] for the output layout
        tr = lambda x: x.transpose(0, 2, 1, 3)
        acc = acc * tr(scale_run) + o_b.astype(jnp.float32) * tr(scale_b)
        l_run = l_run * scale_run + l_b * scale_b
        m_run = m_new
        # rotate kv to the next rank (nearest-neighbor ring on ICI)
        perm = [(i, (i + 1) % n) for i in range(n)]
        kk = lax.ppermute(kk, axis_name, perm)
        vv = lax.ppermute(vv, axis_name, perm)
        return (acc, m_run, l_run, kk, vv), None

    acc0 = jnp.zeros((B, Tl, H, D), jnp.float32)
    m0 = jnp.full((B, H, Tl, 1), _NEG, jnp.float32)
    l0 = jnp.zeros((B, H, Tl, 1), jnp.float32)
    (acc, m_run, l_run, _, _), _ = lax.scan(
        step, (acc0, m0, l0, k, v), jnp.arange(n)
    )
    l_safe = jnp.maximum(l_run, 1e-30).transpose(0, 2, 1, 3)
    return (acc / l_safe).astype(q.dtype)


def ring_attention_sharded(
    q: jax.Array,  # global [B, T, H, Dh]
    k: jax.Array,
    v: jax.Array,
    mesh,
    causal: bool = True,
    cp_axis: str = "cp",
    batch_axes=("dcn", "dp", "fsdp"),
    head_axis: Optional[str] = "tp",
    block_impl: str = "flash",
) -> jax.Array:
    """shard_map wrapper: T over cp, batch over data axes, heads over tp."""
    from jax.sharding import PartitionSpec as P

    from ray_tpu.ops.jax_compat import shard_map_unchecked

    batch = tuple(a for a in batch_axes if a in mesh.shape)
    spec = P(batch if batch else None, cp_axis, head_axis, None)
    impl = ring_attention if block_impl == "flash" else ring_attention_einsum
    fn = functools.partial(impl, axis_name=cp_axis, causal=causal)
    return shard_map_unchecked(
        fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
    )(q, k, v)
