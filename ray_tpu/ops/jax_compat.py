"""Version-compat shims for jax APIs the ops kernels ride on.

The kernels target the current jax surface (``jax.shard_map`` with
``check_vma``, ``pallas.tpu.CompilerParams``); older jax releases spell
the same things ``jax.experimental.shard_map`` / ``check_rep`` /
``TPUCompilerParams``. These shims resolve the spelling ONCE at import
so the kernels stay version-agnostic instead of breaking on every jax
API rename (the "11 seed failures from jax API drift" class of bug).
"""

from __future__ import annotations

import functools
import inspect


@functools.lru_cache(maxsize=1)
def _shard_map_fn_and_kwarg():
    try:
        from jax import shard_map as sm  # jax >= 0.8 export
    except ImportError:  # pragma: no cover — older jax
        from jax.experimental.shard_map import shard_map as sm
    params = inspect.signature(sm).parameters
    if "check_vma" in params:
        return sm, "check_vma"
    if "check_rep" in params:  # the pre-0.6 spelling of the same knob
        return sm, "check_rep"
    return sm, None


def shard_map_unchecked(fn, *, mesh, in_specs, out_specs):
    """shard_map with replication/VMA checking off (our kernels use
    collectives whose replication the checker cannot prove), under
    whichever keyword this jax spells it."""
    sm, kwarg = _shard_map_fn_and_kwarg()
    kwargs = {kwarg: False} if kwarg else {}
    return sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs)


@functools.lru_cache(maxsize=1)
def pallas_tpu_compiler_params_cls():
    """pallas.tpu.CompilerParams (new name) / TPUCompilerParams (old)."""
    from jax.experimental.pallas import tpu as pltpu

    cls = getattr(pltpu, "CompilerParams", None)
    if cls is None:  # pragma: no cover — older jax
        cls = pltpu.TPUCompilerParams
    return cls
