"""TPU compute ops: attention implementations (reference, Pallas flash,
ring/context-parallel) and kernel utilities."""

from ray_tpu.ops.attention import attention

__all__ = ["attention"]
