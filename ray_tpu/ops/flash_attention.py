"""Flash attention — K-blocked online-softmax Pallas TPU kernel, custom VJP.

The hot op of the transformer stack (no reference equivalent: the
reference delegates attention math to torch/vLLM; SURVEY.md §2.4). True
flash algorithm (Dao et al.), shaped for the TPU memory hierarchy
(pallas_guide.md):

  - grid (B*H, T/bq, T/bk) with the K dimension innermost ("arbitrary"
    semantics): running max / normalizer / output accumulator live in VMEM
    scratch across K blocks — only [bq, bk] score tiles ever exist, so
    sequence length is bounded by HBM, not VMEM (the round-1 kernel held
    the full [bq, T] score row and one-shot softmaxed it).
  - causal block skipping: (iq, ik) tiles strictly above the diagonal are
    skipped entirely — for causal attention this halves both MXU and VPU
    work, which matters because at moderate T the kernel is VPU-bound
    (exp/mask/select passes), not MXU-bound.
  - fp32 accumulation for scores/normalizers; bf16 into the MXU for the
    p@v and ds@k products.
  - backward: dq kernel accumulates over K blocks, dk/dv kernel over Q
    blocks, each recomputing only its own [bq, bk] score tile from q, k
    and the saved lse (no full-T recompute as in round 1).

Layout: q,k,v [B, T, H, Dh] (model layout) — folded to [B*H, T, Dh] for
the kernel. lse/delta ride an 8-row sublane layout ([BH, 8, T], ~12MB at
gpt2-small scale) to keep stores tile-legal.

Context parallelism composes on top: ops/ring_attention.py rotates K/V
shards around the mesh and calls the block kernel per shard.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _interpret() -> bool:
    # CPU has no Mosaic backend: run kernels in interpret mode so the same
    # code is testable on the virtual host mesh (SURVEY.md §4 takeaway).
    return jax.default_backend() == "cpu"


_NEG_INF = -1e30
_LANES = 128


def _visible(iq, ik, bq, bk, causal: bool):
    """Does K block ik contribute anything to Q block iq?"""
    if not causal:
        return True
    return ik * bk <= (iq + 1) * bq - 1


def _mask_tile(s, iq, ik, bq, bk, causal: bool):
    """Apply the causal mask to a [bq, bk] score tile (diagonal tiles only)."""
    if not causal:
        return s
    # Strictly-below-diagonal tiles need no mask; the compare/select pair
    # only runs for tiles overlapping the diagonal.
    row = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0) + iq * bq
    col = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1) + ik * bk
    fully_visible = (ik + 1) * bk <= iq * bq + 1
    return jnp.where(
        jnp.logical_or(fully_visible, col <= row), s, _NEG_INF
    )


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                acc_ref, m_ref, l_ref, *, block_q, block_k, causal,
                single_k: bool):
    iq, ik = pl.program_id(1), pl.program_id(2)
    n_k = pl.num_programs(2)

    def _scores():
        q = q_ref[...]
        k = k_ref[...]
        scale = 1.0 / (q.shape[-1] ** 0.5)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # [bq, bk] f32
        return _mask_tile(s, iq, ik, block_q, block_k, causal)

    if single_k:
        # One K block covers the whole sequence: one-shot softmax, no
        # scratch carry — saves the init/rescale VPU passes that dominate
        # at moderate T.
        s = _scores()
        m = jnp.max(s, axis=1, keepdims=True)      # [bq, 1]
        p = jnp.exp(s - m)
        l = jnp.sum(p, axis=1, keepdims=True)      # [bq, 1]
        o = jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[...], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        o_ref[...] = (o / l).astype(o_ref.dtype)
        lse = (m + jnp.log(l))[:, 0]               # [bq]
        lse_ref[...] = jnp.broadcast_to(lse[None, :], (8, block_q))
        return

    @pl.when(ik == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    @pl.when(_visible(iq, ik, block_q, block_k, causal))
    def _compute():
        s = _scores()
        m_prev = m_ref[...]                       # [bq, LANES] replicated
        l_prev = l_ref[...]
        m_cur = jnp.max(s, axis=1, keepdims=True)  # [bq, 1]
        m_next = jnp.maximum(m_prev, m_cur)        # [bq, LANES]
        alpha = jnp.exp(m_prev - m_next)           # [bq, LANES]
        p = jnp.exp(s - m_next[:, :1])             # [bq, bk]
        l_ref[...] = l_prev * alpha + jnp.sum(p, axis=1, keepdims=True)
        m_ref[...] = m_next
        pv = jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[...], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [bq, D]
        acc_ref[...] = acc_ref[...] * alpha[:, :1] + pv

    @pl.when(ik == n_k - 1)
    def _finalize():
        l = l_ref[...][:, :1]  # [bq, 1]
        o_ref[...] = (acc_ref[...] / l).astype(o_ref.dtype)
        lse = m_ref[...][:, 0] + jnp.log(l_ref[...][:, 0])  # [bq]
        lse_ref[...] = jnp.broadcast_to(lse[None, :], (8, block_q))


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
               dq_acc, *, block_q, block_k, causal):
    iq, ik = pl.program_id(1), pl.program_id(2)
    n_k = pl.num_programs(2)

    @pl.when(ik == 0)
    def _init():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    @pl.when(_visible(iq, ik, block_q, block_k, causal))
    def _compute():
        q = q_ref[...]
        k = k_ref[...]
        scale = 1.0 / (q.shape[-1] ** 0.5)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        s = _mask_tile(s, iq, ik, block_q, block_k, causal)
        p = jnp.exp(s - lse_ref[0][:, None])       # [bq, bk]
        dp = jax.lax.dot_general(
            do_ref[...], v_ref[...], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [bq, bk]
        ds = (p * (dp - delta_ref[0][:, None]) * scale).astype(k.dtype)
        dq_acc[...] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(ik == n_k - 1)
    def _finalize():
        dq_ref[...] = dq_acc[...].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, dk_acc, dv_acc, *, block_q, block_k, causal):
    ik, iq = pl.program_id(1), pl.program_id(2)
    n_q = pl.num_programs(2)

    @pl.when(iq == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    @pl.when(_visible(iq, ik, block_q, block_k, causal))
    def _compute():
        q = q_ref[...]
        k = k_ref[...]
        scale = 1.0 / (q.shape[-1] ** 0.5)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # [bq, bk]
        s = _mask_tile(s, iq, ik, block_q, block_k, causal)
        p = jnp.exp(s - lse_ref[0][:, None])       # [bq, bk]
        do = do_ref[...]
        dv_acc[...] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [bk, D]
        dp = jax.lax.dot_general(
            do, v_ref[...], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [bq, bk]
        ds = (p * (dp - delta_ref[0][:, None]) * scale).astype(q.dtype)
        dk_acc[...] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )  # [bk, D]

    @pl.when(iq == n_q - 1)
    def _finalize():
        dk_ref[...] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[...] = dv_acc[...].astype(dv_ref.dtype)


def _pick_block(t: int, target: int) -> int:
    for b in (target, 1024, 512, 256, 128, 64, 32, 16, 8):
        if b <= target and t % b == 0:
            return min(b, t)
    return t


def _block_sizes(T: int):
    """(bq, bk) for sequence length T. 1024x1024 measured fastest on v5e
    for the train step (PROFILE.md): the [bq, bk] f32 score tile is 4MB of
    VMEM, large q tiles amortize the [bq, D]-contraction's half-width MXU
    occupancy (D=64), and at T<=1024 the kernel runs the one-shot
    softmax path (single K block, no online-softmax carries). VMEM stays
    bounded for long sequences (T=128k runs at the same tile size).
    RT_FLASH_BQ/BK (dynamic flags) override per process for sweeps."""
    from ray_tpu.utils.config import config

    return _pick_block(T, int(config.flash_bq)), _pick_block(T, int(config.flash_bk))


def _fold(x):  # [B, T, H, D] -> [B*H, T, D]
    B, T, H, D = x.shape
    return x.transpose(0, 2, 1, 3).reshape(B * H, T, D)


def _unfold(x, B, H):  # [B*H, T, D] -> [B, T, H, D]
    BH, T, D = x.shape
    return x.reshape(B, H, T, D).transpose(0, 2, 1, 3)


def _params():
    from ray_tpu.ops.jax_compat import pallas_tpu_compiler_params_cls

    return pallas_tpu_compiler_params_cls()(
        dimension_semantics=("parallel", "parallel", "arbitrary"),
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def flash_attention(q, k, v, causal: bool = True):
    out, _ = _flash_fwd(q, k, v, causal)
    return out


def _flash_fwd(q, k, v, causal, out_dtype=None):
    B, T, H, D = q.shape
    Tk = k.shape[1]
    if causal and Tk != T:
        raise ValueError("causal flash attention requires Tq == Tk")
    qf, kf, vf = _fold(q), _fold(k), _fold(v)
    BH = B * H
    bq, _ = _block_sizes(T)
    _, bk = _block_sizes(Tk)
    grid = (BH, T // bq, Tk // bk)
    out, lse = pl.pallas_call(
        functools.partial(
            _fwd_kernel, block_q=bq, block_k=bk, causal=causal,
            single_k=(Tk // bk == 1),
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, bq, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((None, bk, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((None, bk, D), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, bq, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((None, 8, bq), lambda b, i, j: (b, 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, T, D), out_dtype or q.dtype),
            jax.ShapeDtypeStruct((BH, 8, T), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, D), jnp.float32),
            pltpu.VMEM((bq, _LANES), jnp.float32),
            pltpu.VMEM((bq, _LANES), jnp.float32),
        ],
        compiler_params=_params(),
        interpret=_interpret(),
    )(qf, kf, vf)
    return _unfold(out, B, H), (q, k, v, out, lse)


def _flash_fwd_rule(q, k, v, causal):
    return _flash_fwd(q, k, v, causal)


def _bwd_kernels(qf, kf, vf, dof, lse, delta, causal, q_dtype, k_dtype,
                 v_dtype):
    """dq + (dk, dv) pallas calls on folded [BH, T, D] operands. Tq and Tk
    may differ (ring attention feeds visiting K/V blocks); lse and delta
    are the GLOBAL log-sum-exp / rowsum(dO*O) for the q rows, which is
    exactly what the flash decomposition needs per block."""
    BH, Tq, D = qf.shape
    Tk = kf.shape[1]
    bq, _ = _block_sizes(Tq)
    _, bk = _block_sizes(Tk)
    dq = pl.pallas_call(
        functools.partial(_dq_kernel, block_q=bq, block_k=bk, causal=causal),
        grid=(BH, Tq // bq, Tk // bk),
        in_specs=[
            pl.BlockSpec((None, bq, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((None, bk, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((None, bk, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((None, bq, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((None, 8, bq), lambda b, i, j: (b, 0, i)),
            pl.BlockSpec((None, 8, bq), lambda b, i, j: (b, 0, i)),
        ],
        out_specs=pl.BlockSpec((None, bq, D), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((BH, Tq, D), q_dtype),
        scratch_shapes=[pltpu.VMEM((bq, D), jnp.float32)],
        compiler_params=_params(),
        interpret=_interpret(),
    )(qf, kf, vf, dof, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, block_q=bq, block_k=bk, causal=causal),
        grid=(BH, Tk // bk, Tq // bq),
        in_specs=[
            pl.BlockSpec((None, bq, D), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((None, bk, D), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((None, bk, D), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((None, bq, D), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((None, 8, bq), lambda b, j, i: (b, 0, i)),
            pl.BlockSpec((None, 8, bq), lambda b, j, i: (b, 0, i)),
        ],
        out_specs=[
            pl.BlockSpec((None, bk, D), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((None, bk, D), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, Tk, D), k_dtype),
            jax.ShapeDtypeStruct((BH, Tk, D), v_dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, D), jnp.float32),
            pltpu.VMEM((bk, D), jnp.float32),
        ],
        compiler_params=_params(),
        interpret=_interpret(),
    )(qf, kf, vf, dof, lse, delta)
    return dq, dk, dv


def _flash_bwd_rule(causal, res, dout):
    q, k, v, out_f, lse = res
    B, T, H, D = q.shape
    qf, kf, vf, dof = _fold(q), _fold(k), _fold(v), _fold(dout)
    BH = B * H
    # delta = rowsum(dO * O), on the same 8-row sublane layout as lse
    delta = jnp.sum(dof.astype(jnp.float32) * out_f.astype(jnp.float32), axis=-1)
    delta = jnp.broadcast_to(delta[:, None, :], (BH, 8, T))
    dq, dk, dv = _bwd_kernels(
        qf, kf, vf, dof, lse, delta, causal, q.dtype, k.dtype, v.dtype
    )
    return _unfold(dq, B, H), _unfold(dk, B, H), _unfold(dv, B, H)


flash_attention.defvjp(_flash_fwd_rule, _flash_bwd_rule)


# ---------------------------------------------------------------------------
# Block-level entry points for ring attention (ops/ring_attention.py):
# one K/V block visits per ring step; outputs merge via the global lse.
# ---------------------------------------------------------------------------


def flash_fwd_block(q, k, v, causal: bool):
    """One (q-shard, kv-block) flash forward.

    q [B,Tq,H,D], k/v [B,Tk,H,D] (Tk may differ when causal=False) ->
    (o [B,Tq,H,D] fp32, normalized within the block, lse [B*H, 8, Tq]).
    fp32 output: the ring merges blocks in fp32, and rounding each
    block's o before the merge would lose the fp32-accumulation guarantee
    the monolithic kernel has across its K tiles."""
    out, (_, _, _, _, lse) = _flash_fwd(q, k, v, causal, out_dtype=jnp.float32)
    return out, lse


def flash_bwd_block(q, k, v, do, lse, delta, causal: bool):
    """Per-block backward against the GLOBAL lse/delta: returns this
    block's (dq-contribution, dk, dv), in fp32 (the ring accumulates
    across blocks; one downcast happens at the very end)."""
    B, Tq, H, D = q.shape
    qf, kf, vf, dof = _fold(q), _fold(k), _fold(v), _fold(do)
    f32 = jnp.float32
    dq, dk, dv = _bwd_kernels(
        qf, kf, vf, dof, lse, delta, causal, f32, f32, f32
    )
    return _unfold(dq, B, H), _unfold(dk, B, H), _unfold(dv, B, H)
