"""Flash attention — Pallas TPU kernel with custom VJP.

The hot op of the transformer stack (no reference equivalent: the
reference delegates attention math to torch/vLLM; SURVEY.md §2.4). Design
for the TPU memory hierarchy (pallas_guide.md): the [T, S] score matrix
lives only in VMEM — queries are tiled over the grid, K/V rows for one
(batch, head) are resident in VMEM (T·Dh·2B each, ≈128KB at T=1024 —
far under the ~16MB budget), and matmuls hit the MXU with fp32
accumulation. This removes the O(B·H·T²) HBM traffic that makes the
einsum reference implementation bandwidth-bound.

VMEM residency bounds the sequence length (~8-16k per chip at Dh=64);
beyond that the context-parallel ring (ops/ring_attention.py) splits T
across chips, with this kernel as the per-shard block computation.

Layout: q,k,v [B, T, H, Dh] (model layout) — folded to [B*H, T, Dh] for
the kernel. Block sizes are multiples of the (8, 128) f32 tile.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _interpret() -> bool:
    # CPU has no Mosaic backend: run kernels in interpret mode so the same
    # code is testable on the virtual host mesh (SURVEY.md §4 takeaway).
    return jax.default_backend() == "cpu"


_NEG_INF = -1e30


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, block_q: int, causal: bool):
    # q_ref: [bq, D]; k_ref/v_ref: [T, D]; o_ref: [bq, D]; lse_ref: [bq]
    iq = pl.program_id(1)
    q = q_ref[...].astype(jnp.float32)
    k = k_ref[...].astype(jnp.float32)
    scale = 1.0 / (q.shape[-1] ** 0.5)
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * scale  # [bq, T]
    if causal:
        T = k.shape[0]
        row = jax.lax.broadcasted_iota(jnp.int32, (block_q, T), 0) + iq * block_q
        col = jax.lax.broadcasted_iota(jnp.int32, (block_q, T), 1)
        s = jnp.where(col <= row, s, _NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    # lse is [8, bq]: a dummy 8-row sublane dim keeps the store tile-legal
    lse_ref[...] = jnp.broadcast_to((m + jnp.log(l))[:, 0][None, :], (8, block_q))
    p = (p / l).astype(v_ref.dtype)
    o_ref[...] = jax.lax.dot_general(
        p, v_ref[...], (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    ).astype(o_ref.dtype)


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
               *, block_q: int, causal: bool):
    iq = pl.program_id(1)
    q = q_ref[...].astype(jnp.float32)
    k = k_ref[...].astype(jnp.float32)
    scale = 1.0 / (q.shape[-1] ** 0.5)
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * scale
    T = k.shape[0]
    if causal:
        row = jax.lax.broadcasted_iota(jnp.int32, (block_q, T), 0) + iq * block_q
        col = jax.lax.broadcasted_iota(jnp.int32, (block_q, T), 1)
        s = jnp.where(col <= row, s, _NEG_INF)
    p = jnp.exp(s - lse_ref[0][:, None])  # [bq, T]
    do = do_ref[...].astype(jnp.float32)
    dp = jax.lax.dot_general(
        do, v_ref[...].astype(jnp.float32), (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [bq, T]
    ds = p * (dp - delta_ref[0][:, None]) * scale
    dq_ref[...] = jax.lax.dot_general(
        ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    ).astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
                *, block_k: int, causal: bool):
    ik = pl.program_id(1)
    q = q_ref[...].astype(jnp.float32)     # [T, D] (all queries)
    k = k_ref[...].astype(jnp.float32)     # [bk, D]
    scale = 1.0 / (q.shape[-1] ** 0.5)
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * scale  # [T, bk]
    T = q.shape[0]
    if causal:
        row = jax.lax.broadcasted_iota(jnp.int32, (T, block_k), 0)
        col = jax.lax.broadcasted_iota(jnp.int32, (T, block_k), 1) + ik * block_k
        s = jnp.where(col <= row, s, _NEG_INF)
    p = jnp.exp(s - lse_ref[0][:, None])  # [T, bk]
    do = do_ref[...].astype(jnp.float32)    # [T, D]
    dv_ref[...] = jax.lax.dot_general(
        p, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
    ).astype(dv_ref.dtype)                  # [bk, D]
    dp = jax.lax.dot_general(
        do, v_ref[...].astype(jnp.float32), (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [T, bk]
    ds = p * (dp - delta_ref[0][:, None]) * scale  # [T, bk]
    dk_ref[...] = jax.lax.dot_general(
        ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
    ).astype(dk_ref.dtype)


def _pick_block(t: int, target: int = 256) -> int:
    for b in (target, 128, 64, 32, 16, 8):
        if t % b == 0:
            return min(b, t)
    return t


def _fold(x):  # [B, T, H, D] -> [B*H, T, D]
    B, T, H, D = x.shape
    return x.transpose(0, 2, 1, 3).reshape(B * H, T, D)


def _unfold(x, B, H):  # [B*H, T, D] -> [B, T, H, D]
    BH, T, D = x.shape
    return x.reshape(B, H, T, D).transpose(0, 2, 1, 3)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def flash_attention(q, k, v, causal: bool = True):
    out, _ = _flash_fwd(q, k, v, causal)
    return out


def _flash_fwd(q, k, v, causal):
    B, T, H, D = q.shape
    qf, kf, vf = _fold(q), _fold(k), _fold(v)
    BH = B * H
    bq = _pick_block(T)
    grid = (BH, T // bq)
    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, block_q=bq, causal=causal),
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, bq, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, T, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, T, D), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, bq, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, 8, bq), lambda b, i: (b, 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, T, D), q.dtype),
            jax.ShapeDtypeStruct((BH, 8, T), jnp.float32),
        ],
        interpret=_interpret(),
    )(qf, kf, vf)
    return _unfold(out, B, H), (q, k, v, _unfold_keep(out), lse)


def _unfold_keep(x):
    return x  # folded layout residual; avoids a transpose round-trip


def _flash_fwd_rule(q, k, v, causal):
    out, res = _flash_fwd(q, k, v, causal)
    return out, res


def _flash_bwd_rule(causal, res, dout):
    q, k, v, out_f, lse = res
    B, T, H, D = q.shape
    qf, kf, vf, dof = _fold(q), _fold(k), _fold(v), _fold(dout)
    BH = B * H
    # delta = rowsum(dO * O), broadcast onto the 8-row sublane layout
    delta = jnp.sum(dof.astype(jnp.float32) * out_f.astype(jnp.float32), axis=-1)
    delta = jnp.broadcast_to(delta[:, None, :], (BH, 8, T))

    bq = _pick_block(T)
    dq = pl.pallas_call(
        functools.partial(_dq_kernel, block_q=bq, causal=causal),
        grid=(BH, T // bq),
        in_specs=[
            pl.BlockSpec((None, bq, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, T, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, T, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, bq, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, 8, bq), lambda b, i: (b, 0, i)),
            pl.BlockSpec((None, 8, bq), lambda b, i: (b, 0, i)),
        ],
        out_specs=pl.BlockSpec((None, bq, D), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((BH, T, D), q.dtype),
        interpret=_interpret(),
    )(qf, kf, vf, dof, lse, delta)

    bk = _pick_block(T)
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, block_k=bk, causal=causal),
        grid=(BH, T // bk),
        in_specs=[
            pl.BlockSpec((None, T, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, bk, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, bk, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, T, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, 8, T), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, 8, T), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, bk, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, bk, D), lambda b, i: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, T, D), k.dtype),
            jax.ShapeDtypeStruct((BH, T, D), v.dtype),
        ],
        interpret=_interpret(),
    )(qf, kf, vf, dof, lse, delta)

    return _unfold(dq, B, H), _unfold(dk, B, H), _unfold(dv, B, H)


flash_attention.defvjp(_flash_fwd_rule, _flash_bwd_rule)
