"""Mixture-of-Experts block — expert parallelism over the mesh "ep" axis.

GShard-style top-k routing with static capacity (TPU-first: fixed
shapes, no data-dependent control flow — over-capacity tokens drop, the
standard accelerator MoE trade), expert weights sharded over "ep", and
token exchange via lax.all_to_all on the ICI mesh axis.

The reference has no native MoE (SURVEY.md §2.4 EP row: vLLM passthrough
only) — this is a capability-parity addition like ring attention.

Layout (under shard_map over the "ep" axis, n = axis size):
  x        [Bl, D]            local token shard
  wg       [D, E]             router (replicated)
  w_in     [El, D, F]         this device's experts (E = n * El)
  w_out    [El, F, D]
dispatch:  [Bl, E, C] one-hot -> all_to_all -> experts run [El, n*C, D]
combine:   reverse all_to_all -> weighted sum back into [Bl, D].
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax


def router_dispatch(
    x: jax.Array,          # [B, D]
    wg: jax.Array,         # [D, E]
    capacity: int,
    top_k: int = 2,
) -> Tuple[jax.Array, jax.Array]:
    """Compute (dispatch [B, E, C] float, combine [B, E, C] float).

    Top-k gating with position-in-expert assignment by cumulative count;
    tokens beyond an expert's capacity C are dropped (their combine
    weights are zero), matching GShard/Switch semantics."""
    B, D = x.shape
    E = wg.shape[1]
    gates = jax.nn.softmax(
        x.astype(jnp.float32) @ wg.astype(jnp.float32), axis=-1
    )  # [B, E]
    topv, topi = lax.top_k(gates, top_k)  # [B, K]
    # renormalize the selected gates
    topv = topv / jnp.maximum(topv.sum(-1, keepdims=True), 1e-9)

    dispatch = jnp.zeros((B, E, capacity), jnp.float32)
    combine = jnp.zeros((B, E, capacity), jnp.float32)
    # fill counts per expert across the k choices in priority order
    fill = jnp.zeros((E,), jnp.int32)
    for k in range(top_k):
        e_k = topi[:, k]                      # [B]
        onehot = jax.nn.one_hot(e_k, E, dtype=jnp.int32)  # [B, E]
        pos_in_e = (jnp.cumsum(onehot, axis=0) - onehot) + fill[None]  # [B, E]
        pos = jnp.sum(pos_in_e * onehot, axis=1)          # [B]
        keep = pos < capacity
        pos_oh = jax.nn.one_hot(pos, capacity, dtype=jnp.float32)
        sel = onehot.astype(jnp.float32) * keep[:, None]
        dispatch = dispatch + sel[:, :, None] * pos_oh[:, None, :]
        combine = combine + (
            sel * topv[:, k][:, None]
        )[:, :, None] * pos_oh[:, None, :]
        fill = fill + jnp.sum(onehot * keep[:, None].astype(jnp.int32), axis=0)
    return dispatch, combine


def moe_block_local(x, wg, w_in, w_out, capacity: int, top_k: int = 2):
    """Single-device MoE (numerics oracle): all experts local."""
    dispatch, combine = router_dispatch(x, wg, capacity, top_k)
    expert_in = jnp.einsum("bec,bd->ecd", dispatch, x.astype(jnp.float32))
    h = jax.nn.gelu(jnp.einsum("ecd,edf->ecf", expert_in, w_in))
    out = jnp.einsum("ecf,efd->ecd", h, w_out)
    return jnp.einsum("bec,ecd->bd", combine, out).astype(x.dtype)


def moe_block(
    x: jax.Array,        # local [Bl, D]
    wg: jax.Array,       # [D, E] replicated
    w_in: jax.Array,     # local experts [El, D, F]
    w_out: jax.Array,    # [El, F, D]
    capacity: int,
    axis_name: str = "ep",
    top_k: int = 2,
) -> jax.Array:
    """Expert-parallel MoE under shard_map: dispatch/combine all_to_all
    over `axis_name` (ICI), experts sharded across it."""
    n = lax.psum(1, axis_name)
    Bl, D = x.shape
    El = w_in.shape[0]
    E = n * El
    dispatch, combine = router_dispatch(x, wg, capacity, top_k)  # [Bl,E,C]
    C = capacity
    # tokens for each expert, grouped by owning device
    expert_in = jnp.einsum(
        "bec,bd->ecd", dispatch, x.astype(jnp.float32)
    )  # [E, C, D]
    expert_in = expert_in.reshape(n, El, C, D)
    # all_to_all: device r sends expert_in[p] to device p; receives its
    # own experts' tokens from every peer -> [n, El, C, D]
    recv = lax.all_to_all(expert_in, axis_name, split_axis=0, concat_axis=0,
                          tiled=False)
    recv = recv.reshape(n, El, C, D).transpose(1, 0, 2, 3).reshape(
        El, n * C, D
    )
    h = jax.nn.gelu(jnp.einsum("etd,edf->etf", recv, w_in))
    out = jnp.einsum("etf,efd->etd", h, w_out)  # [El, n*C, D]
    # reverse exchange: send each peer its tokens' outputs back
    out = out.reshape(El, n, C, D).transpose(1, 0, 2, 3)  # [n, El, C, D]
    back = lax.all_to_all(out, axis_name, split_axis=0, concat_axis=0,
                          tiled=False)
    back = back.reshape(E, C, D)
    return jnp.einsum("bec,ecd->bd", combine, back).astype(x.dtype)


def moe_block_sharded(
    x: jax.Array,        # global [B, D]
    wg: jax.Array,       # [D, E]
    w_in: jax.Array,     # [E, D, F]
    w_out: jax.Array,    # [E, F, D]
    mesh,
    capacity: int,
    ep_axis: str = "ep",
    top_k: int = 2,
) -> jax.Array:
    """shard_map wrapper: batch over ep (tokens sharded), experts over ep."""
    from jax.sharding import PartitionSpec as P

    from ray_tpu.ops.jax_compat import shard_map_unchecked

    fn = functools.partial(
        moe_block, capacity=capacity, axis_name=ep_axis, top_k=top_k
    )
    return shard_map_unchecked(
        fn,
        mesh=mesh,
        in_specs=(
            P(ep_axis, None),       # tokens sharded over ep
            P(None, None),          # router replicated
            P(ep_axis, None, None),  # experts sharded over ep
            P(ep_axis, None, None),
        ),
        out_specs=P(ep_axis, None),
    )(x, wg, w_in, w_out)
