"""Attention implementations.

impl="reference": readable jnp einsum attention (numerics oracle for tests).
impl="flash":     Pallas TPU kernel (ray_tpu.ops.flash_attention) — tiled
                  online-softmax so the T x T score matrix never hits HBM.
impl="ring":      blockwise ring attention over the mesh "cp" axis
                  (ray_tpu.ops.ring_attention) for sequence lengths that
                  don't fit one chip. Absent from the reference entirely
                  (SURVEY.md §5 "long-context"): it delegates long-sequence
                  scaling to vLLM/DeepSpeed; here it is native.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def attention(
    q: jax.Array,  # [B, T, H, Dh]
    k: jax.Array,  # [B, S, H, Dh]
    v: jax.Array,  # [B, S, H, Dh]
    causal: bool = True,
    impl: str = "reference",
    axis_name: Optional[str] = None,  # mesh axis for impl="ring"
) -> jax.Array:
    if impl == "reference":
        return _reference_attention(q, k, v, causal)
    if impl == "flash":
        from ray_tpu.ops.flash_attention import flash_attention

        return flash_attention(q, k, v, causal=causal)
    if impl == "ring":
        from ray_tpu.ops.ring_attention import ring_attention

        return ring_attention(q, k, v, axis_name=axis_name or "cp", causal=causal)
    raise ValueError(f"unknown attention impl {impl!r}")


def _reference_attention(q, k, v, causal):
    *_, T, _, d = q.shape
    S = k.shape[1]
    scale = 1.0 / (d ** 0.5)
    # [B, H, T, S]; bf16 operands, fp32 accumulation on the MXU
    scores = (
        jnp.einsum("bthd,bshd->bhts", q, k, preferred_element_type=jnp.float32)
        * scale
    )
    if causal:
        mask = jnp.tril(jnp.ones((T, S), dtype=bool), k=S - T)
        scores = jnp.where(mask[None, None], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
    return jnp.einsum("bhts,bshd->bthd", probs, v)
