"""XLA device-mesh group bootstrap: jax.distributed coordination via the
control store.

Role parity: where the reference rendezvouses an NCCLUniqueID through a
named store actor (nccl_collective_group.py:29-60), a TPU group
rendezvouses the jax.distributed coordinator address through the control
store KV. After initialize_xla_group() every member process is part of one
JAX runtime; device collectives are then ordinary in-graph mesh ops.
"""

from __future__ import annotations

import socket
import time
from typing import Dict, Optional


def _control():
    from ray_tpu.core import worker as worker_mod

    return worker_mod.global_worker().control


def _free_port() -> int:
    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def get_xla_coordinator(group_name: str, rank: int, timeout_s: float = 60.0) -> str:
    """Rank 0 claims (or reuses) the coordinator address; others poll it."""
    control = _control()
    key = f"xla/{group_name}/coordinator"
    if rank == 0:
        addr = f"{socket.gethostbyname(socket.gethostname())}:{_free_port()}"
        if not control.call("kv_put", ns="coll", key=key, value=addr.encode(),
                            overwrite=False, retryable=True):
            addr = control.call("kv_get", ns="coll", key=key, retryable=True).decode()
        return addr
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        val = control.call("kv_get", ns="coll", key=key, retryable=True)
        if val is not None:
            return val.decode()
        time.sleep(0.05)
    raise TimeoutError(f"no coordinator for xla group {group_name}")


def xla_coordinator_env(
    group_name: str,
    rank: int,
    world_size: int,
    num_slices: int = 1,
    slice_id: int = 0,
) -> Dict[str, str]:
    """Env for a worker joining the group's JAX runtime; includes the
    MEGASCALE multislice variables when num_slices > 1 (parity:
    train/v2/jax/config.py:113-165 + util/tpu.py:198)."""
    coordinator = get_xla_coordinator(group_name, rank)
    env = {
        "JAX_COORDINATOR_ADDRESS": coordinator,
        "JAX_NUM_PROCESSES": str(world_size),
        "JAX_PROCESS_ID": str(rank),
    }
    if num_slices > 1:
        from ray_tpu.accelerators.tpu import get_tpu_coordinator_env_vars

        env.update(
            get_tpu_coordinator_env_vars(coordinator, num_slices, slice_id)
        )
    return env


def initialize_xla_group(
    group_name: str, rank: int, world_size: int
) -> None:
    """Join this process into the group's JAX runtime
    (jax.distributed.initialize with control-store rendezvous)."""
    import jax

    coordinator = get_xla_coordinator(group_name, rank)
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=world_size,
        process_id=rank,
    )
