"""Peer-to-peer ring transport for host collectives.

The KV path (collective.py) relays every payload byte through the
control store: each rank kv_puts its full tensor and kv_waits everyone
else's — O(world²·payload) through one head process, capped at whatever
a single KV server can relay. This module moves collective bytes
DIRECTLY worker↔worker over the multi-segment RPC data plane
(utils/rpc.py): ranks rendezvous once per group through a small
control-store KV exchange (worker host/port + an incarnation token per
rank — the ONLY head traffic, independent of payload size), then stream
chunked tensor segments around the ring.

Transport: every worker process already runs an RpcServer
(core/worker.py) and keeps a worker↔worker client pool; ring chunk
sends are ``coll_deliver`` RPCs whose ndarray payloads ride as raw
out-of-band segments — vectored sendmsg on the sender, recv_into
preallocated buffers on the receiver, never re-pickled in-band
(tools/check_inband_payloads.py pins this). Delivery is idempotent
(tag-deduplicated mailbox), so sends retry safely across connection
drops.

Algorithms (ring/reduce-scatter structure is what makes large-world
collectives scale — MLPerf TPU-pod study, arxiv 1909.09756):

  allreduce     reduce-scatter phase + allgather phase; each ring chunk
                splits into pipeline subchunks (collective_chunk_bytes)
                so subchunk k+1 is on the wire while k reduces in place
  reducescatter the matching single phase (rank r ends owning chunk r)
  allgather     ring forwarding, world-1 hops
  broadcast     chunk-pipelined chain forward from the source rank
  send/recv     direct dial (collective.py routes payloads ≥
                collective_p2p_min_bytes here; smaller ones stay on KV)

Quantized allreduce (EQuARX, arxiv 2506.17615): ``quant="int8"``
quantizes each subchunk blockwise on the SENDING host (int8 payload +
one f32 scale per collective_quant_block elements), accumulates in f32,
and dequantizes once per received chunk — the allgather phase forwards
received quantized payloads VERBATIM, so a fully-reduced chunk is
quantized exactly once (by its owner) no matter how many hops it rides.
~4× fewer wire bytes at a bounded, tested numerics delta
(tests/test_collective_p2p.py pins the per-dtype error bound).

Failure: a rank that cannot deliver to a peer — or times out waiting —
poisons the ring with a tiny ``coll_deliver`` poison message forwarded
neighbor-to-neighbor (deduplicated by poison id, no head traffic), so
every surviving rank raises CollectiveError promptly instead of
hanging. destroy + init_collective_group re-rendezvouses a fresh
incarnation; deliveries from the old one are dropped by token mismatch.

Kill switch: RT_COLLECTIVE_P2P=0 routes everything back through the KV
path (collective.py checks it before dispatching here).
"""

from __future__ import annotations

import threading
import time
import uuid
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ray_tpu.core.exceptions import CollectiveError
from ray_tpu.observability import core_metrics
from ray_tpu.utils import rpc as rpc_mod
from ray_tpu.utils import serialization
from ray_tpu.utils.config import config

# Per-process transport statistics. Tests and bench_core read these
# through actor methods (each rank is its own process) to pin wire-byte
# claims — quantized vs f32, p2p-vs-KV routing — independent of the
# metrics pipeline; core metrics mirror the send side when enabled.
stats = {"bytes_sent": 0, "bytes_recv": 0, "sends": 0, "delivers": 0,
         "bytes_sent_inter": 0}
_stats_lock = threading.Lock()

_DELIVER = "coll_deliver"
_MISSING = object()
# Test hook: called as _step_hook(phase, step) at the top of every ring
# step (failure tests arm it to kill this process deterministically
# MID-ring, between chunk exchanges). None on the hot path.
_step_hook = None
# delivered-tag memory per group (duplicate suppression for retried
# sends); trimmed FIFO so a long-lived group cannot grow unbounded
_SEEN_CAP = 8192


def reset_stats() -> Dict[str, int]:
    """Snapshot-and-zero the per-process transport counters (tests)."""
    with _stats_lock:
        snap = dict(stats)
        for k in stats:
            stats[k] = 0
    return snap


def snapshot_stats() -> Dict[str, int]:
    with _stats_lock:
        return dict(stats)


class _P2PGroup:
    """Per-process ring state for one collective group incarnation."""

    def __init__(self, name: str, world_size: int, rank: int, token: str):
        self.name = name
        self.world_size = world_size
        self.rank = rank
        self.token = token  # my incarnation id (published at rendezvous)
        # rank -> (worker rpc address, incarnation token, host id)
        self.peers: List[Tuple[str, str, str]] = []
        self.mailbox: Dict[str, Any] = {}
        self.seen: set = set()
        self.seen_order: deque = deque()
        self.cv = threading.Condition()
        self.failed: Optional[str] = None
        self.poisons: set = set()


_groups: Dict[str, _P2PGroup] = {}
_groups_lock = threading.Lock()


def _worker():
    from ray_tpu.core import worker as worker_mod

    return worker_mod.global_worker()


def enabled() -> bool:
    return bool(config.collective_p2p)


def min_bytes() -> int:
    return int(config.collective_p2p_min_bytes)


def group_for(name: str) -> Optional[_P2PGroup]:
    with _groups_lock:
        return _groups.get(name)


def host_id() -> str:
    """This process's host identity for collective topology: the
    collective_host_id override (tests/bench model multi-host placement
    on one box with it) or the worker address host."""
    hid = str(config.collective_host_id or "")
    if hid:
        return hid
    addr = getattr(_worker(), "address", "") or ""
    return addr.rsplit(":", 1)[0] or "localhost"


def host_of(g: _P2PGroup, rank: int) -> str:
    return g.peers[rank][2]


# ---------------------------------------------------------------------------
# rendezvous / teardown
# ---------------------------------------------------------------------------


def setup_group(name: str, world_size: int, rank: int,
                timeout_s: Optional[float] = None) -> _P2PGroup:
    """One small KV exchange per member: publish (worker address,
    incarnation token), await every peer's. This — plus destroy's key
    cleanup — is the only control-store traffic a p2p collective ever
    generates: O(world) values of ~100 bytes, independent of payload
    size. Doubles as the group rendezvous barrier (all members are
    provably up once it returns)."""
    from ray_tpu.collective import collective as coll_mod

    w = _worker()
    timeout_s = timeout_s or float(config.collective_op_timeout_s)
    token = uuid.uuid4().hex
    g = _P2PGroup(name, world_size, rank, token)
    # register the mailbox BEFORE publishing: a peer that finishes its
    # rendezvous first may start delivering the instant our record is
    # visible, and an unregistered group would bounce those deliveries
    # as stale (the sender treats a bounce as a dead incarnation)
    with _groups_lock:
        _groups[name] = g
    ns = f"coll/{name}"
    payload = serialization.dumps((w.address, token, host_id()))
    try:
        w.control.call(  # inband: ok — ~100 B rendezvous record, not data
            "kv_put", ns=ns, key=f"p2p/{rank}", value=payload,
            retryable=True,
        )
        out = coll_mod._await_keys(
            w.control, ns, [f"p2p/{r}" for r in range(world_size)],
            timeout_s,
        )
        peers: List[Tuple[str, str, str]] = []
        missing = []
        for r in range(world_size):
            val = out.get(f"p2p/{r}")
            if val is None:
                missing.append(r)
            else:
                peers.append(serialization.loads(val))
        if missing:
            raise TimeoutError(
                f"collective group {name!r} p2p rendezvous: ranks "
                f"{missing} missing after {timeout_s}s"
            )
    except BaseException:
        drop_group(name)
        raise
    g.peers = peers
    return g


def drop_group(name: str) -> None:
    """Forget this process's ring state for a group; any thread blocked
    in a mailbox wait raises. Deliveries addressed to the old
    incarnation token are dropped on arrival from now on."""
    with _groups_lock:
        g = _groups.pop(name, None)
    if g is not None:
        with g.cv:
            if g.failed is None:
                g.failed = "group destroyed"
            g.cv.notify_all()


# ---------------------------------------------------------------------------
# delivery (the worker's rpc_coll_deliver lands here)
# ---------------------------------------------------------------------------


def deliver(group: str, token: str, tag: str, payload=None,
            poison: Optional[str] = None) -> bool:
    g = group_for(group)
    if g is None or token != g.token:
        return False  # stale incarnation / unknown group: drop silently
    if poison is not None:
        _poison_local(g, tag, poison)
        return True
    nbytes = _payload_nbytes(payload)
    with _stats_lock:
        stats["bytes_recv"] += nbytes
        stats["delivers"] += 1
    with g.cv:
        if tag in g.seen:
            return True  # duplicate from a sender retry: already have it
        g.seen.add(tag)
        g.seen_order.append(tag)
        while len(g.seen_order) > _SEEN_CAP:
            g.seen.discard(g.seen_order.popleft())
        g.mailbox[tag] = payload
        g.cv.notify_all()
    return True


def _poison_local(g: _P2PGroup, poison_id: str, reason: str) -> None:
    """Record a ring failure and forward it to both neighbors exactly
    once (dedup by poison id stops the echo) — failure propagation with
    zero head traffic."""
    with g.cv:
        if poison_id in g.poisons:
            return
        g.poisons.add(poison_id)
        if g.failed is None:
            g.failed = reason
        g.cv.notify_all()
    if not g.peers:
        return  # poisoned before rendezvous finished: nothing to dial
    world = g.world_size
    for nb in {(g.rank + 1) % world, (g.rank - 1) % world}:
        if nb == g.rank:
            continue
        try:
            _client(g, nb).call_oneway(
                _DELIVER, group=g.name, token=g.peers[nb][1],
                tag=poison_id, poison=reason,
            )
        except Exception:  # noqa: BLE001 — neighbor may be the dead one
            pass


def poison_group(g: _P2PGroup, reason: str) -> None:
    _poison_local(g, f"__poison__/{uuid.uuid4().hex}", reason)


# ---------------------------------------------------------------------------
# send / recv primitives
# ---------------------------------------------------------------------------


def _client(g: _P2PGroup, rank: int) -> rpc_mod.RpcClient:
    return _worker().workers.get(g.peers[rank][0])


def _payload_nbytes(payload) -> int:
    if isinstance(payload, np.ndarray):
        return payload.nbytes
    if isinstance(payload, tuple):
        return sum(
            p.nbytes for p in payload if isinstance(p, np.ndarray)
        )
    if payload is None:
        return 0
    try:
        return len(payload)
    except TypeError:
        return 0


def send_async(g: _P2PGroup, dst: int, tag: str, payload,
               op: str = "p2p"):
    """Fire one chunk delivery at ``dst``; the frame is on the wire when
    this returns (call_async semantics), so issuing all of a step's
    subchunks back-to-back pipelines the wire against the receiver's
    reduce. Returns a handle for reap(). ndarray / (int8, scales) tuple
    payloads ride as raw out-of-band segments."""
    nbytes = _payload_nbytes(payload)
    # hierarchical-mode accounting: a delivery whose destination host
    # differs from ours crossed a host boundary (with collective_host_id
    # overrides this models multi-host placement even on one box)
    inter = bool(g.peers) and g.peers[dst][2] != g.peers[g.rank][2]
    with _stats_lock:
        stats["bytes_sent"] += nbytes
        stats["sends"] += 1
        if inter:
            stats["bytes_sent_inter"] += nbytes
    if core_metrics.ENABLED:
        core_metrics.collective_bytes_sent.inc(
            nbytes, tags={"op": op, "transport": "p2p"}
        )
        if inter:
            core_metrics.collective_inter_bytes.inc(nbytes, tags={"op": op})
    # chaos parity with RpcClient.call: call_async has no injection
    # point, so the collective transport rolls its own. An injected
    # request drop models a torn send the SENDER sees immediately — the
    # sane transport response is to resend on the spot (a frame that
    # never left cannot be waited out by the receiver, and leaving it to
    # the end-of-step reap could make a full ring of simultaneous drops
    # circular-wait until the op deadline).
    for _ in range(20):
        try:
            rpc_mod.maybe_inject_request_failure(_DELIVER)
            break
        except rpc_mod.RpcConnectionError:
            continue
    try:
        pending = _client(g, dst).call_async(
            _DELIVER, group=g.name, token=g.peers[dst][1], tag=tag,
            payload=payload,
        )
    except (rpc_mod.RpcError, OSError):
        # dial failed: hand reap() a pending-less handle — its retry
        # ladder redials, and poisons the ring if the peer stays dead
        pending = None
    return (dst, tag, payload, pending)


def reap(g: _P2PGroup, handles, deadline: float) -> None:
    """Await delivery acks; failed sends retry synchronously (delivery
    is idempotent, so a resend after a lost ack is harmless). The retry
    ladder is bounded by the OP deadline, not just per-call timeouts —
    each redial to a dead peer burns up to rpc_connect_timeout_s, and a
    stuck op must surface as ring poison within the op budget, not after
    an attempts×connect-timeout stall."""
    for dst, tag, payload, pending in handles:
        last: Optional[Exception] = None
        bounced = False
        if pending is not None:
            try:
                ack = pending.wait(max(0.1, deadline - time.monotonic()))
                rpc_mod.maybe_inject_response_failure(_DELIVER)
                if ack is not False:
                    continue
                bounced = True  # receiver dropped it: stale incarnation
            except rpc_mod.RpcError as e:
                last = e
        delivered = False
        for attempt in range(3):
            if bounced or (attempt and time.monotonic() >= deadline):
                break
            try:
                ack = _client(g, dst).call(
                    _DELIVER, group=g.name, token=g.peers[dst][1],
                    tag=tag, payload=payload,
                    timeout_s=max(0.5, deadline - time.monotonic()),
                    retryable=False,
                )
                if ack is False:
                    bounced = True
                    break
                delivered = True
                break
            except rpc_mod.RpcError as e:
                last = e
        if delivered:
            continue
        reason = (
            f"rank {g.rank} could not deliver {tag} to rank {dst} "
            f"({g.peers[dst][0]}): "
            + ("receiver dropped it (group destroyed or re-initialized "
               "with a new incarnation)" if bounced
               else f"{type(last).__name__}: {last}")
        )
        poison_group(g, reason)
        raise CollectiveError(reason) from last


def send_now(g: _P2PGroup, dst: int, tag: str, payload,
             deadline: float, op: str = "p2p") -> None:
    """Fire-and-ack a single delivery (send/recv and poison-free small
    control messages)."""
    reap(g, [send_async(g, dst, tag, payload, op=op)], deadline)


def recv(g: _P2PGroup, tag: str, deadline: float):
    """Block until ``tag`` lands in the mailbox. Raises CollectiveError
    if the ring is poisoned or the deadline passes (and poisons the ring
    on timeout — a stuck op is broken for everyone)."""
    fail: Optional[str] = None
    with g.cv:
        while True:
            payload = g.mailbox.pop(tag, _MISSING)
            if payload is not _MISSING:
                return payload
            if g.failed is not None:
                fail = g.failed
                break
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            g.cv.wait(min(remaining, 0.5))
    if fail is not None:
        raise CollectiveError(f"collective group {g.name!r}: {fail}")
    reason = (
        f"rank {g.rank} timed out waiting for {tag} on group {g.name!r}"
    )
    poison_group(g, reason)
    raise CollectiveError(reason)


def try_recv(g: _P2PGroup, tag: str, wait_s: float) -> Tuple[bool, Any]:
    """Bounded mailbox wait: (True, payload) if ``tag`` arrived, (False,
    None) if not yet. Raises CollectiveError if the ring is poisoned
    (collective.recv's dual KV/p2p wait loop uses this)."""
    deadline = time.monotonic() + wait_s
    with g.cv:
        while True:
            payload = g.mailbox.pop(tag, _MISSING)
            if payload is not _MISSING:
                return True, payload
            if g.failed is not None:
                raise CollectiveError(
                    f"collective group {g.name!r}: {g.failed}"
                )
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return False, None
            g.cv.wait(remaining)


# ---------------------------------------------------------------------------
# chunking + int8 blockwise quantization (EQuARX-style)
# ---------------------------------------------------------------------------


def _subchunks(view: np.ndarray) -> List[np.ndarray]:
    """Split a 1-D contiguous view into pipeline subchunks of about
    collective_chunk_bytes each (always at least one, possibly empty for
    zero-size chunks so send/recv tag counts still match)."""
    step = max(1, int(config.collective_chunk_bytes) // max(1, view.itemsize))
    if view.size <= step:
        return [view]
    return [view[i:i + step] for i in range(0, view.size, step)]


def _quant_int8(x: np.ndarray) -> Tuple[int, np.ndarray, np.ndarray]:
    """Blockwise int8 quantization: one f32 scale per
    collective_quant_block elements, scale = blockmax/127 so values
    never clip. Returns (block, int8 payload, f32 scales)."""
    block = max(1, int(config.collective_quant_block))
    n = x.size
    nb = max(1, -(-n // block))
    pad = nb * block - n
    xb = x if not pad else np.concatenate(
        [x, np.zeros(pad, dtype=x.dtype)]
    )
    xb = xb.reshape(nb, block)
    scales = (np.abs(xb).max(axis=1) / 127.0).astype(np.float32)
    safe = np.where(scales > 0.0, scales, np.float32(1.0)).astype(np.float32)
    q = np.rint(xb / safe[:, None]).astype(np.int8).reshape(-1)
    return block, q[:n], safe


def _dequant_int8(block: int, q: np.ndarray, scales: np.ndarray) -> np.ndarray:
    n = q.size
    nb = scales.size
    xf = q.astype(np.float32)
    pad = nb * block - n
    if pad:
        xf = np.concatenate([xf, np.zeros(pad, dtype=np.float32)])
    xf = (xf.reshape(nb, block) * scales[:, None]).reshape(-1)
    return xf[:n]


def _encode(sub: np.ndarray, quant: Optional[str]):
    if quant is None:
        return sub  # contiguous view: pickles as a zero-copy oob buffer
    block, q, scales = _quant_int8(sub)
    return ("q8", block, q, scales)


def _decode(incoming, quant: Optional[str]) -> np.ndarray:
    if quant is None:
        return incoming
    _, block, q, scales = incoming
    return _dequant_int8(block, q, scales)


_INPLACE_REDUCERS = {
    "sum": lambda a, b: np.add(a, b, out=a, casting="unsafe"),
    "product": lambda a, b: np.multiply(a, b, out=a, casting="unsafe"),
    "min": lambda a, b: np.minimum(a, b, out=a),
    "max": lambda a, b: np.maximum(a, b, out=a),
}


# ---------------------------------------------------------------------------
# ring collectives
# ---------------------------------------------------------------------------


def _deadline(timeout_s: Optional[float]) -> float:
    return time.monotonic() + (
        timeout_s if timeout_s is not None
        else float(config.collective_op_timeout_s)
    )


def _flat_chunks(acc: np.ndarray, world: int) -> List[np.ndarray]:
    per = acc.size // world
    return [acc[i * per:(i + 1) * per] for i in range(world)]


def ring_allreduce(g: _P2PGroup, arr: np.ndarray, op: str, tag: str,
                   quant: Optional[str] = None,
                   timeout_s: Optional[float] = None,
                   ring: Optional[List[int]] = None) -> np.ndarray:
    """Pipelined ring allreduce: reduce-scatter then allgather, each
    ring chunk split into subchunks so the wire and the local reduce
    overlap. With quant="int8" (SUM over floats only) every wire payload
    is blockwise-int8; accumulation stays f32 and forwarded allgather
    payloads are passed on verbatim, so each final chunk is quantized
    exactly once.

    ``ring`` restricts the op to an ordered subset of the group's ranks
    (every member must pass the SAME list, and this rank must be in it)
    — the hierarchical two-level mode runs its inter-host phase as a
    ring over host leaders only this way."""
    deadline = _deadline(timeout_s)
    shape, dtype = arr.shape, arr.dtype
    members = ring if ring is not None else list(range(g.world_size))
    world = len(members)
    pos = members.index(g.rank)
    if quant is not None:
        if quant != "int8":
            raise ValueError(f"unsupported quant mode {quant!r}")
        if op != "sum":
            raise ValueError("quantized allreduce supports ReduceOp.SUM only")
        if dtype.kind != "f":
            raise ValueError(
                f"quantized allreduce needs a float tensor, got {dtype}"
            )
        acc = np.ascontiguousarray(arr).reshape(-1).astype(
            np.float32, copy=True
        )
    else:
        acc = np.ascontiguousarray(arr).reshape(-1).copy()
    if world < 2:
        return acc.astype(dtype, copy=False).reshape(shape)
    n0 = acc.size
    pad = (-n0) % world
    if pad:
        acc = np.concatenate([acc, np.zeros(pad, dtype=acc.dtype)])
    chunks = _flat_chunks(acc, world)
    nxt = members[(pos + 1) % world]
    red = _INPLACE_REDUCERS[op]

    # phase 1: reduce-scatter — after world-1 steps ring position p owns
    # the fully-reduced chunk (p+1) % world
    for step in range(world - 1):
        if _step_hook is not None:
            _step_hook("rs", step)
        si = (pos - step) % world
        ri = (pos - step - 1) % world
        handles = [
            send_async(g, nxt, f"{tag}/rs{step}/{j}",
                       _encode(sub, quant), op="allreduce")
            for j, sub in enumerate(_subchunks(chunks[si]))
        ]
        for j, sub in enumerate(_subchunks(chunks[ri])):
            incoming = _decode(
                recv(g, f"{tag}/rs{step}/{j}", deadline), quant
            )
            red(sub, incoming)
        reap(g, handles, deadline)

    # phase 2: allgather — forward received payloads VERBATIM (quantized
    # chunks are quantized once by their owner, dequantized once here)
    carry = []
    for sub in _subchunks(chunks[(pos + 1) % world]):
        payload = _encode(sub, quant)
        if quant is not None:
            # the owner adopts the same quantization loss it ships:
            # allreduce must leave every rank with the IDENTICAL tensor
            # (data-parallel replicas diverge otherwise), so the exact
            # f32 chunk is replaced by its own dequantized image
            np.copyto(sub, _decode(payload, quant), casting="unsafe")
        carry.append(payload)
    for step in range(world - 1):
        ri = (pos - step) % world
        handles = [
            send_async(g, nxt, f"{tag}/ag{step}/{j}", payload,
                       op="allreduce")
            for j, payload in enumerate(carry)
        ]
        carry = []
        for j, sub in enumerate(_subchunks(chunks[ri])):
            incoming = recv(g, f"{tag}/ag{step}/{j}", deadline)
            np.copyto(sub, _decode(incoming, quant), casting="unsafe")
            carry.append(incoming)
        reap(g, handles, deadline)

    out = acc[:n0] if pad else acc
    return out.astype(dtype, copy=False).reshape(shape)


def ring_reducescatter(g: _P2PGroup, arr: np.ndarray, op: str, tag: str,
                       timeout_s: Optional[float] = None) -> np.ndarray:
    """Ring reduce-scatter along dim 0: rank r returns the fully-reduced
    r-th 1/world slice. Chunk traversal is shifted by one vs allreduce's
    phase 1 so the final owned chunk index equals the rank."""
    deadline = _deadline(timeout_s)
    world = g.world_size
    if arr.shape[0] % world != 0:
        raise ValueError(
            f"dim 0 ({arr.shape[0]}) not divisible by world size {world}"
        )
    acc = np.ascontiguousarray(arr).copy()
    rows = arr.shape[0] // world
    flat = acc.reshape(-1)
    chunks = _flat_chunks(flat, world)
    nxt = (g.rank + 1) % world
    red = _INPLACE_REDUCERS[op]
    for step in range(world - 1):
        si = (g.rank - step - 1) % world
        ri = (g.rank - step - 2) % world
        handles = [
            send_async(g, nxt, f"{tag}/rs{step}/{j}", sub,
                       op="reducescatter")
            for j, sub in enumerate(_subchunks(chunks[si]))
        ]
        for j, sub in enumerate(_subchunks(chunks[ri])):
            red(sub, recv(g, f"{tag}/rs{step}/{j}", deadline))
        reap(g, handles, deadline)
    return acc[g.rank * rows:(g.rank + 1) * rows]


def ring_allgather(g: _P2PGroup, arr: np.ndarray, tag: str,
                   timeout_s: Optional[float] = None) -> List[np.ndarray]:
    """Ring allgather: world-1 hops, each forwarding the array received
    the hop before (shapes may differ per rank, so whole arrays travel
    as single out-of-band payloads)."""
    deadline = _deadline(timeout_s)
    world = g.world_size
    nxt = (g.rank + 1) % world
    local = np.ascontiguousarray(arr)
    out: List[Optional[np.ndarray]] = [None] * world
    out[g.rank] = local
    carry: Any = local
    for step in range(world - 1):
        handles = [send_async(g, nxt, f"{tag}/ag{step}", carry,
                              op="allgather")]
        src = (g.rank - step - 1) % world
        carry = recv(g, f"{tag}/ag{step}", deadline)
        out[src] = np.asarray(carry)
        reap(g, handles, deadline)
    return out  # type: ignore[return-value]


def ring_broadcast(g: _P2PGroup, arr: Optional[np.ndarray], src: int,
                   tag: str,
                   timeout_s: Optional[float] = None) -> np.ndarray:
    """Chunk-pipelined chain broadcast: the source streams subchunks to
    its ring successor; every other rank forwards each subchunk as soon
    as it lands (unless the successor is the source), so the extra
    latency per hop is one subchunk, not one tensor."""
    deadline = _deadline(timeout_s)
    world = g.world_size
    nxt = (g.rank + 1) % world
    if g.rank == src:
        flat = np.ascontiguousarray(arr).reshape(-1)
        subs = _subchunks(flat)
        header = ("hdr", arr.shape, arr.dtype.str, len(subs))
        if world > 1:
            handles = [send_async(g, nxt, f"{tag}/h", header,
                                  op="broadcast")]
            handles += [
                send_async(g, nxt, f"{tag}/b{j}", sub, op="broadcast")
                for j, sub in enumerate(subs)
            ]
            reap(g, handles, deadline)
        return np.asarray(arr)
    header = recv(g, f"{tag}/h", deadline)
    _, shape, dtype_str, nsubs = header
    forward = nxt != src
    handles = []
    if forward:
        handles.append(send_async(g, nxt, f"{tag}/h", header,
                                  op="broadcast"))
    parts = []
    for j in range(nsubs):
        sub = recv(g, f"{tag}/b{j}", deadline)
        parts.append(np.asarray(sub))
        if forward:
            handles.append(send_async(g, nxt, f"{tag}/b{j}", sub,
                                      op="broadcast"))
    reap(g, handles, deadline)
    flat = parts[0] if len(parts) == 1 else np.concatenate(parts)
    return flat.astype(np.dtype(dtype_str), copy=False).reshape(shape)


def p2p_send(g: _P2PGroup, dst: int, tag: str, arr: np.ndarray,
             timeout_s: Optional[float] = None) -> None:
    """Point-to-point send of one whole array as a single out-of-band
    delivery (collective.send routes payloads ≥ collective_p2p_min_bytes
    here)."""
    send_now(g, dst, tag, np.ascontiguousarray(arr),
             _deadline(timeout_s), op="send")
