"""Host-tensor collectives over the control-store KV (the Gloo role).

Algorithm: each op gets a (group, seq) namespace; every rank publishes
its contribution and awaits peers' via server-side blocking kv_wait
RPCs issued CONCURRENTLY (no client polling — the control store's KV
condition variable wakes every waiter on publish), then reduces locally.
reducescatter exchanges only the per-destination chunks (O(tensor)
traffic per rank, not a full allreduce). Intended for host tensors
(rendezvous payloads, metrics, CPU-tier CI); device tensors should use
in-graph mesh collectives instead.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np

from ray_tpu.utils import serialization


class ReduceOp:
    SUM = "sum"
    PRODUCT = "product"
    MIN = "min"
    MAX = "max"


_REDUCERS = {
    ReduceOp.SUM: lambda xs: sum(xs[1:], xs[0].copy()),
    ReduceOp.PRODUCT: lambda xs: np.prod(np.stack(xs), axis=0),
    ReduceOp.MIN: lambda xs: np.min(np.stack(xs), axis=0),
    ReduceOp.MAX: lambda xs: np.max(np.stack(xs), axis=0),
}


class _GroupState:
    def __init__(self, group_name: str, world_size: int, rank: int):
        self.name = group_name
        self.world_size = world_size
        self.rank = rank
        self.seq = 0
        # p2p streams get their own per-(src,dst) counters: collective seq
        # numbers only align across ranks when every rank runs every op.
        self.p2p_counts: Dict[tuple, int] = {}
        # my published keys, grouped PER OP, deleted with a 2-op lag
        # (peers of op N have all read its keys once op N+2 starts —
        # bounds control-store memory)
        self.gc_queue: List[List[str]] = []
        self.lock = threading.Lock()


_groups: Dict[str, _GroupState] = {}


def _control():
    from ray_tpu.core import worker as worker_mod

    return worker_mod.global_worker().control


def _ns(group: _GroupState) -> str:
    return f"coll/{group.name}"


def init_collective_group(
    world_size: int,
    rank: int,
    backend: str = "cpu",
    group_name: str = "default",
) -> None:
    """Register this process as `rank` of a collective group.

    Called by every participating actor/task (parity: collective.py:171).
    """
    if backend not in ("cpu", "xla"):
        raise ValueError(f"unsupported backend {backend!r}")
    if not 0 <= rank < world_size:
        raise ValueError(f"rank {rank} out of range for world_size {world_size}")
    _groups[group_name] = _GroupState(group_name, world_size, rank)
    # rendezvous barrier so all members see each other before first op
    barrier(group_name)


def destroy_collective_group(group_name: str = "default") -> None:
    """Drop group state and delete its KV namespace (required before a
    group name can be REUSED — stale keys from a prior incarnation would
    otherwise satisfy the new group's rendezvous)."""
    group = _groups.pop(group_name, None)
    try:
        _control().call_oneway("kv_del_prefix", ns=f"coll/{group_name}", prefix="")
    except Exception:  # noqa: BLE001 — cluster may already be down
        pass


def get_rank(group_name: str = "default") -> int:
    return _groups[group_name].rank


def get_collective_group_size(group_name: str = "default") -> int:
    return _groups[group_name].world_size


def _exchange(group: _GroupState, payload: Optional[bytes], tag: str,
              ranks: Optional[List[int]] = None,
              timeout_s: float = 120.0, gc: bool = True) -> Dict[int, bytes]:
    """Publish payload under (tag, my rank); collect peers' payloads.

    gc=True is only valid for full-participation ops (every rank publishes
    and reads every other): completing op N+1 then proves all peers read
    op N-1's keys, so each rank deletes its own keys with a 2-op lag.
    Broadcast/p2p keys are exempt (the publisher can finish before readers
    arrive) and are reclaimed by destroy_collective_group().
    """
    control = _control()
    ns = _ns(group)
    if payload is not None:
        control.call(
            "kv_put", ns=ns, key=f"{tag}/{group.rank}", value=payload,
            retryable=True,
        )
    if payload is not None and gc:
        _gc_publish(group, [f"{tag}/{group.rank}"])
    want = ranks if ranks is not None else list(range(group.world_size))
    out = _await_keys(
        control, ns, [f"{tag}/{r}" for r in want], timeout_s
    )
    missing = [r for r in want if out.get(f"{tag}/{r}") is None]
    if missing:
        raise TimeoutError(
            f"collective {tag} on group {group.name}: ranks {missing} "
            f"missing after {timeout_s}s"
        )
    return {r: out[f"{tag}/{r}"] for r in want}


def _await_keys(control, ns: str, keys: List[str],
                timeout_s: float) -> Dict[str, Optional[bytes]]:
    """Concurrent server-side blocking kv_waits, with reconnect-and-
    reissue on transient control-store failures (the old poll loop's
    retryable=True resilience, kept under the no-polling design)."""
    import time as _time

    from ray_tpu.utils.rpc import RpcConnectionError, RpcTimeout

    deadline = _time.monotonic() + timeout_s
    out: Dict[str, Optional[bytes]] = {}
    remaining_keys = list(keys)
    while remaining_keys:
        remaining = max(0.5, deadline - _time.monotonic())
        pending = {
            k: control.call_async("kv_wait", ns=ns, key=k, wait_s=remaining)
            for k in remaining_keys
        }
        retry = []
        for k, p in pending.items():
            try:
                out[k] = p.wait(remaining + 30.0)
            except (RpcConnectionError, RpcTimeout):
                if _time.monotonic() < deadline:
                    retry.append(k)
                else:
                    out[k] = None
        remaining_keys = retry
        if retry:
            _time.sleep(0.2)  # let the client reconnect
    return out


def _gc_publish(group: _GroupState, keys: List[str]) -> None:
    """Record this op's published keys; delete the keys of ops at least
    2 behind (every peer provably read them by then)."""
    control = _control()
    ns = _ns(group)
    with group.lock:
        group.gc_queue.append(keys)
        stale_ops = group.gc_queue[:-2]
        group.gc_queue = group.gc_queue[-2:]
    for op_keys in stale_ops:
        for key in op_keys:
            try:
                control.call_oneway("kv_del", ns=ns, key=key)
            except Exception:  # noqa: BLE001
                pass


def _next_tag(group: _GroupState, op: str) -> str:
    with group.lock:
        group.seq += 1
        return f"{op}/{group.seq}"


def allreduce(tensor, op: str = ReduceOp.SUM, group_name: str = "default"):
    group = _groups[group_name]
    arr = np.asarray(tensor)
    tag = _next_tag(group, "allreduce")
    parts = _exchange(group, serialization.pack(arr), tag)
    arrays = [serialization.unpack(parts[r]) for r in sorted(parts)]
    return _REDUCERS[op](arrays)


def allgather(tensor, group_name: str = "default") -> List[np.ndarray]:
    group = _groups[group_name]
    tag = _next_tag(group, "allgather")
    parts = _exchange(group, serialization.pack(np.asarray(tensor)), tag)
    return [serialization.unpack(parts[r]) for r in sorted(parts)]


def reducescatter(tensor, op: str = ReduceOp.SUM, group_name: str = "default"):
    """Reduce across ranks, return this rank's 1/world_size slice (dim 0).

    Chunk-scatter algorithm: each rank publishes ONLY the chunk destined
    for each peer and reads only its own n source chunks — O(tensor)
    bytes moved per rank, vs the round-2 allreduce-then-slice which moved
    the whole tensor to every rank."""
    group = _groups[group_name]
    arr = np.asarray(tensor)
    n = group.world_size
    if arr.shape[0] % n != 0:
        raise ValueError(
            f"dim 0 ({arr.shape[0]}) not divisible by world size {n}"
        )
    chunk = arr.shape[0] // n
    control = _control()
    ns = _ns(group)
    tag = _next_tag(group, "reducescatter")
    for dst in range(n):
        control.call(
            "kv_put", ns=ns,
            key=f"{tag}/{dst}/{group.rank}",
            value=serialization.pack(
                np.ascontiguousarray(arr[dst * chunk:(dst + 1) * chunk])
            ),
            retryable=True,
        )
    got = _await_keys(
        control, ns, [f"{tag}/{group.rank}/{src}" for src in range(n)], 120.0
    )
    parts = []
    for src in range(n):
        val = got.get(f"{tag}/{group.rank}/{src}")
        if val is None:
            raise TimeoutError(
                f"reducescatter on {group.name}: rank {src} missing"
            )
        parts.append(serialization.unpack(val))
    _gc_publish(group, [f"{tag}/{dst}/{group.rank}" for dst in range(n)])
    return _REDUCERS[op](parts)


def broadcast(tensor, src_rank: int = 0, group_name: str = "default"):
    group = _groups[group_name]
    tag = _next_tag(group, "broadcast")
    payload = (
        serialization.pack(np.asarray(tensor)) if group.rank == src_rank else None
    )
    parts = _exchange(group, payload, tag, ranks=[src_rank], gc=False)
    return serialization.unpack(parts[src_rank])


def barrier(group_name: str = "default") -> None:
    group = _groups[group_name]
    tag = _next_tag(group, "barrier")
    _exchange(group, b"1", tag)


def _p2p_tag(group: _GroupState, src: int, dst: int) -> str:
    with group.lock:
        n = group.p2p_counts.get((src, dst), 0) + 1
        group.p2p_counts[(src, dst)] = n
        return f"p2p/{src}/{dst}/{n}"


def send(tensor, dst_rank: int, group_name: str = "default") -> None:
    group = _groups[group_name]
    tag = _p2p_tag(group, group.rank, dst_rank)
    _control().call(
        "kv_put", ns=_ns(group), key=f"{tag}/{group.rank}",
        value=serialization.pack(np.asarray(tensor)), retryable=True,
    )


def recv(src_rank: int, group_name: str = "default", timeout_s: float = 120.0):
    group = _groups[group_name]
    tag = _p2p_tag(group, src_rank, group.rank)
    parts = _exchange(group, None, tag, ranks=[src_rank], timeout_s=timeout_s)
    return serialization.unpack(parts[src_rank])
