"""Host-tensor collectives: p2p ring transport with a control-store KV
fallback (the Gloo role).

Two transports, picked per op:

- **p2p ring** (collective/p2p.py, the default for data-sized payloads):
  ranks rendezvous ONCE per group through a small KV exchange of worker
  host/port — the only head traffic, independent of payload size — then
  move chunked tensor segments directly worker↔worker over the
  multi-segment RPC data plane (reduce-scatter + allgather ring
  phases, pipelined subchunks, optional int8 blockwise quantization for
  allreduce). Peer death surfaces as CollectiveError on every surviving
  rank via ring poison propagation, never a hang.

- **KV** (this module's legacy algorithm): each op gets a (group, seq)
  namespace; every rank publishes its contribution and awaits peers'
  via server-side blocking kv_wait RPCs issued CONCURRENTLY, then
  reduces locally. Retained for tiny payloads (< collective_p2p_min_bytes
  — a ring handshake costs more than one head round trip), for
  processes without a worker runtime, and as the RT_COLLECTIVE_P2P=0
  kill switch.

Routing is by local payload size for allreduce/reducescatter (ranks
must hold same-shape tensors, so the decision is group-consistent) and
send (the receiver dual-waits on both transports). broadcast and
allgather ride p2p whenever the group has it: only the source knows the
broadcast size and allgather sizes may differ per rank, so a
size-dependent choice could diverge across ranks and hang.

Intended for host tensors (rendezvous payloads, metrics, CPU-tier CI,
gradient exchange between hosts); device tensors should use in-graph
mesh collectives instead.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

import numpy as np

from ray_tpu.collective import p2p
from ray_tpu.core.exceptions import CollectiveError  # noqa: F401 — re-export
from ray_tpu.observability import core_metrics, tracing
from ray_tpu.utils import serialization


class ReduceOp:
    SUM = "sum"
    PRODUCT = "product"
    MIN = "min"
    MAX = "max"


_REDUCERS = {
    ReduceOp.SUM: lambda xs: sum(xs[1:], xs[0].copy()),
    ReduceOp.PRODUCT: lambda xs: np.prod(np.stack(xs), axis=0),
    ReduceOp.MIN: lambda xs: np.min(np.stack(xs), axis=0),
    ReduceOp.MAX: lambda xs: np.max(np.stack(xs), axis=0),
}


class _GroupState:
    def __init__(self, group_name: str, world_size: int, rank: int):
        self.name = group_name
        self.world_size = world_size
        self.rank = rank
        self.seq = 0
        # p2p streams get their own per-(src,dst) counters: collective seq
        # numbers only align across ranks when every rank runs every op.
        self.p2p_counts: Dict[tuple, int] = {}
        # my published keys, grouped PER OP, deleted with a 2-op lag
        # (peers of op N have all read its keys once op N+2 starts —
        # bounds control-store memory)
        self.gc_queue: List[List[str]] = []
        self.lock = threading.Lock()


_groups: Dict[str, _GroupState] = {}


def _control():
    from ray_tpu.core import worker as worker_mod

    return worker_mod.global_worker().control


def _ns(group: _GroupState) -> str:
    return f"coll/{group.name}"


def _active_p2p(group: _GroupState) -> Optional["p2p._P2PGroup"]:
    """The group's ring transport, when usable: rendezvoused at init AND
    the kill switch is on (checked per op so a process can flip
    RT_COLLECTIVE_P2P / config.collective_p2p for A/B runs). Flips must
    be applied to EVERY rank of a group, as bench_core's A/B does — a
    one-rank mismatch diverges collective routing until the op deadline
    (recv alone tolerates it: it dual-waits both transports)."""
    if group.world_size < 2 or not p2p.enabled():
        return None
    return p2p.group_for(group.name)


def _observe(op: str, t0: float) -> None:
    if core_metrics.ENABLED:
        core_metrics.collective_op_latency_s.observe(
            time.monotonic() - t0, tags={"op": op}
        )
    if tracing.ENABLED:
        # timeline slice for the op, joining the already-counted byte
        # metrics into the same view as task/request/pipeline slices
        ts = tracing.mono_us(t0)
        tracing.emit(tracing.collective_span(op, ts, tracing.now_us() - ts))


def _count_kv_bytes(op: str, nbytes: int) -> None:
    if core_metrics.ENABLED:
        core_metrics.collective_bytes_sent.inc(
            nbytes, tags={"op": op, "transport": "kv"}
        )


def init_collective_group(
    world_size: int,
    rank: int,
    backend: str = "cpu",
    group_name: str = "default",
) -> None:
    """Register this process as `rank` of a collective group.

    Called by every participating actor/task (parity: collective.py:171).
    With p2p enabled (the default) this also performs the ring
    rendezvous — one small KV record per rank — which doubles as the
    membership barrier; the KV barrier only runs on the fallback path.
    """
    if backend not in ("cpu", "xla"):
        raise ValueError(f"unsupported backend {backend!r}")
    if not 0 <= rank < world_size:
        raise ValueError(f"rank {rank} out of range for world_size {world_size}")
    _groups[group_name] = _GroupState(group_name, world_size, rank)
    if world_size > 1 and p2p.enabled():
        try:
            p2p.setup_group(group_name, world_size, rank)
            return  # rendezvous doubles as the membership barrier
        except Exception:  # noqa: BLE001 — no worker runtime / peers on KV
            p2p.drop_group(group_name)
    # rendezvous barrier so all members see each other before first op
    barrier(group_name)


def destroy_collective_group(group_name: str = "default") -> None:
    """Drop group state and delete its KV namespace (required before a
    group name can be REUSED — stale keys from a prior incarnation would
    otherwise satisfy the new group's rendezvous). The ring incarnation
    token dies with it, so in-flight deliveries from old peers are
    dropped on arrival."""
    group = _groups.pop(group_name, None)
    from ray_tpu.collective import bucketed  # local import — avoids cycle
    bucketed.shutdown_lane(group_name)
    p2p.drop_group(group_name)
    try:
        _control().call_oneway("kv_del_prefix", ns=f"coll/{group_name}", prefix="")
    except Exception:  # noqa: BLE001 — cluster may already be down
        pass


def get_rank(group_name: str = "default") -> int:
    return _groups[group_name].rank


def get_collective_group_size(group_name: str = "default") -> int:
    return _groups[group_name].world_size


def _exchange(group: _GroupState, payload: Optional[bytes], tag: str,
              ranks: Optional[List[int]] = None,
              timeout_s: float = 120.0, gc: bool = True) -> Dict[int, bytes]:
    """Publish payload under (tag, my rank); collect peers' payloads.

    gc=True is only valid for full-participation ops (every rank publishes
    and reads every other): completing op N+1 then proves all peers read
    op N-1's keys, so each rank deletes its own keys with a 2-op lag.
    Broadcast/p2p keys are exempt (the publisher can finish before readers
    arrive) and are reclaimed by destroy_collective_group().
    """
    control = _control()
    ns = _ns(group)
    if payload is not None:
        _count_kv_bytes(tag.split("/", 1)[0], len(payload))
        control.call(
            "kv_put", ns=ns, key=f"{tag}/{group.rank}", value=payload,
            retryable=True,
        )
    if payload is not None and gc:
        _gc_publish(group, [f"{tag}/{group.rank}"])
    want = ranks if ranks is not None else list(range(group.world_size))
    out = _await_keys(
        control, ns, [f"{tag}/{r}" for r in want], timeout_s
    )
    missing = [r for r in want if out.get(f"{tag}/{r}") is None]
    if missing:
        raise TimeoutError(
            f"collective {tag} on group {group.name}: ranks {missing} "
            f"missing after {timeout_s}s"
        )
    return {r: out[f"{tag}/{r}"] for r in want}


def _await_keys(control, ns: str, keys: List[str],
                timeout_s: float) -> Dict[str, Optional[bytes]]:
    """Concurrent server-side blocking kv_waits, with reconnect-and-
    reissue on transient control-store failures (the old poll loop's
    retryable=True resilience, kept under the no-polling design).

    The server caps each kv_wait at dispatch_wait_slice_s (so a barrier
    fan-in can't strand the head's dispatcher pool); a None result
    before OUR deadline means the slice expired, not that the key is
    missing — re-issue until the key lands or time runs out."""
    import time as _time

    from ray_tpu.utils.config import config
    from ray_tpu.utils.rpc import RpcConnectionError, RpcTimeout

    deadline = _time.monotonic() + timeout_s
    out: Dict[str, Optional[bytes]] = {}
    remaining_keys = list(keys)
    while remaining_keys:
        remaining = max(0.5, deadline - _time.monotonic())
        wait_slice = min(remaining, float(config.dispatch_wait_slice_s))
        pending = {
            k: control.call_async("kv_wait", ns=ns, key=k, wait_s=wait_slice)
            for k in remaining_keys
        }
        retry = []
        reconnect = False
        for k, p in pending.items():
            try:
                val = p.wait(wait_slice + 30.0)
            except (RpcConnectionError, RpcTimeout):
                if _time.monotonic() < deadline:
                    retry.append(k)
                    reconnect = True
                else:
                    out[k] = None
                continue
            if val is None and _time.monotonic() < deadline:
                retry.append(k)  # server slice expired — re-issue
            else:
                out[k] = val
        remaining_keys = retry
        if reconnect:
            _time.sleep(0.2)  # let the client reconnect
    return out


def _gc_publish(group: _GroupState, keys: List[str]) -> None:
    """Record this op's published keys; delete the keys of ops at least
    2 behind (every peer provably read them by then)."""
    control = _control()
    ns = _ns(group)
    with group.lock:
        group.gc_queue.append(keys)
        stale_ops = group.gc_queue[:-2]
        group.gc_queue = group.gc_queue[-2:]
    for op_keys in stale_ops:
        for key in op_keys:
            try:
                control.call_oneway("kv_del", ns=ns, key=key)
            except Exception:  # noqa: BLE001
                pass


def _next_tag(group: _GroupState, op: str) -> str:
    with group.lock:
        group.seq += 1
        return f"{op}/{group.seq}"


def allreduce(tensor, op: str = ReduceOp.SUM, group_name: str = "default",
              quant: Optional[str] = None,
              timeout_s: Optional[float] = None):
    """Allreduce across the group. quant="int8" turns on blockwise
    quantized wire payloads (p2p transport, ReduceOp.SUM over floats
    only — ~4× fewer wire bytes at a small, bounded numerics delta);
    payloads that route to the KV fallback run exact regardless."""
    group = _groups[group_name]
    arr = np.ascontiguousarray(np.asarray(tensor))
    t0 = time.monotonic()
    tag = _next_tag(group, "allreduce")
    pg = _active_p2p(group)
    if pg is not None and arr.nbytes >= p2p.min_bytes():
        out = p2p.ring_allreduce(pg, arr, op, tag, quant=quant,
                                 timeout_s=timeout_s)
    else:
        parts = _exchange(group, serialization.pack(arr), tag,  # inband: ok — KV fallback stores contiguous blobs
                          timeout_s=timeout_s or 120.0)
        arrays = [serialization.unpack(parts[r]) for r in sorted(parts)]
        out = _REDUCERS[op](arrays)
    _observe("allreduce", t0)
    return out


def allgather(tensor, group_name: str = "default",
              timeout_s: Optional[float] = None) -> List[np.ndarray]:
    group = _groups[group_name]
    t0 = time.monotonic()
    tag = _next_tag(group, "allgather")
    pg = _active_p2p(group)
    if pg is not None:
        # always p2p when the ring exists: per-rank sizes may differ, so
        # a size-dependent transport choice could diverge across ranks
        out = p2p.ring_allgather(pg, np.asarray(tensor), tag,
                                 timeout_s=timeout_s)
    else:
        parts = _exchange(group, serialization.pack(np.asarray(tensor)),  # inband: ok — KV fallback
                          tag, timeout_s=timeout_s or 120.0)
        out = [serialization.unpack(parts[r]) for r in sorted(parts)]
    _observe("allgather", t0)
    return out


def reducescatter(tensor, op: str = ReduceOp.SUM,
                  group_name: str = "default",
                  timeout_s: Optional[float] = None):
    """Reduce across ranks, return this rank's 1/world_size slice (dim 0).

    p2p: ring reduce-scatter (O(tensor/world) wire bytes per step, no
    head traffic). KV fallback: chunk-scatter — each rank publishes ONLY
    the chunk destined for each peer and reads only its own n source
    chunks."""
    group = _groups[group_name]
    arr = np.asarray(tensor)
    t0 = time.monotonic()
    tag = _next_tag(group, "reducescatter")
    pg = _active_p2p(group)
    if pg is not None and arr.nbytes >= p2p.min_bytes():
        out = p2p.ring_reducescatter(pg, arr, op, tag, timeout_s=timeout_s)
        _observe("reducescatter", t0)
        return out
    n = group.world_size
    if arr.shape[0] % n != 0:
        raise ValueError(
            f"dim 0 ({arr.shape[0]}) not divisible by world size {n}"
        )
    chunk = arr.shape[0] // n
    control = _control()
    ns = _ns(group)
    for dst in range(n):
        payload = serialization.pack(
            np.ascontiguousarray(arr[dst * chunk:(dst + 1) * chunk])
        )
        _count_kv_bytes("reducescatter", len(payload))
        control.call(  # inband: ok — KV fallback stores contiguous blobs
            "kv_put", ns=ns,
            key=f"{tag}/{dst}/{group.rank}",
            value=payload,
            retryable=True,
        )
    got = _await_keys(
        control, ns, [f"{tag}/{group.rank}/{src}" for src in range(n)],
        timeout_s or 120.0,
    )
    parts = []
    for src in range(n):
        val = got.get(f"{tag}/{group.rank}/{src}")
        if val is None:
            raise TimeoutError(
                f"reducescatter on {group.name}: rank {src} missing"
            )
        parts.append(serialization.unpack(val))
    _gc_publish(group, [f"{tag}/{dst}/{group.rank}" for dst in range(n)])
    out = _REDUCERS[op](parts)
    _observe("reducescatter", t0)
    return out


def broadcast(tensor, src_rank: int = 0, group_name: str = "default",
              timeout_s: Optional[float] = None):
    group = _groups[group_name]
    t0 = time.monotonic()
    tag = _next_tag(group, "broadcast")
    pg = _active_p2p(group)
    if pg is not None:
        # always p2p when the ring exists: only the source knows the
        # payload size, so a size-dependent choice could diverge
        arr = np.asarray(tensor) if group.rank == src_rank else None
        out = p2p.ring_broadcast(pg, arr, src_rank, tag,
                                 timeout_s=timeout_s)
    else:
        payload = (
            serialization.pack(np.asarray(tensor))
            if group.rank == src_rank else None
        )
        parts = _exchange(group, payload, tag, ranks=[src_rank], gc=False,
                          timeout_s=timeout_s or 120.0)
        out = serialization.unpack(parts[src_rank])
    _observe("broadcast", t0)
    return out


def barrier(group_name: str = "default") -> None:
    group = _groups[group_name]
    tag = _next_tag(group, "barrier")
    _exchange(group, b"1", tag)


def _p2p_tag(group: _GroupState, src: int, dst: int) -> str:
    with group.lock:
        n = group.p2p_counts.get((src, dst), 0) + 1
        group.p2p_counts[(src, dst)] = n
        return f"p2p/{src}/{dst}/{n}"


def send(tensor, dst_rank: int, group_name: str = "default",
         timeout_s: Optional[float] = None) -> None:
    """Point-to-point send. Payloads ≥ collective_p2p_min_bytes ride the
    direct worker↔worker transport; smaller ones ride KV (recv waits on
    both, so the split is invisible to the receiver)."""
    group = _groups[group_name]
    arr = np.ascontiguousarray(np.asarray(tensor))
    t0 = time.monotonic()
    tag = _p2p_tag(group, group.rank, dst_rank)
    pg = _active_p2p(group)
    if pg is not None and arr.nbytes >= p2p.min_bytes():
        p2p.p2p_send(pg, dst_rank, tag, arr, timeout_s=timeout_s)
    else:
        payload = serialization.pack(arr)
        _count_kv_bytes("send", len(payload))
        _control().call(  # inband: ok — KV fallback stores one contiguous blob
            "kv_put", ns=_ns(group), key=f"{tag}/{group.rank}",
            value=payload, retryable=True,
        )
    _observe("send", t0)


def recv(src_rank: int, group_name: str = "default", timeout_s: float = 120.0):
    group = _groups[group_name]
    t0 = time.monotonic()
    tag = _p2p_tag(group, src_rank, group.rank)
    # dual-wait whenever ring state EXISTS, even with the local p2p flag
    # off: the SENDER's flag decides where the payload goes, and a
    # receiver that stopped watching its mailbox after a local-only flag
    # flip would strand a p2p-delivered tensor until timeout
    pg = p2p.group_for(group.name) if group.world_size > 1 else None
    if pg is None:
        parts = _exchange(group, None, tag, ranks=[src_rank],
                          timeout_s=timeout_s)
        out = serialization.unpack(parts[src_rank])
    else:
        out = _recv_either(group, pg, tag, src_rank, timeout_s)
    _observe("recv", t0)
    return out


def _recv_either(group: _GroupState, pg, tag: str, src_rank: int,
                 timeout_s: float):
    """The SENDER picks the transport by payload size, so the receiver
    waits on BOTH: the p2p mailbox (short bounded waits) and a
    server-side blocking kv_wait (issued async, reissued if it expires
    empty or the connection hiccups)."""
    control = _control()
    ns = _ns(group)
    key = f"{tag}/{src_rank}"
    deadline = time.monotonic() + timeout_s
    pending = None
    while True:
        got, payload = p2p.try_recv(pg, tag, wait_s=0.05)
        if got:
            return np.asarray(payload)
        if pending is None:
            try:
                # short server-side slices, reissued while time remains:
                # a payload that arrives via p2p abandons the kv leg, and
                # an abandoned full-deadline kv_wait would strand a head
                # dispatcher thread per recv for up to the whole timeout
                pending = control.call_async(
                    "kv_wait", ns=ns, key=key,
                    wait_s=min(2.0, max(0.5, deadline - time.monotonic())),
                )
            except Exception:  # noqa: BLE001 — reconnect next loop
                pending = None
        elif pending.event.is_set():
            try:
                val = pending.wait(0)
            except Exception:  # noqa: BLE001 — conn hiccup: reissue
                val = None
            pending = None
            if val is not None:
                return serialization.unpack(val)
        if time.monotonic() >= deadline:
            raise TimeoutError(
                f"recv from rank {src_rank} on group {group.name}: "
                f"nothing after {timeout_s}s"
            )
