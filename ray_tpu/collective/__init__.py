"""Collective communication library for actors.

Parity: ray.util.collective (reference python/ray/util/collective/
collective.py — init_collective_group :171, create_collective_group :211,
allreduce/reduce/broadcast/allgather/reducescatter/send/recv :328-724;
backends NCCL/GLOO types.py:34-48).

TPU-first backend mapping (SURVEY.md §2.4 "Collective backend"):
  - device collectives are IN-GRAPH XLA ops over a mesh — the framework's
    main compute path never calls this library on device tensors;
  - "cpu" backend here fills the Gloo role: host-tensor collectives
    between actors, rendezvoused and exchanged through the control store
    KV (the reference rendezvouses NCCLUniqueID through a named store
    actor the same way, nccl_collective_group.py:29-60);
  - "xla" groups bootstrap jax.distributed for multi-host device meshes:
    declare_xla_group/get_xla_coordinator hand out the coordinator
    address through the control store KV so every member can call
    jax.distributed.initialize and then build a global mesh.
"""

from ray_tpu.collective.collective import (
    CollectiveError,
    ReduceOp,
    allgather,
    allreduce,
    barrier,
    broadcast,
    destroy_collective_group,
    get_rank,
    get_collective_group_size,
    init_collective_group,
    recv,
    reducescatter,
    send,
)
from ray_tpu.collective.bucketed import GradSync, allreduce_async, grad_sync
from ray_tpu.collective.xla_group import get_xla_coordinator, xla_coordinator_env

__all__ = [
    "CollectiveError",
    "GradSync",
    "ReduceOp",
    "allgather",
    "allreduce",
    "allreduce_async",
    "barrier",
    "broadcast",
    "destroy_collective_group",
    "get_collective_group_size",
    "get_rank",
    "get_xla_coordinator",
    "grad_sync",
    "init_collective_group",
    "recv",
    "reducescatter",
    "send",
    "xla_coordinator_env",
]
