"""Overlapped bucketed gradient allreduce + hierarchical two-level
collectives (the DP training loop's ``grad_sync``).

The per-leaf DP pattern — full backward, then one blocking allreduce per
pytree leaf — serializes compute and comm and pays per-op bookkeeping
for every tiny bias vector (the MLPerf TPU-pod study, arxiv 1909.09756,
is the scale argument). This module replaces it with:

- **Bucketing**: the gradient pytree is flattened and packed into
  per-dtype byte buckets of ``RT_COLLECTIVE_BUCKET_BYTES`` (4 MiB
  default) in REVERSE leaf order (backward produces output-side grads
  first, so with incremental ``push()`` the last layers ship earliest).
  Tiny leaves (< collective_p2p_min_bytes) coalesce into shared buckets
  instead of each paying its own KV round trip; a bucket that still
  lands under the p2p floor rides the KV fallback as ONE exchange.

- **Overlap**: each closed bucket is submitted to a background comm
  lane (one daemon thread per group, FIFO — every rank processes
  buckets in the same order) whose ring allreduce rides the existing
  p2p.send_async/reap machinery. The caller keeps producing bucket i+1
  (next microbatch, next pipeline stage) while bucket i is on the wire,
  and only blocks in ``join()`` at optimizer apply. The comm-hidden
  fraction — bucket comm spans joined against the window before join()
  — lands in rt_collective_overlap_hidden_frac.

- **Hierarchical two-level mode** (EQuARX-style topology, arxiv
  2506.17615): when the group spans >1 host, each bucket reduces
  intra-host to a designated leader, the ring runs over leaders ONLY,
  and leaders broadcast back — bytes crossing hosts drop from
  O(ranks·bucket) to O(hosts·bucket). Host identity comes from the p2p
  rendezvous record (RT_COLLECTIVE_HOST_ID models multi-host placement
  on one box for tests/bench).

- **Per-bucket quant="int8"**: float buckets reuse the blockwise codec
  (p2p._quant_int8) on their ring phase; non-float buckets and the KV
  fallback stay exact. The PR 7 contract holds per bucket: every rank
  adopts the identical reduced tensor.

Failure semantics are unchanged: a dead rank poisons the ring, every
in-flight and queued bucket errors, and ``join()`` raises ONE
CollectiveError — never a hang. ``RT_COLLECTIVE_BUCKETED=0`` restores
the per-leaf blocking path behind the same ``grad_sync`` API.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ray_tpu.collective import collective as coll_mod
from ray_tpu.collective import p2p
from ray_tpu.core.exceptions import CollectiveError
from ray_tpu.observability import core_metrics, tracing
from ray_tpu.utils import serialization
from ray_tpu.utils.config import config

_LEAF = "leaf"


def bucket_bytes() -> int:
    return int(config.collective_bucket_bytes)


def enabled() -> bool:
    return bool(config.collective_bucketed)


# ---------------------------------------------------------------------------
# pytree flatten/unflatten (dict / list / tuple containers)
# ---------------------------------------------------------------------------


def _flatten(tree) -> Tuple[List[Any], Any]:
    """Deterministic flatten over dict (sorted keys) / list / tuple
    nesting — the same traversal on every rank yields the same leaf
    order, which the bucket schedule depends on."""
    leaves: List[Any] = []

    def rec(node):
        if isinstance(node, dict):
            return ("dict", [(k, rec(node[k])) for k in sorted(node)])
        if isinstance(node, (list, tuple)):
            kind = "list" if isinstance(node, list) else "tuple"
            return (kind, [rec(v) for v in node])
        leaves.append(node)
        return (_LEAF, len(leaves) - 1)

    spec = rec(tree)
    return leaves, spec


def _unflatten(spec, leaves: List[Any]):
    kind, payload = spec
    if kind == _LEAF:
        return leaves[payload]
    if kind == "dict":
        return {k: _unflatten(s, leaves) for k, s in payload}
    vals = [_unflatten(s, leaves) for s in payload]
    return vals if kind == "list" else tuple(vals)


# ---------------------------------------------------------------------------
# bucket scheduler
# ---------------------------------------------------------------------------


class _Bucket:
    """One wire unit: same-dtype leaf segments, concatenated 1-D."""

    __slots__ = ("dtype", "parts", "nbytes")

    def __init__(self, dtype: np.dtype):
        self.dtype = dtype
        self.parts: List[Tuple[int, np.ndarray]] = []  # (slot id, flat leaf)
        self.nbytes = 0

    def concat(self) -> np.ndarray:
        if len(self.parts) == 1:
            return self.parts[0][1]
        return np.concatenate([flat for _, flat in self.parts])


class _Packer:
    """Greedy reverse-order packer: leaves register slots in original
    order (for unflatten) but fill buckets back-to-front, one open
    bucket per dtype; a bucket closes the moment it reaches the byte
    limit. A leaf never splits across buckets, so an oversize leaf gets
    a bucket to itself."""

    def __init__(self, limit: int):
        self.limit = max(1, int(limit))
        self.slots: List[Tuple[tuple, np.dtype]] = []  # (shape, dtype)
        self._open: Dict[str, _Bucket] = {}

    def add_leaves(self, arrs: List[np.ndarray]) -> List[_Bucket]:
        base = len(self.slots)
        flats = []
        for a in arrs:
            self.slots.append((a.shape, a.dtype))
            flats.append(np.ascontiguousarray(a).reshape(-1))
        closed: List[_Bucket] = []
        for i in range(len(flats) - 1, -1, -1):
            flat = flats[i]
            key = flat.dtype.str
            b = self._open.get(key)
            if b is None:
                b = self._open[key] = _Bucket(flat.dtype)
            b.parts.append((base + i, flat))
            b.nbytes += flat.nbytes
            if b.nbytes >= self.limit:
                closed.append(b)
                del self._open[key]
        return closed

    def flush(self) -> List[_Bucket]:
        out = [b for b in self._open.values() if b.parts]
        self._open.clear()
        return out


def pack_buckets(leaves, limit: Optional[int] = None):
    """Pack a flat leaf list into buckets (tests use this directly for
    the boundary property: every leaf in exactly one bucket, bit-exact
    round trip). Returns (buckets, slots)."""
    packer = _Packer(limit or bucket_bytes())
    closed = packer.add_leaves([np.asarray(x) for x in leaves])
    return closed + packer.flush(), packer.slots


# ---------------------------------------------------------------------------
# hierarchical two-level topology
# ---------------------------------------------------------------------------


def _resolve_two_level(pg, hierarchy: Optional[str]):
    """(my leader, my host's ranks, all leaders) when the two-level path
    applies, else None (flat ring). Peers are rank-ordered identically
    on every member, so the derived topology is group-consistent."""
    if hierarchy == "flat":
        return None
    if hierarchy is None and not config.collective_hierarchical:
        return None
    hosts: Dict[str, List[int]] = {}
    for r, peer in enumerate(pg.peers):
        hosts.setdefault(peer[2], []).append(r)
    if len(hosts) < 2 or len(hosts) >= pg.world_size:
        return None  # one host, or one rank per host: two-level = flat
    members = hosts[pg.peers[pg.rank][2]]
    leaders = sorted(ranks[0] for ranks in hosts.values())
    return members[0], members, leaders


def hier_allreduce(pg, arr: np.ndarray, op: str, tag: str, topo,
                   quant: Optional[str] = None,
                   timeout_s: Optional[float] = None) -> np.ndarray:
    """Two-level allreduce: intra-host reduce to the leader (loopback,
    never counted as inter-host bytes), ring allreduce over leaders
    only (the ONLY phase that crosses hosts — quant applies here), then
    intra-host broadcast back. All ranks return the identical tensor."""
    leader, members, leaders = topo
    deadline = p2p._deadline(timeout_s)
    shape, dtype = arr.shape, arr.dtype
    flat = np.ascontiguousarray(arr).reshape(-1)
    if pg.rank != leader:
        p2p.send_now(pg, leader, f"{tag}/up/{pg.rank}", flat, deadline,
                     op="allreduce")
        out = np.asarray(p2p.recv(pg, f"{tag}/dn/{pg.rank}", deadline))
        return out.astype(dtype, copy=False).reshape(shape)
    acc = flat.copy()
    red = p2p._INPLACE_REDUCERS[op]
    for r in members:
        if r == leader:
            continue
        red(acc, np.asarray(p2p.recv(pg, f"{tag}/up/{r}", deadline)))
    if len(leaders) > 1:
        acc = p2p.ring_allreduce(pg, acc, op, f"{tag}/x", quant=quant,
                                 timeout_s=timeout_s, ring=leaders)
    handles = [
        p2p.send_async(pg, r, f"{tag}/dn/{r}", acc, op="allreduce")
        for r in members if r != leader
    ]
    if handles:
        p2p.reap(pg, handles, deadline)
    return acc.astype(dtype, copy=False).reshape(shape)


# ---------------------------------------------------------------------------
# comm lane (one background thread per group, FIFO bucket order)
# ---------------------------------------------------------------------------


class _Lane:
    def __init__(self, group_name: str):
        self.group_name = group_name
        self._q: deque = deque()
        self._cv = threading.Condition()
        self._stop = False
        self.thread = threading.Thread(
            target=self._run, name=f"rt-coll-lane-{group_name}", daemon=True
        )
        self.thread.start()

    def submit(self, handle: "_BucketHandle", fn) -> None:
        with self._cv:
            if self._stop:
                handle.error = CollectiveError(
                    f"collective group {self.group_name!r} destroyed"
                )
                handle.event.set()
                return
            self._q.append((handle, fn))
            self._cv.notify()

    def _run(self) -> None:
        while True:
            with self._cv:
                while not self._q and not self._stop:
                    self._cv.wait(0.5)
                if not self._q:
                    return  # stopped and drained
                _, fn = self._q.popleft()
            fn()

    def shutdown(self, join_timeout_s: float = 5.0) -> None:
        with self._cv:
            self._stop = True
            drained = list(self._q)
            self._q.clear()
            self._cv.notify_all()
        for handle, _ in drained:
            handle.error = CollectiveError(
                f"collective group {self.group_name!r} destroyed with "
                f"bucket {handle.tag} still queued"
            )
            handle.event.set()
        self.thread.join(join_timeout_s)


_lanes: Dict[str, _Lane] = {}
_lanes_lock = threading.Lock()


def _lane_for(group_name: str) -> _Lane:
    with _lanes_lock:
        lane = _lanes.get(group_name)
        if lane is None or not lane.thread.is_alive():
            lane = _lanes[group_name] = _Lane(group_name)
        return lane


def shutdown_lane(group_name: str) -> None:
    """Stop and drain the group's comm lane (destroy_collective_group
    calls this — queued buckets error, the thread exits; nothing
    leaks)."""
    with _lanes_lock:
        lane = _lanes.pop(group_name, None)
    if lane is not None:
        lane.shutdown()


def live_lane_threads() -> int:
    """Alive comm-lane threads in this process (leak tests)."""
    return sum(
        1 for t in threading.enumerate()
        if t.name.startswith("rt-coll-lane-") and t.is_alive()
    )


# ---------------------------------------------------------------------------
# grad_sync
# ---------------------------------------------------------------------------


class _BucketHandle:
    __slots__ = ("arr", "tag", "parts", "nbytes", "event", "result",
                 "error", "t_start", "t_end", "transport")

    def __init__(self, arr: np.ndarray, tag: str, parts):
        self.arr = arr
        self.tag = tag
        self.parts = parts
        self.nbytes = arr.nbytes
        self.event = threading.Event()
        self.result: Optional[np.ndarray] = None
        self.error: Optional[BaseException] = None
        self.t_start: Optional[float] = None
        self.t_end: Optional[float] = None
        self.transport = ""


class GradSync:
    """Handle for one overlapped gradient sync.

    ``push(grads)`` packs a pytree's leaves into buckets and launches
    every bucket that closed; call it once per microbatch/stage (or let
    ``grad_sync(grads)`` do a single push). ``join()`` — at optimizer
    apply — flushes the last partial buckets, blocks until every bucket
    reduced, and returns the reduced pytree (a list of pytrees after
    multiple pushes). ``stats`` afterwards holds buckets/bytes/comm_s/
    hidden_frac for the sync."""

    def __init__(self, group_name: Optional[str] = "default", *,
                 op: str = "sum", average: bool = True,
                 quant: Optional[str] = None,
                 bucket_bytes: Optional[int] = None,
                 hierarchy: Optional[str] = None,
                 timeout_s: Optional[float] = None):
        if hierarchy not in (None, "flat", "two_level"):
            raise ValueError(f"unknown hierarchy mode {hierarchy!r}")
        self._group = (
            coll_mod._groups[group_name] if group_name is not None else None
        )
        self._world = self._group.world_size if self._group else 1
        self._op = op
        self._average = average
        self._quant = quant
        self._hierarchy = hierarchy
        self._timeout_s = timeout_s
        self._use_buckets = (
            self._group is not None and self._world > 1 and enabled()
        )
        self._packer = _Packer(
            bucket_bytes if bucket_bytes is not None
            else int(config.collective_bucket_bytes)
        )
        self._legacy: List[np.ndarray] = []  # kill-switch / local path
        self._pushes: List[Tuple[Any, int, int]] = []  # (spec, base, count)
        self._nleaves = 0
        self._handles: List[_BucketHandle] = []
        self._t0 = time.monotonic()
        self._closed = False
        self._joined = False
        self.stats: Dict[str, Any] = {}

    # -- producing side --

    def push(self, grads) -> "GradSync":
        if self._closed:
            raise RuntimeError("grad_sync handle already closed")
        leaves, spec = _flatten(grads)
        arrs = [np.asarray(x) for x in leaves]
        self._pushes.append((spec, self._nleaves, len(arrs)))
        self._nleaves += len(arrs)
        if not self._use_buckets:
            self._legacy.extend(arrs)
            return self
        for bucket in self._packer.add_leaves(arrs):
            self._submit(bucket)
        return self

    def close(self) -> "GradSync":
        if self._closed:
            return self
        self._closed = True
        if self._use_buckets:
            for bucket in self._packer.flush():
                self._submit(bucket)
        return self

    def _submit(self, bucket: _Bucket) -> None:
        arr = bucket.concat()
        tag = coll_mod._next_tag(self._group, "grad_bucket")
        h = _BucketHandle(arr, tag, bucket.parts)
        self._handles.append(h)
        if arr.size == 0:
            h.result = arr
            h.t_start = h.t_end = time.monotonic()
            h.event.set()
            return
        _lane_for(self._group.name).submit(h, lambda: self._run_bucket(h))

    # -- comm lane side --

    def _run_bucket(self, h: _BucketHandle) -> None:
        g = self._group
        h.t_start = time.monotonic()
        transport = "kv"
        try:
            pg = coll_mod._active_p2p(g)
            quant = (
                self._quant
                if self._quant and h.arr.dtype.kind == "f" else None
            )
            if pg is not None and h.arr.nbytes >= p2p.min_bytes():
                topo = _resolve_two_level(pg, self._hierarchy)
                if topo is not None:
                    transport = "p2p_2l"
                    out = hier_allreduce(pg, h.arr, self._op, h.tag, topo,
                                         quant=quant,
                                         timeout_s=self._timeout_s)
                else:
                    transport = "p2p"
                    out = p2p.ring_allreduce(pg, h.arr, self._op, h.tag,
                                             quant=quant,
                                             timeout_s=self._timeout_s)
            else:
                # coalesced KV fallback: ONE head exchange for the whole
                # bucket, not one per tiny leaf
                payload = serialization.pack(h.arr)
                parts = coll_mod._exchange(
                    g, payload, h.tag, timeout_s=self._timeout_s or 120.0
                )
                arrays = [serialization.unpack(parts[r])
                          for r in sorted(parts)]
                out = coll_mod._REDUCERS[self._op](arrays)
            h.result = out
        except BaseException as e:  # noqa: BLE001 — surfaced at join()
            h.error = e
        finally:
            h.t_end = time.monotonic()
            h.transport = transport
            if core_metrics.ENABLED:
                core_metrics.collective_bucket_bytes.inc(
                    h.nbytes, tags={"transport": transport}
                )
            if tracing.ENABLED:
                ts = tracing.mono_us(h.t_start)
                tracing.emit(tracing.collective_span(
                    "grad_bucket", ts,
                    int((h.t_end - h.t_start) * 1e6),
                    nbytes=h.nbytes, transport=transport, tag=h.tag,
                ))
            h.event.set()

    # -- joining side --

    def wait(self):
        return self.join()

    def join(self):
        """Block until every bucket reduced; return the synced pytree
        (list of pytrees if push() ran more than once). Raises ONE
        CollectiveError if any bucket failed (dead rank, destroyed
        group, deadline)."""
        if self._joined:
            raise RuntimeError("grad_sync handle already joined")
        self.close()
        self._joined = True
        join_start = time.monotonic()
        if not self._use_buckets:
            results = self._join_legacy()
        else:
            results = self._join_buckets(join_start)
        if self._average and self._world > 1:
            results = [r / self._world for r in results]
        trees = [
            _unflatten(spec, results[base:base + count])
            for spec, base, count in self._pushes
        ]
        if not trees:
            return None
        return trees[0] if len(trees) == 1 else trees

    def _join_legacy(self) -> List[np.ndarray]:
        if self._group is None or self._world < 2:
            return list(self._legacy)
        out = []
        for arr in self._legacy:
            quant = (
                self._quant
                if self._quant and arr.dtype.kind == "f" else None
            )
            out.append(coll_mod.allreduce(
                arr, op=self._op, group_name=self._group.name,
                quant=quant, timeout_s=self._timeout_s,
            ))
        return out

    def _join_buckets(self, join_start: float) -> List[np.ndarray]:
        budget = (
            self._timeout_s if self._timeout_s is not None
            else float(config.collective_op_timeout_s)
        )
        failure: Optional[BaseException] = None
        nfailed = 0
        for h in self._handles:
            # lane runs buckets FIFO, so waits complete in order; each
            # bucket's op is internally bounded by the same deadline
            if not h.event.wait(budget + 30.0):
                failure = failure or CollectiveError(
                    f"bucket {h.tag} never completed within {budget}s"
                )
                nfailed += 1
                break
            if h.error is not None:
                failure = failure or h.error
                nfailed += 1
        if failure is not None:
            name = self._group.name if self._group else None
            raise CollectiveError(
                f"grad_sync on group {name!r}: {nfailed} bucket(s) "
                f"failed: {failure}"
            ) from failure
        results: List[Optional[np.ndarray]] = [None] * self._nleaves
        comm = 0.0
        hidden = 0.0
        total_bytes = 0
        for h in self._handles:
            self._unpack(h, results)
            if h.t_start is None or h.t_end is None:
                continue
            comm += max(0.0, h.t_end - h.t_start)
            hidden += max(
                0.0, min(h.t_end, join_start) - min(h.t_start, join_start)
            )
            total_bytes += h.nbytes
        frac = min(1.0, hidden / comm) if comm > 0 else 0.0
        self.stats = {
            "buckets": len(self._handles), "bytes": total_bytes,
            "comm_s": comm, "hidden_frac": frac,
            "join_wait_s": time.monotonic() - join_start,
        }
        if comm > 0:
            if core_metrics.ENABLED:
                core_metrics.collective_overlap_hidden_frac.observe(frac)
            if tracing.ENABLED:
                ts = tracing.mono_us(self._t0)
                tracing.emit(tracing.collective_span(
                    "grad_sync", ts, tracing.now_us() - ts,
                    nbytes=total_bytes, buckets=len(self._handles),
                    hidden_frac=round(frac, 4),
                ))
        return results  # type: ignore[return-value]

    def _unpack(self, h: _BucketHandle, results: List) -> None:
        flat = np.ascontiguousarray(np.asarray(h.result)).reshape(-1)
        off = 0
        for slot_id, part in h.parts:
            n = part.size
            shape, _ = self._packer.slots[slot_id]
            results[slot_id] = flat[off:off + n].reshape(shape)
            off += n


def grad_sync(grads=None, *, group_name: Optional[str] = "default",
              op: str = "sum", average: bool = True,
              quant: Optional[str] = None,
              bucket_bytes: Optional[int] = None,
              hierarchy: Optional[str] = None,
              timeout_s: Optional[float] = None) -> GradSync:
    """Start an overlapped bucketed gradient allreduce. With ``grads``
    it is a one-shot sync (push + close); call ``.join()`` at optimizer
    apply. Without ``grads`` it returns an open handle for incremental
    per-microbatch/per-stage ``push()`` — the overlap driver."""
    h = GradSync(group_name, op=op, average=average, quant=quant,
                 bucket_bytes=bucket_bytes, hierarchy=hierarchy,
                 timeout_s=timeout_s)
    if grads is not None:
        h.push(grads)
        h.close()
    return h


def allreduce_async(tensor, op: str = "sum",
                    group_name: str = "default",
                    quant: Optional[str] = None,
                    hierarchy: Optional[str] = None,
                    timeout_s: Optional[float] = None) -> GradSync:
    """Async allreduce of a single tensor on the group's comm lane;
    ``.wait()`` returns the reduced array."""
    h = GradSync(group_name, op=op, average=False, quant=quant,
                 hierarchy=hierarchy, timeout_s=timeout_s)
    h.push(tensor)
    h.close()
    return h
