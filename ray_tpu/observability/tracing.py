"""Task lifecycle span stamping.

Parity target: the reference's task state transitions
(PENDING_ARGS_AVAIL → SUBMITTED_TO_WORKER → RUNNING → FINISHED) recorded
by task_event_buffer.cc and surfaced through `ray timeline` / the state
API. Here, owner-side lifecycle instants ("submitted", "lease_granted",
"dispatched") and executor-side execution slices share one bounded ring
per worker (CoreWorker._task_events); ``state.timeline()`` joins them by
task_id into Chrome-trace flow events across pids and
``state.task_summary()`` turns them into queue-wait / exec percentiles.

On top of the task lifecycle, the same ring carries:

- request spans (``"type": "request"``) — one per component a serve
  request crosses (proxy / router / replica / engine), all sharing the
  trace id minted at HTTP ingress (``x-rt-trace-id``), joined by
  ``state.timeline()`` into one cross-pid flow and rolled up by
  ``state.request_summary()``;
- pipeline slices (``"type": "pipeline"``) — per-stage fwd / bwd / idle
  slices from the compiled-pipeline exec loop, plus a per-step summary
  carrying the computed bubble fraction;
- collective spans (``"type": "collective"``) — one per host collective
  op, so the bytes counters in core_metrics get a timeline counterpart.

Timestamps: every stamp uses ``now_us()`` — a per-process wall-clock
anchor recorded ONCE at import plus a monotonic delta — so intra-run
ordering (and cross-pid joins within one run) survives NTP steps
mid-run. Different processes may disagree by their boot-time clock skew,
but no process's stamps ever jump backwards.

Hot-path contract: callers guard with the module-level ``ENABLED`` flag
(``if tracing.ENABLED: ...``) so ``RT_TRACE_EVENTS=0`` reduces every
stamp site to one attribute check — no dict building, no time syscall.

Import discipline: only ``ray_tpu.utils.*`` imports allowed here.
"""

from __future__ import annotations

import os
import time
import uuid
from typing import Any, Dict, Optional

from ray_tpu.utils.config import config

ENABLED = bool(config.trace_events)

# Lifecycle event phases (the "type": "lifecycle" events in the ring;
# executor execution slices carry no "type" key — the legacy shape).
SUBMITTED = "submitted"
LEASE_GRANTED = "lease_granted"
DISPATCHED = "dispatched"

# Request span components, in request order. The proxy mints the trace
# id; every downstream component reads it from the request headers under
# TRACE_HEADER and stamps its own span.
TRACE_HEADER = "x-rt-trace-id"
PROXY = "proxy"
ROUTER = "router"
REPLICA = "replica"
ENGINE = "engine"
PREFILL = "prefill"
TRANSFER = "transfer"

# Wall-clock anchor: recorded once per process so every later stamp is
# anchor + monotonic delta. An NTP step after import cannot reorder this
# process's events.
_WALL_ANCHOR = time.time() - time.monotonic()


def now_us() -> int:
    """Microsecond timestamp on the per-process monotonic-anchored
    wall clock."""
    return int((_WALL_ANCHOR + time.monotonic()) * 1e6)


def mono_us(t_monotonic: float) -> int:
    """Convert a ``time.monotonic()`` reading already taken by the
    caller onto the same anchored microsecond clock as ``now_us()``."""
    return int((_WALL_ANCHOR + t_monotonic) * 1e6)


def set_enabled(on: bool) -> None:
    global ENABLED
    ENABLED = bool(on)
    config.set("trace_events", bool(on))


def new_trace_id() -> str:
    """Mint a trace id at HTTP ingress (proxy)."""
    return uuid.uuid4().hex[:16]


def lifecycle_event(
    phase: str,
    task_id: str,
    name: str,
    worker_address: str,
    target: Optional[str] = None,
) -> Dict[str, Any]:
    """Build one lifecycle instant. Callers append it to their worker's
    event ring (CoreWorker._append_task_event)."""
    evt = {
        "type": "lifecycle",
        "phase": phase,
        "task_id": task_id,
        "name": name,
        "ts_us": now_us(),
        "worker": worker_address,
        "pid": os.getpid(),
    }
    if target is not None:
        evt["target"] = target
    return evt


def request_span(
    trace_id: str,
    component: str,
    deployment: str,
    ts_us: int,
    dur_us: int,
    worker_address: str = "",
    **extra: Any,
) -> Dict[str, Any]:
    """Build one request span (proxy/router/replica/engine leg of a
    serve request). ``ts_us`` comes from ``now_us()`` taken at span
    start; extras (e.g. queue_us, status) ride along untyped."""
    evt = {
        "type": "request",
        "trace_id": trace_id,
        "component": component,
        "deployment": deployment,
        "ts_us": ts_us,
        "dur_us": dur_us,
        "worker": worker_address,
        "pid": os.getpid(),
    }
    if extra:
        evt.update(extra)
    return evt


def pipeline_slice(
    stage: int,
    kind: str,
    ts_us: int,
    dur_us: int,
    step: int,
    microbatch: Optional[int] = None,
    worker_address: str = "",
    **extra: Any,
) -> Dict[str, Any]:
    """Build one compiled-pipeline stage slice. ``kind`` is one of
    "fwd" / "bwd" / "idle" / "step" (the per-step summary, which carries
    bubble_frac and schedule in extras)."""
    evt = {
        "type": "pipeline",
        "stage": stage,
        "kind": kind,
        "ts_us": ts_us,
        "dur_us": dur_us,
        "step": step,
        "worker": worker_address,
        "pid": os.getpid(),
    }
    if microbatch is not None:
        evt["microbatch"] = microbatch
    if extra:
        evt.update(extra)
    return evt


def collective_span(
    op: str,
    ts_us: int,
    dur_us: int,
    nbytes: int = 0,
    worker_address: str = "",
    **extra: Any,
) -> Dict[str, Any]:
    """Build one host-collective op span for the timeline (the byte and
    latency *metrics* are core_metrics' job; this is the trace slice)."""
    evt = {
        "type": "collective",
        "op": op,
        "ts_us": ts_us,
        "dur_us": dur_us,
        "nbytes": nbytes,
        "worker": worker_address,
        "pid": os.getpid(),
    }
    if extra:
        evt.update(extra)
    return evt


def emit(evt: Dict[str, Any]) -> None:
    """Append a pre-built event to this process's worker event ring, if
    a worker exists. Import-at-use keeps the utils-only import
    discipline for module import time."""
    from ray_tpu.core import worker as _worker_mod

    w = _worker_mod.global_worker_or_none()
    if w is not None:
        w._append_task_event(evt)
