"""Task lifecycle span stamping.

Parity target: the reference's task state transitions
(PENDING_ARGS_AVAIL → SUBMITTED_TO_WORKER → RUNNING → FINISHED) recorded
by task_event_buffer.cc and surfaced through `ray timeline` / the state
API. Here, owner-side lifecycle instants ("submitted", "lease_granted",
"dispatched") and executor-side execution slices share one bounded ring
per worker (CoreWorker._task_events); ``state.timeline()`` joins them by
task_id into Chrome-trace flow events across pids and
``state.task_summary()`` turns them into queue-wait / exec percentiles.

Hot-path contract: callers guard with the module-level ``ENABLED`` flag
(``if tracing.ENABLED: ...``) so ``RT_TRACE_EVENTS=0`` reduces every
stamp site to one attribute check — no dict building, no time syscall.

Import discipline: only ``ray_tpu.utils.*`` imports allowed here.
"""

from __future__ import annotations

import os
import time
from typing import Any, Dict, Optional

from ray_tpu.utils.config import config

ENABLED = bool(config.trace_events)

# Lifecycle event phases (the "type": "lifecycle" events in the ring;
# executor execution slices carry no "type" key — the legacy shape).
SUBMITTED = "submitted"
LEASE_GRANTED = "lease_granted"
DISPATCHED = "dispatched"


def set_enabled(on: bool) -> None:
    global ENABLED
    ENABLED = bool(on)
    config.set("trace_events", bool(on))


def lifecycle_event(
    phase: str,
    task_id: str,
    name: str,
    worker_address: str,
    target: Optional[str] = None,
) -> Dict[str, Any]:
    """Build one lifecycle instant. Callers append it to their worker's
    event ring (CoreWorker._append_task_event)."""
    evt = {
        "type": "lifecycle",
        "phase": phase,
        "task_id": task_id,
        "name": name,
        "ts_us": int(time.time() * 1e6),
        "worker": worker_address,
        "pid": os.getpid(),
    }
    if target is not None:
        evt["target"] = target
    return evt
