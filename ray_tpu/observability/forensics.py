"""Hang forensics + crash flight recorder.

Three artifacts, one module:

- **Stack dumps** (:func:`all_thread_stacks`) — every live thread's
  frames, served by ``rpc_stack_dump`` on the worker / agent / head and
  fanned out by ``state.stacks()`` / ``rt stacks``. The worker's stall
  watchdog reuses the same walker to stamp a one-shot
  ``{"type": "stall"}`` event (:func:`stall_event`) carrying the stuck
  thread's stack into the task event ring, joinable by task_id in
  ``state.timeline()``. Firing page-severity alerts attach one
  rate-limited capture (:func:`maybe_alert_capture`).
- **Crash files** (:func:`enable_crash_handler`) — ``faulthandler``
  pointed at a per-process ``crash-<role>-<pid>.log`` under the crash
  dir, so SIGSEGV/SIGABRT/SIGBUS in native channel/shm code leaves a
  traceback instead of vanishing. Enabled unconditionally at boot in
  every spawned process (a crash recorder you can switch off records
  nothing).
- **Black box** (:class:`BlackBoxWriter`, thread name ``rt-blackbox``)
  — a compact JSON snapshot (last ~256 ring events, active task ids,
  rss/fds, uptime) rewritten atomically every ``blackbox_interval_s``.
  SIGKILL runs no handler, so the *periodic* rewrite is the artifact
  that survives kill -9; atexit adds a final flush for clean exits.
  ``rt postmortem`` renders the black box of a dead process.

The crash dir is ``RT_CRASH_DIR`` (the node agent points spawned
workers at ``<session dir>/crash``) falling back to
``<temp_dir>/crash``.

Import discipline: only ``ray_tpu.utils.*`` at module import;
``ray_tpu.core.worker`` is imported at use (same pattern as
``tracing.emit``).
"""

from __future__ import annotations

import atexit
import faulthandler
import json
import os
import sys
import threading
import time
import traceback
from typing import Any, Dict, List, Optional

from ray_tpu.utils.config import config
from ray_tpu.utils.metrics import PROCESS_TOKEN

ENABLED = bool(config.observability_enabled)

BLACKBOX_THREAD_NAME = "rt-blackbox"
BLACKBOX_EVENTS = 256


def set_enabled(on: bool) -> None:
    global ENABLED
    ENABLED = bool(on)
    config.set("observability_enabled", bool(on))


def crash_dir() -> str:
    """This process's crash-artifact directory."""
    d = str(config.crash_dir or "")
    return d or os.path.join(str(config.temp_dir), "crash")


def current_role() -> str:
    """Role this process installed the crash handler under ("" before
    install) — lets the node agent re-point an already-installed
    handler at the session crash dir without renaming it."""
    return str(_state["role"])


# --- stack dumps -----------------------------------------------------------

def all_thread_stacks(
    skip_idents: Optional[set] = None,
) -> Dict[str, Any]:
    """Every live thread's stack, leaf-last, as plain dicts."""
    from ray_tpu.observability import tracing

    skip = skip_idents or set()
    names = {t.ident: (t.name, t.daemon) for t in threading.enumerate()}
    threads: List[Dict[str, Any]] = []
    for ident, frame in sys._current_frames().items():
        if ident in skip:
            continue
        name, daemon = names.get(ident, (f"tid-{ident}", True))
        # lookup_lines=False: we only keep file/line/func, and reading
        # source text for every frame of every thread is file I/O the
        # alert path can't afford. walk_stack yields leaf-first, so
        # reverse to keep extract_stack's leaf-last order.
        summary = traceback.StackSummary.extract(
            traceback.walk_stack(frame), lookup_lines=False)
        summary.reverse()
        frames = [
            {"file": fs.filename, "line": fs.lineno, "func": fs.name}
            for fs in summary
        ]
        threads.append({
            "ident": ident,
            "name": name,
            "daemon": daemon,
            "frames": frames,
        })
    threads.sort(key=lambda t: (t["daemon"], t["name"]))
    return {
        "pid": os.getpid(),
        "token": PROCESS_TOKEN,
        "role": _state["role"],
        "ts_us": tracing.now_us(),
        "threads": threads,
    }


def thread_stack(ident: int) -> List[Dict[str, Any]]:
    """One thread's current frames (leaf-last), or [] if it's gone."""
    frame = sys._current_frames().get(ident)
    if frame is None:
        return []
    summary = traceback.StackSummary.extract(
        traceback.walk_stack(frame), lookup_lines=False)
    summary.reverse()
    return [
        {"file": fs.filename, "line": fs.lineno, "func": fs.name}
        for fs in summary
    ]


def format_stack_dump(dump: Dict[str, Any]) -> str:
    lines = [f"pid {dump.get('pid')} — {len(dump.get('threads', []))} "
             f"thread(s)"]
    for t in dump.get("threads", []):
        flag = " daemon" if t.get("daemon") else ""
        lines.append(f"  thread {t.get('name')} (ident "
                     f"{t.get('ident')}{flag}):")
        for fr in t.get("frames", []):
            lines.append(f"    {fr['file']}:{fr['line']} in {fr['func']}")
    return "\n".join(lines)


# --- stall watchdog event --------------------------------------------------

def stall_event(
    task_id: str,
    name: str,
    elapsed_s: float,
    thread_ident: Optional[int],
    worker_address: str,
) -> Dict[str, Any]:
    """Build the one-shot stall event for a task running past
    ``task_stall_dump_s``, carrying the stuck thread's stack."""
    from ray_tpu.observability import tracing

    return {
        "type": "stall",
        "task_id": task_id,
        "name": name,
        "elapsed_s": round(float(elapsed_s), 3),
        "stack": thread_stack(thread_ident) if thread_ident else [],
        "thread": thread_ident,
        "ts_us": tracing.now_us(),
        "worker": worker_address,
        "pid": os.getpid(),
    }


def stamp_stall(
    task_id: str,
    name: str,
    elapsed_s: float,
    thread_ident: Optional[int],
    worker_address: str,
) -> Dict[str, Any]:
    """Stamp one stall event into the event ring and bump the counter.
    Callers guard with ``if forensics.ENABLED:`` (rtlint metric-guards
    contract); the inner tracing/core_metrics flags gate the sinks."""
    from ray_tpu.observability import core_metrics, tracing

    evt = stall_event(task_id, name, elapsed_s, thread_ident,
                      worker_address)
    if tracing.ENABLED:
        tracing.emit(evt)
    if core_metrics.ENABLED:
        core_metrics.task_stalls.inc()
    return evt


# --- alert-triggered capture ----------------------------------------------

_last_alert_capture = [0.0]
_alert_capture_lock = threading.Lock()


def maybe_alert_capture() -> Optional[Dict[str, Any]]:
    """One all-thread capture for a firing page-severity alert, at most
    once per ``alert_capture_min_interval_s``. None when rate-limited."""
    min_interval = float(config.alert_capture_min_interval_s)
    with _alert_capture_lock:
        now = time.monotonic()
        if (_last_alert_capture[0]
                and now - _last_alert_capture[0] < min_interval):
            return None
        _last_alert_capture[0] = now
    return all_thread_stacks()


# --- crash flight recorder -------------------------------------------------

# Keep strong refs: faulthandler writes to the raw fd at crash time, so
# the file object must never be garbage collected.
_crash_file = None
_state: Dict[str, Any] = {
    "role": "",
    "started_ts": time.time(),
    "crash_path": "",
    "blackbox_path": "",
}
_blackbox: Optional["BlackBoxWriter"] = None
_install_lock = threading.Lock()


def enable_crash_handler(role: str) -> str:
    """Point ``faulthandler`` at ``crash-<role>-<pid>.log`` in the crash
    dir and write a header line. Safe to call more than once (the last
    call wins). Returns the crash-file path."""
    global _crash_file
    d = crash_dir()
    os.makedirs(d, exist_ok=True)
    path = os.path.join(d, f"crash-{role}-{os.getpid()}.log")
    f = open(path, "a")
    f.write(json.dumps({
        "role": role,
        "pid": os.getpid(),
        "argv": sys.argv,
        "started_ts": _state["started_ts"],
    }) + "\n")
    f.flush()
    faulthandler.enable(file=f, all_threads=True)
    with _install_lock:
        old, _crash_file = _crash_file, f
    if old is not None:
        try:
            old.close()
        except OSError:
            pass
    _state["role"] = role
    _state["crash_path"] = path
    return path


def _proc_rss_fds() -> Dict[str, Any]:
    rss_kb = None
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    rss_kb = int(line.split()[1])
                    break
    except (OSError, ValueError, IndexError):
        pass
    try:
        open_fds: Optional[int] = len(os.listdir("/proc/self/fd"))
    except OSError:
        open_fds = None
    return {"rss_kb": rss_kb, "open_fds": open_fds}


def blackbox_snapshot() -> Dict[str, Any]:
    """The compact black box: process vitals + the tail of the event
    ring + active task ids."""
    from ray_tpu.core import worker as _worker_mod

    now = time.time()
    snap: Dict[str, Any] = {
        "pid": os.getpid(),
        "role": _state["role"],
        "argv": sys.argv,
        "started_ts": _state["started_ts"],
        "updated_ts": now,
        "uptime_s": round(now - _state["started_ts"], 3),
        "crash_path": _state["crash_path"],
    }
    snap.update(_proc_rss_fds())
    w = _worker_mod.global_worker_or_none()
    if w is not None:
        try:
            snap["active_tasks"] = {
                tid: {
                    "name": info.get("name", ""),
                    "elapsed_s": round(
                        time.monotonic() - info["t0"], 3
                    ) if info.get("t0") else None,
                }
                for tid, info in list(w._running_tasks.items())
            }
            snap["events"] = list(w._task_events)[-BLACKBOX_EVENTS:]
        except (AttributeError, RuntimeError):
            pass
    return snap


def write_blackbox() -> str:
    """Atomically rewrite this process's black box (tmp + rename, so a
    SIGKILL mid-write still leaves the previous snapshot)."""
    d = crash_dir()
    os.makedirs(d, exist_ok=True)
    role = _state["role"] or "proc"
    path = os.path.join(d, f"blackbox-{role}-{os.getpid()}.json")
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(blackbox_snapshot(), f, default=repr)
    os.replace(tmp, path)
    _state["blackbox_path"] = path
    return path


class BlackBoxWriter(threading.Thread):
    """Periodic black-box rewriter — the snapshot that survives
    kill -9."""

    def __init__(self, interval_s: Optional[float] = None):
        super().__init__(name=BLACKBOX_THREAD_NAME, daemon=True)
        self.interval_s = float(
            config.blackbox_interval_s if interval_s is None
            else interval_s
        )
        self._stop = threading.Event()

    def run(self) -> None:
        while not self._stop.is_set():
            try:
                write_blackbox()
            except OSError:
                pass
            self._stop.wait(max(self.interval_s, 0.2))

    def stop(self) -> None:
        self._stop.set()


def _atexit_blackbox() -> None:
    try:
        write_blackbox()
    except OSError:
        pass


def install(role: str) -> str:
    """Boot hook for head/node/worker mains: always enable the crash
    handler (satellite contract — independent of profiler flags);
    start the black-box writer only when observability is on, so
    ``RT_OBSERVABILITY_ENABLED=0`` adds zero threads."""
    global _blackbox
    path = enable_crash_handler(role)
    if ENABLED:
        with _install_lock:
            if _blackbox is None or not _blackbox.is_alive():
                _blackbox = BlackBoxWriter()
                _blackbox.start()
                atexit.register(_atexit_blackbox)
        try:
            write_blackbox()
        except OSError:
            pass
    return path


# --- postmortem scan / render ----------------------------------------------

def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True
    except OSError:
        return False
    return True


def _parse_artifact(fn: str) -> Optional[Dict[str, Any]]:
    """``blackbox-<role>-<pid>.json`` / ``crash-<role>-<pid>.log`` →
    {kind, role, pid}."""
    base = os.path.basename(fn)
    for kind, prefix, suffix in (
        ("blackbox", "blackbox-", ".json"),
        ("crash", "crash-", ".log"),
    ):
        if base.startswith(prefix) and base.endswith(suffix):
            stem = base[len(prefix):-len(suffix)]
            role, _, pid_s = stem.rpartition("-")
            try:
                return {"kind": kind, "role": role, "pid": int(pid_s)}
            except ValueError:
                return None
    return None


def list_crash_reports(
    dirs: Optional[List[str]] = None,
    pid: Optional[int] = None,
) -> List[Dict[str, Any]]:
    """Crash artifacts grouped per (role, pid): blackbox + crash-file
    paths, liveness, and the parsed black box for dead processes."""
    if dirs is None:
        dirs = scan_dirs()
    grouped: Dict[tuple, Dict[str, Any]] = {}
    for d in dirs:
        try:
            entries = sorted(os.listdir(d))
        except OSError:
            continue
        for base in entries:
            meta = _parse_artifact(base)
            if meta is None:
                continue
            if pid is not None and meta["pid"] != pid:
                continue
            key = (meta["role"], meta["pid"])
            rec = grouped.setdefault(key, {
                "role": meta["role"],
                "pid": meta["pid"],
                "alive": _pid_alive(meta["pid"]),
                "blackbox_path": None,
                "crash_path": None,
            })
            rec[meta["kind"] + "_path"] = os.path.join(d, base)
    out = []
    for rec in grouped.values():
        bb = rec.get("blackbox_path")
        if bb:
            try:
                with open(bb) as f:
                    rec["blackbox"] = json.load(f)
            except (OSError, ValueError):
                rec["blackbox"] = None
        out.append(rec)
    out.sort(key=lambda r: (r["role"], r["pid"]))
    return out


def scan_dirs() -> List[str]:
    """Every crash dir reachable from this host's temp_dir: the shared
    default plus each session's crash dir."""
    tmp = str(config.temp_dir)
    dirs = [crash_dir(), os.path.join(tmp, "crash")]
    try:
        for entry in sorted(os.listdir(tmp)):
            if entry.startswith("session_"):
                dirs.append(os.path.join(tmp, entry, "crash"))
    except OSError:
        pass
    seen: set = set()
    out = []
    for d in dirs:
        if d not in seen:
            seen.add(d)
            out.append(d)
    return out


def render_report(rec: Dict[str, Any]) -> str:
    """Human-readable postmortem for one (role, pid) record."""
    lines = [
        f"process {rec.get('role')}/{rec.get('pid')} — "
        + ("ALIVE" if rec.get("alive") else "DEAD")
    ]
    bb = rec.get("blackbox")
    if bb:
        lines.append(
            f"  uptime {bb.get('uptime_s', '?')}s, rss "
            f"{bb.get('rss_kb', '?')} kB, {bb.get('open_fds', '?')} fds, "
            f"last update {time.strftime('%H:%M:%S', time.localtime(bb.get('updated_ts', 0)))}"
        )
        active = bb.get("active_tasks") or {}
        if active:
            lines.append(f"  active tasks at last snapshot ({len(active)}):")
            for tid, info in list(active.items())[:16]:
                lines.append(
                    f"    {tid[:16]} {info.get('name', '')} "
                    f"(running {info.get('elapsed_s', '?')}s)"
                )
        events = bb.get("events") or []
        if events:
            lines.append(f"  last {len(events)} ring event(s), newest last:")
            for evt in events[-12:]:
                etype = evt.get("type") or "exec"
                name = evt.get("name") or evt.get("phase") or \
                    evt.get("component") or evt.get("op") or ""
                tid = (evt.get("task_id") or evt.get("trace_id") or "")[:12]
                lines.append(f"    [{etype}] {name} {tid}".rstrip())
    elif rec.get("blackbox_path"):
        lines.append(f"  black box unreadable: {rec['blackbox_path']}")
    else:
        lines.append("  no black box recorded")
    cp = rec.get("crash_path")
    if cp:
        lines.append(f"  crash file: {cp}")
        try:
            with open(cp) as f:
                tail = f.read().splitlines()
        except OSError:
            tail = []
        # a crash file longer than its JSON header line means
        # faulthandler fired — show the traceback tail
        if len(tail) > 1:
            lines.append("  crash traceback (tail):")
            for ln in tail[-20:]:
                lines.append(f"    {ln}")
    return "\n".join(lines)
