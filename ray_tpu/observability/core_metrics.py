"""Built-in core metrics for the runtime's own hot paths.

Parity target: the reference's ~100 built-in metrics (ray_metric_defs,
exported via OpenCensus → dashboard agent → Prometheus). Instruments
register in the ordinary per-process registry (utils/metrics.py), so
``state.cluster_metrics`` / the dashboard's ``/metrics`` aggregate them
across every process exactly like user metrics — no second pipeline.

Hot-path contract: callers guard every update with the module-level
``ENABLED`` flag (``if core_metrics.ENABLED: core_metrics.X...``), never
a registry lookup, so ``RT_OBSERVABILITY_ENABLED=0`` reduces the whole
subsystem to one attribute check per site.

Import discipline: this module may import only ``ray_tpu.utils.*`` —
it is imported from the RPC substrate itself.
"""

from __future__ import annotations

from ray_tpu.utils.config import config
from ray_tpu.utils.metrics import (
    Counter,
    Gauge,
    Histogram,
    register_reset_hook,
)

ENABLED = bool(config.observability_enabled)

# Latency instruments get sub-millisecond-resolution buckets: the core
# plane's interesting range is 10us..1s (an RPC roundtrip is ~100us).
_LATENCY_BOUNDS = (
    0.00001, 0.00005, 0.0001, 0.0005, 0.001, 0.005,
    0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0,
)
_BATCH_BOUNDS = (1, 2, 4, 8, 16, 32, 64, 128)


def _build() -> dict:
    return {
        # -- scheduler (control_store.py) --
        "sched_queue_depth": Gauge(
            "rt_sched_queue_depth",
            "control-store scheduler queue depth (actors + PGs pending)",
        ),
        "sched_dispatch_latency_s": Histogram(
            "rt_sched_dispatch_latency_s",
            "time a scheduler item waits in the queue before processing",
            boundaries=_LATENCY_BOUNDS,
            tag_keys=("kind",),
        ),
        # -- leases (node_agent.py: agent side; worker.py: owner side) --
        "lease_requests": Counter(
            "rt_lease_requests_total",
            "lease_worker RPCs received by this node agent",
        ),
        "lease_grants": Counter(
            "rt_lease_grants_total",
            "worker leases granted by this node agent",
        ),
        "lease_cache_hits": Counter(
            "rt_lease_cache_hits_total",
            "tasks dispatched onto an already-held (cached) worker lease "
            "without a lease RPC",
        ),
        # per-node gauges carry a node label: the cluster merge keeps the
        # LATEST value per series key, so unlabelled per-node gauges
        # would collapse an N-node cluster to whichever agent answered
        # last
        "worker_pool_size": Gauge(
            "rt_worker_pool_size",
            "workers in this node agent's pool by state",
            tag_keys=("state", "node"),
        ),
        # -- object store (object_store.py) --
        "object_store_used_bytes": Gauge(
            "rt_object_store_used_bytes",
            "bytes of sealed+unsealed segments resident in shm",
            tag_keys=("node",),
        ),
        "object_store_spilled_bytes": Gauge(
            "rt_object_store_spilled_bytes",
            "bytes currently spilled to disk",
            tag_keys=("node",),
        ),
        "object_store_spills": Counter(
            "rt_object_store_spill_total",
            "segments spilled shm -> disk under memory pressure",
        ),
        "object_store_restores": Counter(
            "rt_object_store_restore_total",
            "segments restored disk -> shm for same-host readers",
        ),
        # -- RPC substrate (utils/rpc.py) --
        "rpc_client_latency_s": Histogram(
            "rt_rpc_client_latency_s",
            "client-observed RPC round-trip latency by method family",
            boundaries=_LATENCY_BOUNDS,
            tag_keys=("family",),
        ),
        # -- serve (serve/router.py, serve/batching.py) --
        "serve_router_requests": Counter(
            "rt_serve_router_requests_total",
            "requests routed by deployment",
            tag_keys=("deployment",),
        ),
        "serve_router_queue_wait_s": Histogram(
            "rt_serve_router_queue_wait_s",
            "time a request waits in the router for a replica assignment",
            boundaries=_LATENCY_BOUNDS,
        ),
        "serve_batch_size": Histogram(
            "rt_serve_batch_size",
            "@serve.batch executed batch sizes",
            boundaries=_BATCH_BOUNDS,
        ),
        "serve_batch_wait_s": Histogram(
            "rt_serve_batch_wait_s",
            "time a request waits in a @serve.batch queue before its "
            "batch executes",
            boundaries=_LATENCY_BOUNDS,
        ),
        # -- LLM serving (serve/llm.py, serve/openai/ingress.py) --
        "serve_ttft_s": Histogram(
            "rt_serve_ttft_s",
            "time from request admission to first generated token",
            boundaries=_LATENCY_BOUNDS,
            tag_keys=("deployment",),
        ),
        "serve_inter_token_s": Histogram(
            "rt_serve_inter_token_s",
            "gap between consecutive generated tokens of one request",
            boundaries=_LATENCY_BOUNDS,
            tag_keys=("deployment",),
        ),
        "serve_decode_host_gap_s": Histogram(
            "rt_serve_decode_host_gap_s",
            "host time between consecutive decode dispatches while the "
            "device sat idle with work available; ~0 when the async "
            "decode pipeline keeps a lookahead chunk in flight",
            boundaries=_LATENCY_BOUNDS,
            tag_keys=("deployment",),
        ),
        "serve_tokens_generated": Counter(
            "rt_serve_tokens_generated_total",
            "tokens generated by the LLM engine",
            tag_keys=("deployment",),
        ),
        "serve_kv_slots_occupied": Gauge(
            "rt_serve_kv_slots_occupied",
            "KV-cache slots currently holding an in-flight request, per "
            "engine process",
            tag_keys=("deployment", "node"),
        ),
        "serve_queued_requests": Gauge(
            "rt_serve_queued_requests",
            "requests waiting for a KV slot in this engine process",
            tag_keys=("deployment", "node"),
        ),
        "serve_batch_fill": Histogram(
            "rt_serve_batch_fill",
            "occupied KV slots per continuous-batching decode round",
            boundaries=_BATCH_BOUNDS,
            tag_keys=("deployment",),
        ),
        "serve_prefix_cache_hits": Counter(
            "rt_serve_prefix_cache_hits_total",
            "prompt prefix blocks served from the engine block pool "
            "instead of being re-prefilled",
            tag_keys=("deployment",),
        ),
        "serve_prefix_cache_misses": Counter(
            "rt_serve_prefix_cache_misses_total",
            "prompt prefix blocks that had to be prefilled (not resident)",
            tag_keys=("deployment",),
        ),
        "serve_prefix_cache_evictions": Counter(
            "rt_serve_prefix_cache_evictions_total",
            "prefix blocks LRU-evicted from the engine block pool",
            tag_keys=("deployment",),
        ),
        "serve_prefix_blocks_resident": Gauge(
            "rt_serve_prefix_blocks_resident",
            "prefix KV blocks currently resident in this engine's pool",
            tag_keys=("deployment", "node"),
        ),
        # paged KV pool (serve/prefix_cache.PagedKVPool): one page pool
        # holds generation AND prefix KV; occupied counts pages pinned
        # by live requests or resident as sealed prefix blocks. The
        # paged engine ALSO publishes these numbers under the legacy
        # rt_serve_kv_slots_{occupied,total} names (alias for one
        # release) so the serve_kv_occupancy alert rule and older
        # dashboards keep evaluating.
        "serve_kv_pages_total": Gauge(
            "rt_serve_kv_pages_total",
            "KV page-pool capacity (pages) per engine process",
            tag_keys=("deployment", "node"),
        ),
        "serve_kv_pages_occupied": Gauge(
            "rt_serve_kv_pages_occupied",
            "KV pages pinned by live requests or resident as sealed "
            "prefix blocks, per engine process",
            tag_keys=("deployment", "node"),
        ),
        "serve_kv_pages_prefix_resident": Gauge(
            "rt_serve_kv_pages_prefix_resident",
            "sealed prefix pages resident in this engine's page pool",
            tag_keys=("deployment", "node"),
        ),
        "serve_kv_block_copies": Counter(
            "rt_serve_kv_block_copies_total",
            "KV block copies performed at admission (prefix-pool copy "
            "or KV import write); a paged prefix hit performs ZERO",
            tag_keys=("deployment",),
        ),
        "serve_kv_transfer_bytes": Counter(
            "rt_serve_kv_transfer_bytes_total",
            "KV-cache bytes shipped prefill -> decode over rpc channels",
            tag_keys=("deployment",),
        ),
        "serve_multiplex_loads": Counter(
            "rt_serve_multiplex_loads_total",
            "per-model multiplex loads (cold model pulled into a replica)",
            tag_keys=("model",),
        ),
        "serve_multiplex_evictions": Counter(
            "rt_serve_multiplex_evictions_total",
            "per-model multiplex LRU evictions",
            tag_keys=("model",),
        ),
        # -- compiled pipelines (parallel/pipeline.py) --
        "pipeline_stage_busy_s": Histogram(
            "rt_pipeline_stage_busy_s",
            "per-stage compute time (fwd+bwd) per compiled-pipeline step",
            boundaries=_LATENCY_BOUNDS,
            tag_keys=("stage",),
        ),
        "pipeline_bubble_fraction": Histogram(
            "rt_pipeline_bubble_fraction",
            "per-stage idle/(idle+busy) fraction per compiled-pipeline "
            "step, by schedule",
            boundaries=(0.05, 0.1, 0.2, 0.3, 0.4, 0.5, 0.6, 0.7, 0.8,
                        0.9),
            tag_keys=("stage", "schedule"),
        ),
        # -- channels (core/channels.py) --
        "channel_write_blocks": Counter(
            "rt_channel_write_blocks_total",
            "channel writes that blocked or bounced on a full ring / "
            "mailbox, by transport",
            tag_keys=("transport",),
        ),
        # -- host collectives (collective/collective.py, collective/p2p.py) --
        "collective_bytes_sent": Counter(
            "rt_collective_bytes_sent_total",
            "host-collective payload bytes sent by this process, by op "
            "and transport (p2p ring deliveries vs control-store KV)",
            tag_keys=("op", "transport"),
        ),
        "collective_op_latency_s": Histogram(
            "rt_collective_op_latency_s",
            "end-to-end host collective op latency by op",
            boundaries=_LATENCY_BOUNDS,
            tag_keys=("op",),
        ),
        # -- bucketed grad sync (collective/bucketed.py) --
        "collective_overlap_hidden_frac": Histogram(
            "rt_collective_overlap_hidden_frac",
            "fraction of grad_sync bucket comm time hidden behind caller "
            "compute, from joining bucket spans against the window before "
            "join() (1.0 = fully overlapped)",
            boundaries=(0.05, 0.1, 0.2, 0.3, 0.4, 0.5, 0.6, 0.7, 0.8,
                        0.9),
        ),
        "collective_bucket_bytes": Counter(
            "rt_collective_bucket_bytes_total",
            "gradient bytes shipped through bucketed grad_sync, by "
            "transport (flat ring / two-level hierarchical / KV fallback)",
            tag_keys=("transport",),
        ),
        "collective_inter_bytes": Counter(
            "rt_collective_inter_host_bytes_total",
            "collective payload bytes whose ring delivery crossed a host "
            "boundary (destination host differs from the sender's)",
            tag_keys=("op",),
        ),
        # -- task event buffer (worker.py) --
        "task_events_dropped": Counter(
            "rt_task_events_dropped_total",
            "task lifecycle/execution events evicted from the bounded "
            "per-worker ring buffer",
        ),
        # -- cluster health (core/control_store.py health loop) --
        "cluster_nodes_dead": Gauge(
            "rt_cluster_nodes_dead",
            "nodes currently marked dead by the head's heartbeat health "
            "loop (feeds the node_heartbeat_missed alert rule)",
        ),
        # -- profiler + forensics (observability/profiler.py, forensics.py) --
        "profile_samples": Counter(
            "rt_profile_samples_total",
            "continuous-sampler stack samples by attributed subsystem",
            tag_keys=("subsystem",),
        ),
        "profiler_continuous_hz": Gauge(
            "rt_profiler_hz",
            "continuous sampler rate in this process (0 = off)",
        ),
        "task_stalls": Counter(
            "rt_task_stalls_total",
            "tasks flagged by the stall watchdog (ran past "
            "task_stall_dump_s without finishing)",
        ),
        # total KV capacity next to rt_serve_kv_slots_occupied so the
        # occupancy RATIO is computable by the alert engine without
        # knowing every deployment's max_batch_size
        "serve_kv_slots_total": Gauge(
            "rt_serve_kv_slots_total",
            "KV-cache slot capacity (max_batch_size) per engine process",
            tag_keys=("deployment", "node"),
        ),
        # -- serving control loop (serve/autoscale/) --
        "serve_shed": Counter(
            "rt_serve_shed_total",
            "requests shed by proxy admission control (429/503 + "
            "Retry-After), by deployment and reason",
            tag_keys=("deployment", "reason"),
        ),
        "serve_admission_inflight": Gauge(
            "rt_serve_admission_inflight",
            "requests currently admitted (queued + executing) through "
            "this proxy, per deployment",
            tag_keys=("deployment", "node"),
        ),
        "serve_replicas_running": Gauge(
            "rt_serve_replicas_running",
            "serving replicas currently live per deployment",
            tag_keys=("deployment",),
        ),
        "serve_replicas_target": Gauge(
            "rt_serve_replicas_target",
            "autoscaler target replica count per deployment",
            tag_keys=("deployment",),
        ),
        "serve_replicas_draining": Gauge(
            "rt_serve_replicas_draining",
            "replicas in session-aware drain (out of the routing table, "
            "finishing live streams) per deployment",
            tag_keys=("deployment",),
        ),
        "serve_autoscale_decisions": Counter(
            "rt_serve_autoscale_decisions_total",
            "autoscaler scale decisions by deployment and direction",
            tag_keys=("deployment", "direction"),
        ),
    }


def _reinstall() -> None:
    """(Re)create every instrument and rebind the module attributes.
    Registered as a registry reset hook, so the runtime's
    self-instrumentation survives test resets."""
    for key, instrument in _build().items():
        globals()[key] = instrument


def set_enabled(on: bool) -> None:
    global ENABLED
    ENABLED = bool(on)
    config.set("observability_enabled", bool(on))


_reinstall()
register_reset_hook(_reinstall)
