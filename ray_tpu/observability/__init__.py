"""Runtime self-instrumentation (reference C18: the built-in core
metrics the reference exports through OpenCensus → dashboard-agent →
Prometheus, plus task lifecycle state tracking for `ray timeline` and
the state API).

Subsystems and their kill switches (flags read ONCE into module-level
attributes so a disabled hot path pays a single attribute check):

- ``core_metrics`` — built-in Counter/Gauge/Histogram series wired into
  the scheduler, lease, object-store, RPC, and serve hot paths.
  Disabled with ``RT_OBSERVABILITY_ENABLED=0``.
- ``tracing`` — task lifecycle span stamping (submit / lease-granted /
  dispatched on the owner; start/end execution slices on the executor)
  feeding ``state.timeline()`` flow events and ``state.task_summary()``.
  Disabled with ``RT_TRACE_EVENTS=0``.
- ``history`` — head-side sampler retaining every scraped metric in
  multi-resolution ring buffers (windowed percentiles, ``rt top``
  sparklines, ``state.metrics_history()``). Disabled with
  ``RT_METRICS_SAMPLE_INTERVAL_S=0`` (or observability off).
- ``alerts`` — threshold-for-duration + two-window SLO burn-rate rules
  evaluated over the history store on every sampler tick, surfaced via
  ``state.alerts()`` / ``rt alerts`` / ``/api/alerts``. Disabled with
  ``RT_ALERTS_ENABLED=0`` (or whenever the sampler is off).
- ``profiler`` — sampling profiler over ``sys._current_frames()``:
  on-demand fleet captures (``state.profile()`` / ``rt profile`` →
  folded stacks + flamegraph HTML with per-subsystem attribution) and
  an optional continuous mode (``RT_PROFILER_HZ``, default off)
  feeding ``rt_profile_samples_total{subsystem}``.
- ``forensics`` — hang + crash artifacts: ``rpc_stack_dump`` /
  ``rt stacks``, the worker stall watchdog's ``{"type": "stall"}``
  ring events (``RT_TASK_STALL_DUMP_S``), per-process ``faulthandler``
  crash files and the periodic black box that ``rt postmortem``
  renders after a kill -9.

``history`` and ``alerts`` are NOT imported here: they run only on the
head and are imported by the control store at start, keeping worker
import cost flat. ``profiler`` and ``forensics`` are imported by the
process mains / RPC handlers that wire them in.
"""

from ray_tpu.observability import core_metrics, tracing  # noqa: F401

__all__ = ["core_metrics", "tracing"]
