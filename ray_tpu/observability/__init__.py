"""Runtime self-instrumentation (reference C18: the built-in core
metrics the reference exports through OpenCensus → dashboard-agent →
Prometheus, plus task lifecycle state tracking for `ray timeline` and
the state API).

Subsystems and their kill switches (flags read ONCE into module-level
attributes so a disabled hot path pays a single attribute check):

- ``core_metrics`` — built-in Counter/Gauge/Histogram series wired into
  the scheduler, lease, object-store, RPC, and serve hot paths.
  Disabled with ``RT_OBSERVABILITY_ENABLED=0``.
- ``tracing`` — task lifecycle span stamping (submit / lease-granted /
  dispatched on the owner; start/end execution slices on the executor)
  feeding ``state.timeline()`` flow events and ``state.task_summary()``.
  Disabled with ``RT_TRACE_EVENTS=0``.
- ``history`` — head-side sampler retaining every scraped metric in
  multi-resolution ring buffers (windowed percentiles, ``rt top``
  sparklines, ``state.metrics_history()``). Disabled with
  ``RT_METRICS_SAMPLE_INTERVAL_S=0`` (or observability off).
- ``alerts`` — threshold-for-duration + two-window SLO burn-rate rules
  evaluated over the history store on every sampler tick, surfaced via
  ``state.alerts()`` / ``rt alerts`` / ``/api/alerts``. Disabled with
  ``RT_ALERTS_ENABLED=0`` (or whenever the sampler is off).

``history`` and ``alerts`` are NOT imported here: they run only on the
head and are imported by the control store at start, keeping worker
import cost flat.
"""

from ray_tpu.observability import core_metrics, tracing  # noqa: F401

__all__ = ["core_metrics", "tracing"]
