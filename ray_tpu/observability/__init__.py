"""Runtime self-instrumentation (reference C18: the built-in core
metrics the reference exports through OpenCensus → dashboard-agent →
Prometheus, plus task lifecycle state tracking for `ray timeline` and
the state API).

Two subsystems, two kill switches, both read ONCE into module-level
flags so a disabled hot path pays a single attribute check:

- ``core_metrics`` — built-in Counter/Gauge/Histogram series wired into
  the scheduler, lease, object-store, RPC, and serve hot paths.
  Disabled with ``RT_OBSERVABILITY_ENABLED=0``.
- ``tracing`` — task lifecycle span stamping (submit / lease-granted /
  dispatched on the owner; start/end execution slices on the executor)
  feeding ``state.timeline()`` flow events and ``state.task_summary()``.
  Disabled with ``RT_TRACE_EVENTS=0``.
"""

from ray_tpu.observability import core_metrics, tracing  # noqa: F401

__all__ = ["core_metrics", "tracing"]
