"""Metrics history: head-side time-series ring buffers + sampler.

PR 10 gave every metric an instantaneous scrape; nothing retained a time
series, so "TTFT p95 over the last 60 s" (the SLO question) was
uncomputable — only "p95 since boot". This module keeps a bounded,
multi-resolution in-memory history on the head:

- A sampler thread (``HistorySampler``, started by the control store)
  scrapes ``state.cluster_metrics()`` + ``state.request_summary()``
  every ``metrics_sample_interval_s`` (default 1.0 s;
  ``RT_METRICS_SAMPLE_INTERVAL_S=0`` disables the whole plane).
- Each scraped series lands in fixed-cadence ring buffers at three
  resolutions (defaults, in sample-interval units):
  1×interval × 600 points → 10×interval × 360 → 60×interval × 240 —
  at the 1 s default that is 10 minutes at 1 s, 1 hour at 10 s, and
  4 hours at 1 min. Coarser tiers are folded incrementally at append
  time (no rescan): gauges average, counter deltas sum, histogram
  bucket deltas sum.
- Counters are stored as **reset-aware deltas** (``counter_delta``): a
  restarted replica makes a cumulative counter go backwards, and the
  Prometheus convention — treat a decrease as a reset and count the new
  cumulative value as the delta — keeps rates non-negative without
  silently dropping the post-restart traffic to zero.
- Histograms are stored as **per-window bucket deltas**, so a windowed
  percentile is just "sum the bucket deltas over the window, then
  interpolate" (utils/metrics.hist_quantile).

Memory budget (documented, enforced): per series ≤ 600+360+240 = 1200
points. A scalar point is (ts, value[, extra]) ≈ 100 B → ~120 KiB per
scalar series; a histogram point carries one bucket-delta list (core
latency histograms have 14 buckets) ≈ 300 B → ~360 KiB per histogram
series. The store caps distinct series at ``metrics_history_max_series``
(default 2048, counted per (name, tags) pair; overflow series are
dropped and counted in ``stats()``), bounding the store at roughly
2048 × ~360 KiB ≈ 700 MiB absolute worst case but ~10–40 MiB for a
realistic mix (a serving cluster produces tens of series, not
thousands).

Import discipline: ``ray_tpu.utils.*`` at module level; ``ray_tpu.state``
only inside the sampler loop (import-at-use, like tracing.emit).
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from ray_tpu.utils.metrics import hist_fraction_above, hist_quantile

logger = logging.getLogger(__name__)

# (step multiplier, ring capacity) per tier, in base-interval units.
DEFAULT_TIERS: Tuple[Tuple[int, int], ...] = ((1, 600), (10, 360), (60, 240))


def counter_delta(prev: Optional[float], cur: float) -> float:
    """Reset-aware increase of a cumulative counter between two scrapes
    (Prometheus ``increase`` semantics): normally ``cur - prev``, but a
    decrease means the underlying process restarted and began a fresh
    counter — the observed cumulative value IS the post-reset increase.

    This replaces the old ``max(cur - prev, 0.0)`` clamp in the ``rt
    top`` QPS column, which rendered a silent zero-QPS frame across
    every replica restart."""
    if prev is None or cur < prev:
        return cur
    return cur - prev


def hist_delta(
    prev: Optional[Dict[str, Any]], cur: Dict[str, Any]
) -> Tuple[float, float, List[float]]:
    """Reset-aware (count, sum, buckets) delta between two cumulative
    histogram snapshots. A count decrease marks a reset: the current
    cumulative state is the whole delta."""
    buckets = list(cur.get("buckets") or ())
    if prev is None or cur["count"] < prev["count"]:
        return float(cur["count"]), float(cur["sum"]), buckets
    pb = list(prev.get("buckets") or ())
    if len(pb) != len(buckets):
        # bucket detail appeared/vanished mid-flight (divergent
        # boundaries across workers): restart the delta baseline
        return float(cur["count"]), float(cur["sum"]), buckets
    return (
        float(cur["count"] - prev["count"]),
        float(cur["sum"] - prev["sum"]),
        [c - p for c, p in zip(buckets, pb)],
    )


class _Series:
    """One (metric name, tag values) time series: cumulative baseline
    for delta computation plus per-tier rings and fold accumulators."""

    __slots__ = ("kind", "prev", "rings", "acc")

    def __init__(self, kind: str, tiers: Sequence[Tuple[int, int]]):
        self.kind = kind
        self.prev: Any = None  # last cumulative value (counter/histogram)
        self.rings: List[deque] = [deque(maxlen=cap) for _, cap in tiers]
        # per coarser tier: points accumulated since its last fold
        self.acc: List[List[Tuple]] = [[] for _ in tiers[1:]]


class MetricsHistory:
    """Bounded multi-resolution store for scraped metric snapshots.

    Point shapes per kind (``ts`` = window END, seconds since epoch):
      gauge     ``(ts, value)``          — mean over the window
      counter   ``(ts, delta)``          — reset-aware increase
      histogram ``(ts, count, sum, buckets)`` — per-window deltas
    """

    def __init__(
        self,
        base_step_s: float = 1.0,
        tiers: Sequence[Tuple[int, int]] = DEFAULT_TIERS,
        max_series: int = 2048,
    ):
        self.base_step_s = float(base_step_s)
        self.tiers = tuple((int(m), int(cap)) for m, cap in tiers)
        self.max_series = int(max_series)
        self._lock = threading.Lock()
        self._series: Dict[Tuple[str, Tuple[str, ...]], _Series] = {}
        # name -> {"kind", "tag_keys", "boundaries"} (latest seen)
        self._meta: Dict[str, Dict[str, Any]] = {}
        self._started = time.time()
        self._ticks = 0
        self._dropped_series = 0
        self._scrape_s_total = 0.0
        self._scrape_s = deque(maxlen=128)  # recent per-tick scrape cost

    # -- append path ----------------------------------------------------

    def record(
        self,
        ts: float,
        snapshot: Dict[str, Dict],
        request_summary: Optional[Dict[str, Any]] = None,
        scrape_s: float = 0.0,
    ) -> None:
        """Ingest one merged cluster snapshot (state.cluster_metrics
        shape) plus optional request-summary derived gauges."""
        with self._lock:
            self._ticks += 1
            self._scrape_s_total += scrape_s
            self._scrape_s.append(scrape_s)
            for name, m in snapshot.items():
                self._record_metric_locked(ts, name, m)
            if request_summary:
                for name, m in _derive_request_gauges(request_summary).items():
                    self._record_metric_locked(ts, name, m)

    def _record_metric_locked(self, ts: float, name: str, m: Dict) -> None:
        kind = m["kind"]
        self._meta[name] = {
            "kind": kind,
            "tag_keys": tuple(m.get("tag_keys", ())),
            "boundaries": tuple(m.get("boundaries", ()) or ()),
        }
        for tagvals, value in m["series"].items():
            key = (name, tuple(tagvals))
            s = self._series.get(key)
            if s is None:
                if len(self._series) >= self.max_series:
                    self._dropped_series += 1
                    continue
                s = self._series[key] = _Series(kind, self.tiers)
            if kind == "gauge":
                point: Tuple = (ts, float(value))
            elif kind == "counter":
                point = (ts, counter_delta(s.prev, float(value)))
                s.prev = float(value)
            else:  # histogram
                dcount, dsum, dbuckets = hist_delta(s.prev, value)
                s.prev = {
                    "count": value["count"], "sum": value["sum"],
                    "buckets": list(value.get("buckets") or ()),
                }
                point = (ts, dcount, dsum, dbuckets)
            self._append_locked(s, point)

    def _append_locked(self, s: _Series, point: Tuple) -> None:
        s.rings[0].append(point)
        # incremental fold into coarser tiers: when a tier's accumulator
        # holds ratio-many child points, emit one folded point upward
        child = point
        for i, (mult, _cap) in enumerate(self.tiers[1:]):
            ratio = mult // self.tiers[i][0]
            acc = s.acc[i]
            acc.append(child)
            if len(acc) < ratio:
                return
            child = _fold(s.kind, acc)
            acc.clear()
            s.rings[i + 1].append(child)

    # -- query path -----------------------------------------------------

    def _pick_tier(self, window_s: Optional[float],
                   step_s: Optional[float]) -> int:
        steps = [m * self.base_step_s for m, _ in self.tiers]
        if step_s:
            # coarsest request wins: smallest tier step >= requested
            for i, st in enumerate(steps):
                if st >= step_s * 0.999:
                    return i
            return len(steps) - 1
        if window_s:
            # finest tier whose span covers the window
            for i, ((_m, cap), st) in enumerate(zip(self.tiers, steps)):
                if st * cap >= window_s:
                    return i
            return len(steps) - 1
        return 0

    def query(
        self,
        name: str,
        tags: Optional[Dict[str, str]] = None,
        window_s: Optional[float] = None,
        step_s: Optional[float] = None,
        now: Optional[float] = None,
    ) -> Dict[str, Any]:
        """Aggregated points for one metric: series matching the ``tags``
        subset are summed per timestamp (gauges sum across nodes — queue
        depths and occupancy are cluster totals; counter deltas and
        histogram bucket deltas sum naturally)."""
        now = time.time() if now is None else now
        with self._lock:
            meta = self._meta.get(name)
            if meta is None:
                return {"name": name, "kind": None, "points": [],
                        "step_s": None}
            tier = self._pick_tier(window_s, step_s)
            step = self.tiers[tier][0] * self.base_step_s
            cutoff = (now - window_s) if window_s else None
            kind = meta["kind"]
            agg: Dict[float, List] = {}
            for (mname, tagvals), s in self._series.items():
                if mname != name:
                    continue
                if tags and not _tags_match(meta["tag_keys"], tagvals, tags):
                    continue
                for p in s.rings[tier]:
                    if cutoff is not None and p[0] < cutoff:
                        continue
                    cur = agg.get(p[0])
                    if cur is None:
                        agg[p[0]] = list(p)
                    elif kind == "histogram":
                        cur[1] += p[1]
                        cur[2] += p[2]
                        a, b = cur[3], p[3]
                        if len(b) > len(a):
                            a = a + [0.0] * (len(b) - len(a))
                        cur[3] = [
                            x + (b[i] if i < len(b) else 0.0)
                            for i, x in enumerate(a)
                        ]
                    else:
                        cur[1] += p[1]
            points = []
            for ts in sorted(agg):
                p = agg[ts]
                if kind == "gauge":
                    points.append({"ts": p[0], "value": p[1]})
                elif kind == "counter":
                    points.append({
                        "ts": p[0], "delta": p[1],
                        "rate": p[1] / step if step > 0 else 0.0,
                    })
                else:
                    points.append({
                        "ts": p[0], "count": p[1], "sum": p[2],
                        "buckets": p[3],
                    })
            return {
                "name": name, "kind": kind, "step_s": step,
                "tag_keys": list(meta["tag_keys"]),
                "boundaries": list(meta["boundaries"]),
                "points": points,
            }

    def windowed_hist(
        self,
        name: str,
        window_s: float,
        tags: Optional[Dict[str, str]] = None,
        now: Optional[float] = None,
    ) -> Optional[Dict[str, Any]]:
        """Summed bucket deltas over the trailing window: the windowed
        histogram every percentile/burn-rate computation starts from."""
        q = self.query(name, tags=tags, window_s=window_s, now=now)
        pts = [p for p in q["points"] if "buckets" in p]
        if q["kind"] != "histogram" or not pts:
            return None
        buckets = [0.0] * max(len(p["buckets"]) for p in pts)
        count = 0.0
        total = 0.0
        for p in pts:
            count += p["count"]
            total += p["sum"]
            for i, b in enumerate(p["buckets"]):
                buckets[i] += b
        return {
            "boundaries": q["boundaries"], "buckets": buckets,
            "count": count, "sum": total,
        }

    def quantile(
        self,
        name: str,
        q: float,
        window_s: float,
        tags: Optional[Dict[str, str]] = None,
        now: Optional[float] = None,
    ) -> Optional[float]:
        h = self.windowed_hist(name, window_s, tags=tags, now=now)
        if h is None:
            return None
        return hist_quantile(h["boundaries"], h["buckets"], q)

    def fraction_above(
        self,
        name: str,
        threshold: float,
        window_s: float,
        tags: Optional[Dict[str, str]] = None,
        now: Optional[float] = None,
    ) -> Optional[float]:
        """Windowed share of observations above ``threshold`` — the SLO
        burn-rate numerator ("bad-event fraction over the window")."""
        h = self.windowed_hist(name, window_s, tags=tags, now=now)
        if h is None or not h["count"]:
            return None
        return hist_fraction_above(h["boundaries"], h["buckets"], threshold)

    def windowed_value(
        self,
        name: str,
        window_s: float,
        tags: Optional[Dict[str, str]] = None,
        agg: str = "avg",
        now: Optional[float] = None,
    ) -> Optional[float]:
        """Scalar rollup over the window for threshold rules: gauges
        average or max over points; counters return the windowed rate
        (total delta / window). None when the window holds no samples."""
        qr = self.query(name, tags=tags, window_s=window_s, now=now)
        pts = qr["points"]
        if not pts:
            return None
        if qr["kind"] == "counter":
            return sum(p["delta"] for p in pts) / window_s
        vals = [p.get("value", 0.0) for p in pts]
        return max(vals) if agg == "max" else sum(vals) / len(vals)

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            scrapes = sorted(self._scrape_s)
            return {
                "base_step_s": self.base_step_s,
                "tiers": [
                    {"step_s": m * self.base_step_s, "capacity": cap}
                    for m, cap in self.tiers
                ],
                "series": len(self._series),
                "names": sorted(self._meta),
                "max_series": self.max_series,
                "dropped_series": self._dropped_series,
                "ticks": self._ticks,
                "uptime_s": time.time() - self._started,
                "scrape_s_total": self._scrape_s_total,
                "scrape_s_p50": (
                    scrapes[len(scrapes) // 2] if scrapes else 0.0
                ),
            }


def _fold(kind: str, children: List[Tuple]) -> Tuple:
    """Fold ratio-many fine points into one coarse point (ts = last
    child's window end)."""
    ts = children[-1][0]
    if kind == "gauge":
        return (ts, sum(c[1] for c in children) / len(children))
    if kind == "counter":
        return (ts, sum(c[1] for c in children))
    nb = max(len(c[3]) for c in children)
    buckets = [0.0] * nb
    for c in children:
        for i, b in enumerate(c[3]):
            buckets[i] += b
    return (
        ts,
        sum(c[1] for c in children),
        sum(c[2] for c in children),
        buckets,
    )


def _tags_match(tag_keys: Tuple[str, ...], tagvals: Tuple[str, ...],
                want: Dict[str, str]) -> bool:
    have = dict(zip(tag_keys, tagvals))
    return all(have.get(k) == str(v) for k, v in want.items())


def _derive_request_gauges(reqs: Dict[str, Any]) -> Dict[str, Dict]:
    """Synthesize per-deployment gauges from a request_summary rollup so
    traced end-to-end percentiles get history too (the engine-side TTFT
    histogram measures admission→first-token; these cover the full
    proxy-inclusive path)."""
    out: Dict[str, Dict] = {}
    for dep, entry in (reqs.get("deployments") or {}).items():
        e2e = entry.get("e2e_s") or {}
        for q in ("p50", "p95", "p99"):
            if q not in e2e:
                continue
            m = out.setdefault(f"rt_request_e2e_{q}_s", {
                "kind": "gauge", "tag_keys": ("deployment",), "series": {},
            })
            m["series"][(str(dep),)] = float(e2e[q])
    return out


class HistorySampler:
    """The head-side scrape loop: one daemon thread driving the store
    (and, when alerting is on, the alert engine) every interval. Scrape
    failures during cluster churn/teardown are swallowed — a sampler
    must never take the control store down with it."""

    THREAD_NAME = "cs-obs"

    def __init__(
        self,
        store: MetricsHistory,
        control_address: str,
        stopped: threading.Event,
        interval_s: float,
        on_tick: Optional[Callable[[float], None]] = None,
    ):
        self.store = store
        self.control_address = control_address
        self._stopped = stopped
        self.interval_s = float(interval_s)
        self._on_tick = on_tick
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._loop, name=self.THREAD_NAME, daemon=True
        )
        self._thread.start()

    def _scrape(self) -> Tuple[Dict[str, Dict], Dict[str, Any]]:
        from ray_tpu import state

        mx = state.cluster_metrics(self.control_address)
        reqs = state.request_summary(self.control_address)
        return mx, reqs

    def _loop(self) -> None:
        while not self._stopped.wait(self.interval_s):
            t0 = time.perf_counter()
            try:
                mx, reqs = self._scrape()
            except Exception as e:  # noqa: BLE001 — teardown races
                logger.debug("history scrape failed: %s", e)
                continue
            scrape_s = time.perf_counter() - t0
            now = time.time()
            try:
                self.store.record(
                    now, mx, request_summary=reqs, scrape_s=scrape_s
                )
                if self._on_tick is not None:
                    self._on_tick(now)
            except Exception:  # noqa: BLE001
                logger.exception("history record/evaluate failed")
