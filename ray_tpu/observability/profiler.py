"""Sampling profiler: fleet-wide wall-time attribution with no deps.

Parity target: `ray timeline`'s sibling tooling (py-spy dump / record
wired into the reference dashboard). Here the sampler is in-process —
a thread walking ``sys._current_frames()`` — so it needs no ptrace, no
external binary, and works identically in every spawned process.

Two modes share one sampling core:

- **On-demand capture** — ``rpc_profile(duration_s, hz)`` on the worker
  / node agent / control store runs :func:`capture` and returns folded
  stacks + per-subsystem sample counts; ``state.profile()`` fans the RPC
  across the fleet and :func:`merge` combines replies (deduped by
  per-process token — on a single-node ``init()`` the head, agent and
  driver share one process). ``rt profile`` renders the merge as a
  terminal table, folded-stacks text and a self-contained flamegraph
  HTML (:func:`flamegraph_html` — nested divs, no JS deps).
- **Continuous mode** — ``RT_PROFILER_HZ>0`` starts one low-rate daemon
  sampler per process (:class:`ContinuousSampler`, thread name
  ``rt-prof``) whose per-subsystem shares feed
  ``rt_profile_samples_total{subsystem}`` so history/alerts can trend
  CPU attribution. Default off; ``RT_OBSERVABILITY_ENABLED=0`` means
  zero extra threads (bench_obs pins this).

Attribution walks each stack leaf -> root: the first frame inside a
``ray_tpu`` module maps through :data:`_FRAME_BUCKETS`
(rpc / scheduler / object-store / serve / engine / collective /
pipeline / user / obs); a frame outside both the stdlib and
site-packages is user code (``user``). Stacks that never leave the
stdlib (idle pool threads parked in ``queue.get``) fall back to a
thread-name map, so idle dispatcher threads attribute to their owning
subsystem instead of swamping ``other``.

Import discipline: only ``ray_tpu.utils.*`` imports allowed here.
"""

from __future__ import annotations

import html as _html
import os
import sys
import sysconfig
import threading
import time
from typing import Any, Dict, Iterable, List, Optional, Tuple

from ray_tpu.utils.config import config
from ray_tpu.utils.metrics import PROCESS_TOKEN

ENABLED = bool(config.observability_enabled)

SAMPLER_THREAD_NAME = "rt-prof"

# Leaf-to-root frame attribution: first matching path fragment wins.
# Order matters — specific prefixes before the ray_tpu/ catch-all.
_FRAME_BUCKETS: Tuple[Tuple[str, str], ...] = (
    ("ray_tpu/serve/llm", "engine"),
    ("ray_tpu/serve/models", "engine"),
    ("ray_tpu/serve/kv_transfer", "engine"),
    ("ray_tpu/serve/prefix_cache", "engine"),
    ("ray_tpu/serve/", "serve"),
    ("ray_tpu/collective/", "collective"),
    ("ray_tpu/parallel/", "pipeline"),
    ("ray_tpu/data/", "pipeline"),
    ("ray_tpu/train/", "pipeline"),
    ("ray_tpu/core/object_store", "object-store"),
    ("ray_tpu/core/device_objects", "object-store"),
    ("ray_tpu/core/channels", "object-store"),
    ("ray_tpu/utils/serialization", "object-store"),
    ("ray_tpu/core/control_store", "scheduler"),
    ("ray_tpu/core/scheduling", "scheduler"),
    ("ray_tpu/core/placement", "scheduler"),
    ("ray_tpu/core/node_agent", "scheduler"),
    ("ray_tpu/core/autoscaler", "scheduler"),
    ("ray_tpu/core/ha/", "scheduler"),
    ("ray_tpu/utils/rpc", "rpc"),
    ("ray_tpu/utils/gateway", "rpc"),
    ("ray_tpu/dashboard", "rpc"),
    ("ray_tpu/observability/", "obs"),
    # remaining ray_tpu/core frames are the task-execution machinery
    # (worker.py dispatch around user code) — attribute with the task
    ("ray_tpu/", "user"),
)

# Thread-name fallback for stacks that never leave the stdlib (a pool
# thread parked in queue.get has no ray_tpu frame, but its NAME says
# which subsystem owns it). Order matters: obs names before "cs-".
_THREAD_BUCKETS: Tuple[Tuple[str, str], ...] = (
    (SAMPLER_THREAD_NAME, "obs"),
    ("rt-blackbox", "obs"),
    ("cs-obs", "obs"),
    ("stall-watch", "obs"),
    ("-conn", "rpc"),
    ("-read", "rpc"),
    ("-accept", "rpc"),
    ("-disp", "rpc"),
    ("gw-", "rpc"),
    ("gateway", "rpc"),
    ("dashboard", "rpc"),
    ("cs-", "scheduler"),
    ("agent-", "scheduler"),
    ("autoscaler", "scheduler"),
    ("wal-group", "scheduler"),
    ("task-submit", "scheduler"),
    ("job-pump", "scheduler"),
    ("llm-engine", "engine"),
    ("serve-", "serve"),
    ("router-", "serve"),
    ("rt-rdt", "object-store"),
    ("data-", "pipeline"),
    ("streaming-", "pipeline"),
    ("actor-", "user"),
)

_STDLIB_DIR = sysconfig.get_paths().get("stdlib", "") or "<none>"
_SEP = os.sep


def _norm(path: str) -> str:
    return path.replace("\\", "/")


def classify_frames(filenames: Iterable[str],
                    thread_name: str = "") -> str:
    """Subsystem for one stack given its frame filenames LEAF FIRST."""
    for fn in filenames:
        nfn = _norm(fn)
        idx = nfn.rfind("ray_tpu/")
        if idx >= 0:
            sub = nfn[idx:]
            for fragment, bucket in _FRAME_BUCKETS:
                if sub.startswith(fragment):
                    return bucket
            return "user"
        if fn.startswith(_STDLIB_DIR) or fn.startswith("<"):
            continue  # stdlib / builtin frame: keep walking rootward
        if "site-packages" in nfn or "dist-packages" in nfn:
            continue  # third-party (jax/numpy): attribute to the caller
        return "user"  # a genuine user source file
    name = thread_name or ""
    for fragment, bucket in _THREAD_BUCKETS:
        if name.startswith(fragment) or fragment in name:
            return bucket
    return "other"


def _frame_label(frame) -> str:
    code = frame.f_code
    fn = _norm(code.co_filename)
    idx = fn.rfind("ray_tpu/")
    if idx >= 0:
        mod = fn[idx:-3] if fn.endswith(".py") else fn[idx:]
    else:
        mod = os.path.basename(fn)
        if mod.endswith(".py"):
            mod = mod[:-3]
    return f"{mod}:{code.co_name}"


_MAX_DEPTH = 64


def sample_stacks(
    skip_idents: Optional[Iterable[int]] = None,
) -> List[Tuple[str, str]]:
    """One snapshot of every live thread: ``(folded_stack, subsystem)``
    per thread, stack root-first as ``thread;mod:func;...;leaf``."""
    skip = set(skip_idents or ())
    names = {t.ident: t.name for t in threading.enumerate()}
    out: List[Tuple[str, str]] = []
    for ident, frame in sys._current_frames().items():
        if ident in skip:
            continue
        name = names.get(ident, f"tid-{ident}")
        labels: List[str] = []
        files: List[str] = []  # leaf first
        depth = 0
        while frame is not None and depth < _MAX_DEPTH:
            labels.append(_frame_label(frame))
            files.append(frame.f_code.co_filename)
            frame = frame.f_back
            depth += 1
        labels.reverse()  # root first for folding
        folded = name + ";" + ";".join(labels) if labels else name
        out.append((folded, classify_frames(files, name)))
    return out


def sample_subsystems(
    skip_idents: Optional[Iterable[int]] = None,
) -> Dict[str, int]:
    """Classification-only snapshot: subsystem -> thread count. The
    continuous sampler's per-tick path — skips the folded-label string
    work ``sample_stacks`` pays, and the lazy filename walk stops at
    the first frame that classifies (most stacks resolve in 1-2
    frames), which is what keeps always-on mode under 1% of a core."""
    skip = set(skip_idents or ())
    names = {t.ident: t.name for t in threading.enumerate()}

    def walk(frame):
        depth = 0
        while frame is not None and depth < _MAX_DEPTH:
            yield frame.f_code.co_filename
            frame = frame.f_back
            depth += 1

    out: Dict[str, int] = {}
    for ident, frame in sys._current_frames().items():
        if ident in skip:
            continue
        sub = classify_frames(walk(frame), names.get(ident, ""))
        out[sub] = out.get(sub, 0) + 1
    return out


def capture(duration_s: float = 5.0, hz: float = 99.0) -> Dict[str, Any]:
    """Sample this process for ``duration_s`` at ``hz`` and return the
    aggregated profile. Duration is clamped to
    ``profiler_max_duration_s`` server-side so an RPC caller can never
    pin a dispatcher thread indefinitely."""
    duration_s = min(max(float(duration_s), 0.05),
                     float(config.profiler_max_duration_s))
    hz = min(max(float(hz), 1.0), 1000.0)
    period = 1.0 / hz
    folded: Dict[str, int] = {}
    subsystems: Dict[str, int] = {}
    samples = 0
    ticks = 0
    me = {threading.get_ident()}
    t_start = time.monotonic()
    deadline = t_start + duration_s
    while True:
        t0 = time.monotonic()
        if t0 >= deadline:
            break
        for stack, subsystem in sample_stacks(skip_idents=me):
            folded[stack] = folded.get(stack, 0) + 1
            subsystems[subsystem] = subsystems.get(subsystem, 0) + 1
            samples += 1
        ticks += 1
        rest = min(period - (time.monotonic() - t0),
                   deadline - time.monotonic())
        if rest > 0:
            time.sleep(rest)
    return {
        "pid": os.getpid(),
        "token": PROCESS_TOKEN,
        "duration_s": duration_s,
        "hz": hz,
        "ticks": ticks,
        "samples": samples,
        "folded": folded,
        "subsystems": subsystems,
    }


def merge(profiles: Iterable[Optional[Dict[str, Any]]]) -> Dict[str, Any]:
    """Combine per-process capture replies into one fleet profile,
    deduping by per-process token (single-node init shares one process
    between head, agent and driver — each answers the fan-out)."""
    seen: set = set()
    folded: Dict[str, int] = {}
    subsystems: Dict[str, int] = {}
    pids: List[int] = []
    samples = 0
    ticks = 0
    for p in profiles:
        if not p:
            continue
        tok = p.get("token")
        if tok and tok in seen:
            continue
        if tok:
            seen.add(tok)
        pids.append(int(p.get("pid", -1)))
        samples += int(p.get("samples", 0))
        ticks += int(p.get("ticks", 0))
        for k, v in (p.get("folded") or {}).items():
            folded[k] = folded.get(k, 0) + int(v)
        for k, v in (p.get("subsystems") or {}).items():
            subsystems[k] = subsystems.get(k, 0) + int(v)
    return {
        "processes": len(pids),
        "pids": pids,
        "samples": samples,
        "ticks": ticks,
        "folded": folded,
        "subsystems": subsystems,
    }


def subsystem_rows(
    subsystems: Dict[str, int],
) -> List[Tuple[str, int, float]]:
    """``(subsystem, samples, pct)`` rows sorted by share, descending."""
    total = sum(subsystems.values()) or 1
    return [
        (name, n, 100.0 * n / total)
        for name, n in sorted(
            subsystems.items(), key=lambda kv: (-kv[1], kv[0])
        )
    ]


def subsystem_table(subsystems: Dict[str, int]) -> str:
    rows = subsystem_rows(subsystems)
    if not rows:
        return "(no samples)"
    width = max(len(r[0]) for r in rows)
    lines = [f"{'SUBSYSTEM':<{width}}  {'SAMPLES':>8}  {'%':>6}"]
    for name, n, pct in rows:
        lines.append(f"{name:<{width}}  {n:>8}  {pct:>5.1f}%")
    return "\n".join(lines)


def folded_text(folded: Dict[str, int]) -> str:
    """flamegraph.pl-compatible folded-stacks text (``stack count``)."""
    return "\n".join(
        f"{stack} {count}"
        for stack, count in sorted(
            folded.items(), key=lambda kv: (-kv[1], kv[0])
        )
    )


# --- flamegraph rendering (self-contained HTML, no JS deps) ----------------

_FG_COLORS = (
    "#e4574c", "#e8803f", "#ecae3b", "#c7c23e", "#8fbf4a",
    "#56b063", "#3fa98c", "#3f9cab", "#4a7fc1", "#7a6ccc",
)
_FG_ROW_PX = 17
_FG_MIN_FRAC = 0.0015  # nodes narrower than 0.15% are dropped


def _fg_color(label: str) -> str:
    return _FG_COLORS[hash(label) % len(_FG_COLORS)]


def flamegraph_html(folded: Dict[str, int],
                    title: str = "ray_tpu profile") -> str:
    """Render folded stacks as a static flamegraph: one absolutely
    positioned div per frame, width proportional to sample share, hover
    detail via the title attribute. Opens anywhere, no network."""
    total = sum(folded.values())
    root: Dict[str, Any] = {"n": total, "kids": {}}
    for stack, count in folded.items():
        node = root
        for part in stack.split(";"):
            kid = node["kids"].setdefault(part, {"n": 0, "kids": {}})
            kid["n"] += count
            node = kid
    divs: List[str] = []
    max_depth = 0

    def walk(node: Dict[str, Any], depth: int, x: float) -> None:
        nonlocal max_depth
        for label, kid in sorted(
            node["kids"].items(), key=lambda kv: (-kv[1]["n"], kv[0])
        ):
            frac = kid["n"] / total if total else 0.0
            if frac < _FG_MIN_FRAC:
                x += frac
                continue
            max_depth = max(max_depth, depth + 1)
            pct = 100.0 * frac
            esc = _html.escape(label)
            divs.append(
                f'<div class="f" title="{esc} — {kid["n"]} samples '
                f'({pct:.2f}%)" style="left:{100.0 * x:.3f}%;'
                f"top:{depth * _FG_ROW_PX}px;width:{pct:.3f}%;"
                f'background:{_fg_color(label)}">{esc}</div>'
            )
            walk(kid, depth + 1, x)
            x += frac

    walk(root, 0, 0.0)
    height = max(max_depth, 1) * _FG_ROW_PX
    esc_title = _html.escape(title)
    return f"""<!doctype html>
<html><head><meta charset="utf-8"><title>{esc_title}</title><style>
body{{font:13px sans-serif;margin:16px;background:#fff;color:#222}}
#fg{{position:relative;height:{height}px;border:1px solid #ddd}}
.f{{position:absolute;height:{_FG_ROW_PX - 1}px;overflow:hidden;
white-space:nowrap;font:11px monospace;color:#fff;
text-overflow:ellipsis;box-sizing:border-box;
border-right:1px solid rgba(255,255,255,.4);cursor:default}}
</style></head><body>
<h3>{esc_title}</h3>
<p>{total} samples · hover a frame for its share · width ∝ samples</p>
<div id="fg">{"".join(divs)}</div>
</body></html>
"""


# --- continuous mode -------------------------------------------------------

class ContinuousSampler(threading.Thread):
    """Low-rate per-process sampler feeding
    ``rt_profile_samples_total{subsystem}``. Tracks its own duty cycle
    (sampling time / wall time) so bench_obs can pin overhead without
    relying on A/B wall-clock noise."""

    def __init__(self, hz: float):
        super().__init__(name=SAMPLER_THREAD_NAME, daemon=True)
        self.hz = min(max(float(hz), 0.1), 1000.0)
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self.ticks = 0
        self.samples = 0
        self.busy_s = 0.0
        self.started_monotonic = time.monotonic()

    def run(self) -> None:
        from ray_tpu.observability import core_metrics

        period = 1.0 / self.hz
        me = {threading.get_ident()}
        while not self._stop.is_set():
            t0 = time.monotonic()
            batch = sample_subsystems(skip_idents=me)
            n = sum(batch.values())
            if core_metrics.ENABLED:
                for subsystem, count in batch.items():
                    core_metrics.profile_samples.inc(
                        count, tags={"subsystem": subsystem}
                    )
            busy = time.monotonic() - t0
            with self._lock:
                self.ticks += 1
                self.samples += n
                self.busy_s += busy
            self._stop.wait(max(period - busy, 0.001))

    def stop(self) -> None:
        self._stop.set()

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            wall = time.monotonic() - self.started_monotonic
            duty = self.busy_s / wall if wall > 0 else 0.0
            return {
                "hz": self.hz,
                "ticks": self.ticks,
                "samples": self.samples,
                "busy_s": self.busy_s,
                "wall_s": wall,
                "duty_pct": 100.0 * duty,
            }


_continuous: Optional[ContinuousSampler] = None
_continuous_lock = threading.Lock()


def maybe_start_continuous() -> Optional[ContinuousSampler]:
    """Start the per-process continuous sampler if configured
    (``RT_PROFILER_HZ`` > 0 and observability on). Idempotent."""
    global _continuous
    if not ENABLED:
        return None
    hz = float(config.profiler_hz)
    if hz <= 0:
        return None
    with _continuous_lock:
        if _continuous is not None and _continuous.is_alive():
            return _continuous
        from ray_tpu.observability import core_metrics

        sampler = ContinuousSampler(hz)
        sampler.start()
        _continuous = sampler
        if core_metrics.ENABLED:
            core_metrics.profiler_continuous_hz.set(sampler.hz)
        return sampler


def stop_continuous() -> None:
    global _continuous
    with _continuous_lock:
        if _continuous is not None:
            _continuous.stop()
            _continuous = None


def continuous_status() -> Dict[str, Any]:
    """For ``rt top``/bench: the in-process sampler state."""
    with _continuous_lock:
        sampler = _continuous
    if sampler is None or not sampler.is_alive():
        return {"running": False, "hz": 0.0}
    out = sampler.stats()
    out["running"] = True
    return out


def set_enabled(on: bool) -> None:
    global ENABLED
    ENABLED = bool(on)
    config.set("observability_enabled", bool(on))
