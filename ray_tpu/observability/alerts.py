"""Declarative alerting over the metrics history store.

Two rule kinds, both evaluated by the head's sampler tick against
``MetricsHistory`` (observability/history.py):

- ``threshold``: a windowed scalar (gauge avg/max, counter rate, or a
  gauge/gauge ratio via ``denominator``) compared against a bound, which
  must hold for ``for_s`` before the rule fires (threshold-for-duration
  — transient spikes stay in ``pending``).
- ``burn_rate``: the two-window SLO burn-rate pattern (SRE workbook
  chapter 5): fraction-of-observations-over-target / error-budget,
  required to exceed ``factor`` on BOTH a short and a long window. The
  short window makes firing fast; the long window keeps one stray
  sample from paging; requiring both makes resolve fast once the spike
  ends (the short window drains first).

Alert lifecycle: ``ok → pending → firing → resolved(ok)``. Every
transition is stamped as a ``{"type": "alert"}`` event into the head
process's worker event ring via tracing.emit — guarded by
``tracing.ENABLED`` per the check_metric_guards discipline — so firings
land in ``state.timeline()`` next to the request spans that caused
them. Current state is served by ``state.alerts()`` / ``rt alerts`` /
``GET /api/alerts`` and bannered in ``rt top``.

No-data semantics: a rule whose metric has no samples in the window is
treated as not-met (and resolves if firing) — a freshly idle deployment
must not page.

Extra rules ship via ``RT_ALERTS_RULES_EXTRA`` (a JSON list of rule
dicts, same field names as ``Rule``).
"""

from __future__ import annotations

import json
import logging
import os
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from ray_tpu.utils.config import config

logger = logging.getLogger(__name__)

OK = "ok"
PENDING = "pending"
FIRING = "firing"
RESOLVED = "resolved"

_OPS: Dict[str, Callable[[float, float], bool]] = {
    ">": lambda a, b: a > b,
    ">=": lambda a, b: a >= b,
    "<": lambda a, b: a < b,
    "<=": lambda a, b: a <= b,
}


@dataclass
class Rule:
    name: str
    kind: str  # "threshold" | "burn_rate"
    metric: str
    tags: Optional[Dict[str, str]] = None
    severity: str = "warn"
    # -- threshold fields --
    op: str = ">"
    threshold: float = 0.0
    window_s: float = 30.0
    agg: str = "avg"  # gauge rollup: "avg" | "max" (counters use rate)
    for_s: float = 0.0
    denominator: Optional[str] = None  # ratio rules (e.g. occupancy/total)
    # -- burn_rate fields --
    target_s: float = 0.0  # SLO latency target (bucket threshold)
    budget: float = 0.05  # allowed bad-event fraction
    short_window_s: float = 60.0
    long_window_s: float = 300.0
    factor: float = 1.0  # burn multiple that trips the rule
    extra: Dict[str, Any] = field(default_factory=dict)

    def describe(self) -> Dict[str, Any]:
        d = {
            "name": self.name, "kind": self.kind, "metric": self.metric,
            "severity": self.severity,
        }
        if self.tags:
            d["tags"] = dict(self.tags)
        if self.kind == "burn_rate":
            d.update(target_s=self.target_s, budget=self.budget,
                     short_window_s=self.short_window_s,
                     long_window_s=self.long_window_s, factor=self.factor)
        else:
            d.update(op=self.op, threshold=self.threshold,
                     window_s=self.window_s, for_s=self.for_s)
            if self.denominator:
                d["denominator"] = self.denominator
        return d


def rule_from_dict(d: Dict[str, Any]) -> Rule:
    fields = {f for f in Rule.__dataclass_fields__}
    return Rule(**{k: v for k, v in d.items() if k in fields})


def default_rules() -> List[Rule]:
    """The built-in rule pack. Metric names here are pinned against the
    registered core-metric series by tests/test_alerts.py, so a series
    rename cannot silently orphan a rule."""
    for_s = float(config.alerts_for_s)
    rules = [
        # TTFT SLO: the serving north-star. Burn-rate over the engine
        # admission→first-token histogram.
        Rule(
            name="serve_ttft_p95_burn", kind="burn_rate",
            metric="rt_serve_ttft_s", severity="page",
            target_s=float(config.alerts_ttft_target_s),
            budget=float(config.alerts_ttft_budget),
            short_window_s=float(config.alerts_burn_short_s),
            long_window_s=float(config.alerts_burn_long_s),
            factor=float(config.alerts_burn_factor),
        ),
        # Router/engine backlog: requests waiting for a KV slot.
        Rule(
            name="serve_queue_deep", kind="threshold",
            metric="rt_serve_queued_requests", op=">",
            threshold=float(config.alerts_queue_depth_max),
            window_s=max(for_s, 10.0), agg="avg", for_s=for_s,
        ),
        # KV saturation: occupied/total slot ratio across engines.
        Rule(
            name="serve_kv_occupancy", kind="threshold",
            metric="rt_serve_kv_slots_occupied",
            denominator="rt_serve_kv_slots_total", op=">",
            threshold=float(config.alerts_kv_occupancy_frac),
            window_s=max(for_s, 10.0), agg="avg", for_s=for_s,
        ),
        # Admission control shedding faster than clients should retry:
        # sustained 429/503 volume means capacity, caps, or the
        # autoscaler max bound need attention.
        Rule(
            name="serve_shed_rate", kind="threshold",
            metric="rt_serve_shed_total", op=">",
            threshold=float(config.alerts_shed_rate_max),
            window_s=max(for_s, 10.0), for_s=for_s,
        ),
        # Observability self-check: ring evictions mean truncated
        # timelines and undercounted percentiles.
        Rule(
            name="events_dropped", kind="threshold",
            metric="rt_task_events_dropped_total", op=">",
            threshold=0.0, window_s=30.0, for_s=0.0,
        ),
        # Node health: any node currently marked dead by the health loop.
        Rule(
            name="node_heartbeat_missed", kind="threshold",
            metric="rt_cluster_nodes_dead", op=">", threshold=0.0,
            window_s=15.0, agg="max", for_s=0.0, severity="page",
        ),
    ]
    raw = str(config.alerts_rules_extra).strip()
    if raw:
        try:
            rules.extend(rule_from_dict(d) for d in json.loads(raw))
        except (ValueError, TypeError) as e:
            logger.warning("ignoring malformed alerts_rules_extra: %s", e)
    return rules


class AlertEngine:
    """Evaluates rules against a MetricsHistory on every sampler tick
    and tracks the per-rule state machine."""

    def __init__(self, rules: List[Rule], store,
                 emit: Optional[Callable[[Dict[str, Any]], None]] = None):
        self.rules = list(rules)
        self.store = store
        self._emit = emit
        self._states: Dict[str, Dict[str, Any]] = {
            r.name: {
                "state": OK, "since": None, "pending_since": None,
                "value": None, "last_transition_ts": None, "evals": 0,
            }
            for r in self.rules
        }

    # -- evaluation -----------------------------------------------------

    def evaluate(self, now: Optional[float] = None) -> None:
        now = time.time() if now is None else now
        for rule in self.rules:
            try:
                value, met = self._eval_rule(rule, now)
            except Exception:  # noqa: BLE001 — one bad rule ≠ no alerts
                logger.exception("alert rule %s evaluation failed", rule.name)
                continue
            self._advance(rule, value, met, now)

    def _eval_rule(self, rule: Rule, now: float):
        if rule.kind == "burn_rate":
            short = self.store.fraction_above(
                rule.metric, rule.target_s, rule.short_window_s,
                tags=rule.tags, now=now,
            )
            long = self.store.fraction_above(
                rule.metric, rule.target_s, rule.long_window_s,
                tags=rule.tags, now=now,
            )
            if short is None or long is None or rule.budget <= 0:
                return None, False
            burn_short = short / rule.budget
            burn_long = long / rule.budget
            met = burn_short > rule.factor and burn_long > rule.factor
            return burn_short, met
        value = self.store.windowed_value(
            rule.metric, rule.window_s, tags=rule.tags, agg=rule.agg,
            now=now,
        )
        if value is None:
            return None, False
        if rule.denominator:
            denom = self.store.windowed_value(
                rule.denominator, rule.window_s, tags=rule.tags,
                agg=rule.agg, now=now,
            )
            if not denom:
                return None, False
            value = value / denom
        return value, _OPS[rule.op](value, rule.threshold)

    # -- state machine --------------------------------------------------

    def _advance(self, rule: Rule, value: Optional[float], met: bool,
                 now: float) -> None:
        st = self._states[rule.name]
        st["value"] = value
        st["evals"] += 1
        cur = st["state"]
        if met:
            if cur == OK:
                st["state"] = PENDING
                st["pending_since"] = now
                st["since"] = now
                st["last_transition_ts"] = now
                self._stamp(rule, PENDING, value, now)
                cur = PENDING
            if cur == PENDING and now - st["pending_since"] >= rule.for_s:
                # stamp BEFORE flipping the describe()-visible state: the
                # firing stamp can be slow (page severity attaches a
                # forensics capture), and a poller that sees "firing" via
                # rpc_alerts must also find the firing instant in the ring
                self._stamp(rule, FIRING, value, now)
                st["state"] = FIRING
                st["since"] = now
                st["last_transition_ts"] = now
        else:
            if cur == FIRING:
                self._stamp(rule, RESOLVED, value, now)
                st["last_transition_ts"] = now
            if cur != OK:
                st["state"] = OK
                st["since"] = None
                st["pending_since"] = None

    def _stamp(self, rule: Rule, state: str, value: Optional[float],
               now: float) -> None:
        from ray_tpu.observability import tracing

        if not tracing.ENABLED:
            return
        evt = {
            "type": "alert",
            "rule": rule.name,
            "state": state,
            "metric": rule.metric,
            "severity": rule.severity,
            "value": float(value) if value is not None else None,
            "ts_us": tracing.now_us(),
            "pid": os.getpid(),
        }
        if state == FIRING and rule.severity == "page":
            # one automatic hang-forensics capture rides the page event
            # (rate-limited by alert_capture_min_interval_s): the stacks
            # at firing time are exactly what the responder wants and
            # are gone by the time a human runs `rt stacks`
            from ray_tpu.observability import forensics

            capture = forensics.maybe_alert_capture()
            if capture is not None:
                evt["stacks"] = capture
        if self._emit is not None:
            self._emit(evt)
        else:
            tracing.emit(evt)

    # -- reporting ------------------------------------------------------

    def describe(self, now: Optional[float] = None) -> List[Dict[str, Any]]:
        now = time.time() if now is None else now
        out = []
        for rule in self.rules:
            st = self._states[rule.name]
            entry = dict(rule.describe())
            entry.update(
                state=st["state"],
                value=st["value"],
                since_s=(now - st["since"]) if st["since"] else None,
                evals=st["evals"],
            )
            out.append(entry)
        return out
