"""Train controller — the run state machine.

Parity: reference TrainController actor (python/ray/train/v2/_internal/
execution/controller/controller.py:105 — group start, poll, failure
decisions :235/:283) simplified to the run-restart loop: start worker
group → backend bootstrap → run → on worker failure restart the WHOLE
group from the latest checkpoint (the reference's recommended recovery
for jax.distributed, SURVEY.md §7 hard part c) up to max_failures.
"""

from __future__ import annotations

import logging
import time
import uuid
from typing import Any, Dict, List, Optional

import ray_tpu
from ray_tpu.train.checkpoint import CheckpointManager
from ray_tpu.train.config import FailureConfig, RunConfig, ScalingConfig
from ray_tpu.train.worker_group import PlacementTimeoutError, WorkerGroup
from ray_tpu.utils.config import config

logger = logging.getLogger(__name__)


@ray_tpu.remote
class TrainController:
    def __init__(
        self,
        scaling: ScalingConfig,
        run_dir: str,
        max_failures: int,
        num_to_keep: Optional[int],
        score_attribute: Optional[str],
        score_order: str,
    ):
        self.scaling = scaling
        self.run_dir = run_dir
        self.max_failures = max_failures
        self._resize_hint: Optional[int] = None
        self._view_cache: tuple = (-1, {})
        self.ckpts = CheckpointManager(
            run_dir, num_to_keep=num_to_keep,
            score_attribute=score_attribute, score_order=score_order,
        )

    def run(
        self,
        train_fn_blob: bytes,
        train_loop_config: Optional[Dict[str, Any]],
        use_tpu: bool,
        chips_per_worker: int,
        dataset_blobs: Optional[List[bytes]] = None,
    ) -> Dict[str, Any]:
        attempt = 0
        resizes = 0
        last_error: Optional[str] = None
        while attempt <= self.max_failures:
            scaling = self._current_scaling()
            group_name = f"rt_train_{uuid.uuid4().hex[:8]}"
            wg = WorkerGroup(scaling, self.run_dir)
            try:
                # Elastic: a short ready-bound turns "desired size no
                # longer fits" (e.g. the cluster view had not registered
                # node deaths when we sized) into a prompt feasibility
                # recompute instead of a 120 s stall at a stale size.
                wg.start(
                    ready_timeout_s=5.0 if self.scaling.elastic else 120.0
                )
                self._bootstrap_backend(
                    wg, group_name, use_tpu, chips_per_worker,
                    scaling.num_workers,
                )
                # pick up any complete checkpoints a crashed attempt left
                self.ckpts.rescan(expected_ranks=scaling.num_workers)
                restore = self.ckpts.latest()
                refs = wg.run(
                    train_fn_blob, train_loop_config,
                    restore.path if restore else None, group_name,
                    dataset_blobs,
                )
                outcome = self._monitor(refs, scaling, resizes)
                if outcome == "resize":
                    resizes += 1
                    logger.info(
                        "elastic resize: capacity returned, restarting the "
                        "group (resize %d)", resizes,
                    )
                    continue  # NOT a failure
                all_reports: List[List[Dict[str, Any]]] = ray_tpu.get(refs)
                self._register_checkpoints(all_reports[0])
                last = all_reports[0][-1] if all_reports[0] else None
                latest = self.ckpts.latest()
                return {
                    "metrics": last,
                    "checkpoint_path": latest.path if latest else None,
                    "error": None,
                    "attempts": attempt + 1,
                    "resizes": resizes,
                    "final_world_size": scaling.num_workers,
                }
            except PlacementTimeoutError as e:
                if self.scaling.elastic and resizes < 30:
                    # not a failure: the size was computed from a stale
                    # view — recompute feasibility and retry
                    resizes += 1
                    logger.info("elastic re-size after %s", e)
                else:
                    last_error = f"{type(e).__name__}: {e}"
                    attempt += 1
            except Exception as e:  # noqa: BLE001 — worker/group failure
                last_error = f"{type(e).__name__}: {e}"
                logger.warning(
                    "train attempt %d failed: %s", attempt + 1, last_error
                )
                attempt += 1
                time.sleep(0.5)
            finally:
                wg.shutdown()
        latest = self.ckpts.latest()
        return {
            "metrics": None,
            "checkpoint_path": latest.path if latest else None,
            "error": f"train failed after {attempt} attempts: {last_error}",
            "attempts": attempt,
        }

    def _current_scaling(self):
        """Elastic sizing (reference ElasticScalingPolicy, elastic.py:29):
        wait until at least min_workers are feasible, then take the
        largest feasible size within [min, max]. After an upscale resize,
        `_resize_hint` carries the target computed BEFORE the old group
        released its resources — wait briefly for the release to land in
        the cluster view instead of restarting at idle-capacity-only."""
        if not self.scaling.elastic:
            return self.scaling
        lo, hi = self.scaling.elastic_bounds()
        hint = self._resize_hint
        self._resize_hint = None
        hint_deadline = time.monotonic() + 15.0
        deadline = time.monotonic() + 300.0
        while True:
            n = min(hi, self._feasible_workers())
            if hint and n < hint and time.monotonic() < hint_deadline:
                time.sleep(0.5)
                continue
            if n >= lo:
                return self.scaling.resized(n)
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"elastic train: fewer than min_workers={lo} workers "
                    f"feasible after 300s (feasible={n})"
                )
            time.sleep(1.0)

    def _feasible_workers(self) -> int:
        """How many workers the cluster's AVAILABLE resources could host
        right now (per-node bin-packing of worker_resources). Uses the
        versioned view protocol: an unchanged cluster costs O(1) on the
        wire, not a full per-node resource dump per poll."""
        from ray_tpu.core import worker as worker_mod

        req = self.scaling.worker_resources()
        cached_version, cached_view = self._view_cache
        try:
            reply = worker_mod.global_worker().control.call(
                "get_cluster_view", known_version=cached_version,
                timeout_s=10.0,
            )
            if reply.get("unchanged"):
                view = cached_view
            else:
                view = reply["view"]
                self._view_cache = (reply["version"], view)
        except Exception:  # noqa: BLE001
            return 0
        total = 0
        for node in view.values():
            avail = node.get("resources_available", {})
            fits = min(
                (int(avail.get(k, 0.0) // v) for k, v in req.items() if v > 0),
                default=0,
            )
            total += max(0, fits)
        return total

    def _monitor(self, refs, scaling, resizes: int) -> str:
        """Block on the group's run; in elastic mode, watch for returned
        capacity and trigger an upscale restart (from the latest
        checkpoint) when more workers would fit. Returns "done" or
        "resize" (resize only in elastic mode, capped)."""
        lo, hi = scaling.elastic_bounds()
        can_grow = (
            self.scaling.elastic and scaling.num_workers < hi and resizes < 10
        )
        grow_seen = 0
        idle = 0
        while True:
            ready, pending = ray_tpu.wait(
                refs, num_returns=len(refs), timeout=1.0
            )
            if not pending:
                return "done"
            # A rank that errored while others still run means the group
            # is dying (peers will hang in collectives until their own
            # timeout): fail the whole attempt NOW — restart latency is
            # what bounds elastic recovery, not the barrier timeout.
            for r in ready:
                try:
                    ray_tpu.get(r)
                except BaseException as e:  # noqa: BLE001
                    raise RuntimeError(f"train worker failed: {e}") from None
            if not can_grow:
                continue
            idle = self._feasible_workers()  # capacity beyond our group
            if idle >= 1:
                grow_seen += 1
            else:
                grow_seen = 0
            # require capacity to be stable across a few polls before
            # paying a restart (checkpoint-bounded progress loss)
            if grow_seen >= 3:
                # the restart can host our current workers PLUS the idle
                # capacity; record it so _current_scaling doesn't size
                # from a view where our group still holds its resources
                self._resize_hint = min(hi, scaling.num_workers + idle)
                return "resize"

    def _bootstrap_backend(self, wg: WorkerGroup, group_name: str,
                           use_tpu: bool, chips_per_worker: int,
                           n: Optional[int] = None) -> None:
        """JaxBackend equivalent (reference train/v2/jax/config.py:31-165):
        CPU mode fakes a per-worker host mesh; TPU mode wires
        jax.distributed coordination env through the control store."""
        if n is None:
            n = self.scaling.num_workers
        if not use_tpu:
            envs = [
                {
                    "JAX_PLATFORMS": "cpu",
                    "XLA_FLAGS": (
                        f"--xla_force_host_platform_device_count="
                        f"{max(1, chips_per_worker)}"
                    ),
                }
                for _ in range(n)
            ]
            wg.apply_env(envs)
        else:
            envs = [
                {
                    "RT_XLA_GROUP": group_name,
                    "RT_XLA_RANK": str(i),
                    "RT_XLA_WORLD": str(n),
                }
                for i in range(n)
            ]
            wg.apply_env(envs)
        wg.setup_collectives(group_name)

    def _register_checkpoints(self, rank0_reports: List[Dict[str, Any]]) -> None:
        for entry in rank0_reports:
            if entry.get("_has_checkpoint"):
                metrics = {
                    k: v for k, v in entry.items() if not k.startswith("_")
                }
                self.ckpts.register(entry["_step"], metrics)
