"""Train controller — the run state machine.

Parity: reference TrainController actor (python/ray/train/v2/_internal/
execution/controller/controller.py:105 — group start, poll, failure
decisions :235/:283) simplified to the run-restart loop: start worker
group → backend bootstrap → run → on worker failure restart the WHOLE
group from the latest checkpoint (the reference's recommended recovery
for jax.distributed, SURVEY.md §7 hard part c) up to max_failures.
"""

from __future__ import annotations

import logging
import time
import uuid
from typing import Any, Dict, List, Optional

import ray_tpu
from ray_tpu.train.checkpoint import CheckpointManager
from ray_tpu.train.config import FailureConfig, RunConfig, ScalingConfig
from ray_tpu.train.worker_group import WorkerGroup

logger = logging.getLogger(__name__)


@ray_tpu.remote
class TrainController:
    def __init__(
        self,
        scaling: ScalingConfig,
        run_dir: str,
        max_failures: int,
        num_to_keep: Optional[int],
        score_attribute: Optional[str],
        score_order: str,
    ):
        self.scaling = scaling
        self.run_dir = run_dir
        self.max_failures = max_failures
        self.ckpts = CheckpointManager(
            run_dir, num_to_keep=num_to_keep,
            score_attribute=score_attribute, score_order=score_order,
        )

    def run(
        self,
        train_fn_blob: bytes,
        train_loop_config: Optional[Dict[str, Any]],
        use_tpu: bool,
        chips_per_worker: int,
        dataset_blobs: Optional[List[bytes]] = None,
    ) -> Dict[str, Any]:
        attempt = 0
        last_error: Optional[str] = None
        while attempt <= self.max_failures:
            group_name = f"rt_train_{uuid.uuid4().hex[:8]}"
            wg = WorkerGroup(self.scaling, self.run_dir)
            try:
                wg.start()
                self._bootstrap_backend(wg, group_name, use_tpu, chips_per_worker)
                # pick up any complete checkpoints a crashed attempt left
                self.ckpts.rescan(expected_ranks=self.scaling.num_workers)
                restore = self.ckpts.latest()
                refs = wg.run(
                    train_fn_blob, train_loop_config,
                    restore.path if restore else None, group_name,
                    dataset_blobs,
                )
                all_reports: List[List[Dict[str, Any]]] = ray_tpu.get(refs)
                self._register_checkpoints(all_reports[0])
                last = all_reports[0][-1] if all_reports[0] else None
                latest = self.ckpts.latest()
                return {
                    "metrics": last,
                    "checkpoint_path": latest.path if latest else None,
                    "error": None,
                    "attempts": attempt + 1,
                }
            except Exception as e:  # noqa: BLE001 — worker/group failure
                last_error = f"{type(e).__name__}: {e}"
                logger.warning(
                    "train attempt %d failed: %s", attempt + 1, last_error
                )
                attempt += 1
                time.sleep(0.5)
            finally:
                wg.shutdown()
        latest = self.ckpts.latest()
        return {
            "metrics": None,
            "checkpoint_path": latest.path if latest else None,
            "error": f"train failed after {attempt} attempts: {last_error}",
            "attempts": attempt,
        }

    def _bootstrap_backend(self, wg: WorkerGroup, group_name: str,
                           use_tpu: bool, chips_per_worker: int) -> None:
        """JaxBackend equivalent (reference train/v2/jax/config.py:31-165):
        CPU mode fakes a per-worker host mesh; TPU mode wires
        jax.distributed coordination env through the control store."""
        n = self.scaling.num_workers
        if not use_tpu:
            envs = [
                {
                    "JAX_PLATFORMS": "cpu",
                    "XLA_FLAGS": (
                        f"--xla_force_host_platform_device_count="
                        f"{max(1, chips_per_worker)}"
                    ),
                }
                for _ in range(n)
            ]
            wg.apply_env(envs)
        else:
            envs = [
                {
                    "RT_XLA_GROUP": group_name,
                    "RT_XLA_RANK": str(i),
                    "RT_XLA_WORLD": str(n),
                }
                for i in range(n)
            ]
            wg.apply_env(envs)
        wg.setup_collectives(group_name)

    def _register_checkpoints(self, rank0_reports: List[Dict[str, Any]]) -> None:
        for entry in rank0_reports:
            if entry.get("_has_checkpoint"):
                metrics = {
                    k: v for k, v in entry.items() if not k.startswith("_")
                }
                self.ckpts.register(entry["_step"], metrics)
