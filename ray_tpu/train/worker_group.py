"""Train worker group: placement group + one actor per worker.

Parity: reference WorkerGroup (python/ray/train/v2/_internal/execution/
worker_group/worker_group.py:113 — PG creation :449-488, actors bound to
bundles :384-399) with the TPU worker model: one worker = one host = all
its chips (JaxTrainer behavior, SURVEY.md §7 hard part e).
"""

from __future__ import annotations

import os
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.core.placement import PlacementGroupSchedulingStrategy
from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.config import ScalingConfig
from ray_tpu.utils import serialization


@ray_tpu.remote
class TrainWorker:
    """Hosts one rank of the SPMD training job."""

    def __init__(self, rank: int, world_size: int, run_dir: Optional[str]):
        self.rank = rank
        self.world_size = world_size
        self.run_dir = run_dir

    def apply_env(self, env: Dict[str, str]) -> bool:
        os.environ.update(env)
        return True

    def node_id(self) -> str:
        return ray_tpu.get_runtime_context().get_node_id()

    def setup_collectives(self, group_name: str) -> bool:
        from ray_tpu import collective

        collective.init_collective_group(
            world_size=self.world_size, rank=self.rank, backend="cpu",
            group_name=group_name,
        )
        return True

    def run(
        self,
        train_fn_blob: bytes,
        train_loop_config: Optional[Dict[str, Any]],
        restore_checkpoint_path: Optional[str],
        collective_group: Optional[str],
        datasets_blob: Optional[bytes] = None,
    ) -> List[Dict[str, Any]]:
        """Execute the user train loop; returns this rank's reports."""
        from ray_tpu.train import context as ctx_mod
        from ray_tpu.utils.config import config

        # Multi-host TPU: join this worker into the group's JAX runtime
        # before any jax use in the train fn (parity: reference JaxBackend
        # _setup_jax_distributed_environment, train/v2/jax/config.py:31).
        # RT_XLA_* arrive via apply_env() on this actor; the dynamic flags
        # re-read the process env on each access.
        if config.xla_group:
            from ray_tpu.collective.xla_group import initialize_xla_group

            initialize_xla_group(
                config.xla_group,
                int(config.xla_rank),
                int(config.xla_world),
            )

        train_fn = serialization.loads(train_fn_blob)
        restore = (
            Checkpoint(restore_checkpoint_path) if restore_checkpoint_path else None
        )
        # the blob already holds THIS rank's shard (driver-side split)
        shards = (
            serialization.loads(datasets_blob)
            if datasets_blob is not None
            else None
        )
        ctx = ctx_mod.TrainContext(
            world_rank=self.rank,
            world_size=self.world_size,
            local_rank=0,
            node_rank=self.rank,
            run_dir=self.run_dir,
            restore_checkpoint=restore,
            collective_group=collective_group,
            dataset_shards=shards,
        )
        if restore is not None:
            # continue checkpoint numbering from the restored step so a
            # resumed run never writes below the restore point
            base = os.path.basename(restore.path.rstrip("/"))
            try:
                ctx.report_step = int(base.split("_")[1])
            except (IndexError, ValueError):
                pass
        ctx_mod.set_context(ctx)
        try:
            if train_loop_config is not None:
                train_fn(train_loop_config)
            else:
                train_fn()
        finally:
            ctx_mod.set_context(None)
        return ctx.reports


class PlacementTimeoutError(RuntimeError):
    """The group's placement group did not become ready in time. In
    elastic mode this is a RESIZE signal, not a failure: the desired
    world size was computed from a cluster view that may not have
    registered node deaths yet (health_check_timeout_s lag), so the
    controller recomputes feasibility and retries smaller."""


class WorkerGroup:
    def __init__(self, scaling: ScalingConfig, run_dir: Optional[str]):
        self.scaling = scaling
        self.run_dir = run_dir
        self.pg = None
        self.workers: List[Any] = []

    def start(self, ready_timeout_s: float = 120.0) -> None:
        n = self.scaling.num_workers
        res = self.scaling.worker_resources()
        self.pg = ray_tpu.placement_group(
            [dict(res) for _ in range(n)],
            strategy=self.scaling.placement_strategy,
        )
        if not self.pg.wait(timeout_seconds=ready_timeout_s):
            raise PlacementTimeoutError(
                f"placement group for {n} x {res} not ready in "
                f"{ready_timeout_s}s"
            )
        self.workers = [
            TrainWorker.options(
                num_cpus=res.get("CPU", 1),
                num_tpus=res.get("TPU", 0) or None,
                scheduling_strategy=PlacementGroupSchedulingStrategy(self.pg, i),
            ).remote(i, n, self.run_dir)
            for i in range(n)
        ]

    def apply_env(self, envs: List[Dict[str, str]]) -> None:
        ray_tpu.get([
            w.apply_env.remote(env) for w, env in zip(self.workers, envs)
        ])

    def setup_collectives(self, group_name: str) -> None:
        ray_tpu.get([
            w.setup_collectives.remote(group_name) for w in self.workers
        ], timeout=120)

    def run(self, train_fn_blob, config, restore_path, collective_group,
            dataset_blobs=None):
        return [
            w.run.remote(
                train_fn_blob, config, restore_path, collective_group,
                dataset_blobs[i] if dataset_blobs else None,
            )
            for i, w in enumerate(self.workers)
        ]

    def node_ids(self) -> List[str]:
        return ray_tpu.get([w.node_id.remote() for w in self.workers])

    def shutdown(self) -> None:
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:  # noqa: BLE001
                pass
        self.workers = []
        if self.pg is not None:
            try:
                ray_tpu.remove_placement_group(self.pg)
            except Exception:  # noqa: BLE001
                pass
            self.pg = None
