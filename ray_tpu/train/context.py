"""Per-worker train context + report() (parity: ray.train.get_context /
ray.train.report, reference python/ray/train/context.py)."""

from __future__ import annotations

import os
import shutil
import threading
from typing import Any, Dict, List, Optional

from ray_tpu.train.checkpoint import Checkpoint

_local = threading.local()


class TrainContext:
    def __init__(
        self,
        world_rank: int,
        world_size: int,
        local_rank: int,
        node_rank: int,
        run_dir: Optional[str],
        restore_checkpoint: Optional[Checkpoint],
        collective_group: Optional[str],
        dataset_shards: Optional[Dict[str, Any]] = None,
    ):
        self.world_rank = world_rank
        self.world_size = world_size
        self.local_rank = local_rank
        self.node_rank = node_rank
        self.run_dir = run_dir
        self.restore_checkpoint = restore_checkpoint
        self.collective_group = collective_group
        self.dataset_shards = dataset_shards or {}
        self.reports: List[Dict[str, Any]] = []
        # resume the step counter from the restored checkpoint so a
        # restarted (or elastically resized) run never overwrites earlier
        # steps' checkpoint dirs
        self.report_step = 0
        if restore_checkpoint is not None:
            base = os.path.basename(restore_checkpoint.path.rstrip("/"))
            if base.startswith("checkpoint_"):
                try:
                    self.report_step = int(base.split("_")[1])
                except (IndexError, ValueError):
                    pass

    # -- API parity --

    def get_world_rank(self) -> int:
        return self.world_rank

    def get_world_size(self) -> int:
        return self.world_size

    def get_local_rank(self) -> int:
        return self.local_rank

    def get_node_rank(self) -> int:
        return self.node_rank

    def get_checkpoint(self) -> Optional[Checkpoint]:
        return self.restore_checkpoint

    def get_experiment_name(self) -> Optional[str]:
        return os.path.basename(self.run_dir) if self.run_dir else None

    def get_dataset_shard(self, name: str = "train"):
        """This rank's 1/world_size shard of a Dataset passed to the
        Trainer via datasets= (parity: ray.train.get_dataset_shard,
        reference v2/_internal/data_integration/). Returns a
        ray_tpu.data.DataIterator."""
        ds = self.dataset_shards.get(name)
        if ds is None:
            raise KeyError(
                f"no dataset named {name!r} was passed to the Trainer "
                f"(have: {sorted(self.dataset_shards)})"
            )
        return ds.iterator()

    def grad_sync(self, grads=None, *, average: bool = True,
                  quant: Optional[str] = None,
                  bucket_bytes: Optional[int] = None,
                  hierarchy: Optional[str] = None,
                  timeout_s: Optional[float] = None):
        """Overlapped bucketed DP gradient allreduce on this worker's
        collective group. ``grads = ctx.grad_sync(grads).join()`` is the
        one-shot form; for overlap, take an open handle before backward
        (``h = ctx.grad_sync()``), ``h.push(...)`` per microbatch/stage,
        and ``h.join()`` at optimizer apply. Single-worker runs (or no
        collective group) pass through locally. Averages by world size
        by default — the DP convention."""
        from ray_tpu.collective import bucketed

        group = (
            self.collective_group if self.world_size > 1 else None
        )
        handle = bucketed.GradSync(
            group, average=average, quant=quant,
            bucket_bytes=bucket_bytes, hierarchy=hierarchy,
            timeout_s=timeout_s,
        )
        if grads is not None:
            handle.push(grads)
            handle.close()
        return handle


def set_context(ctx: Optional[TrainContext]) -> None:
    _local.ctx = ctx


def get_context() -> TrainContext:
    ctx = getattr(_local, "ctx", None)
    if ctx is None:
        raise RuntimeError(
            "ray_tpu.train.get_context() called outside a train worker"
        )
    return ctx


def get_dataset_shard(name: str = "train"):
    return get_context().get_dataset_shard(name)


def grad_sync(grads=None, **kwargs):
    """Module-level convenience for ``get_context().grad_sync(...)``."""
    return get_context().grad_sync(grads, **kwargs)


def report(metrics: Dict[str, Any], checkpoint: Optional[Checkpoint] = None) -> None:
    """Record metrics (and optionally persist a checkpoint) for this step.

    Mirrors the reference flow (SURVEY.md §3.4): every worker reaches a
    sync barrier; each worker's checkpoint shard is copied into the shared
    step directory under rank_<r>/; metrics are recorded per worker and
    rank 0's stream becomes the Result metrics.
    """
    ctx = get_context()
    ctx.report_step += 1
    step = ctx.report_step
    if checkpoint is not None and ctx.run_dir is not None:
        step_dir = os.path.join(ctx.run_dir, f"checkpoint_{step:06d}")
        rank_dir = os.path.join(step_dir, f"rank_{ctx.world_rank}")
        os.makedirs(step_dir, exist_ok=True)
        shutil.copytree(checkpoint.as_directory(), rank_dir, dirs_exist_ok=True)
    entry = dict(metrics)
    entry["_step"] = step
    entry["_has_checkpoint"] = checkpoint is not None
    ctx.reports.append(entry)
    # commit barrier so no worker races ahead of a partially-written step
    if ctx.collective_group is not None and ctx.world_size > 1:
        from ray_tpu import collective

        collective.barrier(ctx.collective_group)
    if checkpoint is not None and ctx.run_dir is not None and ctx.world_rank == 0:
        # past the barrier every rank's shard landed: mark the step
        # COMPLETE with the world size that wrote it (an elastic restart
        # at a different size must not mistake a partial write for done)
        import json

        step_dir = os.path.join(ctx.run_dir, f"checkpoint_{step:06d}")
        with open(os.path.join(step_dir, "_complete.json"), "w") as f:
            json.dump({"world_size": ctx.world_size, "step": step}, f)
