"""Checkpoint handling.

Parity: ray.train.Checkpoint (python/ray/train/_checkpoint.py) +
CheckpointManager top-k retention (v2/_internal/execution/checkpoint/
checkpoint_manager.py). Storage is a directory tree under
RunConfig.storage_path:

  <run>/checkpoint_<step:6>/rank_<r>/...   per-worker shard dirs

TPU note: sharded-array async checkpointing (orbax) plugs in at the
train-fn level — workers write their own shards into their rank dir and
report() handles the commit barrier, which is exactly the orbax-style
per-host shard write + barrier described in SURVEY.md §5.
"""

from __future__ import annotations

import json
import os
import shutil
import tempfile
from typing import Any, Dict, List, Optional, Tuple


class Checkpoint:
    """A directory-backed checkpoint handle."""

    def __init__(self, path: str):
        self.path = path

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        return cls(os.path.abspath(path))

    def to_directory(self, dest: Optional[str] = None) -> str:
        dest = dest or tempfile.mkdtemp(prefix="rt_ckpt_")
        if os.path.abspath(dest) != self.path:
            shutil.copytree(self.path, dest, dirs_exist_ok=True)
        return dest

    def as_directory(self) -> str:
        return self.path

    def rank_dir(self, rank: int) -> str:
        return os.path.join(self.path, f"rank_{rank}")

    def __repr__(self):
        return f"Checkpoint({self.path})"


class CheckpointManager:
    """Top-k checkpoint retention with a score attribute."""

    def __init__(self, run_dir: str, num_to_keep: Optional[int] = None,
                 score_attribute: Optional[str] = None, score_order: str = "max"):
        self.run_dir = run_dir
        self.num_to_keep = num_to_keep
        self.score_attribute = score_attribute
        self.score_order = score_order
        os.makedirs(run_dir, exist_ok=True)
        # [(step, score, path)]
        self._checkpoints: List[Tuple[int, Optional[float], str]] = []
        self._load_existing()

    def _load_existing(self, expected_ranks: Optional[int] = None) -> None:
        known = {c[0] for c in self._checkpoints}
        for name in sorted(os.listdir(self.run_dir)):
            if name.startswith("checkpoint_") and os.path.isdir(
                os.path.join(self.run_dir, name)
            ):
                try:
                    step = int(name.split("_")[1])
                except (IndexError, ValueError):
                    continue
                if step in known:
                    continue
                path = os.path.join(self.run_dir, name)
                if os.path.exists(os.path.join(path, "_complete.json")):
                    pass  # all ranks landed (post-barrier marker)
                elif expected_ranks is not None:
                    ranks = [
                        d for d in os.listdir(path) if d.startswith("rank_")
                    ]
                    if len(ranks) < expected_ranks:
                        continue  # partial write from a crashed attempt
                self._checkpoints.append((step, None, path))

    def rescan(self, expected_ranks: Optional[int] = None) -> None:
        """Pick up checkpoints written by a crashed attempt (only steps
        where every rank's shard landed — report()'s barrier guarantees
        completed steps have all rank dirs)."""
        self._load_existing(expected_ranks)

    def dir_for_step(self, step: int) -> str:
        return os.path.join(self.run_dir, f"checkpoint_{step:06d}")

    def register(self, step: int, metrics: Optional[Dict[str, Any]]) -> Checkpoint:
        path = self.dir_for_step(step)
        score = None
        if self.score_attribute and metrics:
            score = metrics.get(self.score_attribute)
        with open(os.path.join(path, "metrics.json"), "w") as f:
            json.dump(metrics or {}, f)
        self._checkpoints = [c for c in self._checkpoints if c[0] != step]
        self._checkpoints.append((step, score, path))
        self._evict()
        return Checkpoint(path)

    def _evict(self) -> None:
        if self.num_to_keep is None or len(self._checkpoints) <= self.num_to_keep:
            return
        if self.score_attribute:
            # scored checkpoints ranked best-first; unscored ones are the
            # first to go regardless of score_order
            scored = [c for c in self._checkpoints if c[1] is not None]
            unscored = [c for c in self._checkpoints if c[1] is None]
            scored.sort(key=lambda c: c[1], reverse=self.score_order == "max")
            unscored.sort(key=lambda c: c[0], reverse=True)
            ranked = scored + unscored
        else:
            ranked = sorted(self._checkpoints, key=lambda c: c[0], reverse=True)
        keep = ranked[: self.num_to_keep]
        for step, score, path in self._checkpoints:
            if (step, score, path) not in keep:
                shutil.rmtree(path, ignore_errors=True)
        self._checkpoints = keep

    def latest(self) -> Optional[Checkpoint]:
        if not self._checkpoints:
            return None
        return Checkpoint(max(self._checkpoints, key=lambda c: c[0])[2])

    def best(self) -> Optional[Checkpoint]:
        scored = [c for c in self._checkpoints if c[1] is not None]
        if not scored:
            return self.latest()
        reverse = self.score_order == "max"
        return Checkpoint(
            sorted(scored, key=lambda c: c[1], reverse=reverse)[0][2]
        )
