"""DataParallelTrainer / JaxTrainer — the user-facing Train API.

Parity: DataParallelTrainer.fit (reference python/ray/train/v2/api/
data_parallel_trainer.py:157) and JaxTrainer (train/v2/jax/jax_trainer.py:20).
"""

from __future__ import annotations

import os
import time
from typing import Any, Callable, Dict, Optional

import ray_tpu
from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.config import Result, RunConfig, ScalingConfig
from ray_tpu.train.controller import TrainController
from ray_tpu.utils import serialization
from ray_tpu.utils.config import config as rt_config


class DataParallelTrainer:
    def __init__(
        self,
        train_loop_per_worker: Callable,
        *,
        train_loop_config: Optional[Dict[str, Any]] = None,
        scaling_config: Optional[ScalingConfig] = None,
        run_config: Optional[RunConfig] = None,
        datasets: Optional[Dict[str, Any]] = None,
        dataset_split_mode: str = "materialize",
    ):
        self._train_fn = train_loop_per_worker
        self._train_loop_config = train_loop_config
        self.scaling_config = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        self.datasets = datasets or {}
        # "materialize": execute the pipeline ONCE on the driver, hand each
        # rank a FromBundles shard (no duplicated read/preprocess compute;
        # costs full materialization in the object store).
        # "reexecute": each rank streams its own execution filtered to
        # 1/world_size of the block stream (no materialization; read/map
        # compute runs world_size times).
        if dataset_split_mode not in ("materialize", "reexecute"):
            raise ValueError(f"unknown dataset_split_mode {dataset_split_mode!r}")
        self.dataset_split_mode = dataset_split_mode

    def _run_dir(self) -> str:
        base = self.run_config.storage_path or os.path.join(
            rt_config.temp_dir, "runs"
        )
        name = self.run_config.name or f"run_{int(time.time())}"
        path = os.path.join(base, name)
        os.makedirs(path, exist_ok=True)
        return path

    def _dataset_blobs(self):
        """Per-rank dataset dicts, sharded driver-side (each rank receives
        exactly its shard — no shard logic on the worker). dumps_function
        (cloudpickle + by-value module registration) so UDFs defined in
        user modules deserialize on workers."""
        if not self.datasets:
            return None
        n = self.scaling_config.num_workers
        per_rank = [dict() for _ in range(n)]
        for name, ds in self.datasets.items():
            if n <= 1:
                parts = [ds]
            elif self.dataset_split_mode == "materialize":
                parts = ds.split(n)
            else:
                parts = [ds.shard(n, i) for i in range(n)]
            for i in range(n):
                per_rank[i][name] = parts[i]
        return [serialization.dumps_function(d) for d in per_rank]

    def fit(self) -> Result:
        if self.scaling_config.elastic and self.datasets:
            raise ValueError(
                "elastic scaling with datasets= is not supported yet: "
                "dataset shards are split at the initial world size"
            )
        run_dir = self._run_dir()
        cc = self.run_config.checkpoint_config
        # Pin the controller to the driver's node (reference v2 runs the
        # controller IN the driver process): it must not die with an
        # arbitrary worker node — its job is to outlive worker failures.
        from ray_tpu.core.api import NodeAffinitySchedulingStrategy

        controller = TrainController.options(
            num_cpus=0,
            scheduling_strategy=NodeAffinitySchedulingStrategy(
                node_id=ray_tpu.get_runtime_context().get_node_id(),
                soft=True,
            ),
        ).remote(
            self.scaling_config,
            run_dir,
            self.run_config.failure_config.max_failures,
            cc.num_to_keep,
            cc.checkpoint_score_attribute,
            cc.checkpoint_score_order,
        )
        try:
            out = ray_tpu.get(
                controller.run.remote(
                    serialization.dumps_function(self._train_fn),
                    self._train_loop_config,
                    self.scaling_config.use_tpu,
                    self.scaling_config.tpu_chips_per_worker,
                    self._dataset_blobs(),
                ),
            )
        finally:
            try:
                ray_tpu.kill(controller)
            except Exception:  # noqa: BLE001
                pass
        error = RuntimeError(out["error"]) if out.get("error") else None
        metrics = out.get("metrics")
        if metrics:
            metrics = {k: v for k, v in metrics.items() if not k.startswith("_")}
        ckpt = (
            Checkpoint(out["checkpoint_path"]) if out.get("checkpoint_path") else None
        )
        return Result(metrics=metrics, checkpoint=ckpt, error=error, path=run_dir)


class JaxTrainer(DataParallelTrainer):
    """SPMD JAX training: one worker per host, a mesh over all chips.

    Parity: reference JaxTrainer (TPU-only, _validate_scaling_config
    train/v2/jax/jax_trainer.py:162)."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        sc = self.scaling_config
        if sc.use_tpu and not sc.tpu_chips_per_worker:
            sc.tpu_chips_per_worker = 1
