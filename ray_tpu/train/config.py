"""Train configuration dataclasses.

Parity: ray.train ScalingConfig/RunConfig/FailureConfig/CheckpointConfig
(reference python/ray/train/v2/api/config.py, python/ray/air/config.py)
with TPU-first fields: resources are TPU chips + slice topology instead of
GPUs; one worker = one host = N chips (SURVEY.md §7 hard part e).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional


@dataclasses.dataclass
class ScalingConfig:
    num_workers: int = 1
    use_tpu: bool = False
    # resources per worker (one worker = one HOST driving all its chips)
    resources_per_worker: Optional[Dict[str, float]] = None
    tpu_chips_per_worker: int = 0
    topology: Optional[str] = None  # e.g. "v5e-16" → slice-aware placement
    placement_strategy: str = "PACK"
    # Elastic bounds (parity: reference ElasticScalingPolicy,
    # train/v2/_internal/execution/scaling_policy/elastic.py:29): when
    # min_workers is set, the controller restarts the group at the
    # largest FEASIBLE world size in [min_workers, max_workers] after a
    # failure, and resizes back up (from the latest checkpoint) when
    # capacity returns. max_workers defaults to num_workers.
    min_workers: Optional[int] = None
    max_workers: Optional[int] = None

    @property
    def elastic(self) -> bool:
        return self.min_workers is not None

    def elastic_bounds(self) -> "tuple[int, int]":
        lo = self.min_workers if self.min_workers is not None else self.num_workers
        hi = self.max_workers if self.max_workers is not None else self.num_workers
        return lo, hi

    def resized(self, n: int) -> "ScalingConfig":
        return dataclasses.replace(self, num_workers=n)

    def worker_resources(self) -> Dict[str, float]:
        res = dict(self.resources_per_worker or {})
        if self.use_tpu and self.tpu_chips_per_worker:
            res.setdefault("TPU", float(self.tpu_chips_per_worker))
        res.setdefault("CPU", 1.0)
        return res


@dataclasses.dataclass
class FailureConfig:
    max_failures: int = 0  # worker-group restarts before giving up


@dataclasses.dataclass
class CheckpointConfig:
    num_to_keep: Optional[int] = None  # None = keep all
    checkpoint_score_attribute: Optional[str] = None
    checkpoint_score_order: str = "max"


@dataclasses.dataclass
class RunConfig:
    name: Optional[str] = None
    storage_path: Optional[str] = None  # local dir (fsspec remotes later)
    failure_config: FailureConfig = dataclasses.field(default_factory=FailureConfig)
    checkpoint_config: CheckpointConfig = dataclasses.field(
        default_factory=CheckpointConfig
    )


@dataclasses.dataclass
class Result:
    metrics: Optional[Dict[str, Any]]
    checkpoint: Optional["Checkpoint"]
    error: Optional[BaseException]
    path: Optional[str] = None

    @property
    def best_checkpoints(self):
        return self._best_checkpoints if hasattr(self, "_best_checkpoints") else []


from ray_tpu.train.checkpoint import Checkpoint  # noqa: E402  (Result type)
