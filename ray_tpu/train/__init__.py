"""ray_tpu.train — distributed training orchestration (Train library).

Parity: ray.train v2 (reference python/ray/train/v2/) with JAX/TPU as the
first-class backend: JaxTrainer spawns one worker actor per host, wires
jax.distributed + mesh env, and the train loop uses ray_tpu.parallel for
dp/fsdp/tp/cp sharding.
"""

from ray_tpu.train.checkpoint import Checkpoint, CheckpointManager
from ray_tpu.train.config import (
    CheckpointConfig,
    FailureConfig,
    Result,
    RunConfig,
    ScalingConfig,
)
from ray_tpu.train.context import get_context, get_dataset_shard, grad_sync, report
from ray_tpu.train.trainer import DataParallelTrainer, JaxTrainer

__all__ = [
    "Checkpoint",
    "CheckpointConfig",
    "CheckpointManager",
    "DataParallelTrainer",
    "FailureConfig",
    "JaxTrainer",
    "Result",
    "RunConfig",
    "ScalingConfig",
    "get_context",
    "get_dataset_shard",
    "grad_sync",
    "report",
]
