"""Native-tier loader: builds and binds the C++ cores via ctypes.

The runtime's hot data-plane paths (channel seqlock + futex handoff,
object-segment IO) are C++ (native/src/*.cpp), mirroring the reference's
native tier (its channel/object plane lives in src/ray/core_worker and
src/ray/object_manager). Python implementations remain as wire- and
layout-compatible fallbacks so the framework still runs where a
toolchain is unavailable (RT_NATIVE=0 forces the fallback).

The .so is built on demand with g++ -O3 and cached next to the sources;
a content hash of the .cpp keys the cache so edits rebuild.
"""

from __future__ import annotations

import ctypes
import hashlib
import logging
import os
import subprocess
import threading

logger = logging.getLogger(__name__)

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_SRC_DIR = os.path.join(_REPO_ROOT, "native", "src")
_BUILD_DIR = os.path.join(_REPO_ROOT, "native", "build")

_lock = threading.Lock()
_libs: dict = {}


def _build(name: str) -> str | None:
    """Compile native/src/<name>.cpp → native/build/<name>-<hash>.so."""
    src = os.path.join(_SRC_DIR, f"{name}.cpp")
    if not os.path.exists(src):
        return None
    with open(src, "rb") as f:
        digest = hashlib.sha1(f.read()).hexdigest()[:12]
    out = os.path.join(_BUILD_DIR, f"{name}-{digest}.so")
    if os.path.exists(out):
        return out
    os.makedirs(_BUILD_DIR, exist_ok=True)
    tmp = out + f".tmp{os.getpid()}"
    cmd = [
        "g++", "-O3", "-shared", "-fPIC", "-std=c++17",
        "-fno-exceptions", src, "-o", tmp,
    ]
    try:
        proc = subprocess.run(
            cmd, capture_output=True, text=True, timeout=120
        )
    except (OSError, subprocess.TimeoutExpired) as e:
        logger.warning("native build of %s unavailable: %s", name, e)
        return None
    if proc.returncode != 0:
        logger.warning(
            "native build of %s failed:\n%s", name, proc.stderr[-2000:]
        )
        return None
    os.replace(tmp, out)  # atomic: concurrent builders race benignly
    return out


def load(name: str) -> ctypes.CDLL | None:
    """Load (building if needed) a native core; None → use the fallback."""
    from ray_tpu.utils.config import config

    if not config.native:
        return None
    with _lock:
        if name in _libs:
            return _libs[name]
        lib = None
        path = _build(name)
        if path is not None:
            try:
                lib = ctypes.CDLL(path)
            except OSError as e:
                logger.warning("native %s load failed: %s", name, e)
        _libs[name] = lib
        return lib


def store_lib() -> ctypes.CDLL | None:
    lib = load("store_core")
    if lib is None:
        return None
    if not getattr(lib, "_rt_sigs_set", False):
        lib.rt_sendfile_full.argtypes = [
            ctypes.c_int, ctypes.c_int, ctypes.c_uint64, ctypes.c_uint64,
        ]
        lib.rt_sendfile_full.restype = ctypes.c_int64
        lib.rt_recv_full.argtypes = [
            ctypes.c_int, ctypes.c_void_p, ctypes.c_uint64,
        ]
        lib.rt_recv_full.restype = ctypes.c_int64
        lib.rt_xxh64.argtypes = [
            ctypes.c_char_p, ctypes.c_uint64, ctypes.c_uint64,
        ]
        lib.rt_xxh64.restype = ctypes.c_uint64
        lib._rt_sigs_set = True
    return lib


def channel_lib() -> ctypes.CDLL | None:
    lib = load("channel_core")
    if lib is None:
        return None
    if not getattr(lib, "_rt_sigs_set", False):
        lib.rt_chan_open.argtypes = [
            ctypes.c_char_p, ctypes.c_uint64, ctypes.c_uint64,
            ctypes.c_int, ctypes.POINTER(ctypes.c_void_p),
        ]
        lib.rt_chan_open.restype = ctypes.c_int
        lib.rt_chan_write.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64,
            ctypes.c_double,
        ]
        lib.rt_chan_write.restype = ctypes.c_int
        lib.rt_chan_write_begin.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_double,
            ctypes.POINTER(ctypes.c_void_p),
        ]
        lib.rt_chan_write_begin.restype = ctypes.c_int
        lib.rt_chan_write_commit.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64,
        ]
        lib.rt_chan_write_commit.restype = ctypes.c_int
        lib.rt_chan_read.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_uint64,
            ctypes.c_double,
        ]
        lib.rt_chan_read.restype = ctypes.c_int64
        lib.rt_chan_read_begin.argtypes = [
            ctypes.c_void_p, ctypes.c_double,
            ctypes.POINTER(ctypes.c_void_p),
        ]
        lib.rt_chan_read_begin.restype = ctypes.c_int64
        lib.rt_chan_read_commit.argtypes = [ctypes.c_void_p]
        lib.rt_chan_read_commit.restype = ctypes.c_int
        lib.rt_chan_close.argtypes = [ctypes.c_void_p]
        lib.rt_chan_close.restype = None
        lib._rt_sigs_set = True
    return lib
