"""User-facing metrics API: Counter / Gauge / Histogram.

Parity: ray.util.metrics (reference python/ray/util/metrics.py:42).
Metrics register in a per-process registry; any process serves its
snapshot over the worker RPC (rpc_get_metrics) and the state API
aggregates across the cluster — the role the reference's OpenCensus →
dashboard-agent → Prometheus pipeline plays, without the Prometheus
dependency (a /metrics text formatter is provided for scraping).
"""

from __future__ import annotations

import bisect
import threading
import uuid
from typing import Callable, Dict, List, Optional, Sequence, Tuple

_lock = threading.Lock()
_registry: Dict[str, "_Metric"] = {}
# Modules holding module-level instrument references (e.g. the built-in
# core metrics) register a hook to re-create them after a registry wipe
# — a wiped registry would otherwise silently detach their instruments.
_reset_hooks: List[Callable[[], None]] = []

# Per-process identity for deduplicating scrapes: the head runs control
# store + node agent + driver in ONE process, so state.cluster_metrics
# must not sum that registry three times when it polls all three
# addresses.
PROCESS_TOKEN = uuid.uuid4().hex

_DEFAULT_BOUNDARIES = (
    0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0, 50.0, 100.0,
)


class _Metric:
    kind = "metric"

    def __init__(self, name: str, description: str = "",
                 tag_keys: Sequence[str] = ()):
        self.name = name
        self.description = description
        self.tag_keys = tuple(tag_keys)
        self._lock = threading.Lock()
        # tag-value tuple -> value state
        self._series: Dict[Tuple[str, ...], object] = {}
        with _lock:
            existing = _registry.get(name)
            if existing is not None:
                if existing.kind != self.kind:
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{existing.kind}"
                    )
                if existing.tag_keys != self.tag_keys:
                    raise ValueError(
                        f"metric {name!r} already registered with tag_keys="
                        f"{existing.tag_keys}"
                    )
                self._validate_rereg(existing)
                # per-name singleton series: re-constructing a metric
                # (e.g. inside a task that runs repeatedly on one worker)
                # must accumulate into the SAME series, not reset it
                self._series = existing._series
                self._lock = existing._lock
            else:
                _registry[name] = self

    def _validate_rereg(self, existing: "_Metric") -> None:
        """Kind-specific compatibility check on re-registration."""

    def _key(self, tags: Optional[Dict[str, str]]) -> Tuple[str, ...]:
        tags = tags or {}
        return tuple(str(tags.get(k, "")) for k in self.tag_keys)

    def snapshot(self) -> Dict:
        raise NotImplementedError


class Counter(_Metric):
    kind = "counter"

    def inc(self, value: float = 1.0,
            tags: Optional[Dict[str, str]] = None) -> None:
        if value < 0:
            raise ValueError("counters only increase")
        k = self._key(tags)
        with self._lock:
            self._series[k] = self._series.get(k, 0.0) + value

    def snapshot(self) -> Dict:
        with self._lock:
            return {
                "kind": self.kind,
                "description": self.description,
                "tag_keys": self.tag_keys,
                "series": {k: v for k, v in self._series.items()},
            }


class Gauge(_Metric):
    kind = "gauge"

    def set(self, value: float, tags: Optional[Dict[str, str]] = None) -> None:
        with self._lock:
            self._series[self._key(tags)] = float(value)

    def snapshot(self) -> Dict:
        with self._lock:
            return {
                "kind": self.kind,
                "description": self.description,
                "tag_keys": self.tag_keys,
                "series": {k: v for k, v in self._series.items()},
            }


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name: str, description: str = "",
                 boundaries: Sequence[float] = _DEFAULT_BOUNDARIES,
                 tag_keys: Sequence[str] = ()):
        self.boundaries = tuple(sorted(boundaries))
        # observe() sits on the RPC hot path: bisect over this prebuilt
        # list instead of rebuilding list(self.boundaries) per call
        self._bounds_list = list(self.boundaries)
        super().__init__(name, description, tag_keys)

    def _validate_rereg(self, existing: "_Metric") -> None:
        # a singleton's bucket arrays are sized for its boundaries —
        # adopting them under different boundaries would misbin counts
        if existing.boundaries != self.boundaries:
            raise ValueError(
                f"histogram {self.name!r} already registered with "
                f"boundaries={existing.boundaries}"
            )

    def observe(self, value: float,
                tags: Optional[Dict[str, str]] = None) -> None:
        k = self._key(tags)
        with self._lock:
            state = self._series.get(k)
            if state is None:
                state = {
                    "buckets": [0] * (len(self.boundaries) + 1),
                    "sum": 0.0,
                    "count": 0,
                }
                self._series[k] = state
            idx = bisect.bisect_left(self._bounds_list, value)
            state["buckets"][idx] += 1
            state["sum"] += value
            state["count"] += 1

    def snapshot(self) -> Dict:
        with self._lock:
            return {
                "kind": self.kind,
                "description": self.description,
                "tag_keys": self.tag_keys,
                "boundaries": self.boundaries,
                "series": {
                    k: dict(v, buckets=list(v["buckets"]))
                    for k, v in self._series.items()
                },
            }


def hist_quantile(
    bounds: Sequence[float],
    buckets: Sequence[float],
    q: float,
) -> Optional[float]:
    """Bucket-interpolated quantile from a (merged) histogram series:
    linear interpolation within the bucket holding the rank, Prometheus
    ``histogram_quantile`` style. None when bucket detail was dropped
    (divergent boundaries across workers) or the series is empty.

    The single shared implementation — state rollups, the ``rt top``
    renderer, the metrics-history store, and the alert engine all
    interpolate identically, so a client-vs-server percentile
    comparison (bench_serve.py) never diverges on interpolation math.
    """
    total = sum(buckets)
    if not bounds or not total:
        return None
    rank = q * total
    cum = 0.0
    lo = 0.0
    for i, n in enumerate(buckets):
        hi = bounds[i] if i < len(bounds) else bounds[-1]
        if n and cum + n >= rank:
            return lo + (hi - lo) * ((rank - cum) / n)
        cum += n
        lo = hi
    return bounds[-1]


def hist_fraction_above(
    bounds: Sequence[float],
    buckets: Sequence[float],
    threshold: float,
) -> Optional[float]:
    """Fraction of observations above ``threshold``, interpolated within
    the bucket the threshold falls in (the SLO burn-rate numerator:
    "what share of requests exceeded the target"). None on an empty
    series or dropped bucket detail."""
    total = sum(buckets)
    if not bounds or not total:
        return None
    above = 0.0
    lo = 0.0
    for i, n in enumerate(buckets):
        hi = bounds[i] if i < len(bounds) else float("inf")
        if threshold <= lo:
            above += n
        elif threshold < hi and hi != float("inf"):
            # threshold splits this bucket: assume uniform density
            above += n * (hi - threshold) / (hi - lo)
        elif threshold < hi:
            # overflow bucket has no upper edge: no interpolation basis,
            # count the whole bucket as above (pessimistic)
            above += n
        lo = hi
    return min(above / total, 1.0)


def snapshot_all() -> Dict[str, Dict]:
    with _lock:
        metrics = list(_registry.values())
    return {m.name: m.snapshot() for m in metrics}


def _escape_label_value(v: str) -> str:
    """Prometheus exposition escaping for label values: backslash, double
    quote, and line feed must be escaped or the line is unparseable."""
    return (
        str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")
    )


def _escape_help(s: str) -> str:
    """HELP text escaping per the exposition spec: backslash and LF."""
    return str(s).replace("\\", "\\\\").replace("\n", "\\n")


def prometheus_text(snapshots: Dict[str, Dict]) -> str:
    """Render aggregated snapshots in Prometheus exposition format."""
    lines: List[str] = []
    for name, snap in sorted(snapshots.items()):
        lines.append(
            f"# HELP {name} {_escape_help(snap.get('description', ''))}"
        )
        kind = snap["kind"]
        if kind == "histogram" and not snap.get("boundaries"):
            # bucket detail was dropped (divergent boundaries across
            # workers, state.cluster_metrics): only count/sum remain,
            # which is a summary, not a histogram
            kind = "summary"
        lines.append(f"# TYPE {name} {kind}")
        for tagvals, value in snap["series"].items():
            labels = ",".join(
                f'{k}="{_escape_label_value(v)}"'
                for k, v in zip(snap["tag_keys"], tagvals) if v
            )
            label_s = "{" + labels + "}" if labels else ""
            if snap["kind"] == "histogram":
                bounds = snap.get("boundaries", ())
                cum = 0
                for le, n in zip(list(bounds) + ["+Inf"], value["buckets"]):
                    cum += n
                    le_label = f'le="{le}"'
                    all_labels = f"{labels},{le_label}" if labels else le_label
                    lines.append(f"{name}_bucket{{{all_labels}}} {cum}")
                lines.append(f"{name}_count{label_s} {value['count']}")
                lines.append(f"{name}_sum{label_s} {value['sum']}")
            else:
                lines.append(f"{name}{label_s} {value}")
    return "\n".join(lines) + "\n"


def register_reset_hook(fn: Callable[[], None]) -> None:
    """Run fn after every registry reset (idempotent registration)."""
    if fn not in _reset_hooks:
        _reset_hooks.append(fn)


def _reset_for_tests() -> None:
    with _lock:
        _registry.clear()
    for fn in _reset_hooks:
        fn()
