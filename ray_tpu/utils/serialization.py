"""Serialization: cloudpickle + pickle-5 out-of-band buffers.

Equivalent of the reference's SerializationContext
(python/ray/_private/serialization.py:147): functions/classes go through
cloudpickle; data goes through pickle protocol 5 with out-of-band buffers so
large numpy arrays are written/read zero-copy against the shared-memory
object store.

Wire format of a serialized object:
  meta:    pickled bytes (with PickleBuffer placeholders)
  buffers: list of raw buffers, referenced in order by the meta stream

The object-frame layout (pack/frame_parts) and the RPC multi-segment frame
(utils/rpc.py) both ride on serialize(): the meta stream travels in-band,
every out-of-band buffer travels as a raw segment. Frame wraps an
already-packed byte frame so it, too, rides out-of-band instead of being
re-pickled (memcpy'd) inside an RPC message.
"""

from __future__ import annotations

import pickle
from typing import Any, Callable, List, Optional, Sequence, Tuple

import cloudpickle

PROTOCOL = 5

# ---------------------------------------------------------------------------
# Copy accounting (test hook)
# ---------------------------------------------------------------------------

# When set, every host-side bulk copy (>= COPY_HOOK_MIN_NBYTES) on the
# object data path reports here as hook(nbytes, site). Tests assert e.g.
# that a same-host put->get roundtrip of a 4 MiB array does at most ONE
# host copy (the write into shm). Off by default: call sites guard on
# `copy_hook is not None`, one predicted-false branch on the hot path.
copy_hook: Optional[Callable[[int, str], None]] = None
COPY_HOOK_MIN_NBYTES = 1 << 18


def note_copy(nbytes: int, site: str) -> None:
    hook = copy_hook
    if hook is not None and nbytes >= COPY_HOOK_MIN_NBYTES:
        hook(nbytes, site)


# ---------------------------------------------------------------------------
# Core pickle-5 split serialization
# ---------------------------------------------------------------------------


def serialize(obj: Any) -> Tuple[bytes, List[memoryview]]:
    """Serialize to (meta, out-of-band buffers). Buffers are zero-copy views."""
    buffers: List[pickle.PickleBuffer] = []
    try:
        meta = pickle.dumps(obj, protocol=PROTOCOL, buffer_callback=buffers.append)
    except Exception:
        # Fallback for closures, lambdas, locally-defined classes.
        buffers = []
        meta = cloudpickle.dumps(obj, protocol=PROTOCOL, buffer_callback=buffers.append)
    views = [b.raw() for b in buffers]
    return meta, views


def deserialize(meta: bytes, buffers: Sequence[Any]) -> Any:
    return pickle.loads(meta, buffers=buffers)


def dumps(obj: Any) -> bytes:
    """One-shot in-band serialization (control-plane messages)."""
    try:
        return pickle.dumps(obj, protocol=PROTOCOL)
    except Exception:
        return cloudpickle.dumps(obj, protocol=PROTOCOL)


def loads(data) -> Any:
    return pickle.loads(data)


class Frame:
    """Zero-copy container for an already-serialized byte frame.

    RPC messages carry packed object frames (pack() output) in their
    payloads; a bare ``bytes`` field would be re-pickled — i.e. memcpy'd
    — in-band. Frame pickles its payload as a PickleBuffer, so under the
    multi-segment wire format (utils/rpc.py) the bytes ride as a raw
    trailing segment: written with vectored sendmsg on one side, received
    with recv_into on the other, never re-pickled. Under plain dumps()
    (legacy peers, the WAL, snapshots) it degrades to an in-band copy and
    reconstructs as Frame(bytes) — both directions stay readable across
    mixed-version clusters.
    """

    __slots__ = ("data",)

    def __init__(self, data):
        self.data = data  # bytes | bytearray | memoryview

    @property
    def nbytes(self) -> int:
        return memoryview(self.data).nbytes

    def __len__(self) -> int:
        return self.nbytes

    def view(self) -> memoryview:
        return memoryview(self.data)

    def __reduce_ex__(self, protocol):
        if protocol >= 5:
            return (Frame, (pickle.PickleBuffer(self.data),))
        return (Frame, (bytes(self.data),))

    def __repr__(self):
        return f"<Frame {self.nbytes}B>"


# Below this size a frame stays in-band: a multi-segment wire frame
# costs the receiver ~3 extra recv(2) calls, which beats a memcpy only
# once the payload dwarfs the syscalls.
FRAME_OOB_MIN = 32 * 1024


def maybe_frame(data):
    """Wrap a packed frame for out-of-band transport when it is big
    enough for zero-copy to win; small frames ride in-band. Honors the
    rpc_multiseg kill switch: a Frame pickles as a global reference to
    this class, which a pre-multiseg peer cannot resolve — with the
    switch off (the mixed-version compat mode) payloads must stay plain
    bytes end to end, not just in-band."""
    from ray_tpu.utils.config import config

    if len(data) >= FRAME_OOB_MIN and config.rpc_multiseg:
        return Frame(data)
    return data


def as_view(data) -> memoryview:
    """Uniform zero-copy view over Frame / bytes / bytearray / memoryview /
    PickleBuffer (what a Frame reconstructs from under buffers=)."""
    if isinstance(data, Frame):
        data = data.data
    if isinstance(data, pickle.PickleBuffer):
        return data.raw()
    return memoryview(data)


def is_bytes_like(data) -> bool:
    """True for anything holding a packed frame: raw buffers or Frame."""
    return isinstance(data, (bytes, bytearray, memoryview, Frame))


def byte_views(parts) -> List[memoryview]:
    """Normalize buffers to flat byte views for a vectored syscall,
    dropping zero-length ones (declared in multiseg headers but never
    handed to the kernel)."""
    views = []
    for p in parts:
        v = memoryview(p)
        if v.format != "B" or v.ndim != 1:
            v = v.cast("B")
        if v.nbytes:
            views.append(v)
    return views


def advance_views(views: List[memoryview], i: int, n: int) -> int:
    """Consume ``n`` bytes of a vectored syscall's progress from
    ``views[i:]``, slicing the partially-consumed view in place; returns
    the index of the first unfinished view. Shared by sendmsg (rpc) and
    pwritev (object_store) resume loops."""
    while n:
        v = views[i]
        if n >= v.nbytes:
            n -= v.nbytes
            i += 1
        else:
            views[i] = v[n:]
            n = 0
    return i


def dumps_function(fn: Any) -> bytes:
    """Serialize a function/class definition (always cloudpickle).

    User modules (anything outside site-packages/stdlib/ray_tpu) are
    registered for by-value pickling so driver-local code runs on workers
    that cannot import it — the role the reference's runtime_env
    working_dir upload plays for module-level functions."""
    _maybe_register_by_value(getattr(fn, "__module__", None))
    return cloudpickle.dumps(fn, protocol=PROTOCOL)


_registered_by_value = set()


def _maybe_register_by_value(module_name, _depth: int = 0) -> None:
    """Register a user module — and the user modules it references — for
    by-value pickling (bounded transitive walk, so `from my_utils import
    helper` inside the user's module also ships by value)."""
    import sys
    import types

    if not module_name or module_name in _registered_by_value or _depth > 3:
        return
    top = module_name.split(".")[0]
    if top in ("ray_tpu", "builtins", "__main__") or top in sys.stdlib_module_names:
        return
    module = sys.modules.get(module_name)
    mod_file = getattr(module, "__file__", None)
    if module is None or mod_file is None:
        return
    if (
        "site-packages" in mod_file
        or "dist-packages" in mod_file
        or mod_file.startswith(sys.prefix)
        or mod_file.startswith(sys.base_prefix)
    ):
        return
    try:
        cloudpickle.register_pickle_by_value(module)
        _registered_by_value.add(module_name)
    except Exception:  # noqa: BLE001 — fall back to by-reference
        return
    # one hop: modules referenced by this module's globals
    for value in list(vars(module).values()):
        if isinstance(value, types.ModuleType):
            _maybe_register_by_value(value.__name__, _depth + 1)
        else:
            ref_mod = getattr(value, "__module__", None)
            if ref_mod and ref_mod != module_name:
                _maybe_register_by_value(ref_mod, _depth + 1)


# ---------------------------------------------------------------------------
# Contiguous object frames (the shm store format)
# ---------------------------------------------------------------------------
#
# Layout: [n_bufs u32][meta_len u64][buf_len u64 * n_bufs][meta][bufs...]
# frame_parts/frame_nbytes expose the scatter-gather pieces so writers can
# pwritev them straight into a shm segment (write-through puts: no
# intermediate concatenation); pack() joins them for callers that need one
# contiguous blob.


def frame_header(meta, views) -> bytes:
    parts = [
        len(views).to_bytes(4, "little"),
        len(meta).to_bytes(8, "little"),
    ]
    for v in views:
        parts.append(v.nbytes.to_bytes(8, "little"))
    return b"".join(parts)


def frame_nbytes(meta, views) -> int:
    return 12 + 8 * len(views) + len(meta) + sum(v.nbytes for v in views)


def frame_parts(meta, views) -> List[Any]:
    """Scatter-gather pieces of the frame: [header, meta, *views]."""
    return [frame_header(meta, views), meta, *views]


def pack_parts(meta, views) -> bytes:
    """Join (meta, views) into one contiguous frame (one host copy)."""
    total = frame_nbytes(meta, views)
    if copy_hook is not None:
        note_copy(total, "pack-join")
    return b"".join(
        bytes(p) if isinstance(p, memoryview) else p
        for p in frame_parts(meta, views)
    )


def pack(obj: Any) -> bytes:
    """Serialize obj into a single contiguous frame: header + meta + buffers.

    Used when an object must travel as one blob (shm store, network).
    Hot paths that can write segments directly (worker put / task returns)
    use serialize() + frame_parts() instead and skip this join.
    """
    meta, views = serialize(obj)
    return pack_parts(meta, views)


def unpack(frame) -> Any:
    """Inverse of pack(). Accepts bytes, memoryview, or Frame; buffers stay
    zero-copy views into the frame (caller keeps the frame alive, e.g. shm
    mapping)."""
    mv = as_view(frame)
    n_bufs = int.from_bytes(mv[0:4], "little")
    meta_len = int.from_bytes(mv[4:12], "little")
    off = 12
    buf_lens = []
    for _ in range(n_bufs):
        buf_lens.append(int.from_bytes(mv[off : off + 8], "little"))
        off += 8
    meta = bytes(mv[off : off + meta_len])
    off += meta_len
    buffers = []
    for ln in buf_lens:
        buffers.append(mv[off : off + ln])
        off += ln
    return deserialize(meta, buffers)
