"""Serialization: cloudpickle + pickle-5 out-of-band buffers.

Equivalent of the reference's SerializationContext
(python/ray/_private/serialization.py:147): functions/classes go through
cloudpickle; data goes through pickle protocol 5 with out-of-band buffers so
large numpy arrays are written/read zero-copy against the shared-memory
object store.

Wire format of a serialized object:
  meta:    pickled bytes (with PickleBuffer placeholders)
  buffers: list of raw buffers, referenced in order by the meta stream
"""

from __future__ import annotations

import pickle
from typing import Any, List, Sequence, Tuple

import cloudpickle

PROTOCOL = 5


def serialize(obj: Any) -> Tuple[bytes, List[memoryview]]:
    """Serialize to (meta, out-of-band buffers). Buffers are zero-copy views."""
    buffers: List[pickle.PickleBuffer] = []
    try:
        meta = pickle.dumps(obj, protocol=PROTOCOL, buffer_callback=buffers.append)
    except Exception:
        # Fallback for closures, lambdas, locally-defined classes.
        buffers = []
        meta = cloudpickle.dumps(obj, protocol=PROTOCOL, buffer_callback=buffers.append)
    views = [b.raw() for b in buffers]
    return meta, views


def deserialize(meta: bytes, buffers: Sequence[Any]) -> Any:
    return pickle.loads(meta, buffers=buffers)


def dumps(obj: Any) -> bytes:
    """One-shot in-band serialization (control-plane messages)."""
    try:
        return pickle.dumps(obj, protocol=PROTOCOL)
    except Exception:
        return cloudpickle.dumps(obj, protocol=PROTOCOL)


def loads(data: bytes) -> Any:
    return pickle.loads(data)


def dumps_function(fn: Any) -> bytes:
    """Serialize a function/class definition (always cloudpickle).

    User modules (anything outside site-packages/stdlib/ray_tpu) are
    registered for by-value pickling so driver-local code runs on workers
    that cannot import it — the role the reference's runtime_env
    working_dir upload plays for module-level functions."""
    _maybe_register_by_value(getattr(fn, "__module__", None))
    return cloudpickle.dumps(fn, protocol=PROTOCOL)


_registered_by_value = set()


def _maybe_register_by_value(module_name, _depth: int = 0) -> None:
    """Register a user module — and the user modules it references — for
    by-value pickling (bounded transitive walk, so `from my_utils import
    helper` inside the user's module also ships by value)."""
    import sys
    import types

    if not module_name or module_name in _registered_by_value or _depth > 3:
        return
    top = module_name.split(".")[0]
    if top in ("ray_tpu", "builtins", "__main__") or top in sys.stdlib_module_names:
        return
    module = sys.modules.get(module_name)
    mod_file = getattr(module, "__file__", None)
    if module is None or mod_file is None:
        return
    if (
        "site-packages" in mod_file
        or "dist-packages" in mod_file
        or mod_file.startswith(sys.prefix)
        or mod_file.startswith(sys.base_prefix)
    ):
        return
    try:
        cloudpickle.register_pickle_by_value(module)
        _registered_by_value.add(module_name)
    except Exception:  # noqa: BLE001 — fall back to by-reference
        return
    # one hop: modules referenced by this module's globals
    for value in list(vars(module).values()):
        if isinstance(value, types.ModuleType):
            _maybe_register_by_value(value.__name__, _depth + 1)
        else:
            ref_mod = getattr(value, "__module__", None)
            if ref_mod and ref_mod != module_name:
                _maybe_register_by_value(ref_mod, _depth + 1)


def pack(obj: Any) -> bytes:
    """Serialize obj into a single contiguous frame: header + meta + buffers.

    Layout: [n_bufs u32][meta_len u64][buf_len u64 * n_bufs][meta][bufs...]
    Used when an object must travel as one blob (shm store, network).
    """
    meta, views = serialize(obj)
    parts = [
        len(views).to_bytes(4, "little"),
        len(meta).to_bytes(8, "little"),
    ]
    for v in views:
        parts.append(v.nbytes.to_bytes(8, "little"))
    parts.append(meta)
    parts.extend(views)
    return b"".join(bytes(p) if isinstance(p, memoryview) else p for p in parts)


def unpack(frame) -> Any:
    """Inverse of pack(). Accepts bytes or memoryview; buffers stay zero-copy
    views into the frame (caller keeps the frame alive, e.g. shm mapping)."""
    mv = memoryview(frame)
    n_bufs = int.from_bytes(mv[0:4], "little")
    meta_len = int.from_bytes(mv[4:12], "little")
    off = 12
    buf_lens = []
    for _ in range(n_bufs):
        buf_lens.append(int.from_bytes(mv[off : off + 8], "little"))
        off += 8
    meta = bytes(mv[off : off + meta_len])
    off += meta_len
    buffers = []
    for ln in buf_lens:
        buffers.append(mv[off : off + ln])
        off += ln
    return deserialize(meta, buffers)
