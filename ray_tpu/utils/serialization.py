"""Serialization: cloudpickle + pickle-5 out-of-band buffers.

Equivalent of the reference's SerializationContext
(python/ray/_private/serialization.py:147): functions/classes go through
cloudpickle; data goes through pickle protocol 5 with out-of-band buffers so
large numpy arrays are written/read zero-copy against the shared-memory
object store.

Wire format of a serialized object:
  meta:    pickled bytes (with PickleBuffer placeholders)
  buffers: list of raw buffers, referenced in order by the meta stream
"""

from __future__ import annotations

import pickle
from typing import Any, List, Sequence, Tuple

import cloudpickle

PROTOCOL = 5


def serialize(obj: Any) -> Tuple[bytes, List[memoryview]]:
    """Serialize to (meta, out-of-band buffers). Buffers are zero-copy views."""
    buffers: List[pickle.PickleBuffer] = []
    try:
        meta = pickle.dumps(obj, protocol=PROTOCOL, buffer_callback=buffers.append)
    except Exception:
        # Fallback for closures, lambdas, locally-defined classes.
        buffers = []
        meta = cloudpickle.dumps(obj, protocol=PROTOCOL, buffer_callback=buffers.append)
    views = [b.raw() for b in buffers]
    return meta, views


def deserialize(meta: bytes, buffers: Sequence[Any]) -> Any:
    return pickle.loads(meta, buffers=buffers)


def dumps(obj: Any) -> bytes:
    """One-shot in-band serialization (control-plane messages)."""
    try:
        return pickle.dumps(obj, protocol=PROTOCOL)
    except Exception:
        return cloudpickle.dumps(obj, protocol=PROTOCOL)


def loads(data: bytes) -> Any:
    return pickle.loads(data)


def dumps_function(fn: Any) -> bytes:
    """Serialize a function/class definition (always cloudpickle)."""
    return cloudpickle.dumps(fn, protocol=PROTOCOL)


def pack(obj: Any) -> bytes:
    """Serialize obj into a single contiguous frame: header + meta + buffers.

    Layout: [n_bufs u32][meta_len u64][buf_len u64 * n_bufs][meta][bufs...]
    Used when an object must travel as one blob (shm store, network).
    """
    meta, views = serialize(obj)
    parts = [
        len(views).to_bytes(4, "little"),
        len(meta).to_bytes(8, "little"),
    ]
    for v in views:
        parts.append(v.nbytes.to_bytes(8, "little"))
    parts.append(meta)
    parts.extend(views)
    return b"".join(bytes(p) if isinstance(p, memoryview) else p for p in parts)


def unpack(frame) -> Any:
    """Inverse of pack(). Accepts bytes or memoryview; buffers stay zero-copy
    views into the frame (caller keeps the frame alive, e.g. shm mapping)."""
    mv = memoryview(frame)
    n_bufs = int.from_bytes(mv[0:4], "little")
    meta_len = int.from_bytes(mv[4:12], "little")
    off = 12
    buf_lens = []
    for _ in range(n_bufs):
        buf_lens.append(int.from_bytes(mv[off : off + 8], "little"))
        off += 8
    meta = bytes(mv[off : off + meta_len])
    off += meta_len
    buffers = []
    for ln in buf_lens:
        buffers.append(mv[off : off + ln])
        off += ln
    return deserialize(meta, buffers)
