"""Config registry: typed flags with env-var overrides.

Equivalent of the reference's RAY_CONFIG system
(src/ray/common/ray_config_def.h — ~230 flags, overridable via RAY_<name>
env vars, head-distributed to all nodes). Here: ``define(name, default)``
registers a flag; ``RT_<NAME>`` env vars override; the head node snapshots
its config and ships it to joining nodes so a cluster shares one view.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Any, Callable, Dict

_ENV_PREFIX = "RT_"


class _Flag:
    __slots__ = ("name", "default", "parser", "value", "overridden",
                 "dynamic")

    def __init__(self, name: str, default: Any, parser: Callable[[str], Any],
                 dynamic: bool = False):
        self.name = name
        self.default = default
        self.parser = parser
        self.overridden = False
        self.dynamic = dynamic
        env = None if dynamic else os.environ.get(
            _ENV_PREFIX + name.upper()
        )
        if env is not None:
            self.value = parser(env)
            self.overridden = True
        else:
            self.value = default

    def read(self) -> Any:
        """Current value.  Static flags resolved env once at define time;
        dynamic flags re-read the environment on every access (per-host /
        per-process values — a worker's XLA rank, a node's chip count —
        that land in os.environ after import, e.g. via runtime-env
        ``apply_env``).  An explicit ``config.set`` still wins."""
        if not self.dynamic or self.overridden:
            return self.value
        env = os.environ.get(_ENV_PREFIX + self.name.upper())
        if env is None or env == "":
            return self.default
        try:
            return self.parser(env)
        except ValueError:
            return self.default


def _parse_bool(s: str) -> bool:
    return s.strip().lower() in ("1", "true", "yes", "on")


class Config:
    """Process-global flag registry."""

    def __init__(self):
        self._flags: Dict[str, _Flag] = {}
        self._lock = threading.Lock()

    def define(self, name: str, default: Any, dynamic: bool = False) -> None:
        if isinstance(default, bool):
            parser: Callable[[str], Any] = _parse_bool
        elif isinstance(default, int):
            parser = int
        elif isinstance(default, float):
            parser = float
        else:
            parser = str
        with self._lock:
            if name not in self._flags:
                self._flags[name] = _Flag(name, default, parser, dynamic)

    def get(self, name: str) -> Any:
        return self._flags[name].read()

    def set(self, name: str, value: Any) -> None:
        with self._lock:
            self._flags[name].value = value
            self._flags[name].overridden = True

    def snapshot(self) -> str:
        """Serialize current values (for head → node distribution).
        Dynamic flags are per-host/per-process and never ship: the
        head's chip count or XLA rank must not overwrite a node's."""
        with self._lock:
            return json.dumps({
                k: f.value for k, f in self._flags.items() if not f.dynamic
            })

    def load_snapshot(self, payload: str) -> None:
        """Apply a head-node snapshot; local env overrides still win."""
        data = json.loads(payload)
        with self._lock:
            for k, v in data.items():
                flag = self._flags.get(k)
                if flag is not None and not flag.overridden \
                        and not flag.dynamic:
                    flag.value = v

    def __getattr__(self, name: str) -> Any:
        try:
            return self._flags[name].read()
        except KeyError:
            raise AttributeError(name) from None


config = Config()

# --- Core flags (subset of the reference's ray_config_def.h surface) ---
config.define("rpc_connect_timeout_s", 10.0)
config.define("rpc_request_timeout_s", 60.0)
config.define("rpc_max_retries", 3)
config.define("rpc_retry_delay_s", 0.1)
# Multi-segment scatter-gather frames for data-bearing RPC messages
# (utils/rpc.py). Off = every frame is legacy single-segment (in-band
# payload pickling): the one-release compat escape hatch for clusters
# mixing pre-multiseg readers with new writers.
config.define("rpc_multiseg", True)
# Fault injection: "Service.Method:p_request:p_response" comma list
# (mirror of RAY_testing_rpc_failure, src/ray/common/ray_config_def.h:862).
config.define("testing_rpc_failure", "")
# Serve proxy → replica hot path: one direct RPC to the hosting worker
# (rpc_actor_direct_call) instead of the actor-task machinery. Off =
# every proxied request takes the ordinary submit/reply path (the
# mixed-version escape hatch, and the A/B lever for bench_core).
config.define("serve_direct_rpc", True)
config.define("health_check_period_s", 1.0)
config.define("health_check_timeout_s", 10.0)
config.define("max_direct_call_object_size", 100 * 1024)
config.define("object_store_memory_mb", 1024)
# Cross-node object transfer chunk size (reference C8 push/pull: 1MB
# chunks, object_manager.proto); larger here since transport is TCP.
config.define("object_transfer_chunk_size", 4 * 1024 * 1024)
# Sliding window of chunk RPCs in flight per pull (reference
# push_manager.h pipelining).
config.define("object_transfer_window", 8)
# Pulls at/above this size stream into a disk-backed mmap instead of a
# heap bytearray (bounding worker RSS for huge objects).
config.define("object_pull_disk_threshold", 256 * 1024 * 1024)
config.define("worker_register_timeout_s", 30.0)
config.define("worker_pool_prestart", 0)
config.define("worker_idle_timeout_s", 600.0)
config.define("scheduler_spread_threshold", 0.5)
config.define("task_max_retries", 3)
config.define("borrow_pin_ttl_s", 600.0)
# Streaming generators: once the done-marker says item i exists, how long
# to wait for its (in-flight) push before declaring the item lost.
config.define("stream_item_grace_s", 30.0)
# After a stream's error marker lands, how long to keep delivering the
# validly-produced prefix (whose pushes ride a different connection and can
# trail the error reply) before raising the error.
config.define("stream_error_grace_s", 2.0)
# Normal-task lease cache (reference normal_task_submitter.h:52-82):
# how long a granted worker lease is kept warm after its queue drains
# before being returned to the node agent, and how many lease requests
# one scheduling key keeps in flight (owner-side rate limiting; reference
# max_pending_lease_requests).
config.define("lease_keepalive_s", 1.0)
config.define("max_lease_requests_per_key", 10)
# Lease pool sizing (Little's law): hold enough workers to drain the
# queue in about this long given the measured per-task service latency.
# Short tasks pipeline onto few warm workers (a worker process per nop
# task is pure context-switch overhead); long tasks scale wide.
config.define("lease_rampup_target_s", 0.1)
# pip runtime envs install OFFLINE from these local wheel directories
# (os.pathsep-separated; this image has no egress to an index)
config.define("pip_find_links", "/tmp/ray_tpu/wheels")
# Owner-side lineage entries kept for object reconstruction (reference
# bounds lineage by bytes; we bound by task count).
config.define("lineage_max_entries", 10000)
# Memory monitor (reference C19): kill a worker when host memory usage
# crosses the threshold. testing_memory_usage >= 0 injects a fake reading.
config.define("memory_usage_threshold", 0.95)
config.define("memory_monitor_period_s", 1.0)
config.define("testing_memory_usage", -1.0)
# Control-store metadata persistence (reference C14 Redis FT mode):
# empty = in-memory only; a path enables the HA durable log (snapshot at
# <path>, write-ahead log at <path>.wal) so a restarted head rebuilds an
# identical control plane (core/ha/).
config.define("control_store_persistence_path", "")
# HA durable-log tuning: WAL entries between snapshot compactions, and
# whether each append fsyncs (off by default: flush-to-OS survives a head
# process crash — the failure mode HA targets; power loss needs fsync).
config.define("ha_wal_compact_entries", 1000)
config.define("ha_wal_fsync", False)
# Reconciliation window after a head restart: scheduling stays paused
# this long (or until every restored-alive node re-attaches, whichever
# is sooner) while agents re-assert leases/bundles/workers; nodes that
# never re-attach are then GC'd as dead.
config.define("ha_reconcile_window_s", 8.0)
# Budget for a client (agent/worker/driver) to re-attach to a bounced
# head: retryable control-store calls keep redialing (with backoff,
# consulting ha_head_address_file for a moved head) up to this long.
config.define("ha_reattach_max_s", 60.0)
# Rendezvous file the head publishes its address to (shared storage);
# empty = same-address restarts only.
config.define("ha_head_address_file", "")
config.define("lineage_max_bytes", 256 * 1024 * 1024)
# Host collectives (collective/): peer-to-peer ring transport over the
# worker<->worker multiseg RPC data plane. RT_COLLECTIVE_P2P=0 is the
# kill switch — every collective byte rides the control-store KV again
# (the pre-p2p path, and the A/B lever for bench_core).
config.define("collective_p2p", True)
# Payloads below this ride the KV path even with p2p on: a tiny tensor's
# ring handshake costs more than one head round trip.
config.define("collective_p2p_min_bytes", 32 * 1024)
# Ring pipeline granularity: each ring chunk is split into subchunks of
# about this many bytes so subchunk k+1 is on the wire while k reduces.
config.define("collective_chunk_bytes", 1 * 1024 * 1024)
# Deadline for one collective op (mailbox waits + delivery acks); a dead
# peer surfaces as CollectiveError within this budget, never a hang.
config.define("collective_op_timeout_s", 120.0)
# Quantized allreduce (quant="int8"): elements per blockwise f32 scale.
config.define("collective_quant_block", 2048)
# Overlapped bucketed gradient allreduce (collective/bucketed.py):
# grad_sync packs the gradient pytree into per-dtype byte buckets (in
# reverse leaf order — backward produces output-side grads first) and
# allreduces each bucket on a background comm lane, joining only at
# optimizer apply. RT_COLLECTIVE_BUCKETED=0 is the kill switch: grad_sync
# degrades to the per-leaf blocking allreduce path.
config.define("collective_bucketed", True)
config.define("collective_bucket_bytes", 4 * 1024 * 1024)
# Hierarchical two-level allreduce: when a group spans >1 host (and has
# more ranks than hosts), bucketed allreduce reduces intra-host to a
# leader, runs the ring over leaders only, and broadcasts back — wire
# bytes crossing hosts scale with hosts, not ranks. 0 = always flat ring.
config.define("collective_hierarchical", True)
# Per-process host identity override for the collective topology (used
# by tests/bench to model multi-host placement on one box; empty = the
# worker address host). Dynamic: per-process, never shipped in the head
# config snapshot.
config.define("collective_host_id", "", dynamic=True)
# Compiled pipeline (parallel/pipeline.py CompiledPipeline): force EVERY
# stage-boundary channel onto the cross-host RpcChannel tier even when
# the stages share a node — the test/A-B lever for the worker<->worker
# chan_push path (same-node edges normally ride shm seqlock rings).
config.define("pipeline_force_rpc_channels", False)
# TPU-RDT device-object export: device->host copy of chunk k overlaps
# the shm/socket write of chunk k-1 through a depth-2 staging queue
# (core/device_objects.py write_arrays_overlapped). Chunk size trades
# overlap granularity against per-chunk bookkeeping (clamped to a
# 64 KiB floor); rdt_d2h_overlap off falls back to the serial
# convert-then-write path.
config.define("rdt_d2h_overlap", True)
config.define("rdt_d2h_chunk_bytes", 4 * 1024 * 1024)
# Producer-side eager export: start the (cached, single-flight) segment
# export the moment a device-transport task return is parked, so the
# D2H + shm write overlap the consumer task's submit/schedule latency.
# Off = export lazily on the consumer's first get (the pre-overlap
# behavior; saves the work when consumers are usually in-process).
config.define("rdt_eager_export", True)
config.define("actor_max_restarts", 0)
config.define("log_to_driver", True)
config.define("temp_dir", "/tmp/ray_tpu")
# Observability (C18). trace_events gates task lifecycle span stamping
# (RT_TRACE_EVENTS=0 disables); observability_enabled gates the built-in
# core metrics (scheduler/lease/object-store/RPC/serve). Both are read
# once into module-level flags (ray_tpu/observability) so the disabled
# hot path costs a single attribute check, not a registry lookup.
config.define("trace_events", True)
config.define("observability_enabled", True)
# Prefix KV caching (serve/prefix_cache.py): content-hashed prompt
# prefix blocks are kept in a refcounted, LRU-evicted per-engine pool
# and copied into a slot at admission instead of re-running prefill
# over them. RT_SERVE_PREFIX_CACHE=0 is the kill switch (and the A/B
# lever for bench_core's TTFT rows): every admission pays full prefill.
config.define("serve_prefix_cache", True)
# Tokens per prefix block: the unit of hashing, refcounting and reuse.
# Must be uniform across replicas of a deployment (the router's
# prefix-hash hint assumes one block geometry).
config.define("serve_prefix_block_tokens", 64)
# Max resident blocks per engine pool; refcount-0 blocks evict LRU
# beyond this.
config.define("serve_prefix_pool_blocks", 512)
# Paged KV pool (serve/llm.py + prefix_cache.PagedKVPool): the engine's
# generation KV and the prefix cache share ONE block-granular refcounted
# page pool — a prefix hit is a refcount bump (zero block copies),
# eviction is global LRU over pages not pinned by a live request, and
# continuous batching admits by free PAGES instead of free slots.
# RT_SERVE_PAGED_KV=0 is the kill switch: the engine reverts to the
# pre-paged slot cache + copy-based BlockPool (and the A/B lever for
# bench_serve's pagedkv leg). Page size inherits
# serve_prefix_block_tokens so page identity == prefix-block identity.
config.define("serve_paged_kv", True)
# Total pages in the engine pool; 0 = auto-size to MATCHED MEMORY with
# the slot engine (max_batch_size x ceil(n_positions/page_tokens)).
config.define("serve_kv_pool_pages", 0)
# Max concurrent sequences the paged engine decodes per step (the
# static batch width of the jitted decode); 0 = auto
# (4 x max_batch_size, capped by the pool's page count).
config.define("serve_paged_max_seqs", 0)
# Chunked prefill: at most this many prompt tokens are prefilled per
# engine round, so one long prompt is spread across rounds interleaved
# with decode steps (bounding in-flight streams' ITL and per-step
# memory). 0 = unchunked (a prompt prefills in one round).
config.define("serve_prefill_chunk_tokens", 512)
# Async decode pipeline (serve/llm.py): the engine dispatches decode
# chunk N+1 from chunk N's device-resident outputs BEFORE materializing
# chunk N's tokens on the host, so token fan-out, SSE queue puts,
# metrics stamps and the admission scan overlap with device compute
# (one-step lookahead). Page frees are deferred by one step so an
# in-flight chunk never reads freed pages. RT_SERVE_ASYNC_DECODE=0 is
# the kill switch (and the A/B lever for bench_serve's asyncdecode
# leg): the engine harvests every chunk synchronously before the next
# dispatch, exactly the pre-pipeline loop.
config.define("serve_async_decode", True)
# Disaggregated prefill/decode (serve/kv_transfer.py): the ingress
# calls a separate prefill deployment which ships the slot's KV rows
# back over an RpcChannel (zero-copy multiseg frames); the local engine
# imports them and only decodes. RT_SERVE_DISAGG=0 is the kill switch —
# every request prefills in the decode replica even when a prefill
# deployment exists.
config.define("serve_disagg", True)
# Budget for one prefill+transfer leg; a SIGKILLed prefill replica
# surfaces as a request failure within this, never a decode hang.
config.define("serve_disagg_timeout_s", 60.0)
# Server-side slice cap for blocking rpc_* waits on the head (kv_wait,
# wait_actor_alive, wait_placement_group): a handler never holds a
# dispatcher thread longer than this per call — clients re-issue slices
# until their own deadline (tools/rtlint dispatcher-block pass).
config.define("dispatch_wait_slice_s", 2.0)
# Control-plane scale envelope (ISSUE 14). actor_batch_flush_ms: the
# worker-side lifecycle batcher coalesces create/kill submissions for
# this long, then ships ONE register_actors/kill_actors RPC (0 = legacy
# one-RPC-per-actor path, also the bench A/B lever). wal_group_commit_ms:
# the HA WAL buffers appends from concurrent dispatcher threads and
# lands them as one buffered write (+ one fsync when ha_wal_fsync) per
# window; every RPC reply still barriers on durability of its own ops,
# so acked => in-WAL is unchanged (0 = per-op appends).
config.define("actor_batch_flush_ms", 2.0)
config.define("wal_group_commit_ms", 2.0)
# Bounded fan-out for parallel actor teardown (exit/release RPCs to
# workers and node agents during kill-drain).
config.define("actor_kill_fanout", 16)
# Metrics history + alerting plane (ISSUE 15, observability/history.py +
# alerts.py). The head samples state.cluster_metrics()+request_summary()
# every metrics_sample_interval_s into multi-resolution ring buffers
# (0 disables the sampler AND the alert engine; observability_enabled=0
# also disables both). metrics_history_max_series caps distinct
# (metric, tags) series retained — overflow series are dropped and
# counted, bounding head memory.
config.define("metrics_sample_interval_s", 1.0)
config.define("metrics_history_max_series", 2048)
# Alert engine: alerts_enabled gates rule evaluation on the sampler
# tick. The default rule pack reads the knobs below; extra rules ship as
# a JSON list of rule dicts in alerts_rules_extra.
config.define("alerts_enabled", True)
# TTFT SLO burn-rate rule: target latency, allowed bad-event fraction
# (error budget), the two burn windows, and the burn multiple that
# trips the rule on BOTH windows.
config.define("alerts_ttft_target_s", 2.0)
config.define("alerts_ttft_budget", 0.05)
config.define("alerts_burn_short_s", 60.0)
config.define("alerts_burn_long_s", 300.0)
config.define("alerts_burn_factor", 1.0)
# Threshold rules: sustained router/engine queue depth, KV-slot
# occupancy ratio (occupied/total), and the for-duration both must hold
# before firing.
config.define("alerts_queue_depth_max", 64.0)
config.define("alerts_kv_occupancy_frac", 0.95)
config.define("alerts_for_s", 30.0)
config.define("alerts_rules_extra", "")
# Profiler + forensics plane (ISSUE 16, observability/profiler.py +
# forensics.py). profiler_hz > 0 starts a low-rate continuous sampler
# thread in every process (per-subsystem shares feed
# rt_profile_samples_total); 0 = on-demand captures only. Server-side
# rpc_profile durations are clamped to profiler_max_duration_s so a
# caller can never pin a dispatcher thread indefinitely.
config.define("profiler_hz", 0.0)
config.define("profiler_max_duration_s", 60.0)
# Stall watchdog: a worker task running longer than this gets ONE
# {"type":"stall"} event carrying its thread stack stamped into the
# event ring (0 disables the watchdog).
config.define("task_stall_dump_s", 300.0)
# Crash flight recorder: period of the black-box writer thread that
# snapshots last-ring-events/active-tasks/rss to the crash dir (the
# snapshot that survives SIGKILL).
config.define("blackbox_interval_s", 5.0)
# Firing page-severity alerts attach one all-thread stack capture to
# the alert event, at most once per this interval.
config.define("alert_capture_min_interval_s", 60.0)
# Serving control loop (ISSUE 17, serve/autoscale/). The policy engine
# replaces the naive requests-per-replica autoscaler: every
# serve_autoscale_interval_s the controller reads windowed TTFT p95 /
# KV occupancy / queue depth from the head's metrics history (over
# serve_autoscale_window_s) plus the burn-rate alert state, and scales
# with hysteresis — up at the high watermarks (or a firing TTFT burn
# alert), down one replica at a time only after every signal stayed
# below the low watermarks for serve_autoscale_down_cooldown_s.
config.define("serve_autoscale_interval_s", 2.0)
config.define("serve_autoscale_window_s", 30.0)
config.define("serve_autoscale_up_cooldown_s", 2.0)
config.define("serve_autoscale_down_cooldown_s", 15.0)
# TTFT pressure watermark as a fraction of the SLO target
# (alerts_ttft_target_s): p95 above target*high_frac is a scale-up
# hint; below target*low_frac counts toward sustained-ok.
config.define("serve_autoscale_ttft_high_frac", 0.8)
config.define("serve_autoscale_ttft_low_frac", 0.4)
# KV-slot occupancy (occupied/total) watermarks.
config.define("serve_autoscale_kv_high_frac", 0.85)
config.define("serve_autoscale_kv_low_frac", 0.5)
# Session-aware drain: a scale-down victim stops taking new sessions
# (dropped from the routing table, HRW re-pins its sessions) and exits
# when its in-flight streams finish — or at this deadline, force-killed.
config.define("serve_autoscale_drain_deadline_s", 30.0)
# Admission control + load shedding at the proxy: bounded per-deployment
# in-flight work (queued + executing at THIS proxy; 503 + Retry-After
# past the bound — per-deployment override via
# @serve.deployment(max_queued_requests=...)) and an optional per-model
# concurrency cap (429 + Retry-After; 0 = uncapped). Kill switch:
# RT_SERVE_ADMISSION_ENABLED=0 admits everything.
config.define("serve_admission_enabled", True)
config.define("serve_admission_max_inflight", 256)
config.define("serve_admission_model_concurrency", 0)
config.define("serve_admission_retry_after_s", 1.0)
# Shed-rate alert rule: sustained sheds/s (rt_serve_shed_total windowed
# rate) above this trips serve_shed_rate.
config.define("alerts_shed_rate_max", 1.0)

# --- Per-host / per-process flags (dynamic) ----------------------------
# Re-read from the environment on every access and EXCLUDED from
# snapshot()/load_snapshot(): these describe the host or the process
# (chip inventory, XLA rank injected by the train controller via
# runtime-env apply_env), so a head-side value must never ship to nodes.
config.define("address", "", dynamic=True)
config.define("num_cpus", 0.0, dynamic=True)
# TPU inventory overrides (accelerators/tpu.py): "" = autodetect from
# the metadata server / PCI scan.
config.define("num_tpus", "", dynamic=True)
config.define("tpu_pod_type", "", dynamic=True)
config.define("tpu_topology", "", dynamic=True)
config.define("tpu_worker_id", "", dynamic=True)
# SPMD process-group coordinates the train controller injects into each
# TrainWorker's env between boot and run() (train/worker_group.py).
config.define("xla_group", "", dynamic=True)
config.define("xla_rank", "", dynamic=True)
config.define("xla_world", "", dynamic=True)
# Flash-attention block geometry (ops/flash_attention.py); tests tune
# these per-case via monkeypatch.setenv.
config.define("flash_bq", 1024, dynamic=True)
config.define("flash_bk", 1024, dynamic=True)
config.define("usage_stats_enabled", True, dynamic=True)
# Native (C/rust) data-plane toggle (native/__init__.py): RT_NATIVE=0
# forces the pure-python fallbacks.
config.define("native", True, dynamic=True)
# Crash-file directory for THIS process (forensics.py). The node agent
# points spawned workers at the session crash dir via RT_CRASH_DIR;
# empty = <temp_dir>/crash. Per-process by construction, so dynamic.
config.define("crash_dir", "", dynamic=True)
