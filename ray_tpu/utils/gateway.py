"""Single-endpoint driver gateway — the ``ray://`` client equivalent.

Parity: the reference's remote-driver proxy
(python/ray/util/client/ARCHITECTURE.md, util/client/worker.py:1): a
driver that can reach ONLY the head node's gateway port gets full
cluster access. Design here is a TCP-splicing gateway rather than a
gRPC re-encoding proxy — every existing protocol (framed RPC, the raw
sendfile data plane) rides through unchanged:

- **forward tunnels**: the driver's RpcClients and data-plane pulls
  connect to the gateway and name their real target in one header
  frame; the gateway dials the target and splices bytes both ways.
- **reverse binds**: cluster peers must also reach the DRIVER (its
  owner services: get_object, stream pushes, borrow callbacks). The
  driver asks the gateway to listen on a head-side port on its behalf
  and parks pre-opened *anchor* connections; each inbound peer
  connection is paired with an anchor and spliced, and the driver
  adopts the anchor socket into its RpcServer. The address the driver
  advertises in specs/refs is the gateway-side one, so NAT in front of
  the driver never matters.

Header frames use the rpc module's [8-byte LE length][pickle] framing:
    ("tunnel", "host:port")   -> ("ok",) then raw splice
    ("info",)                 -> {"control_address": ...}, then close
    ("reverse_bind", bind_id) -> ("ok", "host:port"), then close
    ("anchor", bind_id)       -> parks; ("go",) when a peer arrives,
                                 then raw splice
"""

from __future__ import annotations

import logging
import socket
import struct
import threading
from collections import deque
from typing import Dict, Optional

from ray_tpu.utils import serialization

logger = logging.getLogger(__name__)

# wire framing: REUSED from the rpc module (one definition of the
# [8-byte LE length][payload] format in the codebase)
from ray_tpu.utils.rpc import _LEN, _recv_exact  # noqa: E402

# driver-side process-global: when set, every RpcClient / data-plane
# connection is tunneled through this gateway address
_gateway_addr: Optional[str] = None


def set_gateway(addr: Optional[str]) -> None:
    global _gateway_addr
    _gateway_addr = addr


def gateway_address() -> Optional[str]:
    return _gateway_addr


def _send_frame(sock: socket.socket, payload: bytes) -> None:
    sock.sendall(_LEN.pack(len(payload)) + payload)


def _recv_frame(sock: socket.socket):
    (n,) = _LEN.unpack(_recv_exact(sock, 8))
    return serialization.loads(_recv_exact(sock, n))


def _dial(addr: str, timeout: float = 10.0) -> socket.socket:
    host, port = addr.rsplit(":", 1)
    sock = socket.create_connection((host, int(port)), timeout=timeout)
    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    sock.settimeout(None)
    return sock


def open_tunnel(target: str, timeout: float = 10.0) -> socket.socket:
    """Driver-side: a socket that behaves like a direct connection to
    ``target``, spliced through the configured gateway."""
    assert _gateway_addr is not None
    sock = _dial(_gateway_addr, timeout)
    _send_frame(sock, serialization.dumps(("tunnel", target)))
    reply = _recv_frame(sock)
    if reply[0] != "ok":
        sock.close()
        raise ConnectionError(f"gateway refused tunnel to {target}: {reply}")
    return sock


def fetch_info(gateway: str) -> dict:
    sock = _dial(gateway)
    try:
        _send_frame(sock, serialization.dumps(("info",)))
        return _recv_frame(sock)
    finally:
        sock.close()


def _splice(a: socket.socket, b: socket.socket) -> None:
    """Copy a->b until EOF, then shut both down (the b->a direction runs
    on its own thread doing the mirror image)."""
    try:
        while True:
            data = a.recv(1 << 16)
            if not data:
                break
            b.sendall(data)
    except OSError:
        pass
    finally:
        for s in (a, b):
            try:
                s.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass


def _splice_pair(a: socket.socket, b: socket.socket) -> None:
    t = threading.Thread(target=_splice, args=(b, a), daemon=True,
                         name="gw-splice")
    t.start()
    _splice(a, b)
    t.join()
    for s in (a, b):
        try:
            s.close()
        except OSError:
            pass


class _ReverseBind:
    def __init__(self, listener: socket.socket, port: int):
        self.listener = listener
        self.port = port
        self.anchors: deque = deque()
        self.cv = threading.Condition()


class Gateway:
    """Head-side gateway daemon. One per cluster, colocated with the
    control store."""

    def __init__(self, control_address: str, host: str = "127.0.0.1",
                 port: int = 0):
        # loopback by default, like every other listener in the codebase:
        # the tunnel op dials arbitrary client-named targets, so exposing
        # it beyond the host (host="0.0.0.0") is an explicit deployment
        # opt-in, made alongside whatever network policy guards the head
        self.control_address = control_address
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self._listener.listen(128)
        self.port = self._listener.getsockname()[1]
        self._binds: Dict[str, _ReverseBind] = {}
        self._lock = threading.Lock()
        self._stopped = threading.Event()

    @property
    def address(self) -> str:
        host = self._listener.getsockname()[0]
        if host in ("0.0.0.0", "::"):
            host = "127.0.0.1"
        return f"{host}:{self.port}"

    def start(self) -> None:
        threading.Thread(
            target=self._accept_loop, name="gateway-accept", daemon=True
        ).start()

    def stop(self) -> None:
        self._stopped.set()
        for sock in [self._listener]:
            try:
                sock.shutdown(socket.SHUT_RDWR)  # wakes blocked accept(2)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass
        with self._lock:
            binds = list(self._binds.values())
            self._binds.clear()
        for b in binds:
            try:
                b.listener.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                b.listener.close()
            except OSError:
                pass

    # -- gateway-port connections ---------------------------------------

    def _accept_loop(self) -> None:
        while not self._stopped.is_set():
            try:
                sock, _ = self._listener.accept()
            except OSError:
                return
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            threading.Thread(
                target=self._serve, args=(sock,), name="gw-conn",
                daemon=True,
            ).start()

    def _serve(self, sock: socket.socket) -> None:
        try:
            msg = _recv_frame(sock)
        except (ConnectionError, OSError):
            sock.close()
            return
        kind = msg[0]
        try:
            if kind == "tunnel":
                try:
                    target = _dial(msg[1])
                except OSError as e:
                    _send_frame(sock, serialization.dumps(("error", str(e))))
                    sock.close()
                    return
                _send_frame(sock, serialization.dumps(("ok",)))
                _splice_pair(sock, target)
            elif kind == "info":
                _send_frame(
                    sock,
                    serialization.dumps(
                        {"control_address": self.control_address}
                    ),
                )
                sock.close()
            elif kind == "reverse_bind":
                addr = self._ensure_bind(msg[1])
                _send_frame(sock, serialization.dumps(("ok", addr)))
                sock.close()
            elif kind == "anchor":
                self._park_anchor(msg[1], sock)
            else:
                sock.close()
        except (ConnectionError, OSError):
            try:
                sock.close()
            except OSError:
                pass

    # -- reverse binds --------------------------------------------------

    def _ensure_bind(self, bind_id: str) -> str:
        with self._lock:
            bind = self._binds.get(bind_id)
            if bind is None:
                listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
                listener.setsockopt(
                    socket.SOL_SOCKET, socket.SO_REUSEADDR, 1
                )
                listener.bind((self._listener.getsockname()[0], 0))
                listener.listen(64)
                bind = _ReverseBind(listener, listener.getsockname()[1])
                self._binds[bind_id] = bind
                threading.Thread(
                    target=self._bind_accept_loop, args=(bind,),
                    name="gw-rev-accept", daemon=True,
                ).start()
        host = self.address.rsplit(":", 1)[0]
        return f"{host}:{bind.port}"

    def _park_anchor(self, bind_id: str, sock: socket.socket) -> None:
        addr = self._ensure_bind(bind_id)  # idempotent
        bind = self._binds.get(bind_id)
        if bind is None:
            sock.close()
            return
        with bind.cv:
            bind.anchors.append(sock)
            bind.cv.notify_all()
        del addr

    def _bind_accept_loop(self, bind: _ReverseBind) -> None:
        while not self._stopped.is_set():
            try:
                peer, _ = bind.listener.accept()
            except OSError:
                return
            peer.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            threading.Thread(
                target=self._pair, args=(bind, peer), name="gw-pair",
                daemon=True,
            ).start()

    def _pair(self, bind: _ReverseBind, peer: socket.socket) -> None:
        deadline = 30.0
        with bind.cv:
            while not bind.anchors:
                if not bind.cv.wait(timeout=deadline):
                    peer.close()
                    return
            anchor = bind.anchors.popleft()
        try:
            _send_frame(anchor, serialization.dumps(("go",)))
        except OSError:
            peer.close()
            return
        _splice_pair(anchor, peer)


class ReverseListener:
    """Driver-side: keeps anchors parked at the gateway and adopts each
    paired connection into the local RpcServer."""

    def __init__(self, server, bind_id: str, n_anchors: int = 8):
        self.server = server
        self.bind_id = bind_id
        self.n_anchors = n_anchors
        self.public_address: Optional[str] = None
        self._stopped = threading.Event()
        self._anchors_lock = threading.Lock()
        self._open_anchors: set = set()

    def start(self) -> str:
        sock = _dial(_gateway_addr)
        try:
            _send_frame(
                sock, serialization.dumps(("reverse_bind", self.bind_id))
            )
            reply = _recv_frame(sock)
        finally:
            sock.close()
        if reply[0] != "ok":
            raise ConnectionError(f"reverse bind failed: {reply}")
        self.public_address = reply[1]
        for _ in range(self.n_anchors):
            self._launch_anchor()
        return self.public_address

    def stop(self) -> None:
        self._stopped.set()
        with self._anchors_lock:
            anchors, self._open_anchors = self._open_anchors, set()
        for sock in anchors:
            try:
                sock.close()  # unblocks the parked _recv_frame
            except OSError:
                pass

    def _launch_anchor(self) -> None:
        threading.Thread(
            target=self._anchor_loop, name="gw-anchor", daemon=True
        ).start()

    def _anchor_loop(self) -> None:
        while not self._stopped.is_set():
            gw = _gateway_addr
            if gw is None:
                return  # shutdown reset the gateway address
            sock = None
            try:
                sock = _dial(gw)
                with self._anchors_lock:
                    self._open_anchors.add(sock)
                _send_frame(
                    sock, serialization.dumps(("anchor", self.bind_id))
                )
                msg = _recv_frame(sock)  # blocks until a peer arrives
                if msg[0] != "go":
                    sock.close()
                    continue
            except (ConnectionError, OSError):
                if sock is not None:
                    with self._anchors_lock:
                        self._open_anchors.discard(sock)
                    try:
                        sock.close()
                    except OSError:
                        pass
                if self._stopped.wait(1.0):
                    return
                continue
            with self._anchors_lock:
                self._open_anchors.discard(sock)
            # replace ourselves BEFORE serving: the pool of parked
            # anchors must stay full while this one carries traffic
            self._launch_anchor()
            self.server.adopt(sock, ("gateway", 0))
            return
