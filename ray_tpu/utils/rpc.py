"""TCP RPC substrate: framed request/response + server push, retry, chaos.

Equivalent of the reference's gRPC wrappers (src/ray/rpc/grpc_server.h:86,
client_call.h:203, retryable_grpc_client.cc) and its fault-injection hook
(rpc_chaos.cc). One RpcServer per daemon (control store / node agent /
worker); RpcClient is thread-safe and multiplexes concurrent calls over one
connection. Push messages implement the pubsub substrate (reference C16).

Wire format — two frame kinds, distinguished by the first 8 bytes:

  legacy:   [u64 len][pickled message]            (len < 2^48)
  multiseg: [u64 MAGIC][u32 nsegs][u64 len]×nsegs [seg 0][seg 1]…

Multi-segment frames carry pickle-5 out-of-band buffers as raw trailing
segments: seg 0 is the meta stream, segs 1… are its buffers in order.
The sender writes all segments with vectored sendmsg (no header+payload
concatenation, ndarray/Frame payloads never re-pickled in-band); the
receiver reads each segment with recv_into on a preallocated buffer and
reassembles with pickle.loads(meta, buffers=…). Messages with no
out-of-band buffers — all control traffic — use the legacy frame, so a
mixed-version cluster only trips on data-bearing frames, and setting
config.rpc_multiseg=False forces even those in-band for one release of
compat with pre-multiseg readers.

Messages:
  ("req",  req_id, method, args, kwargs)
  ("resp", req_id, ok, payload)          # payload = result or exception
  ("push", topic, payload)               # server → client, no req_id
"""

from __future__ import annotations

import logging
import random
import socket
import struct
import threading
import time
import traceback
from typing import Any, Callable, Dict, Optional, Tuple

from ray_tpu.observability import core_metrics
from ray_tpu.utils import serialization
from ray_tpu.utils.config import config

logger = logging.getLogger(__name__)

_LEN = struct.Struct("<Q")

# Method-family buckets for the built-in RPC latency histogram: one
# series per subsystem, not per method (bounded cardinality).
_FAMILY_PREFIXES = (
    ("kv_", "kv"),
    ("lease_worker", "lease"),
    ("release_worker", "lease"),
    ("push_task", "task"),
    ("actor_task", "task"),
    ("stream_item", "task"),
    ("cancel_task", "task"),
    ("create_actor", "actor"),
    ("get_actor", "actor"),
    ("wait_actor", "actor"),
    ("get_named_actor", "actor"),
    ("kill_actor", "actor"),
    ("actor_", "actor"),
    ("report_actor", "actor"),
    ("get_object", "object"),
    ("peek_object", "object"),
    ("free_object", "object"),
    ("create_object", "object"),
    ("seal_object", "object"),
    ("delete_objects", "object"),
    ("object_contains", "object"),
    ("read_object_chunk", "object"),
    ("wait_objects", "object"),
    ("add_borrow", "object"),
    ("release_borrow", "object"),
    ("store_usage", "object"),
    ("register_", "node"),
    ("heartbeat", "node"),
    ("get_nodes", "node"),
    ("get_cluster_view", "node"),
    ("capacity_freed", "node"),
    ("drain_node", "node"),
    ("prepare_bundles", "pg"),
    ("commit_bundles", "pg"),
    ("return_bundles", "pg"),
    ("create_placement_group", "pg"),
    ("get_placement_group", "pg"),
    ("wait_placement_group", "pg"),
    ("remove_placement_group", "pg"),
    ("list_placement_groups", "pg"),
    ("coll_deliver", "collective"),
    ("chan_push", "channel"),
    ("get_state", "state"),
    ("get_metrics", "state"),
    ("get_task_events", "state"),
    ("list_", "state"),
)
_family_cache: Dict[str, str] = {}


def _method_family(method: str) -> str:
    family = _family_cache.get(method)
    if family is None:
        family = "other"
        for prefix, fam in _FAMILY_PREFIXES:
            if method.startswith(prefix):
                family = fam
                break
        _family_cache[method] = family
    return family


class RpcError(Exception):
    pass


class RpcConnectionError(RpcError):
    pass


class RpcTimeout(RpcError):
    pass


class RemoteError(RpcError):
    """Exception raised on the server, re-raised at the caller."""

    def __init__(self, message: str, remote_traceback: str = ""):
        super().__init__(message)
        self.remote_traceback = remote_traceback


# ---------------------------------------------------------------------------
# Chaos / fault injection (mirror of src/ray/rpc/rpc_chaos.{h,cc})
# ---------------------------------------------------------------------------


def _chaos_probabilities(method: str) -> Tuple[float, float]:
    spec = config.testing_rpc_failure
    if not spec:
        return 0.0, 0.0
    for entry in spec.split(","):
        parts = entry.strip().split(":")
        if len(parts) >= 1 and parts[0] == method:
            p_req = float(parts[1]) if len(parts) > 1 else 0.0
            p_resp = float(parts[2]) if len(parts) > 2 else 0.0
            return p_req, p_resp
    return 0.0, 0.0


def maybe_inject_request_failure(method: str) -> None:
    p_req, _ = _chaos_probabilities(method)
    if p_req > 0 and random.random() < p_req:
        raise RpcConnectionError(f"[chaos] injected request failure for {method}")


def maybe_inject_response_failure(method: str) -> None:
    _, p_resp = _chaos_probabilities(method)
    if p_resp > 0 and random.random() < p_resp:
        raise RpcConnectionError(f"[chaos] injected response failure for {method}")


# ---------------------------------------------------------------------------
# Framing helpers (multi-segment scatter-gather; see module docstring)
# ---------------------------------------------------------------------------

# A u64 no legacy frame length can ever equal (16 EiB range): marks a
# multi-segment frame. Legacy lengths are sanity-capped well below it.
_MULTISEG_MAGIC = 0xFFFF_FFFF_5347_0001  # 'SG' + version 1
_NSEG = struct.Struct("<I")
_MAX_FRAME = 1 << 48
_MAX_SEGS = 1 << 20
# iovecs per sendmsg call: far below IOV_MAX (1024), large enough that a
# typical message (header + meta + a few arrays) goes in one syscall
_IOV_CAP = 64
# below this total size a single concatenated sendall beats the iovec
# setup cost; above it the copy dominates and vectored wins
_VECTOR_MIN = 1 << 16


def encode_message(msg, allow_multiseg: Optional[bool] = None) -> list:
    """Encode an RPC message into its wire buffers (scatter-gather list).

    Data-bearing messages (pickle-5 produced out-of-band buffers) become
    one multi-segment frame — but only once the buffers total
    FRAME_OOB_MIN: below that the extra per-segment recv(2)s on the
    receiver cost more than the memcpy they save (a 16-byte ndarray in
    task args must not quadruple the frame's syscall count), so small
    ones re-pickle in-band. Pure control messages keep the legacy
    single-segment frame. allow_multiseg=False (or config.rpc_multiseg
    off) forces legacy framing — which any pre-multiseg reader
    understands."""
    if allow_multiseg is None:
        allow_multiseg = config.rpc_multiseg
    if allow_multiseg:
        meta, views = serialization.serialize(msg)
        if not views:
            return [_LEN.pack(len(meta)), meta]
        nsegs = 1 + len(views)
        if (
            nsegs <= _MAX_SEGS
            and sum(v.nbytes for v in views) >= serialization.FRAME_OOB_MIN
        ):
            header = struct.pack(
                f"<QI{nsegs}Q", _MULTISEG_MAGIC, nsegs, len(meta),
                *[v.nbytes for v in views],
            )
            return [header, meta, *views]
        # small (or absurdly fragmented) buffers: re-pickle in-band. The
        # second pickling pass is the price of not knowing whether
        # buffers exist before serializing; it is bounded by the 32 KiB
        # floor and beats the per-segment recv(2)s it avoids.
    payload = serialization.dumps(msg)
    return [_LEN.pack(len(payload)), payload]


def _sendmsg_all(sock: socket.socket, bufs: list) -> None:
    """Vectored send of every buffer, resuming across partial sends.
    Never mutates ``bufs`` (pre-encoded push frames are shared across
    subscriber connections)."""
    views = serialization.byte_views(bufs)
    i = 0
    while i < len(views):
        sent = sock.sendmsg(views[i:i + _IOV_CAP])
        if sent <= 0:
            raise ConnectionError("sendmsg made no progress")
        i = serialization.advance_views(views, i, sent)


def _send_buffers(sock: socket.socket, bufs: list, lock: threading.Lock) -> None:
    total = 0
    for b in bufs:
        total += b.nbytes if isinstance(b, memoryview) else len(b)
    with lock:
        if total <= _VECTOR_MIN or not hasattr(sock, "sendmsg"):
            # sendall is one C call: atomic w.r.t. async cancel
            # interrupts (PyThreadState_SetAsyncExc only fires between
            # bytecodes), so a small frame can never tear
            sock.sendall(b"".join(bufs))
        else:
            try:
                _sendmsg_all(sock, bufs)
            except BaseException:
                # the vectored send is a Python loop, so a stray cancel
                # interrupt (or any error) can strand a PARTIAL frame on
                # the wire — the multiplexed stream is unrecoverable
                # past that point. Kill the socket so both ends resync
                # via reconnect instead of unpickling garbage.
                try:
                    sock.close()
                except OSError:
                    pass
                raise


def _send_message(sock: socket.socket, msg, lock: threading.Lock) -> None:
    _send_buffers(sock, encode_message(msg), lock)


def _recv_exact_into(sock: socket.socket, view: memoryview) -> None:
    got = 0
    n = view.nbytes
    while got < n:
        r = sock.recv_into(view[got:], n - got)
        if r <= 0:
            raise ConnectionError("socket closed")
        got += r


def _recv_exact(sock: socket.socket, n: int) -> bytearray:
    """Receive exactly n bytes into ONE preallocated buffer (no chunk
    list + join copy)."""
    buf = bytearray(n)
    if n:
        _recv_exact_into(sock, memoryview(buf))
    return buf


def recv_message(sock: socket.socket):
    """Read one frame (either kind) and deserialize it."""
    (first,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
    if first != _MULTISEG_MAGIC:
        if first > _MAX_FRAME:
            raise ConnectionError(f"bad frame length {first:#x}")
        return serialization.loads(_recv_exact(sock, first))
    (nsegs,) = _NSEG.unpack(_recv_exact(sock, _NSEG.size))
    if not 0 < nsegs <= _MAX_SEGS:
        raise ConnectionError(f"bad multiseg frame: nsegs={nsegs}")
    lens = struct.unpack(f"<{nsegs}Q", _recv_exact(sock, 8 * nsegs))
    if any(ln > _MAX_FRAME for ln in lens):
        raise ConnectionError("bad multiseg frame: oversized segment")
    meta = _recv_exact(sock, lens[0])
    buffers = [_recv_exact(sock, ln) for ln in lens[1:]]
    return serialization.deserialize(meta, buffers)


# ---------------------------------------------------------------------------
# Dispatch pool
# ---------------------------------------------------------------------------


class _DispatchPool:
    """Cached dispatcher threads for request handling.

    Thread-per-request semantics at thread-pool cost: an idle dispatcher
    (LIFO, so the cache-hot one goes first) is reused when available and
    a fresh thread is spawned when none is — submissions NEVER queue, so
    a handler blocked for hours (get_object waits) cannot delay an
    unrelated request, unlike a fixed-size executor. Idle dispatchers
    retire after _IDLE_S, and at most _MAX_IDLE park at once — a request
    burst must not leave a thread pile behind it (steady-state traffic
    only ever needs a few hot threads). Spawn cost on this class of box
    is ~30 µs per request; at tens of kRPC/s that was a measurable slice
    of every control-plane round trip."""

    _IDLE_S = 30.0
    _MAX_IDLE = 6

    def __init__(self, name: str):
        self._name = name
        self._lock = threading.Lock()
        self._idle: list = []
        self._seq = 0

    def submit(self, fn, args) -> None:
        with self._lock:
            if self._idle:
                worker = self._idle.pop()
                worker.job = (fn, args)
                worker.evt.set()
                return
            self._seq += 1
            seq = self._seq
        threading.Thread(
            target=self._loop, args=((fn, args),),
            name=f"{self._name}-disp-{seq}", daemon=True,
        ).start()

    def _loop(self, job) -> None:
        while True:
            fn, args = job
            try:
                fn(*args)
            except BaseException:  # noqa: BLE001 — incl. stray cancel interrupts
                logger.debug("dispatcher: handler raised", exc_info=True)
            me = _DispatchSlot()
            with self._lock:
                if len(self._idle) >= self._MAX_IDLE:
                    return  # enough warm dispatchers parked already
                self._idle.append(me)
            try:
                signaled = me.evt.wait(self._IDLE_S)
            except BaseException:  # noqa: BLE001 — stray KeyboardInterrupt
                # (cancel aimed at a reused thread ident) while parked: a
                # dead thread must not linger in the idle list where
                # submit() would hand it a job that never runs
                signaled = None
            with self._lock:
                if me in self._idle:
                    self._idle.remove(me)
                    return  # timed out (or interrupted) while unclaimed
            # claimed by submit() concurrently with the wakeup/interrupt:
            # the job handoff is ours to honor — including across further
            # stray interrupts (same hazard as the parked wait above; an
            # unguarded wait here would drop a request submit() already
            # handed us)
            while not signaled:
                try:
                    signaled = me.evt.wait(1.0)
                except BaseException:  # noqa: BLE001
                    pass
            job = me.job


class _DispatchSlot:
    __slots__ = ("evt", "job")

    def __init__(self):
        self.evt = threading.Event()
        self.job = None


# ---------------------------------------------------------------------------
# Server
# ---------------------------------------------------------------------------


class ClientConnection:
    """Server-side handle to one connected client (for pushes)."""

    def __init__(self, sock: socket.socket, addr):
        self.sock = sock
        self.addr = addr
        self.send_lock = threading.Lock()
        self.alive = True
        self.meta: Dict[str, Any] = {}  # server code can stash identity here

    def push(self, topic: str, payload: Any) -> bool:
        return self.push_encoded(encode_message(("push", topic, payload)))

    def push_encoded(self, bufs: list) -> bool:
        """Send a pre-encoded push frame (encode_message output). Fan-out
        callers — pubsub publish — encode the message ONCE per topic
        publish and reuse the buffers across every subscriber connection
        instead of re-pickling per subscriber."""
        if not self.alive:
            return False
        try:
            _send_buffers(self.sock, bufs, self.send_lock)
            return True
        except OSError:
            self.alive = False
            return False


class RpcServer:
    """Threaded TCP RPC server.

    Handlers: ``server.register(name, fn)``; fn(conn, *args, **kwargs).
    The first argument is the ClientConnection so handlers can register
    subscribers / track identity.
    """

    def __init__(self, name: str, host: str = "127.0.0.1", port: int = 0):
        self.name = name
        self._handlers: Dict[str, Callable] = {}
        self._raw_handlers: Dict[str, Callable] = {}
        self._pool = _DispatchPool(name)
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self._listener.listen(512)
        self.host, self.port = self._listener.getsockname()
        self._stopped = threading.Event()
        self._conns: Dict[int, ClientConnection] = {}
        self._conns_lock = threading.Lock()
        self._accept_thread: Optional[threading.Thread] = None
        self.on_disconnect: Optional[Callable[[ClientConnection], None]] = None
        # Optional hook run in the dispatch thread after a handler returns
        # and before its reply is sent (skipped for one-way calls). The
        # control store points this at the WAL group-commit barrier so an
        # ack still implies durability under batched writes.
        self.post_dispatch: Optional[Callable[[], None]] = None

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def register(self, name: str, fn: Callable) -> None:
        self._handlers[name] = fn

    def register_raw(self, name: str, fn: Callable) -> None:
        """Register an in-order handler: called synchronously in the
        connection read loop as fn(conn, req_id, args, kwargs). The handler
        must not block; it replies later via RpcServer.reply(). Used for
        actor task queues where per-caller submission order must be
        preserved (reference: ordered actor execution queues,
        src/ray/core_worker/task_execution/)."""
        self._raw_handlers[name] = fn

    @staticmethod
    def reply(conn: "ClientConnection", req_id, ok: bool, payload: Any) -> None:
        if req_id is None:
            return
        try:
            _send_message(
                conn.sock, ("resp", req_id, ok, payload), conn.send_lock,
            )
        except OSError:
            conn.alive = False

    def register_instance(self, obj: Any, prefix: str = "") -> None:
        """Register every public method of obj whose name starts with rpc_."""
        for attr in dir(obj):
            if attr.startswith("rpc_"):
                self._handlers[prefix + attr[4:]] = getattr(obj, attr)

    def start(self) -> None:
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name=f"{self.name}-accept", daemon=True
        )
        self._accept_thread.start()

    def stop(self) -> None:
        self._stopped.set()
        try:
            # shutdown() BEFORE close: close(2) does not wake a thread
            # blocked in accept(2) — it would stay parked on the old fd
            # NUMBER, and once the kernel reuses that number for a new
            # listener in this process, the zombie thread steals and
            # instantly drops the new server's connections
            self._listener.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._listener.close()
        except OSError:
            pass
        with self._conns_lock:
            conns = list(self._conns.values())
        for c in conns:
            try:
                c.sock.close()
            except OSError:
                pass

    def _accept_loop(self) -> None:
        while not self._stopped.is_set():
            try:
                sock, addr = self._listener.accept()
            except OSError:
                return
            if self._stopped.is_set():
                # belt for the fd-reuse race: a stolen accept on a reused
                # fd must drop the socket without serving it
                try:
                    sock.close()
                except OSError:
                    pass
                return
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            conn = ClientConnection(sock, addr)
            with self._conns_lock:
                self._conns[id(conn)] = conn
            threading.Thread(
                target=self._serve_conn, args=(conn,),
                name=f"{self.name}-conn", daemon=True,
            ).start()

    def adopt(self, sock: socket.socket, addr) -> None:
        """Serve a pre-connected socket as if it had arrived via accept()
        — the driver-gateway reverse tunnel hands sockets in this way
        (utils/gateway.py ReverseListener)."""
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        conn = ClientConnection(sock, addr)
        with self._conns_lock:
            self._conns[id(conn)] = conn
        threading.Thread(
            target=self._serve_conn, args=(conn,),
            name=f"{self.name}-conn", daemon=True,
        ).start()

    def _serve_conn(self, conn: ClientConnection) -> None:
        try:
            while not self._stopped.is_set():
                msg = recv_message(conn.sock)
                kind = msg[0]
                if kind == "req":
                    _, req_id, method, args, kwargs = msg
                    raw = self._raw_handlers.get(method)
                    if raw is not None:
                        try:
                            raw(conn, req_id, args, kwargs)
                        except Exception as e:  # noqa: BLE001
                            self.reply(conn, req_id, False,
                                       RemoteError(f"{type(e).__name__}: {e}",
                                                   traceback.format_exc()))
                        continue
                    self._pool.submit(
                        self._dispatch, (conn, req_id, method, args, kwargs)
                    )
                else:
                    logger.warning("%s: unexpected message kind %r", self.name, kind)
        except (ConnectionError, OSError):
            pass
        except Exception:  # noqa: BLE001 — garbage frame (peer desync)
            logger.warning(
                "%s: dropping desynced connection", self.name, exc_info=True
            )
        except KeyboardInterrupt:
            # stray cancel interrupt on a reused thread ident: tear the
            # connection down cleanly (callers retry on conn loss) rather
            # than spewing an unhandled-thread traceback
            pass
        finally:
            conn.alive = False
            with self._conns_lock:
                self._conns.pop(id(conn), None)
            if self.on_disconnect is not None:
                try:
                    self.on_disconnect(conn)
                except Exception:
                    logger.exception("%s: on_disconnect handler failed", self.name)
            try:
                conn.sock.close()
            except OSError:
                pass

    def _dispatch(self, conn, req_id, method, args, kwargs) -> None:
        try:
            handler = self._handlers.get(method)
            if handler is None:
                raise RpcError(f"no handler for method {method!r} on {self.name}")
            result = handler(conn, *args, **kwargs)
            ok, payload = True, result
        except KeyboardInterrupt:
            # a cancel interrupt aimed at a task that already finished can
            # land in this (per-request) dispatch thread: drop the
            # connection — conn loss is the one failure every owner-side
            # ladder classifies as retryable (a RemoteError reply would
            # read as a permanent app failure)
            conn.alive = False
            try:
                conn.sock.close()
            except OSError:
                pass
            return
        except Exception as e:  # noqa: BLE001 — faithfully forward any error
            ok = False
            payload = RemoteError(
                f"{type(e).__name__}: {e}", traceback.format_exc()
            ) if not isinstance(e, RemoteError) else e
        if req_id is None:  # one-way call
            return
        if self.post_dispatch is not None:
            # ack barrier (e.g. WAL group commit): runs after the handler
            # released its locks but before the caller can observe the
            # reply. A barrier failure must fail the ack — the op may not
            # be durable.
            try:
                self.post_dispatch()
            except Exception as e:  # noqa: BLE001
                if ok:
                    ok = False
                    payload = RemoteError(
                        f"{type(e).__name__}: {e}", traceback.format_exc()
                    )
        try:
            _send_message(
                conn.sock, ("resp", req_id, ok, payload), conn.send_lock,
            )
        except OSError:
            conn.alive = False
        except KeyboardInterrupt:
            # stray cancel interrupt mid-send: a partial frame may be on
            # the wire, so resending would desync the multiplexed stream
            # — drop the connection (the caller retries on conn loss)
            conn.alive = False
            try:
                conn.sock.close()
            except OSError:
                pass
            return


# ---------------------------------------------------------------------------
# Client
# ---------------------------------------------------------------------------


class RpcClient:
    """Thread-safe client multiplexing calls over one connection."""

    def __init__(self, address: str, name: str = "client",
                 resolver: Optional[Callable[[], Optional[str]]] = None):
        self.address = address
        self.name = name
        host, port = address.rsplit(":", 1)
        self._host, self._port = host, int(port)
        self._sock: Optional[socket.socket] = None
        self._send_lock = threading.Lock()
        self._conn_lock = threading.Lock()
        self._pending: Dict[int, "_PendingCall"] = {}
        self._pending_lock = threading.Lock()
        self._next_id = 0
        self._reader: Optional[threading.Thread] = None
        self._push_handlers: Dict[str, Callable[[Any], None]] = {}
        self._closed = False
        # HA re-attach (core/ha/reattach.py): a resolver makes this client
        # survive a head bounce — reconnects consult it for a possibly-
        # updated address, retryable calls keep redialing for up to
        # config.ha_reattach_max_s, and reconnect callbacks restore
        # connection-scoped server state (pubsub subscriptions).
        self._resolver = resolver
        self._reconnect_cbs: list = []
        self._ever_connected = False

    # -- connection management --

    def connect(self) -> None:
        is_reconnect = False
        with self._conn_lock:
            if self._sock is not None:
                return
            if self._resolver is not None and self._ever_connected:
                # the head may have come back at a new address
                try:
                    new = self._resolver()
                except Exception:  # noqa: BLE001 — resolver is best-effort
                    new = None
                if new and new != self.address:
                    logger.info(
                        "%s: target moved %s -> %s",
                        self.name, self.address, new,
                    )
                    self.address = new
                    host, port = new.rsplit(":", 1)
                    self._host, self._port = host, int(port)
            deadline = time.monotonic() + config.rpc_connect_timeout_s
            last_err: Optional[Exception] = None
            connected = False
            from ray_tpu.utils import gateway as gateway_mod

            gw = gateway_mod.gateway_address()
            while time.monotonic() < deadline:
                try:
                    if gw is not None and self.address != gw:
                        # remote-driver mode: every connection rides the
                        # head gateway (utils/gateway.py)
                        sock = gateway_mod.open_tunnel(
                            self.address,
                            timeout=config.rpc_connect_timeout_s,
                        )
                    else:
                        sock = socket.create_connection(
                            (self._host, self._port),
                            timeout=config.rpc_connect_timeout_s,
                        )
                    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                    sock.settimeout(None)
                    self._sock = sock
                    self._reader = threading.Thread(
                        target=self._read_loop, name=f"{self.name}-read", daemon=True
                    )
                    self._reader.start()
                    is_reconnect = self._ever_connected
                    self._ever_connected = True
                    connected = True
                    break
                except OSError as e:
                    last_err = e
                    time.sleep(0.05)
            if not connected:
                raise RpcConnectionError(
                    f"cannot connect to {self.address}: {last_err}"
                )
        if is_reconnect:
            # outside the conn lock: callbacks typically issue calls on
            # this client (e.g. re-subscribing pubsub topics)
            for cb in list(self._reconnect_cbs):
                try:
                    cb()
                except Exception:  # noqa: BLE001 — must not break connect
                    logger.exception("%s: reconnect callback failed", self.name)

    def add_reconnect_callback(self, cb: Callable[[], None]) -> None:
        """Run cb() after every re-established connection (not the first
        connect). Used to restore connection-scoped server state — pubsub
        subscriptions — after a head bounce."""
        self._reconnect_cbs.append(cb)

    def close(self) -> None:
        self._closed = True
        with self._conn_lock:
            if self._sock is not None:
                try:
                    self._sock.close()
                except OSError:
                    pass
                self._sock = None

    def _read_loop(self) -> None:
        sock = self._sock
        try:
            while True:
                msg = recv_message(sock)
                if msg[0] == "resp":
                    _, req_id, ok, payload = msg
                    with self._pending_lock:
                        pending = self._pending.pop(req_id, None)
                    if pending is not None:
                        if pending.t0 is not None and core_metrics.ENABLED:
                            core_metrics.rpc_client_latency_s.observe(
                                time.monotonic() - pending.t0,
                                tags={
                                    "family": _method_family(pending.method)
                                },
                            )
                        pending.set(ok, payload)
                elif msg[0] == "push":
                    _, topic, payload = msg
                    handler = self._push_handlers.get(topic)
                    if handler is not None:
                        try:
                            handler(payload)
                        except Exception:
                            logger.exception("push handler for %r failed", topic)
        except (ConnectionError, OSError):
            pass
        except Exception:  # noqa: BLE001 — garbage frame (peer desync)
            logger.warning(
                "%s: dropping desynced connection", self.name, exc_info=True
            )
        finally:
            try:
                sock.close()  # a desynced-but-alive socket must not linger
            except OSError:
                pass
            err = RpcConnectionError(f"connection to {self.address} lost")
            with self._pending_lock:
                pending = list(self._pending.values())
                self._pending.clear()
            for p in pending:
                p.set(False, err)
            with self._conn_lock:
                if self._sock is sock:
                    self._sock = None

    def on_push(self, topic: str, handler: Callable[[Any], None]) -> None:
        self._push_handlers[topic] = handler

    # -- calls --

    def call(
        self,
        method: str,
        *args,
        timeout_s: Optional[float] = None,
        retryable: bool = False,
        **kwargs,
    ) -> Any:
        timeout_s = timeout_s if timeout_s is not None else config.rpc_request_timeout_s
        attempts = 1 + (config.rpc_max_retries if retryable else 0)
        # HA clients (resolver set) ride out a whole head bounce: retryable
        # calls keep redialing on CONNECTION failures until the re-attach
        # budget runs out, not just for rpc_max_retries quick attempts.
        # (Only idempotent calls are marked retryable, so replaying an
        # in-flight request whose reply was lost in the bounce is safe.)
        reattach_deadline: Optional[float] = None
        if retryable and self._resolver is not None:
            reattach_deadline = time.monotonic() + float(
                config.ha_reattach_max_s
            )
        last_err: Optional[Exception] = None
        attempt = 0  # timeout/plain-retry budget (rpc_max_retries)
        redials = 0  # reattach redials — budgeted by TIME, not count, so
        # they must not consume the attempt budget: after riding out a
        # bounce, a slow first answer still gets its full retry allowance
        while True:
            try:
                maybe_inject_request_failure(method)
                result = self._call_once(method, args, kwargs, timeout_s)
                maybe_inject_response_failure(method)
                return result
            except (RpcConnectionError, RpcTimeout) as e:
                last_err = e
                if self._closed:
                    raise
                if (
                    isinstance(e, RpcConnectionError)
                    and reattach_deadline is not None
                ):
                    if time.monotonic() < reattach_deadline:
                        redials += 1
                        time.sleep(
                            min(config.rpc_retry_delay_s * (2 ** min(redials, 4)), 1.0)
                        )
                        continue
                    raise
                attempt += 1
                if attempt < attempts:
                    time.sleep(config.rpc_retry_delay_s * (2 ** (attempt - 1)))
                    continue
                raise
            except RemoteError:
                raise
        raise last_err  # pragma: no cover

    def call_async(self, method: str, *args, **kwargs) -> "_PendingCall":
        """Send a request now; wait for the reply later via handle.wait().

        The frame is on the wire when this returns, so two call_async()s
        made in order arrive at the server in order — the property actor
        submission uses for per-caller ordered execution."""
        sock = self._ensure_sock()
        with self._pending_lock:
            self._next_id += 1
            req_id = self._next_id
            pending = _PendingCall()
            self._pending[req_id] = pending
        if core_metrics.ENABLED:
            pending.method = method
            pending.t0 = time.monotonic()
        try:
            _send_message(
                sock, ("req", req_id, method, args, kwargs), self._send_lock
            )
        except OSError as e:
            with self._pending_lock:
                self._pending.pop(req_id, None)
            raise RpcConnectionError(str(e)) from e
        return pending

    def _ensure_sock(self) -> socket.socket:
        """Snapshot the socket — _read_loop may null self._sock at any
        moment; operating on a local copy turns that race into an OSError
        (mapped to RpcConnectionError) instead of an AttributeError."""
        sock = self._sock
        if sock is None:
            self.connect()
            sock = self._sock
        if sock is None:
            raise RpcConnectionError(f"connection to {self.address} lost")
        return sock

    def call_oneway(self, method: str, *args, **kwargs) -> None:
        sock = self._ensure_sock()
        try:
            _send_message(
                sock, ("req", None, method, args, kwargs), self._send_lock
            )
        except OSError as e:
            raise RpcConnectionError(str(e)) from e

    def _call_once(self, method, args, kwargs, timeout_s) -> Any:
        sock = self._ensure_sock()
        with self._pending_lock:
            self._next_id += 1
            req_id = self._next_id
            pending = _PendingCall()
            self._pending[req_id] = pending
        if core_metrics.ENABLED:
            pending.method = method
            pending.t0 = time.monotonic()
        try:
            _send_message(
                sock, ("req", req_id, method, args, kwargs), self._send_lock
            )
        except OSError as e:
            with self._pending_lock:
                self._pending.pop(req_id, None)
            raise RpcConnectionError(str(e)) from e
        if not pending.event.wait(timeout_s):
            with self._pending_lock:
                self._pending.pop(req_id, None)
            raise RpcTimeout(f"{method} on {self.address} timed out after {timeout_s}s")
        if not pending.ok:
            raise pending.payload
        return pending.payload


class _PendingCall:
    __slots__ = (
        "event", "ok", "payload", "_cbs", "_cb_lock", "_done",
        "t0", "method",
    )

    def __init__(self):
        self.event = threading.Event()
        self.ok = False
        self.payload = None
        self._cbs = []
        self._cb_lock = threading.Lock()
        self._done = False
        # set when core metrics are enabled: the read loop observes the
        # round-trip into rt_rpc_client_latency_s on reply
        self.t0 = None
        self.method = None

    def set(self, ok: bool, payload: Any) -> None:
        self.ok = ok
        self.payload = payload
        self.event.set()
        with self._cb_lock:
            self._done = True
            cbs, self._cbs = self._cbs, []
        self._run_cbs(cbs)

    def add_done_callback(self, cb) -> None:
        """Invoke cb(self) once the reply (or failure) lands; every
        registered callback fires exactly once, including ones added
        after completion (concurrent.futures semantics). Runs on the
        client read-loop thread — keep it cheap (enqueue, don't
        process)."""
        with self._cb_lock:
            if not self._done:
                self._cbs.append(cb)
                return
        self._run_cbs([cb])

    def _run_cbs(self, cbs) -> None:
        for cb in cbs:
            try:
                cb(self)
            except Exception:  # noqa: BLE001 — must not kill the read loop
                pass

    def wait(self, timeout_s: Optional[float] = None) -> Any:
        if not self.event.wait(timeout_s):
            raise RpcTimeout(f"call timed out after {timeout_s}s")
        if not self.ok:
            raise self.payload
        return self.payload


class ClientPool:
    """Cache of RpcClients keyed by address (reference: client pools in
    src/ray/rpc/)."""

    def __init__(self, name: str = "pool"):
        self._name = name
        self._clients: Dict[str, RpcClient] = {}
        self._lock = threading.Lock()

    def get(self, address: str) -> RpcClient:
        with self._lock:
            client = self._clients.get(address)
            if client is None:
                client = RpcClient(address, name=f"{self._name}->{address}")
                self._clients[address] = client
            return client

    def drop(self, address: str) -> None:
        with self._lock:
            client = self._clients.pop(address, None)
        if client is not None:
            client.close()

    def close_all(self) -> None:
        with self._lock:
            clients = list(self._clients.values())
            self._clients.clear()
        for c in clients:
            c.close()
