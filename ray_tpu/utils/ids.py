"""Binary identifiers for jobs, tasks, actors and objects.

Design follows the reference's lineage-embedding scheme
(src/ray/common/id.h): an ObjectID embeds the TaskID that created it plus an
index; a TaskID embeds the JobID (and ActorID for actor tasks). This lets any
process recover "which task produced this object" without a directory lookup
— the property the ownership and lineage-reconstruction protocols rely on.

Sizes (bytes):
  JobID    4
  ActorID  4 (job) + 8 (unique)            = 12
  TaskID   12 (actor-or-padding) + 8 (unique) = 20
  ObjectID 20 (task) + 4 (index)           = 24
"""

from __future__ import annotations

import os
import threading

_JOB_LEN = 4
_ACTOR_UNIQUE_LEN = 8
_ACTOR_LEN = _JOB_LEN + _ACTOR_UNIQUE_LEN  # 12
_TASK_UNIQUE_LEN = 8
_TASK_LEN = _ACTOR_LEN + _TASK_UNIQUE_LEN  # 20
_INDEX_LEN = 4
_OBJECT_LEN = _TASK_LEN + _INDEX_LEN  # 24

_NIL_ACTOR_UNIQUE = b"\x00" * _ACTOR_UNIQUE_LEN


class BaseID:
    """Immutable binary id with hex round-tripping."""

    SIZE = 0
    __slots__ = ("_binary", "_hash")

    def __init__(self, binary: bytes):
        if len(binary) != self.SIZE:
            raise ValueError(
                f"{type(self).__name__} requires {self.SIZE} bytes, got {len(binary)}"
            )
        object.__setattr__(self, "_binary", binary)
        # ids are dict/set keys on every hot path: hash once
        object.__setattr__(
            self, "_hash", hash((type(self).__name__, binary))
        )

    def __setattr__(self, name, value):  # immutability
        raise AttributeError(f"{type(self).__name__} is immutable")

    def binary(self) -> bytes:
        return self._binary

    def hex(self) -> str:
        return self._binary.hex()

    @classmethod
    def from_hex(cls, hex_str: str):
        return cls(bytes.fromhex(hex_str))

    @classmethod
    def from_random(cls):
        return cls(os.urandom(cls.SIZE))

    @classmethod
    def nil(cls):
        return cls(b"\x00" * cls.SIZE)

    def is_nil(self) -> bool:
        return self._binary == b"\x00" * self.SIZE

    def __eq__(self, other):
        return type(other) is type(self) and other._binary == self._binary

    def __hash__(self):
        return self._hash

    def __repr__(self):
        return f"{type(self).__name__}({self.hex()})"

    def __reduce__(self):
        return (type(self), (self._binary,))


class JobID(BaseID):
    SIZE = _JOB_LEN
    __slots__ = ()

    _counter = [0]
    _lock = threading.Lock()

    @classmethod
    def from_int(cls, value: int) -> "JobID":
        return cls(value.to_bytes(_JOB_LEN, "little"))

    def int_value(self) -> int:
        return int.from_bytes(self._binary, "little")


class ActorID(BaseID):
    SIZE = _ACTOR_LEN
    __slots__ = ()

    @classmethod
    def of(cls, job_id: JobID) -> "ActorID":
        return cls(job_id.binary() + os.urandom(_ACTOR_UNIQUE_LEN))

    def job_id(self) -> JobID:
        return JobID(self._binary[:_JOB_LEN])


class TaskID(BaseID):
    SIZE = _TASK_LEN
    __slots__ = ()

    @classmethod
    def for_normal_task(cls, job_id: JobID) -> "TaskID":
        prefix = job_id.binary() + _NIL_ACTOR_UNIQUE
        return cls(prefix + os.urandom(_TASK_UNIQUE_LEN))

    @classmethod
    def for_actor_task(cls, actor_id: ActorID) -> "TaskID":
        return cls(actor_id.binary() + os.urandom(_TASK_UNIQUE_LEN))

    @classmethod
    def for_driver(cls, job_id: JobID) -> "TaskID":
        """The implicit "driver task" that owns objects created by the driver."""
        prefix = job_id.binary() + _NIL_ACTOR_UNIQUE
        return cls(prefix + b"\xff" * _TASK_UNIQUE_LEN)

    def job_id(self) -> JobID:
        return JobID(self._binary[:_JOB_LEN])

    def actor_id(self) -> ActorID:
        return ActorID(self._binary[:_ACTOR_LEN])

    def is_actor_task(self) -> bool:
        return self._binary[_JOB_LEN:_ACTOR_LEN] != _NIL_ACTOR_UNIQUE


class ObjectID(BaseID):
    SIZE = _OBJECT_LEN
    __slots__ = ()

    @classmethod
    def from_task(cls, task_id: TaskID, index: int) -> "ObjectID":
        if not 0 <= index < 2**32:
            raise ValueError(f"return index out of range: {index}")
        return cls(task_id.binary() + index.to_bytes(_INDEX_LEN, "little"))

    def task_id(self) -> TaskID:
        return TaskID(self._binary[:_TASK_LEN])

    def job_id(self) -> JobID:
        return JobID(self._binary[:_JOB_LEN])

    def index(self) -> int:
        return int.from_bytes(self._binary[_TASK_LEN:], "little")


class NodeID(BaseID):
    SIZE = 16
    __slots__ = ()


class WorkerID(BaseID):
    SIZE = 16
    __slots__ = ()


class PlacementGroupID(BaseID):
    SIZE = 16
    __slots__ = ()
