"""Compiled graphs: static actor DAGs over pre-allocated shm channels.

Parity: Ray Compiled Graphs (aDAG) — reference
python/ray/dag/compiled_dag_node.py:805 (``experimental_compile``),
``execute`` :2546, DAG nodes python/ray/dag/dag_node.py, channels
python/ray/experimental/channel/shared_memory_channel.py.

The per-call RPC path (submit → lease → push → reply) costs ~ms; a
static inference/pipeline loop re-running the same actor methods can
amortize all of it away. Compiling a DAG:

- allocates one :class:`ray_tpu.core.channels.ShmChannel` per
  cross-process edge (driver→actor, actor→actor, actor→driver) — a
  mutable shm RING of ``channel_slots`` message slots reused every call
  (one mmap, then one scatter-gather copy + seqlock flip per message),
  so exec loops stream up to ``channel_slots`` rounds ahead of their
  consumers;
- parks a persistent exec loop on every participating actor (a system
  actor task, ``__rt_dag_exec_loop__``): each round it reads its input
  channels, runs its bound methods in topological order, and writes
  results downstream — no scheduler, no lease, no RPC framing on the
  hot path;
- ``dag.execute(x)`` = write the input channel(s), read the output
  channel(s): µs-scale per call (bench_core.py measures the ratio vs
  ``actor.f.remote()`` + ``get``).

Same-host only (shm channels), like the reference's default channel
tier; the compiled loop occupies one executor slot on each actor until
``teardown()``. Usage:

    with InputNode() as inp:
        dag = b.g.bind(a.f.bind(inp))
    cdag = dag.experimental_compile()
    out = cdag.execute(5).get()
    cdag.teardown()
"""

from __future__ import annotations

import itertools
import logging
import threading
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.core.channels import ShmChannel
from ray_tpu.utils import serialization

logger = logging.getLogger(__name__)

_STOP = b"__rt_dag_stop__"
_node_counter = itertools.count()


def _is_stop(frame) -> bool:
    """A raw channel frame is the teardown sentinel (RpcChannel reads
    can surface Frame-wrapped payloads; the sentinel is tiny and always
    arrives as plain bytes)."""
    return isinstance(frame, (bytes, bytearray)) and frame == _STOP


def send_value(channels, value: Any,
               timeout_s: Optional[float] = 60.0) -> None:
    """Serialize once, scatter-gather the frame into every channel —
    pickle-5 out-of-band buffers are copied straight into each shm slot
    (or ride as multiseg segments on an RpcChannel), never joined into
    an intermediate in-band blob."""
    meta, views = serialization.serialize(value)
    parts = serialization.frame_parts(meta, views)
    for ch in channels:
        ch.write_views(parts, timeout_s=timeout_s)


class DAGNode:
    def __init__(self):
        self._id = next(_node_counter)

    def experimental_compile(
        self,
        channel_capacity: int = 4 * 1024 * 1024,
        max_inflight: int = 2,
        channel_slots: Optional[int] = None,
    ) -> "CompiledDAG":
        """Compile the static graph: allocate channels, park exec loops.

        Backpressure contract: at most ``max_inflight`` ``execute()``
        rounds may be unconsumed (``get()`` not yet called) — the next
        ``execute()`` past that raises instead of blocking (parity:
        ``RayCgraphCapacityExceeded``). Every channel is a ring of
        ``channel_slots`` message slots (default: ``max_inflight``), so
        exec loops stream that many rounds ahead before a write blocks
        on its consumer; with the default sizing the driver-side
        ``max_inflight`` check always trips BEFORE an input ring can
        fill, so ``execute()`` never blocks inside its lock. Passing
        ``channel_slots < max_inflight`` is allowed but re-introduces
        writer-side blocking once the smaller ring fills. Each slot
        holds one message of up to ``channel_capacity`` bytes.
        """
        return CompiledDAG(self, channel_capacity, max_inflight,
                           channel_slots)


class InputNode(DAGNode):
    """The driver-supplied input (one per DAG)."""

    def __enter__(self) -> "InputNode":
        return self

    def __exit__(self, *exc) -> None:
        return None


class ClassMethodNode(DAGNode):
    """``actor.method.bind(*args)`` — one actor method invocation in the
    static graph. Args may be DAGNodes or plain (constant) values."""

    def __init__(self, actor_handle, method_name: str, args: Tuple[Any, ...]):
        super().__init__()
        self.actor = actor_handle
        self.method_name = method_name
        self.args = args


class MultiOutputNode(DAGNode):
    """Terminal node returning several leaves as a list."""

    def __init__(self, nodes: List[DAGNode]):
        super().__init__()
        self.nodes = list(nodes)


def _topo_collect(root: DAGNode) -> List[DAGNode]:
    """Topological order of the DAG reachable from ``root``."""
    order: List[DAGNode] = []
    seen: Dict[int, bool] = {}

    def visit(n: DAGNode):
        if n._id in seen:
            return
        seen[n._id] = True
        if isinstance(n, ClassMethodNode):
            for a in n.args:
                if isinstance(a, DAGNode):
                    visit(a)
        elif isinstance(n, MultiOutputNode):
            for c in n.nodes:
                visit(c)
        order.append(n)

    visit(root)
    return order


class CompiledDAGRef:
    """Result handle for one ``execute`` round (FIFO: rounds must be
    consumed in submission order — each output channel holds one
    in-flight message, which is also the backpressure bound)."""

    def __init__(self, cdag: "CompiledDAG", seq: int):
        self._cdag = cdag
        self._seq = seq
        self._value: Any = None
        self._error: Optional[BaseException] = None
        self._done = False

    def get(self, timeout_s: Optional[float] = 60.0) -> Any:
        if not self._done:
            try:
                self._value = self._cdag._read_output(self._seq, timeout_s)
            except Exception as e:  # noqa: BLE001 — cache for re-gets
                self._error = e
                raise
            finally:
                self._done = True
        if self._error is not None:
            raise self._error
        return self._value


class CompiledDAG:
    """The compiled form: channels allocated, exec loops parked."""

    def __init__(self, root: DAGNode, channel_capacity: int,
                 max_inflight: int = 2,
                 channel_slots: Optional[int] = None):
        from ray_tpu.core import worker as worker_mod

        if max_inflight < 1:
            raise ValueError(f"max_inflight must be >= 1, got {max_inflight}")
        if channel_slots is None:
            channel_slots = max_inflight
        if channel_slots < 1:
            raise ValueError(
                f"channel_slots must be >= 1, got {channel_slots}"
            )
        self._w = worker_mod.global_worker()
        self._capacity = channel_capacity
        self._slots = channel_slots
        self._lock = threading.Lock()
        self._exec_seq = 0
        self._read_seq = 0
        # FIFO backpressure bound: each channel rings channel_slots
        # messages, so unconsumed rounds beyond max_inflight would block
        # execute() inside the lock once the ring fills (reference raises
        # RayCgraphCapacityExceeded for the same reason) — surface a
        # clear error instead.
        self._max_inflight = max_inflight
        self._torn_down = False
        self._broken = False

        nodes = _topo_collect(root)
        inputs = [n for n in nodes if isinstance(n, InputNode)]
        if len(inputs) > 1:
            raise ValueError("a DAG takes exactly one InputNode")
        self._input = inputs[0] if inputs else None
        if isinstance(root, MultiOutputNode):
            self._outputs = root.nodes
            self._multi = True
        else:
            self._outputs = [root]
            self._multi = False
        for out in self._outputs:
            if not isinstance(out, ClassMethodNode):
                raise ValueError("DAG outputs must be actor method nodes")
        self._method_nodes = [n for n in nodes if isinstance(n, ClassMethodNode)]
        if not self._method_nodes:
            raise ValueError("DAG has no actor method calls")

        # group nodes by actor, preserving topological order
        self._actors: Dict[str, Any] = {}
        per_actor: Dict[str, List[ClassMethodNode]] = {}
        for n in self._method_nodes:
            aid = n.actor._actor_id
            self._actors[aid] = n.actor
            per_actor.setdefault(aid, []).append(n)

        node_actor = {n._id: n.actor._actor_id for n in self._method_nodes}

        # channels: one per (producer node or input) × consuming actor,
        # plus one per output node back to the driver
        self._input_channels: List[ShmChannel] = []   # driver writes
        self._output_channels: List[ShmChannel] = []  # driver reads
        plans: Dict[str, Dict[str, Any]] = {
            aid: {"in": {}, "steps": [], "out": {}} for aid in per_actor
        }
        chan_for: Dict[Tuple[int, str], ShmChannel] = {}

        def edge_channel(producer_id: int, consumer_aid: str) -> ShmChannel:
            """One channel per (producer, consumer-actor) EDGE — a node
            consumed twice by the same actor shares the channel (the
            consumer's per-round cache reads it once), and the producer
            registers exactly one out-handle for it."""
            key = (producer_id, consumer_aid)
            ch = chan_for.get(key)
            if ch is None:
                ch = ShmChannel.create(self._capacity, slots=self._slots)
                chan_for[key] = ch
                plans[consumer_aid]["in"][producer_id] = ch.handle()
                if producer_id == -1:
                    self._input_channels.append(ch)
                elif producer_id >= 0:
                    plans[node_actor[producer_id]]["out"].setdefault(
                        str(producer_id), []
                    ).append(ch.handle())
            return ch

        for n in self._method_nodes:
            aid = node_actor[n._id]
            arg_specs: List[Tuple[str, Any]] = []
            for a in n.args:
                if isinstance(a, InputNode):
                    edge_channel(-1, aid)
                    arg_specs.append(("chan", -1))
                elif isinstance(a, ClassMethodNode):
                    if node_actor[a._id] == aid:
                        arg_specs.append(("local", a._id))
                    else:
                        edge_channel(a._id, aid)
                        arg_specs.append(("chan", a._id))
                elif isinstance(a, DAGNode):
                    raise ValueError(f"unsupported DAG node arg {type(a)}")
                else:
                    arg_specs.append(("const", a))
            plans[aid]["steps"].append({
                "node_id": n._id,
                "method": n.method_name,
                "args": arg_specs,
            })

        for out in self._outputs:
            ch = ShmChannel.create(self._capacity, slots=self._slots)
            self._output_channels.append(ch)
            plans[node_actor[out._id]]["out"].setdefault(
                str(out._id), []
            ).append(ch.handle())

        # the driver owns EVERY channel's shm lifetime (actor→actor edges
        # included): teardown unlinks them all, so a wedged exec loop
        # cannot strand /dev/shm/rtchan_* debris for sweep_stale_runtime
        self._edge_channels = [
            ch for (pid, _), ch in chan_for.items() if pid >= 0
        ]

        # park the exec loops (their replies arrive at teardown)
        self._loop_refs = []
        for aid, plan in plans.items():
            refs = self._w.submit_actor_task(
                aid, "__rt_dag_exec_loop__",
                (serialization.pack(plan),), {}, num_returns=1,
            )
            self._loop_refs.extend(refs)

    # -- driver-side hot path ------------------------------------------

    def execute(self, *args) -> CompiledDAGRef:
        with self._lock:
            if self._torn_down:
                raise RuntimeError("compiled DAG was torn down")
            if self._broken:
                raise RuntimeError(
                    "compiled DAG stream desynced (an earlier round failed "
                    "mid-write); teardown and recompile"
                )
            if self._exec_seq - self._read_seq >= self._max_inflight:
                raise RuntimeError(
                    f"compiled DAG has {self._exec_seq - self._read_seq} "
                    f"unconsumed executions (max_inflight="
                    f"{self._max_inflight}); get() earlier results first"
                )
            if self._input is not None:
                meta, views = serialization.serialize(
                    args[0] if len(args) == 1 else args
                )
                parts = serialization.frame_parts(meta, views)
                for i, ch in enumerate(self._input_channels):
                    try:
                        ch.write_views(parts)
                    except Exception:
                        if i > 0:
                            # earlier channels already hold this round's
                            # payload: actors would pair inputs across
                            # rounds — poison the DAG so later calls fail
                            # loudly instead of silently desyncing
                            self._broken = True
                        raise
            self._exec_seq += 1
            return CompiledDAGRef(self, self._exec_seq)

    def _read_output(self, seq: int, timeout_s: Optional[float]) -> Any:
        with self._lock:
            if self._broken:
                raise RuntimeError(
                    "compiled DAG stream desynced (an earlier read timed "
                    "out mid-round); teardown and recompile"
                )
            if seq != self._read_seq + 1:
                raise RuntimeError(
                    "compiled DAG results must be consumed in order "
                    f"(expected round {self._read_seq + 1}, got {seq})"
                )
            outs = []
            for i, ch in enumerate(self._output_channels):
                try:
                    frame = ch.read(timeout_s)
                except TimeoutError:
                    if i > 0:
                        # earlier channels of this round were consumed:
                        # leaves would pair across rounds — poison the DAG
                        self._broken = True
                    raise
                if _is_stop(frame):
                    raise RuntimeError("compiled DAG torn down mid-read")
                outs.append(serialization.unpack(frame))
            self._read_seq = seq
        for o in outs:
            if isinstance(o, Exception):
                raise o
        return outs if self._multi else outs[0]

    def teardown(self, timeout_s: float = 60.0) -> None:
        import time as _time

        with self._lock:
            if self._torn_down:
                return
            self._torn_down = True
        # Exec loops may be BLOCKED writing an output the driver never
        # consumed (execute() without get()): keep draining the
        # driver-facing output channels while the _STOP propagates, so
        # every blocked writer unwedges and reaches its input read.
        from ray_tpu.core import api

        pending = list(self._loop_refs)
        stop_sent = [False] * len(self._input_channels)
        deadline = _time.monotonic() + timeout_s
        while pending and _time.monotonic() < deadline:
            for i, ch in enumerate(self._input_channels):
                if not stop_sent[i]:
                    try:
                        ch.write(_STOP, timeout_s=0.2)
                        stop_sent[i] = True
                    except (TimeoutError, ValueError):
                        pass  # input slot still full: drain + retry
            for ch in self._output_channels:
                try:
                    ch.read(timeout_s=0.05)
                except Exception:  # noqa: BLE001 — empty/closed: fine
                    pass
            try:
                _, pending = api.wait(
                    pending, num_returns=len(pending), timeout=0.3
                )
            except Exception:  # noqa: BLE001 — actor may already be dead
                pending = []
                break
        if pending:
            # a wedged exec loop (stage blocked in user code, actor
            # half-dead) outlived the drain deadline: say so loudly —
            # the channels are unlinked below regardless, so no
            # /dev/shm/rtchan_* debris survives for sweep_stale_runtime,
            # but the actor's executor slot stays occupied until the
            # loop dies with its process.
            logger.warning(
                "compiled DAG teardown: %d exec loop(s) still running "
                "after the %.0fs drain deadline; unlinking all %d "
                "channel(s) anyway (wedged loops keep their actors' "
                "executor slots until the actor dies)",
                len(pending), timeout_s,
                len(self._input_channels) + len(self._output_channels)
                + len(self._edge_channels),
            )
        for ch in (self._input_channels + self._output_channels
                   + self._edge_channels):
            ch.close(unlink=True)


def _actor_exec_loop(instance, plan_blob: bytes) -> int:
    """The per-actor compiled loop (runs as a system actor task and
    occupies one executor slot until teardown). Reads input channels
    lazily per step (cached per round), executes bound methods in topo
    order, pushes results downstream. Returns the round count."""
    plan = serialization.unpack(plan_blob)
    in_ch = {
        pid: ShmChannel.from_handle(h) for pid, h in plan["in"].items()
    }
    out_ch = {
        nid: [ShmChannel.from_handle(h) for h in handles]
        for nid, handles in plan["out"].items()
    }
    rounds = 0
    stopping = False
    while not stopping:
        cache: Dict[int, Any] = {}
        produced: Dict[int, Any] = {}

        def read_chan(pid: int):
            nonlocal stopping
            if pid in cache:
                return cache[pid]
            frame = in_ch[pid].read(timeout_s=None)
            if _is_stop(frame):
                stopping = True
                return None
            value = serialization.unpack(frame)
            cache[pid] = value
            return value

        for step in plan["steps"]:
            argv = []
            failed: Optional[Exception] = None
            for kind, ref in step["args"]:
                if kind == "const":
                    argv.append(ref)
                    continue
                if kind == "local":
                    value = produced[ref]
                else:
                    value = read_chan(ref)
                    if stopping:
                        break
                if isinstance(value, Exception):
                    failed = value  # propagate upstream errors downstream
                argv.append(value)
            if stopping:
                break
            if failed is not None:
                result: Any = failed
            else:
                try:
                    result = getattr(instance, step["method"])(*argv)
                except Exception as e:  # noqa: BLE001 — ship to consumer
                    result = e
            produced[step["node_id"]] = result
            send_value(out_ch.get(str(step["node_id"]), ()), result,
                       timeout_s=None)
        rounds += 1
    for ch in list(in_ch.values()):
        ch.close()
    # propagate the stop downstream so every loop unblocks
    for chans in out_ch.values():
        for ch in chans:
            try:
                ch.write(_STOP, timeout_s=1.0)
            except (TimeoutError, ValueError):
                pass
            ch.close()
    return rounds
