"""Environment interface + built-in CartPole.

Parity: the reference RLlib's env layer (rllib/env/) is gymnasium-based;
this image has no gymnasium, so the interface is the same shape
(reset() -> (obs, info), step(a) -> (obs, reward, terminated, truncated,
info)) with a self-contained CartPole-v1 implementation (standard
Barto-Sutton-Anderson dynamics) as the canonical test env.
"""

from __future__ import annotations

import math
from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np


class Env:
    observation_size: int
    num_actions: int

    def reset(self, seed: Optional[int] = None) -> Tuple[np.ndarray, Dict]:
        raise NotImplementedError

    def step(self, action: int) -> Tuple[np.ndarray, float, bool, bool, Dict]:
        raise NotImplementedError


class CartPole(Env):
    """CartPole-v1 dynamics (gymnasium-compatible semantics: reward 1 per
    step, terminated on |x|>2.4 or |theta|>12deg, truncated at 500)."""

    observation_size = 4
    num_actions = 2

    GRAVITY = 9.8
    MASSCART = 1.0
    MASSPOLE = 0.1
    LENGTH = 0.5
    FORCE_MAG = 10.0
    TAU = 0.02
    THETA_LIMIT = 12 * 2 * math.pi / 360
    X_LIMIT = 2.4
    MAX_STEPS = 500

    def __init__(self):
        self._rng = np.random.default_rng(0)
        self._state = np.zeros(4, np.float64)
        self._steps = 0

    def reset(self, seed: Optional[int] = None):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._state = self._rng.uniform(-0.05, 0.05, size=4)
        self._steps = 0
        return self._state.astype(np.float32).copy(), {}

    def step(self, action: int):
        x, x_dot, theta, theta_dot = self._state
        force = self.FORCE_MAG if action == 1 else -self.FORCE_MAG
        costheta, sintheta = math.cos(theta), math.sin(theta)
        total_mass = self.MASSCART + self.MASSPOLE
        polemass_length = self.MASSPOLE * self.LENGTH
        temp = (
            force + polemass_length * theta_dot**2 * sintheta
        ) / total_mass
        thetaacc = (self.GRAVITY * sintheta - costheta * temp) / (
            self.LENGTH * (4.0 / 3.0 - self.MASSPOLE * costheta**2 / total_mass)
        )
        xacc = temp - polemass_length * thetaacc * costheta / total_mass
        x += self.TAU * x_dot
        x_dot += self.TAU * xacc
        theta += self.TAU * theta_dot
        theta_dot += self.TAU * thetaacc
        self._state = np.asarray([x, x_dot, theta, theta_dot])
        self._steps += 1
        terminated = bool(
            abs(x) > self.X_LIMIT or abs(theta) > self.THETA_LIMIT
        )
        truncated = self._steps >= self.MAX_STEPS
        return (
            self._state.astype(np.float32).copy(), 1.0, terminated,
            truncated, {},
        )


ENV_REGISTRY: Dict[str, Callable[[], Env]] = {"CartPole-v1": CartPole}


def make_env(name_or_factory: Any) -> Env:
    if callable(name_or_factory):
        return name_or_factory()
    return ENV_REGISTRY[name_or_factory]()
