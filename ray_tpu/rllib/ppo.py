"""PPO — the RLlib-lite flagship algorithm.

Parity: the reference Algorithm/EnvRunnerGroup/LearnerGroup split
(rllib/algorithms/algorithm.py:212, env_runner_group.py:70,
learner_group.py:100) at BASELINE config #4's shape: CPU env-runner
ACTORS sample rollouts with a numpy copy of the policy, the LEARNER runs
the jitted PPO update (clipped surrogate + value loss + entropy bonus
over GAE advantages) on the driver's accelerator — chips never wait on
environment stepping, hosts never run SGD.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.rllib.env import make_env
from ray_tpu.utils import serialization


# ---------------------------------------------------------------------------
# policy: 2-layer MLP -> (logits, value)
# ---------------------------------------------------------------------------


def init_policy(rng, obs_size: int, num_actions: int, hidden: int = 64):
    import jax

    k1, k2, k3, k4 = jax.random.split(rng, 4)
    import jax.numpy as jnp

    def norm(k, shape, scale):
        return jax.random.normal(k, shape, jnp.float32) * scale

    return {
        "w1": norm(k1, (obs_size, hidden), 0.5 / obs_size**0.5),
        "b1": jnp.zeros((hidden,)),
        "w2": norm(k2, (hidden, hidden), 1.0 / hidden**0.5),
        "b2": jnp.zeros((hidden,)),
        "pi": norm(k3, (hidden, num_actions), 0.01),
        "v": norm(k4, (hidden, 1), 1.0 / hidden**0.5),
    }


def _forward_np(params: Dict[str, np.ndarray], obs: np.ndarray):
    """Numpy policy forward for the CPU rollout path (no jax import in
    the hot sampling loop)."""
    h = np.tanh(obs @ params["w1"] + params["b1"])
    h = np.tanh(h @ params["w2"] + params["b2"])
    logits = h @ params["pi"]
    value = (h @ params["v"])[..., 0]
    return logits, value


def _forward_jnp(params, obs):
    import jax.numpy as jnp

    h = jnp.tanh(obs @ params["w1"] + params["b1"])
    h = jnp.tanh(h @ params["w2"] + params["b2"])
    return h @ params["pi"], (h @ params["v"])[..., 0]


# ---------------------------------------------------------------------------
# env runner actor (CPU sampling)
# ---------------------------------------------------------------------------


@ray_tpu.remote
class EnvRunner:
    """Samples rollouts with a numpy snapshot of the policy (parity:
    SingleAgentEnvRunner)."""

    def __init__(self, env_spec, seed: int):
        self.env = make_env(env_spec)
        self.rng = np.random.default_rng(seed)
        self.obs, _ = self.env.reset(seed=seed)
        self.episode_return = 0.0
        self.completed_returns: List[float] = []

    def sample(self, params_blob: bytes, num_steps: int) -> Dict[str, Any]:
        params = {
            k: np.asarray(v)
            for k, v in serialization.unpack(params_blob).items()
        }
        obs_buf = np.empty((num_steps, self.env.observation_size), np.float32)
        act_buf = np.empty((num_steps,), np.int32)
        logp_buf = np.empty((num_steps,), np.float32)
        val_buf = np.empty((num_steps,), np.float32)
        rew_buf = np.empty((num_steps,), np.float32)
        done_buf = np.empty((num_steps,), np.float32)
        self.completed_returns = []
        for t in range(num_steps):
            logits, value = _forward_np(params, self.obs)
            z = logits - logits.max()
            p = np.exp(z)
            p /= p.sum()
            action = int(self.rng.choice(len(p), p=p))
            obs_buf[t] = self.obs
            act_buf[t] = action
            logp_buf[t] = float(np.log(p[action] + 1e-12))
            val_buf[t] = float(value)
            nxt, reward, terminated, truncated, _ = self.env.step(action)
            rew_buf[t] = reward
            done = terminated or truncated
            done_buf[t] = float(done)
            self.episode_return += reward
            if done:
                self.completed_returns.append(self.episode_return)
                self.episode_return = 0.0
                nxt, _ = self.env.reset()
            self.obs = nxt
        _, last_val = _forward_np(params, self.obs)
        return {
            "obs": obs_buf, "actions": act_buf, "logp": logp_buf,
            "values": val_buf, "rewards": rew_buf, "dones": done_buf,
            "last_value": float(last_val),
            "episode_returns": self.completed_returns,
        }


def _gae(batch: Dict[str, np.ndarray], gamma: float, lam: float):
    """Generalized advantage estimation over one runner's rollout."""
    rewards, values, dones = batch["rewards"], batch["values"], batch["dones"]
    T = len(rewards)
    adv = np.zeros(T, np.float32)
    last = 0.0
    next_value = batch["last_value"]
    for t in range(T - 1, -1, -1):
        nonterminal = 1.0 - dones[t]
        delta = rewards[t] + gamma * next_value * nonterminal - values[t]
        last = delta + gamma * lam * nonterminal * last
        adv[t] = last
        next_value = values[t]
    return adv, adv + values


# ---------------------------------------------------------------------------
# PPO algorithm
# ---------------------------------------------------------------------------


class PPOConfig:
    def __init__(
        self,
        env: Any = "CartPole-v1",
        num_env_runners: int = 2,
        rollout_length: int = 1024,
        gamma: float = 0.99,
        gae_lambda: float = 0.95,
        clip: float = 0.2,
        lr: float = 1e-3,
        entropy_coeff: float = 0.01,
        vf_coeff: float = 0.5,
        num_epochs: int = 6,
        minibatch_size: int = 256,
        hidden: int = 64,
        seed: int = 0,
    ):
        self.env = env
        self.num_env_runners = num_env_runners
        self.rollout_length = rollout_length
        self.gamma = gamma
        self.gae_lambda = gae_lambda
        self.clip = clip
        self.lr = lr
        self.entropy_coeff = entropy_coeff
        self.vf_coeff = vf_coeff
        self.num_epochs = num_epochs
        self.minibatch_size = minibatch_size
        self.hidden = hidden
        self.seed = seed

    def build(self) -> "PPO":
        return PPO(self)


class PPO:
    def __init__(self, cfg: PPOConfig):
        import jax
        import optax

        self.cfg = cfg
        probe = make_env(cfg.env)
        self.params = init_policy(
            jax.random.PRNGKey(cfg.seed), probe.observation_size,
            probe.num_actions, cfg.hidden,
        )
        # global-norm gradient clipping ahead of adam: the stock PPO
        # stabilizer against late-training policy collapse (reference
        # rllib default grad_clip on the same loss family)
        self.opt = optax.chain(
            optax.clip_by_global_norm(0.5), optax.adam(cfg.lr)
        )
        self.opt_state = self.opt.init(self.params)
        # best-iterate checkpoint (by rollout return): greedy evaluation
        # serves the best policy seen, not whatever the last SGD epoch
        # left behind — the in-memory analogue of keep-best checkpointing
        self.best_params = None
        self.best_return = -float("inf")
        self.runners = [
            EnvRunner.remote(cfg.env, cfg.seed * 1000 + i)
            for i in range(cfg.num_env_runners)
        ]
        self._train_minibatch = jax.jit(self._make_train_minibatch())
        self.iteration = 0

    def _make_train_minibatch(self):
        import jax
        import jax.numpy as jnp

        cfg = self.cfg

        def loss_fn(params, mb):
            logits, values = _forward_jnp(params, mb["obs"])
            logp_all = jax.nn.log_softmax(logits)
            logp = jnp.take_along_axis(
                logp_all, mb["actions"][:, None], axis=1
            )[:, 0]
            ratio = jnp.exp(logp - mb["logp"])
            adv = mb["adv"]
            adv = (adv - adv.mean()) / (adv.std() + 1e-8)
            surr = jnp.minimum(
                ratio * adv,
                jnp.clip(ratio, 1 - cfg.clip, 1 + cfg.clip) * adv,
            )
            entropy = -jnp.sum(jnp.exp(logp_all) * logp_all, axis=1)
            vf_loss = jnp.mean((values - mb["targets"]) ** 2)
            return (
                -jnp.mean(surr)
                + cfg.vf_coeff * vf_loss
                - cfg.entropy_coeff * jnp.mean(entropy)
            )

        def train_minibatch(params, opt_state, mb):
            loss, grads = jax.value_and_grad(loss_fn)(params, mb)
            updates, opt_state = self.opt.update(grads, opt_state, params)
            params = jax.tree.map(lambda p, u: p + u, params, updates)
            return params, opt_state, loss

        return train_minibatch

    def train(self) -> Dict[str, Any]:
        """One iteration: parallel rollouts -> GAE -> minibatch SGD."""
        import jax.numpy as jnp

        cfg = self.cfg
        params_np = {k: np.asarray(v) for k, v in self.params.items()}
        blob = serialization.pack(params_np)
        batches = ray_tpu.get(
            [
                r.sample.remote(blob, cfg.rollout_length)
                for r in self.runners
            ],
            timeout=600,
        )
        advs, targets = [], []
        for b in batches:
            a, t = _gae(b, cfg.gamma, cfg.gae_lambda)
            advs.append(a)
            targets.append(t)
        data = {
            "obs": np.concatenate([b["obs"] for b in batches]),
            "actions": np.concatenate([b["actions"] for b in batches]),
            "logp": np.concatenate([b["logp"] for b in batches]),
            "adv": np.concatenate(advs),
            "targets": np.concatenate(targets),
        }
        n = len(data["obs"])
        rng = np.random.default_rng(cfg.seed + self.iteration)
        losses = []
        for _ in range(cfg.num_epochs):
            order = rng.permutation(n)
            for start in range(0, n, cfg.minibatch_size):
                idx = order[start:start + cfg.minibatch_size]
                mb = {k: jnp.asarray(v[idx]) for k, v in data.items()}
                self.params, self.opt_state, loss = self._train_minibatch(
                    self.params, self.opt_state, mb
                )
                losses.append(float(loss))
        self.iteration += 1
        episode_returns = [
            r for b in batches for r in b["episode_returns"]
        ]
        return_mean = (
            float(np.mean(episode_returns)) if episode_returns else None
        )
        if return_mean is not None and return_mean > self.best_return:
            # snapshot the params that PRODUCED these rollouts (pre-update
            # for this iteration's SGD — the policy the returns measure)
            self.best_return = return_mean
            self.best_params = params_np
        return {
            "training_iteration": self.iteration,
            "episode_return_mean": return_mean,
            "best_return": (
                self.best_return if self.best_params is not None else None
            ),
            "num_episodes": len(episode_returns),
            "loss": float(np.mean(losses)),
            "num_env_steps": n,
        }

    def get_policy_params(self):
        return self.params

    def compute_action(self, obs: np.ndarray, use_best: bool = True) -> int:
        """Greedy action. With use_best (default) the best-return iterate
        serves the action — deploy-the-best-checkpoint semantics;
        use_best=False evaluates the live (latest) params."""
        params = self.params
        if use_best and self.best_params is not None:
            params = self.best_params
        params_np = {k: np.asarray(v) for k, v in params.items()}
        logits, _ = _forward_np(params_np, np.asarray(obs, np.float32))
        return int(np.argmax(logits))

    def stop(self) -> None:
        for r in self.runners:
            try:
                ray_tpu.kill(r)
            except Exception:  # noqa: BLE001
                pass
