"""ray_tpu.rllib — reinforcement learning (RLlib-lite).

Parity target: the reference RLlib's PPO path at BASELINE config #4's
shape (CPU env-runner actors + accelerator learner); algorithms beyond
PPO follow the same EnvRunner/Learner split.
"""

from ray_tpu.rllib.env import ENV_REGISTRY, CartPole, Env, make_env
from ray_tpu.rllib.ppo import PPO, PPOConfig

__all__ = [
    "CartPole",
    "ENV_REGISTRY",
    "Env",
    "PPO",
    "PPOConfig",
    "make_env",
]
